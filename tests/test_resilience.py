"""The resilience layer (gethsharding_tpu/resilience): retry policies,
circuit-breaker backend failover with differential half-open probes,
the dispatch watchdog, the crash-safe vote journal, and deterministic
chaos injection — plus the drain-and-fail dispatcher shutdown and the
SMCClient stop contract."""

import logging
import threading
import time

import pytest

from gethsharding_tpu import metrics
from gethsharding_tpu.actors.notary import Notary
from gethsharding_tpu.actors.proposer import create_collation
from gethsharding_tpu.core.shard import Shard
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.db.kv import MemoryKV, SqliteKV
from gethsharding_tpu.mainchain.accounts import AccountManager
from gethsharding_tpu.mainchain.client import ClientStopped, SMCClient
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.resilience.breaker import (
    CLOSED, OPEN, CircuitBreaker, FailoverSigBackend)
from gethsharding_tpu.resilience.chaos import (
    ChaosSchedule, ChaosSigBackend, InjectedFault, parse_spec, wrap)
from gethsharding_tpu.resilience.errors import (
    DeadlineExceeded, DispatcherClosed)
from gethsharding_tpu.resilience.journal import VoteJournal
from gethsharding_tpu.resilience.policy import RetryExecutor, RetryPolicy
from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
from gethsharding_tpu.serving.pipeline import PipelinedDispatcher
from gethsharding_tpu.sigbackend import PythonSigBackend, get_backend
from gethsharding_tpu.smc.chain import SimulatedMainchain
from gethsharding_tpu.utils.hexbytes import Hash32


def _garbage_rows(n):
    """n invalid ecrecover rows: both backends answer None for each, so
    results compare equal across primary and fallback."""
    return ([b"\x11" * 32] * n, [b"\x22" * 65] * n)


# -- retry policy ------------------------------------------------------------


def test_retry_then_succeed_counts_retries():
    registry = metrics.Registry()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    executor = RetryExecutor(
        "t1", RetryPolicy(attempts=5, base_s=0.0, jitter=0.0),
        registry=registry)
    assert executor.call(flaky) == "ok"
    assert len(calls) == 3
    assert registry.counter("resilience/retry/t1/retries").value == 2
    assert registry.counter("resilience/retry/t1/giveups").value == 0


def test_retry_exhausted_reraises_and_counts_giveup():
    registry = metrics.Registry()
    executor = RetryExecutor(
        "t2", RetryPolicy(attempts=3, base_s=0.0, jitter=0.0),
        registry=registry)

    def always():
        raise TimeoutError("down")

    with pytest.raises(TimeoutError):
        executor.call(always)
    assert registry.counter("resilience/retry/t2/retries").value == 2
    assert registry.counter("resilience/retry/t2/giveups").value == 1


def test_retry_only_transient_classes():
    executor = RetryExecutor(
        "t3", RetryPolicy(attempts=5, base_s=0.0),
        registry=metrics.Registry())
    calls = []

    def fatal():
        calls.append(1)
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        executor.call(fatal)
    assert len(calls) == 1  # no retry on non-transient classes


def test_retry_non_retryable_oserror_children_raise_immediately():
    """FileNotFoundError/PermissionError are OSError, but they are
    deterministic misconfiguration, not weather — the ladder must not
    hammer them with backoff."""
    for exc_type in (FileNotFoundError, PermissionError):
        registry = metrics.Registry()
        executor = RetryExecutor(
            "t3b", RetryPolicy(attempts=5, base_s=0.0, jitter=0.0),
            registry=registry)
        calls = []

        def fatal():
            calls.append(1)
            raise exc_type("bad endpoint path")

        with pytest.raises(exc_type):
            executor.call(fatal)
        assert len(calls) == 1
        assert registry.counter("resilience/retry/t3b/retries").value == 0


def test_retry_deadline_bounds_attempts():
    executor = RetryExecutor(
        "t4",
        RetryPolicy(attempts=50, base_s=0.02, deadline_s=0.06, jitter=0.0),
        registry=metrics.Registry())
    calls = []

    def always():
        calls.append(1)
        raise ConnectionError("down")

    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        executor.call(always)
    assert time.monotonic() - t0 < 1.0
    assert len(calls) < 50  # the deadline cut the ladder short


def test_retry_jitter_deterministic_with_seed():
    a = RetryPolicy(attempts=6, seed=9)
    b = RetryPolicy(attempts=6, seed=9)
    assert [a.backoff_s(i) for i in range(5)] == \
        [b.backoff_s(i) for i in range(5)]


# -- circuit breaker + failover backend --------------------------------------


class _FaultyBackend(PythonSigBackend):
    """Scalar-correct backend that raises while `faults` is positive."""

    name = "faulty"

    def __init__(self):
        self.faults = 0
        self.calls = 0

    def ecrecover_addresses(self, digests, sigs65):
        self.calls += 1
        if self.faults > 0:
            self.faults -= 1
            raise RuntimeError("device on fire")
        return super().ecrecover_addresses(digests, sigs65)


def _failover(fault_threshold=2, reset_s=60.0):
    registry = metrics.Registry()
    primary = _FaultyBackend()
    breaker = CircuitBreaker(name="t", fault_threshold=fault_threshold,
                             reset_s=reset_s, registry=registry)
    backend = FailoverSigBackend(primary, PythonSigBackend(),
                                 breaker=breaker, registry=registry)
    return backend, primary, breaker, registry


def test_breaker_trips_after_consecutive_faults_and_serves_fallback():
    backend, primary, breaker, registry = _failover(fault_threshold=2)
    want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(3))
    primary.faults = 2
    # each faulted call is served from the fallback — callers never see
    # the device error — and the second consecutive fault trips it open
    assert backend.ecrecover_addresses(*_garbage_rows(3)) == want
    assert breaker.state == CLOSED
    assert backend.ecrecover_addresses(*_garbage_rows(3)) == want
    assert breaker.state == OPEN
    assert registry.counter("resilience/breaker/t/trips").value == 1
    # while open the primary is not touched at all
    calls_before = primary.calls
    assert backend.ecrecover_addresses(*_garbage_rows(3)) == want
    assert primary.calls == calls_before
    assert registry.counter(
        "resilience/breaker/t/fallback_calls").value >= 3
    assert registry.gauge("resilience/breaker/t/state").value == OPEN


def test_breaker_success_between_faults_resets_the_run():
    backend, primary, breaker, _ = _failover(fault_threshold=2)
    primary.faults = 1
    backend.ecrecover_addresses(*_garbage_rows(1))  # fault 1
    backend.ecrecover_addresses(*_garbage_rows(1))  # success: run resets
    primary.faults = 1
    backend.ecrecover_addresses(*_garbage_rows(1))  # fault 1 again
    assert breaker.state == CLOSED  # never two CONSECUTIVE faults


def test_breaker_half_open_probe_match_recloses():
    backend, primary, breaker, registry = _failover(
        fault_threshold=1, reset_s=0.02)
    primary.faults = 1
    backend.ecrecover_addresses(*_garbage_rows(2))
    assert breaker.state == OPEN
    time.sleep(0.03)
    # cooldown elapsed: this call runs the differential spot-check —
    # primary healed and agrees with the fallback, so the breaker closes
    want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(2))
    assert backend.ecrecover_addresses(*_garbage_rows(2)) == want
    assert breaker.state == CLOSED
    assert registry.counter("resilience/breaker/t/probes").value == 1
    assert registry.counter("resilience/breaker/t/closes").value == 1
    # closed again: the primary serves
    calls_before = primary.calls
    backend.ecrecover_addresses(*_garbage_rows(2))
    assert primary.calls == calls_before + 1


def test_breaker_probe_exception_reopens():
    backend, primary, breaker, registry = _failover(
        fault_threshold=1, reset_s=0.02)
    primary.faults = 5  # stays broken through the first probe
    backend.ecrecover_addresses(*_garbage_rows(1))
    assert breaker.state == OPEN
    time.sleep(0.03)
    backend.ecrecover_addresses(*_garbage_rows(1))  # probe raises
    assert breaker.state == OPEN
    assert registry.counter("resilience/breaker/t/probes").value == 1
    assert registry.counter("resilience/breaker/t/closes").value == 0


def test_breaker_probe_mismatch_reopens():
    class _WrongBackend(PythonSigBackend):
        name = "wrong"

        def ecrecover_addresses(self, digests, sigs65):
            return ["not-the-answer"] * len(digests)

    registry = metrics.Registry()
    breaker = CircuitBreaker(name="t", fault_threshold=1, reset_s=0.0,
                             registry=registry)
    backend = FailoverSigBackend(_WrongBackend(), PythonSigBackend(),
                                 breaker=breaker, registry=registry)
    breaker.record_fault(RuntimeError("seed fault"))
    assert breaker.state == OPEN
    # probe: the "recovered" primary answers — wrongly. The fallback's
    # answer is served and the breaker refuses to re-promote.
    want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(2))
    assert backend.ecrecover_addresses(*_garbage_rows(2)) == want
    assert breaker.state == OPEN
    assert registry.counter(
        "resilience/breaker/t/probe_mismatches").value == 1


def test_breaker_probe_concludes_even_when_fallback_raises():
    """A raising FALLBACK during the differential probe must still
    conclude the probe (re-open) — a dangling probe flag would bench
    the primary forever with every later call routed to the fallback."""

    class _BrokenFallback(PythonSigBackend):
        name = "broken"

        def ecrecover_addresses(self, digests, sigs65):
            raise RuntimeError("fallback also on fire")

    registry = metrics.Registry()
    breaker = CircuitBreaker(name="t", fault_threshold=1, reset_s=0.0,
                             registry=registry)
    backend = FailoverSigBackend(PythonSigBackend(), _BrokenFallback(),
                                 breaker=breaker, registry=registry)
    breaker.record_fault(RuntimeError("seed"))
    assert breaker.state == OPEN
    with pytest.raises(RuntimeError, match="fallback also on fire"):
        backend.ecrecover_addresses(*_garbage_rows(1))  # the probe
    # the probe concluded: the NEXT eligible call probes again (it is
    # not starved by a stuck probe-in-flight flag)
    assert breaker.state == OPEN
    assert backend._call("bls_verify_aggregates", [], [], []) == []
    assert registry.counter("resilience/breaker/t/probes").value == 2
    # the fallback's failure is NOT a primary fault: only the seed
    # fault is on the counter
    assert registry.counter(
        "resilience/breaker/t/primary_faults").value == 1


def test_breaker_probe_abort_keeps_cooldown_timestamp():
    """probe_aborted (fallback raised, primary untested) re-opens
    WITHOUT restarting the cooldown: the next call re-probes
    immediately, unlike probe_failed which benches the primary for a
    fresh reset_s."""
    now = [0.0]
    breaker = CircuitBreaker(name="t", fault_threshold=1, reset_s=10.0,
                             registry=metrics.Registry(),
                             clock=lambda: now[0])
    breaker.record_fault(RuntimeError("seed"))
    assert breaker.state == OPEN
    now[0] = 10.0
    assert breaker.on_call() == "probe"
    breaker.probe_aborted("fallback raised")
    assert breaker.on_call() == "probe"  # no fresh cooldown
    breaker.probe_failed(mismatch=True)
    assert breaker.on_call() == "fallback"  # a REAL probe verdict does
    now[0] = 20.0
    assert breaker.on_call() == "probe"


def test_breaker_stale_deferred_faults_do_not_retrip():
    """A backlog of watchdog-failed futures submitted BEFORE a recovery
    must not re-trip the breaker against the recovered primary when the
    caller finally drains them: deferred outcomes carry the epoch of
    their submit, and a re-close bumps it."""
    breaker = CircuitBreaker(name="t", fault_threshold=2, reset_s=0.0,
                             registry=metrics.Registry())
    old = breaker.epoch
    breaker.record_fault(RuntimeError("f1"), epoch=old)
    breaker.record_fault(RuntimeError("f2"), epoch=old)
    assert breaker.state == OPEN
    assert breaker.on_call() == "probe"
    breaker.probe_matched()
    assert breaker.state == CLOSED
    for _ in range(5):  # the stale backlog drains after recovery
        breaker.record_fault(DeadlineExceeded("stale"), epoch=old)
    assert breaker.state == CLOSED
    # ... and a stale SUCCESS must not mask fresh faults
    new = breaker.epoch
    breaker.record_fault(RuntimeError("fresh1"), epoch=new)
    breaker.record_success(epoch=old)  # ignored: pre-recovery submit
    breaker.record_fault(RuntimeError("fresh2"), epoch=new)
    assert breaker.state == OPEN  # two FRESH consecutive faults trip


def test_failover_future_result_is_idempotent_on_failure():
    """Polling a failed serving future twice must not double-count the
    fault or recompute the fallback."""
    from concurrent.futures import Future

    from gethsharding_tpu.resilience.breaker import _FailoverFuture

    inner: Future = Future()
    inner.set_exception(RuntimeError("device fault"))
    recoveries = []

    def recover(exc):
        recoveries.append(exc)
        return ["fallback-answer"]

    future = _FailoverFuture(inner, recover, lambda: None)
    assert future.result() == ["fallback-answer"]
    assert future.result() == ["fallback-answer"]
    assert len(recoveries) == 1


def test_failover_backpressure_shed_is_not_a_device_fault():
    """A ServingOverloadError escaping the primary is the CALLER's
    backpressure signal: it must re-raise (the shed contract) and must
    not count toward tripping the breaker."""
    from gethsharding_tpu.serving.queue import ServingOverloadError

    class _SheddingBackend(PythonSigBackend):
        name = "shedding"

        def ecrecover_addresses(self, digests, sigs65):
            raise ServingOverloadError("queue at capacity")

    registry = metrics.Registry()
    breaker = CircuitBreaker(name="t", fault_threshold=1, reset_s=60,
                             registry=registry)
    backend = FailoverSigBackend(_SheddingBackend(), PythonSigBackend(),
                                 breaker=breaker, registry=registry)
    for _ in range(3):
        with pytest.raises(ServingOverloadError):
            backend.ecrecover_addresses(*_garbage_rows(1))
    assert breaker.state == CLOSED
    assert registry.counter("resilience/breaker/t/trips").value == 0
    assert registry.counter(
        "resilience/breaker/t/primary_faults").value == 0


def test_failover_probe_shed_is_not_a_probe_failure():
    """A backpressure shed at PROBE time gets the same exemption as the
    closed path: the probe concludes without a verdict — no fault
    count, no fresh cooldown — and the fallback's answer is served."""
    from gethsharding_tpu.serving.queue import ServingOverloadError

    class _SheddingBackend(PythonSigBackend):
        name = "shedding"

        def ecrecover_addresses(self, digests, sigs65):
            raise ServingOverloadError("queue at capacity")

    registry = metrics.Registry()
    now = [0.0]
    breaker = CircuitBreaker(name="t", fault_threshold=1, reset_s=10.0,
                             registry=registry, clock=lambda: now[0])
    backend = FailoverSigBackend(_SheddingBackend(), PythonSigBackend(),
                                 breaker=breaker, registry=registry)
    breaker.record_fault(RuntimeError("seed"))
    assert breaker.state == OPEN
    now[0] = 10.0
    want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(2))
    assert backend.ecrecover_addresses(*_garbage_rows(2)) == want
    assert breaker.state == OPEN
    # no fault beyond the seed, and no cooldown restart: the very next
    # call is a probe again instead of 10 more seconds of fallback
    assert registry.counter(
        "resilience/breaker/t/primary_faults").value == 1
    assert breaker.on_call() == "probe"


def test_failover_future_caller_timeout_is_not_a_fault():
    """result(timeout) expiring on a still-pending batch re-raises the
    caller's TimeoutError; a later poll still gets the real answer."""
    from concurrent import futures
    from concurrent.futures import Future

    from gethsharding_tpu.resilience.breaker import _FailoverFuture

    inner: Future = Future()
    faults = []
    future = _FailoverFuture(inner, lambda exc: faults.append(exc),
                             lambda: None)
    with pytest.raises(futures.TimeoutError):
        future.result(timeout=0.01)
    assert not faults  # no fault recorded, no fallback recompute
    inner.set_result(["late-but-right"])
    assert future.result() == ["late-but-right"]


def test_failover_async_caller_error_at_pull_is_not_a_fault():
    """A ValueError surfacing at result() time on the primary-routed
    async committee path gets the same exemption as the sync path:
    re-raised to the caller, no fault counted, no fallback recompute —
    one buggy caller must not demote a healthy device for everyone."""
    from gethsharding_tpu.sigbackend import VerdictFuture

    class _RaggedBackend(PythonSigBackend):
        name = "ragged"

        def bls_verify_committees_async(self, messages, sig_rows,
                                        pk_rows, pk_row_keys=None):
            def finalize():
                raise ValueError("ragged rows")

            return VerdictFuture(finalize)

    registry = metrics.Registry()
    breaker = CircuitBreaker(name="t", fault_threshold=1, reset_s=60,
                             registry=registry)
    backend = FailoverSigBackend(_RaggedBackend(), PythonSigBackend(),
                                 breaker=breaker, registry=registry)
    future = backend.bls_verify_committees_async([b"\x01" * 32], [[]], [[]])
    with pytest.raises(ValueError):
        future.result()
    with pytest.raises(ValueError):
        future.result()  # cached, not re-derived
    assert breaker.state == CLOSED
    assert registry.counter(
        "resilience/breaker/t/primary_faults").value == 0
    assert registry.counter(
        "resilience/breaker/t/fallback_calls").value == 0


def test_failover_async_pull_fault_counts_once_when_fallback_raises():
    """`VerdictFuture.result()` re-runs finalize when it raised, so a
    caller polling a doubly-failed verification twice must still count
    exactly ONE primary fault (not one per poll) and re-raise the
    cached fallback failure instead of re-deriving it."""
    from gethsharding_tpu.sigbackend import VerdictFuture

    class _DeadBackend(PythonSigBackend):
        name = "dead"

        def bls_verify_committees_async(self, messages, sig_rows,
                                        pk_rows, pk_row_keys=None):
            def finalize():
                raise RuntimeError("device on fire")

            return VerdictFuture(finalize)

    class _BrokenFallback(PythonSigBackend):
        name = "broken"
        calls = 0

        def bls_verify_committees(self, messages, sig_rows, pk_rows,
                                  pk_row_keys=None):
            type(self).calls += 1
            raise RuntimeError("fallback also down")

    registry = metrics.Registry()
    breaker = CircuitBreaker(name="t", fault_threshold=3, reset_s=60,
                             registry=registry)
    backend = FailoverSigBackend(_DeadBackend(), _BrokenFallback(),
                                 breaker=breaker, registry=registry)
    future = backend.bls_verify_committees_async([b"\x01" * 32], [[]], [[]])
    with pytest.raises(RuntimeError, match="fallback also down"):
        future.result()
    with pytest.raises(RuntimeError, match="fallback also down"):
        future.result()
    assert registry.counter(
        "resilience/breaker/t/primary_faults").value == 1
    assert _BrokenFallback.calls == 1
    assert breaker.state == CLOSED  # one op, one fault — not two of three


def test_failover_submit_caller_error_is_not_a_fault():
    """The serving `submit` recover path: a deterministic caller error
    failing the batch's future re-raises without counting a device
    fault or recomputing on the fallback (sync-path parity)."""
    from concurrent.futures import Future

    class _ServingLike(PythonSigBackend):
        name = "servinglike"

        def submit(self, op, *args, **kwargs):
            future: Future = Future()
            future.set_exception(TypeError("bad G1 point"))
            return future

    registry = metrics.Registry()
    breaker = CircuitBreaker(name="t", fault_threshold=1, reset_s=60,
                             registry=registry)
    backend = FailoverSigBackend(_ServingLike(), PythonSigBackend(),
                                 breaker=breaker, registry=registry)
    future = backend.submit("ecrecover_addresses", *_garbage_rows(1))
    with pytest.raises(TypeError):
        future.result()
    with pytest.raises(TypeError):
        future.result()  # idempotent: cached, no second recover
    assert breaker.state == CLOSED
    assert registry.counter(
        "resilience/breaker/t/primary_faults").value == 0


def test_failover_matches_python_backend_differentially():
    backend, primary, _, _ = _failover()
    py = PythonSigBackend()
    digests, sigs = _garbage_rows(5)
    assert backend.ecrecover_addresses(digests, sigs) == \
        py.ecrecover_addresses(digests, sigs)
    # async committee face, fault at submit -> recovered on fallback
    primary.faults = 0
    future = backend.bls_verify_committees_async([], [], [])
    assert future.result() == []


def test_failover_open_logs_transitions(caplog):
    backend, primary, breaker, _ = _failover(fault_threshold=1)
    primary.faults = 1
    with caplog.at_level(logging.WARNING, logger="resilience.breaker"):
        backend.ecrecover_addresses(*_garbage_rows(1))
    assert breaker.state == OPEN
    assert any("breaker t open" in rec.message for rec in caplog.records)


# -- dispatch watchdog -------------------------------------------------------


class _HangBackend(PythonSigBackend):
    """First `hangs` calls block on the release event (a wedged device
    dispatch); later calls answer instantly."""

    name = "hang"

    def __init__(self, hangs=1):
        self.hangs = hangs
        self.release = threading.Event()

    def ecrecover_addresses(self, digests, sigs65):
        if self.hangs > 0:
            self.hangs -= 1
            self.release.wait(10.0)
        return super().ecrecover_addresses(digests, sigs65)


def test_watchdog_fails_hung_batch_and_restarts_dispatcher():
    hang = _HangBackend(hangs=1)
    serving = ServingSigBackend(
        hang, ServingConfig(flush_us=100.0, watchdog_s=0.15))
    try:
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            serving.ecrecover_addresses(*_garbage_rows(2))
        # failed within ~the deadline, not the 10s the device hung for
        assert time.monotonic() - t0 < 2.0
        hang.release.set()  # let the superseded thread die
        # the restarted dispatcher serves the next batch
        want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(2))
        assert serving.ecrecover_addresses(*_garbage_rows(2)) == want
        assert metrics.DEFAULT_REGISTRY.counter(
            "resilience/watchdog/timeouts").value >= 1
    finally:
        serving.close()


def test_watchdog_timeout_feeds_failover_breaker():
    """A chaos-hung dispatch under serving surfaces as DeadlineExceeded;
    the failover face above counts it as a primary fault and answers
    from the scalar fallback — the caller sees a RESULT, not an error."""
    schedule = ChaosSchedule(seed=3, rules={"dispatch.ecrecover_addresses": 1})
    chaotic = ChaosSigBackend(PythonSigBackend(), schedule, hang_s=5.0)
    serving = ServingSigBackend(
        chaotic, ServingConfig(flush_us=100.0, watchdog_s=0.15))
    registry = metrics.Registry()
    breaker = CircuitBreaker(name="wd", fault_threshold=3, reset_s=60,
                             registry=registry)
    backend = FailoverSigBackend(serving, PythonSigBackend(),
                                 breaker=breaker, registry=registry)
    try:
        want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(1))
        t0 = time.monotonic()
        assert backend.ecrecover_addresses(*_garbage_rows(1)) == want
        assert time.monotonic() - t0 < 3.0
        assert registry.counter(
            "resilience/breaker/wd/primary_faults").value == 1
        # healed: the next call rides the primary serving path again
        assert backend.ecrecover_addresses(*_garbage_rows(1)) == want
    finally:
        serving.close()


def test_fail_current_min_age_spares_a_fresh_batch():
    """The watchdog's observe-then-abandon is racy: the hung batch can
    complete and a FRESH batch start between the age read and the
    fail_current call. min_age_s re-checks under the lock so the fresh
    batch survives instead of being failed moments after it started."""
    dispatcher = PipelinedDispatcher(name="t-minage")
    started, release = threading.Event(), threading.Event()

    def batch():
        started.set()
        release.wait(5.0)

    failed = []
    try:
        dispatcher.submit(batch, fail=failed.append)
        assert started.wait(2.0)
        # the in-flight batch is fresh: a watchdog that observed an
        # OLDER batch hanging must not abandon this one
        assert dispatcher.fail_current(
            DeadlineExceeded("stale observation"), min_age_s=3.0) is False
        assert not failed
        # the unconditional path (shutdown) still abandons it
        assert dispatcher.fail_current(
            DeadlineExceeded("really hung")) is True
        assert len(failed) == 1
    finally:
        release.set()
        dispatcher.close(wait=True)


def test_failover_future_proxies_serving_request():
    """observe_future_wake attributes wake latency via the serving
    future's `_serving_request`; the failover wrapper must pass it
    through or the future_wake span silently disappears under
    failover-* + --serving."""
    from concurrent.futures import Future

    from gethsharding_tpu.resilience.breaker import _FailoverFuture

    inner: Future = Future()
    inner._serving_request = sentinel = object()
    wrapped = _FailoverFuture(inner, lambda exc: None, lambda: None)
    assert wrapped._serving_request is sentinel
    bare = _FailoverFuture(Future(), lambda exc: None, lambda: None)
    assert bare._serving_request is None


# -- drain-and-fail dispatcher shutdown --------------------------------------


def test_dispatcher_close_while_busy_fails_queued_work():
    dispatcher = PipelinedDispatcher(name="t-close")
    started, release = threading.Event(), threading.Event()

    def slow():
        started.set()
        release.wait(5.0)

    failed = []
    dispatcher.submit(slow, fail=failed.append)
    assert started.wait(2.0)
    # queued-but-undispatched behind the busy batch
    dispatcher.submit(lambda: pytest.fail("must never run"),
                      fail=failed.append)
    t0 = time.monotonic()
    dispatcher.close(wait=True, grace_s=0.2)
    assert time.monotonic() - t0 < 2.0  # deterministic, no 10s hang
    # both the wedged in-flight batch and the queued one were failed
    assert len(failed) == 2
    assert all(isinstance(exc, DispatcherClosed) for exc in failed)
    release.set()


def test_dispatcher_close_healthy_drains_by_running():
    dispatcher = PipelinedDispatcher(name="t-drain")
    ran, failed = [], []
    dispatcher.submit(lambda: ran.append(1), fail=failed.append)
    dispatcher.close(wait=True)
    assert ran == [1] and failed == []
    with pytest.raises(RuntimeError):
        dispatcher.submit(lambda: None)


def test_dispatcher_close_nowait_leaves_inflight_work_alone():
    """close(wait=False) keeps its fire-and-forget contract: a healthy
    in-flight batch completes instead of being failed."""
    dispatcher = PipelinedDispatcher(name="t-nowait")
    started, release = threading.Event(), threading.Event()
    done, failed = [], []

    def slow():
        started.set()
        release.wait(5.0)
        done.append(1)

    dispatcher.submit(slow, fail=failed.append)
    assert started.wait(2.0)
    # second batch fills the ready slot, so close's sentinel is dropped
    dispatcher.submit(lambda: done.append(2), fail=failed.append)
    dispatcher.close(wait=False)  # returns immediately, fails nothing
    assert failed == []
    release.set()
    deadline = time.monotonic() + 2.0
    while len(done) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert done == [1, 2]
    # ... and the dispatch thread still exits despite the lost sentinel
    dispatcher._thread.join(timeout=2.0)
    assert not dispatcher._thread.is_alive()


def test_serving_close_while_hung_fails_futures_not_hangs():
    """Regression: close-while-busy at the serving level — a queued
    request behind a wedged dispatch gets a shutdown error instead of
    hanging the closing thread or the caller forever."""
    hang = _HangBackend(hangs=1)
    serving = ServingSigBackend(hang, ServingConfig(flush_us=100.0))
    results = []

    def call():
        try:
            results.append(serving.ecrecover_addresses(*_garbage_rows(1)))
        except Exception as exc:  # noqa: BLE001 - recording, not hiding
            results.append(exc)

    threads = [threading.Thread(target=call) for _ in range(2)]
    for thread in threads:
        thread.start()
    time.sleep(0.3)  # both flushed; one executing (hung), one behind it
    serving.batcher._dispatcher.close(wait=True, grace_s=0.2)
    hang.release.set()
    for thread in threads:
        thread.join(timeout=5.0)
    assert not any(thread.is_alive() for thread in threads)
    assert len(results) == 2
    assert any(isinstance(r, DispatcherClosed) for r in results)
    serving.close()


# -- crash-safe vote journal -------------------------------------------------


def test_kv_prefix_key_scan_skips_values(tmp_path):
    """The journal's namespace scan is key-only: both engines serve
    keys(prefix) without touching the (potentially huge) values."""
    for kv in (MemoryKV(), SqliteKV(str(tmp_path / "kv.db"))):
        kv.put(b"vj/v/a", b"\x01")
        kv.put(b"vj/v/b", b"\x01")
        kv.put(b"vj/audit_hwm", b"\x02")
        kv.put(b"chunk/huge", b"\xff" * 4096)
        assert sorted(kv.keys(b"vj/v/")) == [b"vj/v/a", b"vj/v/b"]
        assert sorted(kv.keys(b"vj/")) == [b"vj/audit_hwm", b"vj/v/a",
                                           b"vj/v/b"]
        assert len(list(kv.keys())) == 4
        kv.close()


def test_vote_journal_period_zero_watermark_is_real():
    """'period 0 audited' and 'nothing audited' must not conflate: the
    watermark is None until set, and set(0) persists."""
    journal = VoteJournal(MemoryKV(), registry=metrics.Registry())
    assert journal.audit_high_water() is None
    journal.set_audit_high_water(0)
    assert journal.audit_high_water() == 0


def test_vote_journal_roundtrip_and_prune(tmp_path):
    kv = SqliteKV(str(tmp_path / "journal.db"))
    journal = VoteJournal(kv, registry=metrics.Registry())
    assert not journal.has_vote(3, 7)
    journal.record_vote(3, 7)
    journal.record_vote(4, 7)
    journal.record_vote(3, 9)
    assert journal.has_vote(3, 7)
    assert sorted(journal.votes()) == [(3, 7), (3, 9), (4, 7)]
    assert journal.prune_votes(before_period=9) == 2
    assert sorted(journal.votes()) == [(3, 9)]
    journal.set_audit_high_water(5)
    journal.set_audit_high_water(3)  # monotonic: cannot go back
    assert journal.audit_high_water() == 5
    kv.close()
    # durability: a fresh handle on the same file sees the same state
    kv2 = SqliteKV(str(tmp_path / "journal.db"))
    journal2 = VoteJournal(kv2, registry=metrics.Registry())
    assert journal2.audit_high_water() == 5
    assert sorted(journal2.votes()) == [(3, 9)]
    kv2.close()


def _drive_period_with_collation(backend, client, notary, config):
    """Create + register a collation for the CURRENT period, then mine
    heads until the period ends (the notary votes along the way).
    Returns the period driven."""
    period = backend.current_period()
    collation = create_collation(client, 0, period,
                                 [Transaction(nonce=period, payload=b"x")])
    notary.shard.save_collation(collation)
    client.add_header(0, period, collation.header.chunk_root,
                      collation.header.proposer_signature)
    while backend.current_period() == period:
        backend.commit()
    return period


def test_vote_journal_exactly_once_across_notary_restart():
    """Kill a notary mid-period and restart it over the SAME journal:
    the restarted instance must neither re-submit the period's vote nor
    re-audit already-finished periods — even when the chain's own
    has_voted view is unreachable."""
    config = Config(quorum_size=1, period_length=4)
    backend = SimulatedMainchain(config=config)
    accounts = AccountManager()
    account = accounts.new_account()
    backend.fund(account.address, 2000 * ETHER)
    journal_kv = MemoryKV()
    journal = VoteJournal(journal_kv, registry=metrics.Registry())
    shard_kv = MemoryKV()

    client1 = SMCClient(backend=backend, accounts=accounts,
                        account=account, config=config)
    notary1 = Notary(client=client1, shard=Shard(0, shard_kv),
                     config=config, deposit_flag=True, all_shards=False,
                     journal=journal)
    notary1.start()
    backend.fast_forward(1)  # off period 0: the high-water mark is real
    p1 = _drive_period_with_collation(backend, client1, notary1, config)
    # one head into the next period so notary1 audits p1 (hwm -> p1)
    p2 = backend.current_period()
    collation = create_collation(client1, 0, p2,
                                 [Transaction(nonce=99, payload=b"y")])
    notary1.shard.save_collation(collation)
    client1.add_header(0, p2, collation.header.chunk_root,
                       collation.header.proposer_signature)
    backend.commit()  # head mid-period: audit p1 + vote p2
    assert notary1.votes_submitted == 2, notary1.errors
    assert journal.has_vote(0, p1) and journal.has_vote(0, p2)
    assert journal.audit_high_water() == p1
    audits1 = notary1.audits_run
    assert audits1 >= 1
    notary1.stop()  # the mid-period crash

    # restart: same account + journal; the chain's has_voted view is
    # DOWN (always-faulting), so only the journal can prevent a
    # double-vote
    schedule = ChaosSchedule(rules={"mainchain.has_voted": True})
    client2 = SMCClient(backend=wrap(backend, schedule, "mainchain"),
                        accounts=accounts, account=account, config=config)
    notary2 = Notary(client=client2, shard=Shard(0, shard_kv),
                     config=config, deposit_flag=True, all_shards=False,
                     journal=journal)
    notary2.start()
    try:
        # journal replay: "p1 audited" recovers as watermark p1 + 1
        assert notary2._last_audited_period == p1 + 1
        # mine out the REST of p2 without crossing into p3 (staying
        # mid-period keeps the p1-re-audit temptation alive every head)
        plen = config.period_length
        while (backend.block_number + 1) // plen == p2:
            backend.commit()
        assert notary2.votes_submitted == 0  # exactly-once across restart
        assert notary2.audits_run == 0       # p1 NOT re-audited
        # p2's single on-chain vote stands, un-doubled
        assert backend.collation_record(0, p2).vote_count == 1
        assert not notary2.errors, notary2.errors
    finally:
        notary2.stop()


def test_vote_journal_cleared_when_ahead_of_chain():
    """A journal that outlived its chain (wiped devnet: old datadir,
    fresh chain at period 0) must be invalidated on recovery — replay
    would silently mute the notary until the new chain catches up to
    the stale watermark."""
    journal = VoteJournal(MemoryKV(), registry=metrics.Registry())
    journal.record_vote(0, 5)
    journal.record_vote(0, 7)
    journal.set_audit_high_water(6)
    # same-chain restart: nothing ahead of the chain, journal kept
    assert not journal.invalidate_if_reset(current_period=7)
    assert journal.audit_high_water() == 6
    # chain reset: watermark/votes are ahead — cleared
    assert journal.invalidate_if_reset(current_period=2)
    assert journal.audit_high_water() is None
    assert list(journal.votes()) == []

    # the notary-level path: the stale journal from a previous chain
    # lifetime is cleared on on_start, and the notary votes normally
    config = Config(quorum_size=1, period_length=4)
    backend = SimulatedMainchain(config=config)
    client = SMCClient(backend=backend, config=config)
    backend.fund(client.account(), 2000 * ETHER)
    stale = VoteJournal(MemoryKV(), registry=metrics.Registry())
    stale.record_vote(0, 1)          # "already voted" period 1...
    stale.set_audit_high_water(40)   # ...and audited far ahead
    notary = Notary(client=client, shard=Shard(0, MemoryKV()),
                    config=config, deposit_flag=True, all_shards=False,
                    journal=stale)
    notary.start()
    try:
        assert stale.audit_high_water() is None  # cleared on replay
        assert notary._last_audited_period == 0
        backend.fast_forward(1)
        period = _drive_period_with_collation(backend, client, notary,
                                              config)
        assert notary.votes_submitted == 1, notary.errors
        assert backend.collation_record(0, period).vote_count == 1
    finally:
        notary.stop()


# -- deterministic chaos -----------------------------------------------------


def test_chaos_schedule_deterministic_and_seeded():
    rules = {"backend.op": 0.5}
    a = ChaosSchedule(seed=11, rules=rules)
    b = ChaosSchedule(seed=11, rules=rules)
    verdicts_a = [a.should_fail("backend.op") for _ in range(64)]
    verdicts_b = [b.should_fail("backend.op") for _ in range(64)]
    assert verdicts_a == verdicts_b
    assert any(verdicts_a) and not all(verdicts_a)
    c = ChaosSchedule(seed=12, rules=rules)
    assert [c.should_fail("backend.op") for _ in range(64)] != verdicts_a


def test_chaos_first_n_heals_and_prefix_rules():
    schedule = ChaosSchedule(rules={"backend.x": 2, "mainchain": True})
    assert schedule.should_fail("backend.x")
    assert schedule.should_fail("backend.x")
    assert not schedule.should_fail("backend.x")  # healed after n
    assert schedule.should_fail("mainchain.anything")  # bare prefix rule
    assert not schedule.should_fail("backend.other")
    assert schedule.injected == {"backend.x": 2, "mainchain.anything": 1}


def test_parse_spec():
    schedule = parse_spec(
        "seed=42, backend.bls_verify_committees=2, "
        "mainchain.collation_record=0.25, client.sign=always")
    assert schedule.seed == 42
    assert schedule.rules == {"backend.bls_verify_committees": 2,
                              "mainchain.collation_record": 0.25,
                              "client.sign": True}
    with pytest.raises(ValueError):
        parse_spec("not-a-rule")


def test_unwired_seams_flags_rules_no_injector_routes():
    from gethsharding_tpu.resilience.chaos import unwired_seams

    schedule = parse_spec(
        "seed=1,backend.ecrecover_addresses=2,client.sign=always,"
        "mainchain=0.5,typo.op=always")
    assert unwired_seams(
        schedule, ("mainchain", "backend", "dispatch")) == \
        ["client.sign", "typo.op"]
    assert unwired_seams(
        schedule, ("mainchain", "backend", "dispatch", "client")) == \
        ["typo.op"]


def test_chaos_property_backed_attribute_seam_injects():
    """A rule NAMING a property-backed attribute (mainchain.block_number
    is a @property, not a method) must inject on the read — silently
    returning the value would make the experiment test less than the
    operator asked for. Un-ruled data attributes pass through without
    consuming schedule slots."""
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    schedule = ChaosSchedule(rules={"mainchain.block_number": 2})
    proxy = wrap(backend, schedule, "mainchain")
    with pytest.raises(InjectedFault):
        proxy.block_number
    with pytest.raises(InjectedFault):
        proxy.block_number
    assert proxy.block_number == backend.block_number  # healed after n
    _ = proxy.config  # no rule names it: off the books
    assert schedule.calls("mainchain.config") == 0


def test_chaos_backend_seam_under_client_retry():
    """mainchain-seam injection sits UNDER the client's retry executor:
    a first-n schedule is absorbed by retries (retry-then-succeed)."""
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    schedule = ChaosSchedule(rules={"mainchain.shard_count": 2})
    client = SMCClient(
        backend=wrap(backend, schedule, "mainchain"), config=config,
        retry_policy=RetryPolicy(attempts=4, base_s=0.0, jitter=0.0))
    assert client.shard_count() == config.shard_count
    assert schedule.calls("mainchain.shard_count") == 3  # 2 faults + 1 ok


# -- the acceptance chaos run ------------------------------------------------


def test_chaos_device_fault_mid_audit_full_breaker_cycle(tracer):
    """ISSUE 5 acceptance: an injected device fault mid-audit trips the
    breaker, the notary completes the same period's votes on the scalar
    fallback with ZERO missed (shard, period) votes, and the breaker is
    observed closed again (open -> half-open differential probe ->
    closed) in metrics and trace output."""
    config = Config(quorum_size=1, period_length=4)
    backend = SimulatedMainchain(config=config)
    client = SMCClient(backend=backend, config=config)
    backend.fund(client.account(), 2000 * ETHER)

    # the first two committee-audit dispatches on the primary fail (the
    # injected device fault); everything after is healed
    schedule = ChaosSchedule(seed=5,
                             rules={"backend.bls_verify_committees": 2})
    registry = metrics.Registry()
    breaker = CircuitBreaker(name="accept", fault_threshold=1,
                             reset_s=0.005, registry=registry)
    failover = FailoverSigBackend(
        ChaosSigBackend(PythonSigBackend(), schedule),
        PythonSigBackend(), breaker=breaker, registry=registry)

    notary = Notary(client=client, shard=Shard(0, MemoryKV()),
                    config=config, deposit_flag=True, all_shards=False,
                    sig_backend=failover)
    notary.start()
    backend.fast_forward(1)
    periods = []
    try:
        for _ in range(5):
            periods.append(_drive_period_with_collation(
                backend, client, notary, config))
            time.sleep(0.01)  # let the open-state cooldown elapse
    finally:
        notary.stop()

    # zero missed votes: every driven period's (shard 0, period) vote
    # landed — including the ones audited/verified on the fallback
    assert notary.votes_submitted == len(periods), notary.errors
    for period in periods:
        assert backend.collation_record(0, period).vote_count == 1
    assert backend.last_approved_collation(0) == periods[-1]
    assert notary.audits_run >= 3
    assert notary.audit_mismatches == 0

    # the breaker went through the whole cycle: tripped open on the
    # injected fault, probed half-open, re-closed on a matching
    # differential spot-check — and ended closed
    assert schedule.injected.get("backend.bls_verify_committees") == 2
    assert registry.counter("resilience/breaker/accept/trips").value >= 1
    assert registry.counter("resilience/breaker/accept/probes").value >= 1
    assert registry.counter("resilience/breaker/accept/closes").value >= 1
    assert registry.counter(
        "resilience/breaker/accept/fallback_calls").value >= 1
    assert breaker.state == CLOSED
    assert registry.gauge("resilience/breaker/accept/state").value == CLOSED

    # ... and in trace output: the transition events were recorded
    names = {span["name"] for span in tracer.recent_spans()}
    assert "resilience/breaker/trip" in names
    assert "resilience/breaker/probe" in names
    assert "resilience/breaker/close" in names


@pytest.fixture
def tracer():
    from gethsharding_tpu import tracing

    tracing.enable(ring_spans=65536)
    tracing.TRACER.clear()
    yield tracing.TRACER
    tracing.disable()
    tracing.TRACER.clear()


# -- SMCClient stop contract -------------------------------------------------


def test_client_stop_exits_wait_for_transaction_promptly():
    client = SMCClient(backend=SimulatedMainchain())
    client.start()
    outcome = []

    def waiter():
        try:
            client.wait_for_transaction(Hash32(b"\xaa" * 32), timeout_s=30.0)
        except Exception as exc:  # noqa: BLE001 - recording the outcome
            outcome.append(exc)

    thread = threading.Thread(target=waiter)
    t0 = time.monotonic()
    thread.start()
    time.sleep(0.05)
    client.stop()
    thread.join(timeout=5.0)
    assert not thread.is_alive()
    assert time.monotonic() - t0 < 5.0  # nowhere near the 30s timeout
    assert len(outcome) == 1 and isinstance(outcome[0], ClientStopped)


def test_client_post_stop_calls_raise_client_stopped():
    client = SMCClient(backend=SimulatedMainchain())
    client.start()
    assert client.current_period() == 0
    client.stop()
    with pytest.raises(ClientStopped):
        client.current_period()
    with pytest.raises(ClientStopped):
        client.sign(b"\x00" * 32)
    with pytest.raises(ClientStopped):
        client.submit_vote(0, 1, 0, Hash32(b"\x00" * 32))
    client.start()  # restartable: the gate clears
    assert client.current_period() == 0


def test_client_stop_interrupts_inflight_retry_backoff():
    """stop() during a retry ladder's backoff must wake the sleeper and
    end the ladder with ClientStopped — not run the rest of the backoff
    budget against a backend that is going away."""
    config = Config(quorum_size=1)
    backend = SimulatedMainchain(config=config)
    schedule = ChaosSchedule(rules={"mainchain.shard_count": True})
    client = SMCClient(
        backend=wrap(backend, schedule, "mainchain"), config=config,
        retry_policy=RetryPolicy(attempts=50, base_s=5.0, cap_s=5.0,
                                 jitter=0.0))
    client.start()
    outcome = []

    def reader():
        try:
            client.shard_count()
        except Exception as exc:  # noqa: BLE001 - recording the outcome
            outcome.append(exc)

    thread = threading.Thread(target=reader)
    t0 = time.monotonic()
    thread.start()
    time.sleep(0.05)  # let the ladder enter its first 5s backoff
    client.stop()
    thread.join(timeout=5.0)
    assert not thread.is_alive()
    assert time.monotonic() - t0 < 2.0  # nowhere near one backoff step
    assert len(outcome) == 1 and isinstance(outcome[0], ClientStopped)


# -- netstore retry seam -----------------------------------------------------


def test_netstore_fetch_retries_rebroadcast_and_give_up():
    from gethsharding_tpu.p2p.service import Hub, P2PServer
    from gethsharding_tpu.storage.chunker import ChunkStoreError
    from gethsharding_tpu.storage.netstore import NetStore

    retries = metrics.DEFAULT_REGISTRY.counter(
        "resilience/retry/netstore/retries")
    giveups = metrics.DEFAULT_REGISTRY.counter(
        "resilience/retry/netstore/giveups")
    retries_before, giveups_before = retries.value, giveups.value
    ns = NetStore(p2p=P2PServer(hub=Hub()), fetch_timeout=0.06,
                  fetch_attempts=2, poll_interval=0.01)
    ns.start()
    try:
        with pytest.raises(ChunkStoreError, match="unavailable"):
            ns.get_chunk(b"\x42" * 32)
    finally:
        ns.stop()
    assert retries.value == retries_before + 1
    assert giveups.value == giveups_before + 1


# -- the closed-breaker overhead budget --------------------------------------


def test_breaker_closed_overhead_on_serving_hot_path():
    """With the breaker closed and no faults injected, the failover
    guard work per call (on_call + record_success + a counter) must
    cost <2% of a serving request — the same instrumentation budget the
    observability tests pin for tracing."""
    serving = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=500.0))
    backend, _, breaker, _ = _failover()
    try:
        serving.ecrecover_addresses(*_garbage_rows(0))  # warm the threads
        n = 100
        t0 = time.perf_counter()
        for i in range(n):
            serving.ecrecover_addresses(*_garbage_rows(i % 97))
        per_request_s = (time.perf_counter() - t0) / n
    finally:
        serving.close()

    m = 50_000
    t0 = time.perf_counter()
    for _ in range(m):
        if breaker.on_call() == "primary":
            breaker.record_success()
    guard_s = (time.perf_counter() - t0) / m
    # charge 3 guard evaluations per request (3x the real count of 1)
    assert 3 * guard_s < 0.02 * per_request_s, (
        f"breaker-closed overhead {3 * guard_s * 1e6:.3f}us vs request "
        f"{per_request_s * 1e6:.1f}us")
