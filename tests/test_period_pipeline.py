"""Period pipeline: single-device vs 8-device-mesh parity, quorum rules,
masked shards — the cross-shard "training step" (BASELINE.md config 3)."""

import numpy as np
import pytest

from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.params import Config
from gethsharding_tpu.parallel import make_mesh
from gethsharding_tpu.parallel.period import PeriodInputs, PeriodPipeline

CFG = Config(shard_count=8, committee_size=5, quorum_size=3)


def _period_case():
    """8 shards: 0-5 signed headers (varying vote counts), 6 tampered,
    7 no submission."""
    headers, sigs, pks, counts = [], [], [], []
    keys = [bls.bls_keygen(bytes([i])) for i in range(3)]
    agg_pk = bls.bls_aggregate_pks([pk for _, pk in keys])
    for s in range(6):
        header = b"hdr" + bytes([s])
        agg = bls.bls_aggregate_sigs(
            [bls.bls_sign(header, sk) for sk, _ in keys])
        headers.append(header)
        sigs.append(agg)
        pks.append(agg_pk)
        counts.append(5 if s % 2 == 0 else 2)  # alternate quorum/no-quorum
    # shard 6: tampered aggregate signature
    header6 = b"hdr6"
    agg6 = bls.g1_add(
        bls.bls_aggregate_sigs([bls.bls_sign(header6, sk) for sk, _ in keys]),
        bls.G1_GEN)
    headers.append(header6), sigs.append(agg6), pks.append(agg_pk)
    counts.append(5)
    # shard 7: no submission
    headers.append(None), sigs.append(None), pks.append(None)
    counts.append(0)
    return headers, sigs, pks, counts


EXPECT_VERIFIED = [True] * 6 + [False, False]
EXPECT_APPROVED = [True, False, True, False, True, False, False, False]


def test_single_device_period():
    pipe = PeriodPipeline(config=CFG)
    out = pipe.run(pipe.build_inputs(*_period_case()))
    assert list(np.asarray(out.verified)) == EXPECT_VERIFIED
    assert list(np.asarray(out.approved)) == EXPECT_APPROVED
    assert int(out.total_votes) == 5 + 2 + 5 + 2 + 5 + 2
    assert int(out.total_approved) == 3


def test_mesh_matches_single_device():
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    single = PeriodPipeline(config=CFG)
    meshed = PeriodPipeline(config=CFG, mesh=make_mesh(8))
    inputs = single.build_inputs(*_period_case())
    a = single.run(inputs)
    b = meshed.run(inputs)
    np.testing.assert_array_equal(np.asarray(a.verified), np.asarray(b.verified))
    np.testing.assert_array_equal(np.asarray(a.approved), np.asarray(b.approved))
    assert int(a.total_votes) == int(b.total_votes)
    assert int(a.total_approved) == int(b.total_approved)


def test_committee_pipeline_mesh_matches_single_device():
    """The committee-granular period step (device aggregation + pairing +
    psum tally) gives identical outcomes on the 8-device mesh and a
    single device, with uneven shards AND uneven committees."""
    from gethsharding_tpu.crypto import bn256 as ref
    from gethsharding_tpu.parallel import make_mesh
    from gethsharding_tpu.parallel.period import CommitteePeriodPipeline
    from gethsharding_tpu.params import Config

    config = Config(committee_size=4, quorum_size=2)
    keys = [ref.bls_keygen(bytes([40 + i])) for i in range(4)]
    n_shards = 11  # not a multiple of 8: exercises row padding
    headers, sig_rows, pk_rows, counts = [], [], [], []
    for s in range(n_shards):
        header = b"cpp-%d" % s
        voters = keys[: 1 + (s % 4)]
        sigs = [ref.bls_sign(header, sk) for sk, _ in voters]
        if s == 5:
            sigs = [ref.bls_sign(b"evil", voters[0][0])] + sigs[1:]
        headers.append(header if s != 7 else None)  # shard 7: no header
        sig_rows.append(sigs)
        pk_rows.append([pk for _, pk in voters])
        counts.append(len(voters))

    single = CommitteePeriodPipeline(config=config, mesh=None)
    meshed = CommitteePeriodPipeline(config=config, mesh=make_mesh(8))
    out_s = single.run(single.build_inputs(headers, sig_rows, pk_rows))
    out_m = meshed.run(meshed.build_inputs(headers, sig_rows, pk_rows))
    assert np.array_equal(np.asarray(out_s.verified),
                          np.asarray(out_m.verified))
    assert np.array_equal(np.asarray(out_s.approved),
                          np.asarray(out_m.approved))
    assert int(out_s.total_votes) == int(out_m.total_votes)
    assert int(out_s.total_approved) == int(out_m.total_approved)
    verified = np.asarray(out_s.verified)
    # the tally counts exactly the verified shards' filled vote slots
    assert int(out_s.total_votes) == sum(
        c for c, v in zip(counts, verified) if v)
    assert not verified[5] and not verified[7]
    assert verified[[i for i in range(n_shards) if i not in (5, 7)]].all()


def test_committee_pipeline_on_multihost_mesh():
    """The same period step over a 2x4 ("dcn", "ici") mesh — the
    multi-host layout: tallies reduce over ICI first, one scalar crosses
    DCN — must match the 1-D mesh and single-device outcomes."""
    from gethsharding_tpu.crypto import bn256 as ref
    from gethsharding_tpu.parallel.mesh import make_multihost_mesh
    from gethsharding_tpu.parallel.period import CommitteePeriodPipeline
    from gethsharding_tpu.params import Config

    config = Config(committee_size=4, quorum_size=2)
    keys = [ref.bls_keygen(bytes([60 + i])) for i in range(3)]
    headers, sig_rows, pk_rows = [], [], []
    for s in range(13):  # uneven over 8 devices
        header = b"mh-%d" % s
        voters = keys[: 1 + (s % 3)]
        sigs = [ref.bls_sign(header, sk) for sk, _ in voters]
        if s == 9:
            sigs[0] = ref.bls_sign(b"zz", voters[0][0])
        headers.append(header)
        sig_rows.append(sigs)
        pk_rows.append([pk for _, pk in voters])

    mesh = make_multihost_mesh(n_hosts=2, devices_per_host=4)
    assert mesh.axis_names == ("dcn", "ici")
    single = CommitteePeriodPipeline(config=config, mesh=None)
    multihost = CommitteePeriodPipeline(config=config, mesh=mesh)
    out_s = single.run(single.build_inputs(headers, sig_rows, pk_rows))
    out_m = multihost.run(multihost.build_inputs(headers, sig_rows,
                                                 pk_rows))
    assert np.array_equal(np.asarray(out_s.verified),
                          np.asarray(out_m.verified))
    assert np.array_equal(np.asarray(out_s.approved),
                          np.asarray(out_m.approved))
    assert int(out_s.total_votes) == int(out_m.total_votes)
    assert int(out_s.total_approved) == int(out_m.total_approved)
    assert not np.asarray(out_s.verified)[9]
