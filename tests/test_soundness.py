"""The continuous soundness audit (gethsharding_tpu/resilience/
soundness.py): randomized spot-checks against the scalar reference,
the always-on verdict-plane invariant check, the chaos silent-
corruption mode that feeds it, and the breaker composition that turns
a detected silent corruption into a trip — sync, async, and serving.
"""

import pytest

from gethsharding_tpu import metrics
from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.resilience.breaker import (
    CLOSED, OPEN, CircuitBreaker, FailoverSigBackend)
from gethsharding_tpu.resilience.chaos import (
    ChaosSchedule, ChaosSigBackend, parse_spec, unwired_seams)
from gethsharding_tpu.resilience.errors import SoundnessViolation
from gethsharding_tpu.resilience.soundness import (
    DEFAULT_ROWS, SpotCheckSigBackend, detection_probability,
    dispatches_to_detect, soundness_table)
from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
from gethsharding_tpu.sigbackend import PythonSigBackend, VerdictFuture


def _garbage_rows(n):
    """n invalid ecrecover rows (both backends answer None per row)."""
    return ([b"\x11" * 32] * n, [b"\x22" * 65] * n)


def _committees(n_rows=2, members=2, tamper_row=None):
    """Real BLS committee rows; `tamper_row` signs a wrong message so
    the scalar verdict plane has both True and False rows."""
    msgs, sig_rows, pk_rows = [], [], []
    for i in range(n_rows):
        tag = b"soundness-%d" % i
        keys = [bls.bls_keygen(tag + bytes([j])) for j in range(members)]
        sigs = [bls.bls_sign(tag, sk) for sk, _ in keys]
        if i == tamper_row:
            sigs[0] = bls.bls_sign(b"tampered", keys[0][0])
        msgs.append(tag)
        sig_rows.append(sigs)
        pk_rows.append([pk for _, pk in keys])
    return msgs, sig_rows, pk_rows


def _spot(inner, rate=1.0, rows=DEFAULT_ROWS, seed=0):
    registry = metrics.Registry()
    backend = SpotCheckSigBackend(inner, rate=rate, rows=rows, seed=seed,
                                  registry=registry)
    return backend, registry


def _count(registry, op, which):
    return registry.counter(f"resilience/soundness/{op}/{which}").value


# -- the soundness accounting ------------------------------------------------


def test_detection_probability_math():
    # full-coverage check of a fully corrupted dispatch is certain
    assert detection_probability(1.0, 8, 8, corrupt_rows=8) == 1.0
    # sampling every row catches any corruption at rate 1
    assert detection_probability(1.0, 64, 64, corrupt_rows=1) == 1.0
    # rate scales the per-dispatch probability linearly
    p1 = detection_probability(1.0, 4, 64)
    assert detection_probability(0.5, 4, 64) == pytest.approx(p1 / 2)
    # more checked rows / more dispatches never hurt
    assert detection_probability(0.5, 8, 64) > detection_probability(
        0.5, 4, 64)
    assert detection_probability(0.5, 4, 64, dispatches=100) > \
        detection_probability(0.5, 4, 64, dispatches=10)
    # the closed form matches the 1-row hypergeometric: s/n
    assert detection_probability(1.0, 4, 64) == pytest.approx(4 / 64)
    with pytest.raises(ValueError):
        detection_probability(1.5, 4, 64)
    with pytest.raises(ValueError):
        detection_probability(0.5, 4, 0)


def test_dispatches_to_detect_budget():
    assert dispatches_to_detect(1.0, 8, 8) == 1  # p=1: first dispatch
    budget = dispatches_to_detect(0.25, 4, 8, confidence=0.999)
    p = detection_probability(0.25, 4, 8)
    # the budget is the smallest D with 1-(1-p)^D >= confidence
    assert 1.0 - (1.0 - p) ** budget >= 0.999
    assert 1.0 - (1.0 - p) ** (budget - 1) < 0.999
    with pytest.raises(ValueError):
        dispatches_to_detect(0.0, 4, 8)  # undetectable: no budget exists
    with pytest.raises(ValueError):
        dispatches_to_detect(0.5, 4, 8, confidence=1.0)


def test_soundness_table_shape():
    table = soundness_table(64, 4, rates=(0.05, 1.0))
    assert [row["rate"] for row in table] == [0.05, 1.0]
    assert all(0.0 < row["p_detect_per_dispatch"] <= 1.0 for row in table)
    assert all(row["dispatches_p99"] >= 1 for row in table)


# -- clean-path behavior -----------------------------------------------------


def test_spot_check_clean_backend_all_ops_byte_identical():
    backend, registry = _spot(PythonSigBackend(), rate=1.0)
    py = PythonSigBackend()

    digests, sigs = _garbage_rows(5)
    assert backend.ecrecover_addresses(digests, sigs) == \
        py.ecrecover_addresses(digests, sigs)

    msgs, sig_rows, pk_rows = _committees(2, members=1, tamper_row=1)
    want = py.bls_verify_committees(msgs, sig_rows, pk_rows)
    assert want == [True, False]  # the plane has both verdicts
    assert backend.bls_verify_committees(msgs, sig_rows, pk_rows) == want

    agg_sigs = [bls.bls_aggregate_sigs(row) for row in sig_rows]
    agg_pks = [bls.bls_aggregate_pks(row) for row in pk_rows]
    assert backend.bls_verify_aggregates(msgs, agg_sigs, agg_pks) == \
        py.bls_verify_aggregates(msgs, agg_sigs, agg_pks)

    # malformed das rows are False on both sides, never an exception
    assert backend.das_verify_samples(
        [b"\x00" * 16], [0], [[]], [b"\x01" * 32]) == [False]

    for op in ("ecrecover_addresses", "bls_verify_committees",
               "bls_verify_aggregates", "das_verify_samples"):
        assert _count(registry, op, "checks") == 1
        assert _count(registry, op, "mismatches") == 0
        assert _count(registry, op, "invariant_violations") == 0
    assert _count(registry, "ecrecover_addresses", "rows") == 4


def test_spot_check_sampling_is_seeded_and_deterministic():
    runs = []
    for _ in range(2):
        backend, registry = _spot(PythonSigBackend(), rate=0.5, seed=7)
        for _ in range(40):
            backend.ecrecover_addresses(*_garbage_rows(6))
        runs.append(_count(registry, "ecrecover_addresses", "checks"))
    assert runs[0] == runs[1]  # same seed, same decisions
    assert 0 < runs[0] < 40    # and it IS sampling, not all-or-nothing


def test_spot_check_rate_zero_never_checks_but_invariants_stay_on():
    class _ShortBackend(PythonSigBackend):
        name = "short"

        def ecrecover_addresses(self, digests, sigs65):
            return super().ecrecover_addresses(digests, sigs65)[:-1]

    backend, registry = _spot(_ShortBackend(), rate=0.0)
    with pytest.raises(SoundnessViolation, match="result rows"):
        backend.ecrecover_addresses(*_garbage_rows(3))
    assert _count(registry, "ecrecover_addresses", "checks") == 0
    assert _count(registry, "ecrecover_addresses",
                  "invariant_violations") == 1


# -- detection: spot-check mismatches ---------------------------------------


def test_spot_check_detects_corrupted_ecrecover():
    schedule = ChaosSchedule(
        seed=1, rules={"backend.ecrecover_addresses": True},
        modes={"backend.ecrecover_addresses": "corrupt"})
    backend, registry = _spot(
        ChaosSigBackend(PythonSigBackend(), schedule), rate=1.0, rows=8)
    with pytest.raises(SoundnessViolation, match="mismatch"):
        backend.ecrecover_addresses(*_garbage_rows(4))
    assert _count(registry, "ecrecover_addresses", "mismatches") == 1


def test_spot_check_detects_flipped_committee_verdict():
    schedule = ChaosSchedule(
        seed=2, rules={"backend.bls_verify_committees": True},
        modes={"backend.bls_verify_committees": "corrupt"})
    backend, registry = _spot(
        ChaosSigBackend(PythonSigBackend(), schedule), rate=1.0)
    msgs, sig_rows, pk_rows = _committees(2, members=1)
    with pytest.raises(SoundnessViolation, match="mismatch"):
        backend.bls_verify_committees(msgs, sig_rows, pk_rows)
    assert _count(registry, "bls_verify_committees", "mismatches") == 1


def test_spot_check_detects_corrupted_das_verdict():
    schedule = ChaosSchedule(
        seed=3, rules={"backend.das_verify_samples": True},
        modes={"backend.das_verify_samples": "corrupt"})
    backend, registry = _spot(
        ChaosSigBackend(PythonSigBackend(), schedule), rate=1.0)
    # a malformed row is False by contract; the corruptor flips it True
    with pytest.raises(SoundnessViolation, match="mismatch"):
        backend.das_verify_samples([b"\x00" * 16], [0], [[]],
                                   [b"\x01" * 32])
    assert _count(registry, "das_verify_samples", "mismatches") == 1


def test_spot_check_violation_emits_trace_event(tracer):
    schedule = ChaosSchedule(
        seed=1, rules={"backend.ecrecover_addresses": True},
        modes={"backend.ecrecover_addresses": "corrupt"})
    backend, _ = _spot(
        ChaosSigBackend(PythonSigBackend(), schedule), rate=1.0, rows=8)
    with pytest.raises(SoundnessViolation):
        backend.ecrecover_addresses(*_garbage_rows(4))
    names = {span["name"] for span in tracer.recent_spans()}
    assert "resilience/soundness/violation" in names


# -- detection: the always-on invariant plane --------------------------------


def test_invariant_rejects_out_of_domain_verdicts():
    class _WeirdBackend(PythonSigBackend):
        name = "weird"

        def das_verify_samples(self, chunks, indices, proofs, roots):
            return [2] * len(chunks)  # not a 0/1 verdict

    backend, registry = _spot(_WeirdBackend(), rate=0.0)
    with pytest.raises(SoundnessViolation, match="0/1 domain"):
        backend.das_verify_samples([b"\x00" * 16], [0], [[]],
                                   [b"\x01" * 32])
    assert _count(registry, "das_verify_samples",
                  "invariant_violations") == 1


def test_invariant_rejects_malformed_recovered_address():
    class _StubbyBackend(PythonSigBackend):
        name = "stubby"

        def ecrecover_addresses(self, digests, sigs65):
            return [b"\x01\x02"] * len(digests)  # not 20 bytes

    backend, _ = _spot(_StubbyBackend(), rate=0.0)
    with pytest.raises(SoundnessViolation, match="20 bytes"):
        backend.ecrecover_addresses(*_garbage_rows(2))


def test_invariant_rejects_empty_committee_row_verifying_true():
    class _GullibleBackend(PythonSigBackend):
        name = "gullible"

        def bls_verify_committees(self, messages, sig_rows, pk_rows,
                                  pk_row_keys=None):
            return [True] * len(messages)  # even for empty committees

    backend, registry = _spot(_GullibleBackend(), rate=0.0)
    with pytest.raises(SoundnessViolation, match="empty committee"):
        backend.bls_verify_committees([b"m"], [[]], [[]])
    assert _count(registry, "bls_verify_committees",
                  "invariant_violations") == 1


# -- async + serving faces ---------------------------------------------------


def test_async_spot_check_runs_at_pull_time_and_counts_once():
    schedule = ChaosSchedule(
        seed=4, rules={"backend.bls_verify_committees": True},
        modes={"backend.bls_verify_committees": "corrupt"})
    backend, registry = _spot(
        ChaosSigBackend(PythonSigBackend(), schedule), rate=1.0)
    msgs, sig_rows, pk_rows = _committees(2, members=1)
    future = backend.bls_verify_committees_async(msgs, sig_rows, pk_rows)
    with pytest.raises(SoundnessViolation):
        future.result()
    with pytest.raises(SoundnessViolation):
        future.result()  # memoized: re-raised, not re-derived
    assert _count(registry, "bls_verify_committees", "mismatches") == 1


def test_serving_submit_face_spot_checks_at_pull_time():
    schedule = ChaosSchedule(
        seed=5, rules={"backend.ecrecover_addresses": True},
        modes={"backend.ecrecover_addresses": "corrupt"})
    serving = ServingSigBackend(
        ChaosSigBackend(PythonSigBackend(), schedule),
        ServingConfig(flush_us=100.0))
    backend, registry = _spot(serving, rate=1.0, rows=8)
    try:
        future = backend.submit("ecrecover_addresses", *_garbage_rows(3))
        with pytest.raises(SoundnessViolation):
            future.result()
        with pytest.raises(SoundnessViolation):
            future.result()  # memoized
        assert _count(registry, "ecrecover_addresses", "mismatches") == 1
        # the clean tail still serves byte-identical answers
        schedule.rules["backend.ecrecover_addresses"] = False
        want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(3))
        assert backend.submit("ecrecover_addresses",
                              *_garbage_rows(3)).result() == want
    finally:
        serving.close()


def test_serving_nesting_guard_sees_through_the_spot_checker():
    serving = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=100.0))
    try:
        wrapped, _ = _spot(serving, rate=1.0)
        with pytest.raises(ValueError, match="nest serving"):
            ServingSigBackend(wrapped, ServingConfig(flush_us=100.0))
    finally:
        serving.close()


# -- the breaker composition: silent corruption trips ------------------------


def _corrupt_failover(rate=1.0, rule=True, fault_threshold=1,
                      reset_s=60.0, seed=0, rows=DEFAULT_ROWS,
                      op="ecrecover_addresses"):
    schedule = ChaosSchedule(seed=seed, rules={f"backend.{op}": rule},
                             modes={f"backend.{op}": "corrupt"})
    registry = metrics.Registry()
    breaker = CircuitBreaker(name="snd", fault_threshold=fault_threshold,
                             reset_s=reset_s, registry=registry)
    spot = SpotCheckSigBackend(
        ChaosSigBackend(PythonSigBackend(), schedule), rate=rate,
        rows=rows, seed=seed, registry=registry)
    backend = FailoverSigBackend(spot, PythonSigBackend(),
                                 breaker=breaker, registry=registry)
    return backend, breaker, registry, schedule


def test_breaker_trips_on_silent_corruption_sync():
    """ISSUE 7 acceptance, sync: the corrupting primary raises NOTHING,
    yet the spot-check trips the breaker and the caller still gets the
    right answer (served from the fallback)."""
    backend, breaker, registry, _ = _corrupt_failover(rate=1.0, rows=8)
    want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(4))
    assert backend.ecrecover_addresses(*_garbage_rows(4)) == want
    assert breaker.state == OPEN
    assert registry.counter("resilience/breaker/snd/trips").value == 1
    # while open, the corrupting primary is not consulted at all
    assert backend.ecrecover_addresses(*_garbage_rows(4)) == want


def test_breaker_trips_within_predicted_dispatch_budget():
    """The statistical contract: at rate r and s checked rows, an
    every-dispatch corruptor must be caught within the
    `dispatches_to_detect(confidence=0.999)` budget."""
    rate, batch = 0.5, 8
    budget = dispatches_to_detect(rate, DEFAULT_ROWS, batch,
                                  confidence=0.999)
    backend, breaker, _, _ = _corrupt_failover(rate=rate, seed=11)
    tripped_at = None
    for i in range(budget):
        backend.ecrecover_addresses(*_garbage_rows(batch))
        if breaker.state == OPEN:
            tripped_at = i + 1
            break
    assert tripped_at is not None and tripped_at <= budget


def test_breaker_trips_on_silent_corruption_async():
    """The async face: corruption lands at pull time, the violation
    surfaces through the failover finalize, the breaker trips, and the
    caller's future resolves to the fallback's correct answer."""
    backend, breaker, registry, _ = _corrupt_failover(
        op="bls_verify_committees")
    msgs, sig_rows, pk_rows = _committees(2, members=1, tamper_row=1)
    want = PythonSigBackend().bls_verify_committees(msgs, sig_rows,
                                                    pk_rows)
    future = backend.bls_verify_committees_async(msgs, sig_rows, pk_rows)
    assert future.result() == want  # recovered on the fallback
    assert breaker.state == OPEN
    assert future.result() == want  # idempotent
    assert registry.counter(
        "resilience/breaker/snd/primary_faults").value == 1


def test_breaker_trips_on_silent_corruption_through_serving():
    """The full production composition: chaos-corrupted device under
    the coalescing serving tier, spot-checker over it, failover over
    everything — a silently wrong serving future trips the breaker at
    pull time and the caller still gets the right rows."""
    schedule = ChaosSchedule(
        seed=6, rules={"backend.ecrecover_addresses": True},
        modes={"backend.ecrecover_addresses": "corrupt"})
    registry = metrics.Registry()
    breaker = CircuitBreaker(name="snd", fault_threshold=1, reset_s=60,
                             registry=registry)
    serving = ServingSigBackend(
        ChaosSigBackend(PythonSigBackend(), schedule),
        ServingConfig(flush_us=100.0))
    spot = SpotCheckSigBackend(serving, rate=1.0, rows=8,
                               registry=registry)
    backend = FailoverSigBackend(spot, PythonSigBackend(),
                                 breaker=breaker, registry=registry)
    try:
        want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(3))
        future = backend.submit("ecrecover_addresses", *_garbage_rows(3))
        assert future.result() == want
        assert breaker.state == OPEN
        assert registry.counter(
            "resilience/soundness/ecrecover_addresses/mismatches"
        ).value == 1
        assert future.result() == want  # memoized end to end
        assert registry.counter(
            "resilience/breaker/snd/primary_faults").value == 1
    finally:
        serving.close()


def test_zero_false_trips_on_a_clean_primary():
    """With corruption off, spot-checking at full rate must never trip:
    every check agrees, the breaker stays closed."""
    registry = metrics.Registry()
    breaker = CircuitBreaker(name="snd", fault_threshold=1, reset_s=60,
                             registry=registry)
    spot = SpotCheckSigBackend(PythonSigBackend(), rate=1.0,
                               registry=registry)
    backend = FailoverSigBackend(spot, PythonSigBackend(),
                                 breaker=breaker, registry=registry)
    msgs, sig_rows, pk_rows = _committees(2, members=1, tamper_row=0)
    for _ in range(10):
        backend.ecrecover_addresses(*_garbage_rows(5))
        backend.bls_verify_committees(msgs, sig_rows, pk_rows)
    assert breaker.state == CLOSED
    assert registry.counter("resilience/breaker/snd/trips").value == 0
    assert registry.counter(
        "resilience/soundness/ecrecover_addresses/mismatches").value == 0


# -- half-open + epoch interplay ---------------------------------------------


def test_probe_soundness_violation_counts_as_probe_mismatch_once():
    """A spot-check violation DURING the half-open differential probe
    is the probe's verdict: exactly one probe_mismatches count, no
    extra primary fault (no double-accounting), breaker back to open,
    fallback answer served."""
    backend, breaker, registry, _ = _corrupt_failover(reset_s=0.0)
    breaker.record_fault(RuntimeError("seed fault"))
    assert breaker.state == OPEN
    want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(4))
    assert backend.ecrecover_addresses(*_garbage_rows(4)) == want  # probe
    assert breaker.state == OPEN
    assert registry.counter(
        "resilience/breaker/snd/probe_mismatches").value == 1
    # only the seed fault is on the counter: the violation was counted
    # as the probe's mismatch verdict, not ALSO as a primary fault
    assert registry.counter(
        "resilience/breaker/snd/primary_faults").value == 1


def test_probe_match_after_corruption_heals_recloses():
    """first-n corrupt rule: the corruption window ends, the next probe
    agrees byte-for-byte, and the breaker re-promotes the primary —
    the corrupt mode composes with the standard recovery cycle."""
    backend, breaker, _, schedule = _corrupt_failover(
        rate=1.0, rule=1, reset_s=0.0)
    want = PythonSigBackend().ecrecover_addresses(*_garbage_rows(4))
    assert backend.ecrecover_addresses(*_garbage_rows(4)) == want  # trips
    assert breaker.state == OPEN
    assert backend.ecrecover_addresses(*_garbage_rows(4)) == want  # probe
    assert breaker.state == CLOSED
    assert schedule.injected.get("backend.ecrecover_addresses") == 1


def test_stale_pre_trip_future_violation_does_not_retrip():
    """Epoch guard: a corrupted async dispatch submitted BEFORE a trip
    + recovery must not re-trip the recovered primary when its future
    is finally pulled — the violation is a stale outcome (PR 4's
    rule), counted on the fault metric but not toward tripping."""
    backend, breaker, registry, schedule = _corrupt_failover(
        rate=1.0, rule=1, reset_s=0.0, op="bls_verify_committees")
    msgs, sig_rows, pk_rows = _committees(2, members=1)
    want = PythonSigBackend().bls_verify_committees(msgs, sig_rows,
                                                    pk_rows)
    # submit while closed: this dispatch IS the one corrupted call
    stale = backend.bls_verify_committees_async(msgs, sig_rows, pk_rows)
    # an unrelated loud fault trips the breaker...
    breaker.record_fault(RuntimeError("loud fault"), epoch=breaker.epoch)
    assert breaker.state == OPEN
    # ...and a matching probe recovers it (the corrupt rule has healed)
    assert backend.bls_verify_committees(msgs, sig_rows, pk_rows) == want
    assert breaker.state == CLOSED
    epoch_after_recovery = breaker.epoch
    # NOW the stale future drains: the violation fires, is recovered on
    # the fallback, and must not re-trip the recovered primary
    assert stale.result() == want
    assert breaker.state == CLOSED
    assert breaker.epoch == epoch_after_recovery
    assert registry.counter(
        "resilience/soundness/bls_verify_committees/mismatches").value == 1


# -- chaos corrupt mode ------------------------------------------------------


def test_chaos_corrupt_mode_is_silent_and_seeded():
    digests, sigs = _garbage_rows(4)
    outs = []
    for _ in range(2):
        schedule = ChaosSchedule(
            seed=9, rules={"backend.ecrecover_addresses": True},
            modes={"backend.ecrecover_addresses": "corrupt"})
        chaotic = ChaosSigBackend(PythonSigBackend(), schedule)
        outs.append(chaotic.ecrecover_addresses(digests, sigs))
        assert schedule.injected["backend.ecrecover_addresses"] == 1
    assert outs[0] == outs[1]  # same seed corrupts the same row the same
    clean = PythonSigBackend().ecrecover_addresses(digests, sigs)
    assert outs[0] != clean
    # exactly one row perturbed, same row count (silent, not loud)
    assert len(outs[0]) == len(clean)
    assert sum(a != b for a, b in zip(outs[0], clean)) == 1


def test_chaos_corrupt_first_n_heals():
    schedule = ChaosSchedule(
        seed=9, rules={"backend.das_verify_samples": 2},
        modes={"backend.das_verify_samples": "corrupt"})
    chaotic = ChaosSigBackend(PythonSigBackend(), schedule)
    row = ([b"\x00" * 16], [0], [[]], [b"\x01" * 32])
    assert chaotic.das_verify_samples(*row) == [True]   # flipped
    assert chaotic.das_verify_samples(*row) == [True]   # flipped
    assert chaotic.das_verify_samples(*row) == [False]  # healed


def test_chaos_corrupt_empty_batch_passes_through_off_the_books():
    """An empty batch has nothing to corrupt: it must pass through
    WITHOUT consuming a schedule slot or counting as injected, so
    `schedule.injected` equals results actually corrupted (the number
    bench --chaos reports detected counts against) — sync and async."""
    schedule = ChaosSchedule(
        seed=9, rules={"backend": True}, modes={"backend": "corrupt"})
    chaotic = ChaosSigBackend(PythonSigBackend(), schedule)
    assert chaotic.ecrecover_addresses([], []) == []
    assert chaotic.bls_verify_committees_async([], [], []).result() == []
    assert schedule.injected == {}
    assert schedule.calls("backend.ecrecover_addresses") == 0
    assert schedule.calls("backend.bls_verify_committees") == 0


def test_chaos_corrupt_async_lands_at_pull_time():
    schedule = ChaosSchedule(
        seed=9, rules={"backend.bls_verify_committees": True},
        modes={"backend.bls_verify_committees": "corrupt"})
    chaotic = ChaosSigBackend(PythonSigBackend(), schedule)
    msgs, sig_rows, pk_rows = _committees(2, members=1)
    future = chaotic.bls_verify_committees_async(msgs, sig_rows, pk_rows)
    clean = PythonSigBackend().bls_verify_committees(msgs, sig_rows,
                                                     pk_rows)
    got = future.result()
    assert got != clean and len(got) == len(clean)


def test_chaos_schedule_rejects_unknown_mode():
    with pytest.raises(ValueError, match="frobnicate"):
        ChaosSchedule(rules={"backend.x": True},
                      modes={"backend.x": "frobnicate"})


def test_corrupt_mode_restricted_to_backend_seams():
    """mode=corrupt on a seam with no result plane (mainchain.*,
    dispatch.*) would silently degrade to every-call LOUD faults — the
    opposite of the requested experiment. It must fail fast instead,
    through both the spec parser and the programmatic constructor."""
    for spec in ("mainchain.block_number:mode=corrupt",
                 "dispatch.bls_verify_committees:mode=corrupt",
                 "mainchain.*:mode=corrupt"):
        with pytest.raises(ValueError, match="backend"):
            parse_spec(spec)
    with pytest.raises(ValueError, match="backend"):
        ChaosSchedule(rules={"mainchain.sign": True},
                      modes={"mainchain.sign": "corrupt"})
    # the bare backend prefix and backend.<op> stay legal
    assert parse_spec("backend.*:mode=corrupt").modes == \
        {"backend": "corrupt"}
    assert parse_spec("backend.das_verify_samples:mode=corrupt").modes \
        == {"backend.das_verify_samples": "corrupt"}


# -- parse_spec + unwired seams ----------------------------------------------


def test_parse_spec_corrupt_mode_entries():
    schedule = parse_spec("seed=3,backend.*:mode=corrupt")
    assert schedule.seed == 3
    assert schedule.rules == {"backend": True}
    assert schedule.modes == {"backend": "corrupt"}
    assert schedule.mode_for("backend.ecrecover_addresses") == "corrupt"
    # a mode entry composes with an explicit rule for the same seam
    schedule = parse_spec(
        "backend.ecrecover_addresses=2,"
        "backend.ecrecover_addresses:mode=corrupt")
    assert schedule.rules == {"backend.ecrecover_addresses": 2}
    assert schedule.modes == {"backend.ecrecover_addresses": "corrupt"}
    # un-tagged seams stay in fault mode
    assert schedule.mode_for("backend.das_verify_samples") == "fault"


def test_parse_spec_malformed_mode_fails_fast_naming_the_token():
    with pytest.raises(ValueError, match="explode"):
        parse_spec("backend.x:mode=explode")
    with pytest.raises(ValueError, match="frob"):
        parse_spec("backend.x:frob=corrupt")


def test_unwired_seams_covers_corrupt_rules():
    """A mode-only corrupt entry materializes a rule, so a caller that
    never routes the backend seam through an injector (a bench or test
    harness without a ChaosSigBackend) sees it flagged like any other
    unwired rule."""
    schedule = parse_spec("seed=1,backend.ecrecover_addresses:mode=corrupt,"
                          "mainchain.sign=2")
    assert unwired_seams(schedule, ("mainchain",)) == \
        ["backend.ecrecover_addresses"]
    assert unwired_seams(schedule, ("mainchain", "backend")) == []


# the PR 4 `FetchAborted`-missing-from-__all__ lint that used to live
# here is now the corpus-wide `export-completeness` analysis rule
# (gethsharding_tpu/analysis/exports.py), gated over every package by
# tests/test_analysis.py — which also keeps a live-import twin of the
# original assertion (test_export_completeness_live_resilience_contract).


def test_describe_reports_knobs_and_detection():
    backend, _ = _spot(PythonSigBackend(), rate=0.25, rows=4)
    info = backend.describe()
    assert info["rate"] == 0.25
    assert info["rows_per_check"] == 4
    assert info["reference"] == "python"
    assert info["p_detect_per_dispatch_64"] == pytest.approx(
        detection_probability(0.25, 4, 64), abs=1e-6)
    assert info["dispatches_p99_64"] == dispatches_to_detect(0.25, 4, 64)


def test_soundness_counters_reach_prometheus_exposition():
    from gethsharding_tpu.metrics import prometheus_text

    metrics.counter(
        "resilience/soundness/ecrecover_addresses/checks").inc(2)
    metrics.counter(
        "resilience/soundness/ecrecover_addresses/mismatches").inc(0)
    text = prometheus_text()
    for needle in (
            "gethsharding_resilience_soundness_ecrecover_addresses_"
            "checks_total",
            "gethsharding_resilience_soundness_ecrecover_addresses_"
            "mismatches_total"):
        assert needle in text, needle


@pytest.fixture
def tracer():
    from gethsharding_tpu import tracing

    tracing.enable(ring_spans=65536)
    tracing.TRACER.clear()
    yield tracing.TRACER
    tracing.disable()
    tracing.TRACER.clear()
