"""RPC boundary tests: in-process server/client round-trips, revert
propagation, head subscriptions — and the flagship cross-process test:
the full proposer -> notary period pipeline with the chain in a SEPARATE
OS PROCESS reached only over the wire (the reference's topology,
`sharding/mainchain/utils.go:17-22`)."""

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from gethsharding_tpu.actors import Notary, Proposer, TXPool
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.node.backend import ShardNode
from gethsharding_tpu.params import Config, ETHER
from gethsharding_tpu.rpc import RemoteMainchain, RPCServer
from gethsharding_tpu.smc.chain import SimulatedMainchain
from gethsharding_tpu.smc.state_machine import SMCRevert
from gethsharding_tpu.utils.hexbytes import Address20

REPO_ROOT = Path(__file__).resolve().parents[1]


def wait_until(predicate, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(step)
    return predicate()


@pytest.fixture()
def rpc_pair():
    backend = SimulatedMainchain(config=Config(quorum_size=1))
    server = RPCServer(backend)
    server.start()
    remote = RemoteMainchain.dial(*server.address)
    yield backend, remote
    remote.close()
    server.stop()


def test_views_round_trip(rpc_pair):
    backend, remote = rpc_pair
    assert remote.block_number == 0
    assert remote.shard_count() == backend.smc.shard_count
    backend.commit()
    assert remote.block_number == 1
    block = remote.block_by_number(1)
    assert bytes(block.hash) == bytes(backend.blocks[1].hash)
    assert remote.collation_record(0, 1) is None


def test_transactions_and_revert(rpc_pair):
    backend, remote = rpc_pair
    addr = Address20(b"\x11" * 20)
    remote.fund(addr, 2000 * ETHER)
    assert remote.balance_of(addr) == 2000 * ETHER
    receipt = remote.register_notary(addr)
    assert receipt.status == 1
    entry = remote.notary_registry(addr)
    assert entry.deposited and entry.pool_index == 0
    # second deposit reverts — and arrives as SMCRevert, not a generic error
    with pytest.raises(SMCRevert, match="already deposited"):
        remote.register_notary(addr)
    assert remote.transaction_receipt(receipt.tx_hash).status == 1


def test_head_subscription_pushes(rpc_pair):
    backend, remote = rpc_pair
    seen = []
    remote.subscribe_new_head(lambda b: seen.append(b.number))
    backend.commit()
    backend.commit()
    assert wait_until(lambda: len(seen) >= 2)
    assert seen[:2] == [1, 2]


def test_full_period_pipeline_cross_process(tmp_path):
    """test_end_to_end's period pipeline with the mainchain in its own OS
    process: proposer + notary live here, the chain and SMC live in the
    child, and EVERYTHING crosses the JSON-RPC wire — SMC transactions,
    head subscriptions, AND the shardp2p body sync (each node's p2p rides
    its own socket through the chain process's relay)."""
    from gethsharding_tpu.p2p.remote import RemoteHub

    proc = subprocess.Popen(
        [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
         "--periodlength", "5", "--quorum", "1", "--runtime", "120"],
        cwd=REPO_ROOT, stdout=subprocess.PIPE, text=True,
    )
    try:
        endpoint = json.loads(proc.stdout.readline())
        config = Config(quorum_size=1)
        chain_ctl = RemoteMainchain.dial(endpoint["host"], endpoint["port"])
        shard_id = 2

        proposer_node = ShardNode(
            actor="proposer", shard_id=shard_id, config=config,
            backend=RemoteMainchain.dial(endpoint["host"], endpoint["port"]),
            hub=RemoteHub.dial(endpoint["host"], endpoint["port"]),
            txpool_interval=None)
        notary_node = ShardNode(
            actor="notary", shard_id=shard_id, config=config,
            backend=RemoteMainchain.dial(endpoint["host"], endpoint["port"]),
            hub=RemoteHub.dial(endpoint["host"], endpoint["port"]),
            deposit=True)
        chain_ctl.fund(notary_node.client.account(), 2000 * ETHER)

        proposer_node.start()
        notary_node.start()
        try:
            notary = notary_node.service(Notary)
            assert notary.is_account_in_notary_pool()

            chain_ctl.fast_forward(1)
            period = chain_ctl.current_period()
            proposer_node.service(TXPool).submit(
                Transaction(nonce=1, payload=b"cross-process tx"))
            assert wait_until(
                lambda: proposer_node.service(Proposer).collations_proposed >= 1
            ), notary_node.errors() + proposer_node.errors()
            # the local counter leads the SMC tx: wait for the chain-side
            # submission too (the bare equality flaked under CPU
            # starvation in full-suite runs)
            assert wait_until(
                lambda: chain_ctl.last_submitted_collation(shard_id) == period,
                timeout=15.0), notary_node.errors() + proposer_node.errors()

            approved = False
            for _ in range(config.period_length - 1):
                chain_ctl.commit()
                if wait_until(
                        lambda: chain_ctl.last_approved_collation(shard_id)
                        == period, timeout=3.0):
                    approved = True
                    break
            assert approved, notary_node.errors() + proposer_node.errors()
            record = chain_ctl.collation_record(shard_id, period)
            assert record.is_elected is True
            assert record.vote_sigs  # the BLS-signed vote crossed the wire
            assert wait_until(lambda: notary.canonical_set >= 1, timeout=5.0)
            # de-starred data plane: every directed body response flowed
            # peer-to-peer over the direct sockets; the chain process
            # relayed ZERO directed sends
            stats = chain_ctl.rpc.call("shard_p2pStats")
            assert stats["relayed_sends"] == 0, stats
        finally:
            notary_node.stop()
            proposer_node.stop()
            chain_ctl.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def _hub_identity(seed: bytes):
    from gethsharding_tpu.mainchain.accounts import AccountManager

    manager = AccountManager()
    account = manager.new_account(seed=seed)
    return manager, account.address


def test_p2p_handshake_and_peer_table():
    """Protocol/version/network gate + PROVEN identity on relay attach
    (the RLPx authenticated-handshake analog, p2p/rlpx.go:178) and the
    admin_peers-style table."""
    import pytest

    from gethsharding_tpu.p2p.remote import RemoteHub
    from gethsharding_tpu.p2p.service import P2PServer
    from gethsharding_tpu.params import Config
    from gethsharding_tpu.rpc.client import RemoteMainchain
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    backend = SimulatedMainchain(config=Config(network_id=77))
    server = RPCServer(backend, port=0)
    server.start()
    try:
        host, port = server.address
        manager, address = _hub_identity(b"peer-table")

        # matching network + proven identity -> attached, listed
        hub = RemoteHub.dial(host, port, network_id=77,
                             accounts=manager, account=address)
        p2p = P2PServer(hub=hub)
        p2p.start()
        chain = RemoteMainchain.dial(host, port)
        assert chain.network_id() == 77
        peers = chain.p2p_peers()
        assert [p["account"] for p in peers] == [bytes(address).hex()]
        assert peers[0]["version"] == 1
        assert peers[0]["endpoint"]  # the direct-listener introduction

        # wrong network -> rejected at attach (before signature checks)
        mgr2, addr2 = _hub_identity(b"wrong-net")
        bad_hub = RemoteHub.dial(host, port, network_id=78,
                                 accounts=mgr2, account=addr2)
        bad_p2p = P2PServer(hub=bad_hub)
        with pytest.raises(Exception, match="network mismatch"):
            bad_p2p.start()
        bad_hub.close()

        # wrong protocol version -> rejected
        worse = RemoteHub.dial(host, port)
        with pytest.raises(Exception, match="version mismatch"):
            worse.rpc.call("shard_p2pAttach", {"protocol": "shardp2p",
                                               "version": 99})
        worse.close()

        # detach drops the peer from the table
        p2p.stop()
        assert chain.p2p_peers() == []
        chain.close()
    finally:
        server.stop()


def test_unsigned_and_forged_attaches_refused():
    """The relay's trust model: `account` is proven by a signature over a
    relay-issued challenge — an unsigned attach, a forged account, and a
    replayed/absent challenge are all refused."""
    import pytest

    from gethsharding_tpu.p2p import direct
    from gethsharding_tpu.p2p.remote import RemoteHub
    from gethsharding_tpu.p2p.service import P2PServer
    from gethsharding_tpu.params import Config
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    backend = SimulatedMainchain(config=Config(network_id=5))
    server = RPCServer(backend, port=0)
    server.start()
    try:
        host, port = server.address
        manager, address = _hub_identity(b"honest")
        thief_mgr, thief_addr = _hub_identity(b"thief")

        # no identity at all -> the client itself refuses to attach
        anon = RemoteHub.dial(host, port)
        with pytest.raises(RuntimeError, match="identity required"):
            P2PServer(hub=anon).start()
        anon.close()

        # unsigned attach straight at the wire -> refused by the relay
        bare = RemoteHub.dial(host, port)
        with pytest.raises(Exception, match="unsigned attach"):
            bare.rpc.call("shard_p2pAttach", {
                "protocol": "shardp2p", "version": 1, "network_id": 5,
                "account": bytes(address).hex()})

        # forged: thief signs with its own key but claims the honest
        # account -> signature does not prove the claim
        challenge = bytes.fromhex(bare.rpc.call("shard_p2pChallenge"))
        sig = thief_mgr.sign_hash(thief_addr, direct.attach_digest(
            5, challenge))
        with pytest.raises(Exception, match="does not prove"):
            bare.rpc.call("shard_p2pAttach", {
                "protocol": "shardp2p", "version": 1, "network_id": 5,
                "account": bytes(address).hex(), "sig": sig.hex()})

        # a correct signature without a FRESH challenge -> refused (the
        # failed attach above consumed it)
        sig = manager.sign_hash(address, direct.attach_digest(5, challenge))
        with pytest.raises(Exception, match="no pending challenge"):
            bare.rpc.call("shard_p2pAttach", {
                "protocol": "shardp2p", "version": 1, "network_id": 5,
                "account": bytes(address).hex(), "sig": sig.hex()})
        bare.close()

        # the honest flow still works
        hub = RemoteHub.dial(host, port, accounts=manager, account=address)
        p2p = P2PServer(hub=hub)
        p2p.start()
        p2p.stop()
    finally:
        server.stop()


def test_directed_messages_flow_peer_to_peer():
    """De-starred data plane: a directed send crosses a direct socket
    between the two actor processes' listeners — the relay sees ZERO
    relayed sends — and a forged direct connection is refused."""
    import socket

    from gethsharding_tpu.p2p import direct
    from gethsharding_tpu.p2p.messages import CollationBodyRequest
    from gethsharding_tpu.p2p.remote import RemoteHub
    from gethsharding_tpu.p2p.service import P2PServer
    from gethsharding_tpu.params import Config
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.utils.hexbytes import Hash32

    backend = SimulatedMainchain(config=Config(network_id=9))
    server = RPCServer(backend, port=0)
    server.start()
    try:
        host, port = server.address
        mgr_a, addr_a = _hub_identity(b"alice")
        mgr_b, addr_b = _hub_identity(b"bob")
        hub_a = RemoteHub.dial(host, port, accounts=mgr_a, account=addr_a)
        hub_b = RemoteHub.dial(host, port, accounts=mgr_b, account=addr_b)
        a, b = P2PServer(hub=hub_a), P2PServer(hub=hub_b)
        a.start()
        b.start()
        try:
            sub = b.subscribe(CollationBodyRequest)
            req = CollationBodyRequest(
                shard_id=1, period=2, chunk_root=Hash32(b"\x11" * 32),
                proposer=addr_a)
            assert a.send(req, b.self_peer) is True
            msg = sub.get(timeout=5.0)
            assert msg.data == req
            assert msg.peer == a.self_peer  # reply routing intact
            # ...and the relay never carried it
            assert server.p2p_relayed_sends == 0
            # the connection negotiated AEAD frames (ECDH + AES-256-GCM:
            # the RLPx encrypted-transport parity), not plaintext
            conn = next(iter(hub_a._dialer._conns.values()))
            assert conn[3] is not None
            # reply back over B's own direct connection to A
            sub_a = a.subscribe(CollationBodyRequest)
            assert b.send(req, msg.peer) is True
            assert sub_a.get(timeout=5.0).peer == b.self_peer
            assert server.p2p_relayed_sends == 0

            # forged direct connection: correct wire protocol, but the
            # signature can't prove the account the relay has for peer A
            info = hub_a.peer_info(a.self_peer.peer_id)
            thief_mgr, thief_addr = _hub_identity(b"mallory")
            with socket.create_connection(tuple(
                    hub_b.peer_info(b.self_peer.peer_id)["endpoint"]),
                    timeout=5.0) as sock:
                rfile = sock.makefile("rb")
                wfile = sock.makefile("wb")
                challenge = bytes.fromhex(
                    json.loads(rfile.readline())["challenge"])
                sig = thief_mgr.sign_hash(
                    thief_addr, direct.direct_digest(9, challenge))
                wfile.write((json.dumps({
                    "peer_id": a.self_peer.peer_id,  # claims to be A
                    "account": bytes(addr_a).hex(),
                    "challenge2": bytes(32).hex(),
                    "sig": sig.hex()}) + "\n").encode())
                wfile.flush()
                reply = json.loads(rfile.readline())
            assert "error" in reply and "prove" in reply["error"]
            assert info["account"] == bytes(addr_a).hex()
        finally:
            a.stop()
            b.stop()
    finally:
        server.stop()


def test_gossip_introduction_survives_relay_death():
    """Decentralized introduction (p2p/discovery.py): nodes exchange
    SIGNED announces via gossip over the direct plane; after the relay
    process dies, directed sends AND broadcasts still reach every
    introduced peer — the relay is first contact, not a chokepoint
    (p2p/discover/table.go + p2p/dial.go role; VERDICT r3 Missing #1)."""
    from gethsharding_tpu.p2p.messages import CollationBodyRequest
    from gethsharding_tpu.p2p.remote import RemoteHub
    from gethsharding_tpu.p2p.service import P2PServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.utils.hexbytes import Hash32

    backend = SimulatedMainchain(config=Config(network_id=11))
    server = RPCServer(backend, port=0)
    server.start()
    host, port = server.address
    hubs, servers = [], []
    try:
        for seed in (b"ga", b"gb", b"gc"):
            mgr, addr = _hub_identity(seed)
            hub = RemoteHub.dial(host, port, accounts=mgr, account=addr)
            srv = P2PServer(hub=hub)
            srv.start()
            hubs.append(hub)
            servers.append(srv)
        a, b, c = servers

        # gossip until everyone holds everyone's VERIFIED announce
        deadline = time.time() + 10.0
        while time.time() < deadline:
            for hub in hubs:
                hub.gossip_once()
            if all(len(hub.directory.gossip_set()) == 3 for hub in hubs):
                break
            time.sleep(0.05)
        assert all(len(hub.directory.gossip_set()) == 3 for hub in hubs)

        # broadcasts while the relay is up already do NOT transit it
        sub_b = b.subscribe(CollationBodyRequest)
        sub_c = c.subscribe(CollationBodyRequest)
        req = CollationBodyRequest(shard_id=3, period=1,
                                   chunk_root=Hash32(b"\x22" * 32),
                                   proposer=None)
        bcasts_before = server.method_calls.get("shard_p2pBroadcast", 0)
        sends_before = server.p2p_relayed_sends
        assert a.broadcast(req) == 2
        assert sub_b.get(timeout=5.0).data == req
        assert sub_c.get(timeout=5.0).data == req
        assert server.method_calls.get(
            "shard_p2pBroadcast", 0) == bcasts_before
        assert server.p2p_relayed_sends == sends_before

        # kill the relay: introduction already happened, the network
        # must keep working peer-to-peer
        server.stop()
        req2 = CollationBodyRequest(shard_id=4, period=2,
                                    chunk_root=Hash32(b"\x33" * 32),
                                    proposer=None)
        assert a.broadcast(req2) == 2
        assert sub_b.get(timeout=5.0).data == req2
        assert sub_c.get(timeout=5.0).data == req2
        # directed body exchange without the relay
        sub_a = a.subscribe(CollationBodyRequest)
        assert b.send(req2, a.self_peer) is True
        assert sub_a.get(timeout=5.0).peer == b.self_peer
    finally:
        for srv in servers:
            srv.stop()
        server.stop()


def test_mirror_snapshot_bulk_over_rpc():
    """A remote actor's state mirror pulls ONE bulk snapshot per head
    instead of ~3 RPC calls per shard."""
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.mainchain.mirror import StateMirror
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.rpc.client import RemoteMainchain
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.utils.hexbytes import Hash32

    config = Config(shard_count=5)
    backend = SimulatedMainchain(config=config)
    manager = AccountManager()
    acct = manager.new_account(seed=b"mirror-rpc")
    backend.fund(acct.address, 2000 * ETHER)
    server = RPCServer(backend, port=0)
    server.start()
    try:
        remote = RemoteMainchain.dial(*server.address)
        client = SMCClient(backend=remote, accounts=manager, account=acct,
                           config=config)
        mirror = StateMirror(client=client)
        mirror.start()
        try:
            backend.fast_forward(1)
            period = backend.current_period()
            root = Hash32(keccak256(b"rpc-mirror"))
            backend.add_header(acct.address, 4, period, root)
            backend.commit()
            import time

            deadline = time.time() + 5.0
            while time.time() < deadline:
                if (mirror.period() == period
                        and mirror.record(4) is not None):
                    break
                time.sleep(0.05)
            assert mirror.period() == period
            assert mirror.record(4)["chunk_root"] == bytes(root).hex()
            assert mirror.snapshot()["last_submitted"][4] == period
        finally:
            mirror.stop()
        remote.close()
    finally:
        server.stop()


def test_remote_notary_hot_loop_is_o1_per_head():
    """The mirror-backed hot loop: a remote notary's per-head read
    chatter is ONE bulk mirrorSnapshot pull, not O(shards) record/
    watermark calls — asserted against the server's per-method counters
    with a 32-shard config."""
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.mainchain.mirror import StateMirror

    config = Config(shard_count=32, quorum_size=1)
    backend = SimulatedMainchain(config=config)
    server = RPCServer(backend, port=0)
    server.start()
    node = None
    try:
        remote = RemoteMainchain.dial(*server.address)
        node = ShardNode(actor="notary", backend=remote, config=config,
                         deposit=False, txpool_interval=None)
        backend.fund(node.client.account(), 2000 * ETHER)
        node.client.register_notary()
        node.start()
        notary = node.service(Notary)
        assert node.service(StateMirror) is notary.mirror

        baseline = dict(server.method_calls)
        heads = 3 * config.period_length
        for _ in range(heads):
            backend.commit()
        assert wait_until(
            lambda: (node.service(StateMirror).snapshot() or {}).get(
                "block_number", 0) >= backend.block_number)

        calls = {m: n - baseline.get(m, 0)
                 for m, n in server.method_calls.items()}
        # the O(shards) scan methods never cross the wire per head
        assert calls.get("shard_collationRecord", 0) == 0, calls
        assert calls.get("shard_lastSubmittedCollation", 0) == 0, calls
        assert calls.get("shard_committeeContext", 0) == 0, calls
        assert calls.get("shard_getNotaryInCommittee", 0) == 0, calls
        # the bulk pull happens about once per head (head callback +
        # at most one catch-up refresh from the notary)
        assert calls.get("shard_mirrorSnapshot", 0) <= 2 * heads + 2, calls
        # total per-head chatter is O(1): bounded well under shard_count
        per_head = sum(calls.values()) / heads
        assert per_head < 8, (per_head, calls)
    finally:
        if node is not None:
            node.stop()
        server.stop()


def test_remote_windback_reads_come_from_the_snapshot():
    """Enforced windback over RPC: prior-period records ride the mirror
    snapshot's `prior_records` (closed periods are immutable), so a
    remote notary's windback availability checks cost ZERO extra
    `shard_collationRecord` round trips (r3's O(depth)-RPC gap)."""
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.actors.proposer import create_collation
    from gethsharding_tpu.core.types import Transaction
    from gethsharding_tpu.mainchain.mirror import StateMirror

    config = Config(shard_count=2, quorum_size=1, windback_depth=3)
    backend = SimulatedMainchain(config=config)
    server = RPCServer(backend, port=0)
    server.start()
    node = None
    try:
        remote = RemoteMainchain.dial(*server.address)
        node = ShardNode(actor="notary", backend=remote, config=config,
                         deposit=False, txpool_interval=None)
        backend.fund(node.client.account(), 2000 * ETHER)
        node.client.register_notary()
        node.start()
        notary = node.service(Notary)
        shard_id = notary.shard.shard_id
        for period in (1, 2, 3):
            backend.fast_forward(1)
            coll = create_collation(node.client, shard_id, period,
                                    [Transaction(nonce=period)])
            notary.shard.save_collation(coll)
            node.client.add_header(shard_id, period, coll.header.chunk_root,
                                   coll.header.proposer_signature)
        backend.commit()
        assert wait_until(
            lambda: (node.service(StateMirror).snapshot() or {}).get(
                "period") == 3)
        snap = node.service(StateMirror).snapshot()
        assert set(snap["prior_records"]) == {1, 2}, snap["prior_records"]

        baseline = dict(server.method_calls)
        checks_before = notary.m_windback_checks.value
        notary.notarize_collations()
        calls = {m: n - baseline.get(m, 0)
                 for m, n in server.method_calls.items()}
        # windback DID run (periods 1-2 were checked for availability)...
        assert notary.m_windback_checks.value >= checks_before + 2
        # ...and no per-period record read crossed the wire for it
        assert calls.get("shard_collationRecord", 0) == 0, calls
        assert notary.votes_submitted >= 1
    finally:
        if node is not None:
            node.stop()
        server.stop()


def test_bootnode_introduction_without_a_chain():
    """cmd/bootnode parity: a chainless introduction node serves the
    authenticated peer table and the direct data plane works through it,
    while every chain/SMC method is refused."""
    from gethsharding_tpu.p2p.messages import CollationBodyRequest
    from gethsharding_tpu.p2p.remote import RemoteHub
    from gethsharding_tpu.p2p.service import P2PServer
    from gethsharding_tpu.rpc.bootnode import make_bootnode
    from gethsharding_tpu.utils.hexbytes import Hash32

    server = make_bootnode(network_id=12)
    server.start()
    try:
        host, port = server.address
        mgr_a, addr_a = _hub_identity(b"boot-a")
        mgr_b, addr_b = _hub_identity(b"boot-b")
        hub_a = RemoteHub.dial(host, port, accounts=mgr_a, account=addr_a)
        hub_b = RemoteHub.dial(host, port, accounts=mgr_b, account=addr_b)
        a, b = P2PServer(hub=hub_a), P2PServer(hub=hub_b)
        a.start()
        b.start()
        try:
            assert hub_a.rpc.call("shard_networkId") == 12
            sub = b.subscribe(CollationBodyRequest)
            req = CollationBodyRequest(shard_id=0, period=1,
                                       chunk_root=Hash32(b"\x22" * 32),
                                       proposer=addr_a)
            assert a.send(req, b.self_peer) is True
            assert sub.get(timeout=5.0).data == req
            assert server.p2p_relayed_sends == 0  # payload went direct
            # chain methods are refused, not silently faked
            with pytest.raises(Exception, match="chain process"):
                hub_a.rpc.call("shard_blockNumber")
        finally:
            a.stop()
            b.stop()
    finally:
        server.stop()
