"""Fleet-scale serving: admission classes, tenant quotas, the shard-
aware router, and breaker-aware draining (gethsharding_tpu/fleet/ +
the reworked serving/queue.py).

Five contracts:

- CLASSES: the admission queue drains by weighted priority (bulk can
  never starve interactive, interactive can never fully starve bulk),
  sheds by class under overload (catchup first, interactive last),
  enforces per-tenant row quotas, and expires work past its class
  deadline — all with typed errors.
- LIFECYCLE: a closed queue fails fast (`QueueClosed`) for late and
  blocked putters alike; `chain_server`-style drain refuses new work
  with a typed "replica draining" error and strands no caller.
- ROUTING: consistent shard→replica affinity, least-loaded keyless
  routing, retry-on-next-replica on hang/trip/shed, the typed
  `AllReplicasDraining` when nothing accepts, and rebalance after a
  drained replica re-enters.
- DRAINING: a replica whose breaker trips (seeded chaos, no ad-hoc
  mocks) is marked draining, takes no new work, and re-enters only
  after its half-open differential probe re-promotes the primary.
- CLOSED LOOP (the acceptance bar): under a seeded chaos schedule that
  trips one replica's breaker mid-soak, zero requests are lost or
  mis-answered (every result verified against the known signer),
  interactive traffic stays within its latency SLO while catchup is
  shed first, and the replica re-enters the rotation.
"""

import threading
import time

import pytest

from gethsharding_tpu import metrics
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.fleet import (
    AllReplicasDraining,
    FleetRouter,
    Replica,
    RouterSigBackend,
)
from gethsharding_tpu.serving.classes import (
    CLASS_BULK_AUDIT,
    CLASS_CATCHUP,
    CLASS_INTERACTIVE,
    ClassPolicy,
    admission_class,
    default_policies,
)
from gethsharding_tpu.resilience.breaker import (
    CircuitBreaker,
    FailoverSigBackend,
)
from gethsharding_tpu.resilience.chaos import ChaosSchedule, ChaosSigBackend
from gethsharding_tpu.serving import (
    AdmissionQueue,
    ClassDeadlineExceeded,
    QueueClosed,
    Request,
    ServingConfig,
    ServingOverloadError,
    ServingSigBackend,
    TenantQuotaExceeded,
)
from gethsharding_tpu.sigbackend import PythonSigBackend, SigBackend


def _registry() -> metrics.Registry:
    return metrics.Registry()


def _req(rows: int = 1, klass: str = CLASS_INTERACTIVE,
         tenant: str = "") -> Request:
    digests = tuple(keccak256(b"q-%d" % i) for i in range(rows))
    sigs = tuple(b"\x00" * 65 for _ in range(rows))
    return Request("ecrecover_addresses", (digests, sigs), rows,
                   klass=klass, tenant=tenant)


class SlowBackend(SigBackend):
    """Real results, controllable pace: every dispatch sleeps
    `delay_s` first (the load-shaping brake of the soak tests —
    results stay verifiable against the known signer)."""

    name = "slow"

    def __init__(self, inner, delay_s: float = 0.0):
        self.inner = inner
        self.delay_s = delay_s

    def _op(self, op, *args, **kwargs):
        if self.delay_s:
            time.sleep(self.delay_s)
        return getattr(self.inner, op)(*args, **kwargs)

    def ecrecover_addresses(self, digests, sigs65):
        return self._op("ecrecover_addresses", digests, sigs65)

    def bls_verify_aggregates(self, messages, agg_sigs, agg_pks):
        return self._op("bls_verify_aggregates", messages, agg_sigs,
                        agg_pks)

    def bls_verify_committees(self, messages, sig_rows, pk_rows,
                              pk_row_keys=None):
        return self._op("bls_verify_committees", messages, sig_rows,
                        pk_rows, pk_row_keys=pk_row_keys)

    def das_verify_samples(self, chunks, indices, proofs, roots):
        return self._op("das_verify_samples", chunks, indices, proofs,
                        roots)


def _ecdsa_cases(n: int):
    cases = []
    for i in range(n):
        priv = int.from_bytes(keccak256(b"fleet-%d" % i), "big") % ecdsa.N
        digest = keccak256(b"fleet-msg-%d" % i)
        cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                      ecdsa.priv_to_address(priv)))
    return cases


# == admission classes in the queue =========================================


def test_weighted_take_serves_every_class_its_share():
    """A 12-row batch over a 3-class backlog splits 8/3/1 by weight,
    interactive first — bulk cannot starve interactive AND interactive
    cannot fully starve bulk."""
    queue = AdmissionQueue(cap_rows=1024, max_batch=12, flush_us=0)
    for klass in (CLASS_CATCHUP, CLASS_BULK_AUDIT, CLASS_INTERACTIVE):
        for _ in range(20):
            queue.put(_req(1, klass=klass))
    batch, reason = queue.take_batch()
    assert reason == "full"
    counts = {}
    for request in batch:
        counts[request.klass] = counts.get(request.klass, 0) + 1
    assert counts == {CLASS_INTERACTIVE: 8, CLASS_BULK_AUDIT: 3,
                      CLASS_CATCHUP: 1}
    # priority order inside the batch: interactive rows lead
    assert batch[0].klass == CLASS_INTERACTIVE
    # a lone-class backlog takes the whole batch (weights only split
    # among NONEMPTY classes)
    queue2 = AdmissionQueue(cap_rows=1024, max_batch=8, flush_us=0)
    for _ in range(8):
        queue2.put(_req(1, klass=CLASS_CATCHUP))
    batch2, _ = queue2.take_batch()
    assert len(batch2) == 8


def test_shed_by_class_catchup_first_interactive_last():
    """At the cap, a higher-priority arrival displaces queued catchup
    (newest first) with a typed failure; same-or-lower priority is
    shed itself; interactive is never displaced."""
    queue = AdmissionQueue(cap_rows=8, policy="shed", max_batch=8,
                           flush_us=1_000_000)
    catchup = [_req(1, klass=CLASS_CATCHUP) for _ in range(8)]
    for request in catchup:
        queue.put(request)
    assert queue.depth_rows == 8

    interactive = _req(1)
    queue.put(interactive)  # displaces the NEWEST catchup request
    assert queue.depth_rows == 8
    with pytest.raises(ServingOverloadError, match="displaced by"):
        catchup[-1].future.result(timeout=1)
    assert not interactive.future.done()
    assert queue.shed_by_class[CLASS_CATCHUP] == 1

    with pytest.raises(ServingOverloadError, match="request shed"):
        queue.put(_req(1, klass=CLASS_CATCHUP))  # nothing lower: shed self
    assert queue.shed_by_class[CLASS_CATCHUP] == 2

    bulk = _req(1, klass=CLASS_BULK_AUDIT)
    queue.put(bulk)  # displaces catchup, not interactive
    assert queue.shed_by_class[CLASS_CATCHUP] == 3
    assert queue.shed_by_class[CLASS_INTERACTIVE] == 0
    assert not bulk.future.done()
    # drain: interactive + bulk survived, catchup thinned from the tail
    batch, _ = queue.take_batch()
    survivors = {request.klass for request in batch}
    assert CLASS_INTERACTIVE in survivors and CLASS_BULK_AUDIT in survivors


def test_tenant_quota_bounds_one_tenant():
    """A tenant at its quota is refused with `TenantQuotaExceeded`
    (counted); other tenants are unaffected; drain frees the quota."""
    queue = AdmissionQueue(cap_rows=64, max_batch=64, flush_us=0,
                           tenant_quota_rows=4)
    for _ in range(4):
        queue.put(_req(1, tenant="noisy"))
    with pytest.raises(TenantQuotaExceeded, match="noisy"):
        queue.put(_req(1, tenant="noisy"))
    queue.put(_req(1, tenant="quiet"))  # other tenants unaffected
    queue.put(_req(1))                  # untenanted traffic unaffected
    assert queue.quota_rejections == 1
    assert queue.tenant_rows("noisy") == 4
    queue.take_batch()
    assert queue.tenant_rows("noisy") == 0
    queue.put(_req(1, tenant="noisy"))  # drained: admitted again


def test_wfq_tenant_fairness_preserves_class_weighting():
    """The tenant-fair drain (deficit round-robin, PR 15) nests INSIDE
    the class-weighted drain: with two tenants queued in every class,
    the batch still splits 8/3/1 by class weight, and within the
    interactive share both tenants are served. (The starvation-bound
    and carried-deficit contracts live in test_fleet_frontend.py.)"""
    queue = AdmissionQueue(cap_rows=1024, max_batch=12, flush_us=0)
    for klass in (CLASS_CATCHUP, CLASS_BULK_AUDIT, CLASS_INTERACTIVE):
        for tenant in ("a", "b"):
            for _ in range(10):
                queue.put(_req(1, klass=klass, tenant=tenant))
    batch, reason = queue.take_batch()
    assert reason == "full"
    counts: dict = {}
    for request in batch:
        counts[request.klass] = counts.get(request.klass, 0) + 1
    assert counts == {CLASS_INTERACTIVE: 8, CLASS_BULK_AUDIT: 3,
                      CLASS_CATCHUP: 1}
    interactive_tenants = {r.tenant for r in batch
                           if r.klass == CLASS_INTERACTIVE}
    assert interactive_tenants == {"a", "b"}


def test_put_after_close_fails_fast():
    queue = AdmissionQueue(cap_rows=16, max_batch=16, flush_us=0)
    queue.close()
    with pytest.raises(QueueClosed):
        queue.put(_req(1))


def test_close_wakes_blocked_putter_with_queue_closed():
    """A putter blocked on a full queue must not hang across close():
    it fails fast with `QueueClosed`."""
    queue = AdmissionQueue(cap_rows=4, policy="block", max_batch=4,
                           flush_us=1_000_000)
    for _ in range(4):
        queue.put(_req(1))
    outcome: dict = {}

    def blocked_put():
        try:
            queue.put(_req(1))
            outcome["result"] = "enqueued"
        except QueueClosed:
            outcome["result"] = "closed"

    thread = threading.Thread(target=blocked_put)
    thread.start()
    time.sleep(0.1)
    assert thread.is_alive()  # genuinely blocked at the cap
    queue.close()
    thread.join(timeout=5)
    assert outcome["result"] == "closed"


def test_batcher_submit_after_close_is_queue_closed():
    serving = ServingSigBackend(PythonSigBackend(), registry=_registry())
    serving.close()
    with pytest.raises(QueueClosed):
        serving.submit("ecrecover_addresses", [keccak256(b"x")],
                       [b"\x00" * 65])


def test_class_deadline_expires_stale_requests():
    """A request past its class deadline fails with
    `ClassDeadlineExceeded` even when the queue then empties (the
    consumer must not strand it behind an indefinite wait)."""
    policies = default_policies()
    policies[CLASS_CATCHUP] = ClassPolicy(
        CLASS_CATCHUP, priority=2, weight=1, flush_mult=8.0,
        deadline_s=0.05)
    queue = AdmissionQueue(cap_rows=64, max_batch=64, flush_us=1_000_000,
                           policies=policies)
    stale = _req(1, klass=CLASS_CATCHUP)
    queue.put(stale)
    got: dict = {}

    def consume():
        got["batch"] = queue.take_batch()

    thread = threading.Thread(target=consume, daemon=True)
    thread.start()
    with pytest.raises(ClassDeadlineExceeded, match="expired"):
        stale.future.result(timeout=5)
    assert queue.expired_by_class[CLASS_CATCHUP] == 1
    assert queue.depth_rows == 0
    # the consumer is still serving: a fresh interactive request flows
    fresh = _req(1)
    queue.put(fresh)
    thread.join(timeout=5)
    assert not thread.is_alive()
    batch, _ = got["batch"]
    assert batch == [fresh]


def test_class_resolution_context_defaults_and_metrics():
    """Class resolution: explicit kwarg > thread context > per-op
    default; the per-class request counters attribute each."""
    registry = _registry()
    serving = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=500),
                                registry=registry)
    try:
        digest, sig = keccak256(b"cls"), b"\x00" * 65
        serving.ecrecover_addresses([digest], [sig])  # default interactive
        with admission_class(CLASS_CATCHUP, tenant="t9"):
            serving.ecrecover_addresses([digest], [sig])  # context
            serving.submit("ecrecover_addresses", [digest], [sig],
                           klass=CLASS_BULK_AUDIT).result(timeout=10)
        # das_verify defaults to bulk_audit by the per-op map
        assert serving.das_verify_samples([], [], [], []) == []
        base = "serving/ecrecover/class"
        assert registry.counter(
            f"{base}/{CLASS_INTERACTIVE}/requests").value == 1
        assert registry.counter(
            f"{base}/{CLASS_CATCHUP}/requests").value == 1
        assert registry.counter(
            f"{base}/{CLASS_BULK_AUDIT}/requests").value == 1
        assert registry.counter(
            f"serving/das_verify/class/{CLASS_BULK_AUDIT}/requests"
        ).value == 1
        # the classed() facade pins a class without the context
        classed = serving.classed(CLASS_CATCHUP, tenant="t10")
        classed.ecrecover_addresses([digest], [sig])
        assert registry.counter(
            f"{base}/{CLASS_CATCHUP}/requests").value == 2
    finally:
        serving.close()


# == the router =============================================================


def _plain_replicas(n: int, registry) -> list:
    return [Replica(f"r{i}", PythonSigBackend(), probe=None,
                    registry=registry)
            for i in range(n)]


def test_affinity_stable_and_rebalances_after_reentry():
    """The same key routes to the same replica order; draining the
    preferred replica moves ONLY its keys; re-entry moves them back."""
    registry = _registry()
    router = FleetRouter(_plain_replicas(3, registry),
                         health_interval_s=0.0, registry=registry)
    orders = {key: [r.name for r in router.route(key)]
              for key in ("shard-0", "shard-1", "shard-2", "shard-3")}
    for key, order in orders.items():
        assert [r.name for r in router.route(key)] == order  # stable
    victim = orders["shard-0"][0]
    router.drain(victim)
    assert router._replica(victim).state == "draining"
    moved = [r.name for r in router.route("shard-0")]
    assert moved == orders["shard-0"][1:]  # only the head drops out
    for key, order in orders.items():
        expect = [name for name in order if name != victim]
        assert [r.name for r in router.route(key)] == expect
    router.undrain(victim)
    assert [r.name for r in router.route("shard-0")] == orders["shard-0"]
    assert router._replica(victim).reentries == 1


def test_keyless_routing_prefers_least_in_flight():
    registry = _registry()
    replicas = _plain_replicas(2, registry)
    router = FleetRouter(replicas, health_interval_s=0.0,
                         registry=registry)
    replicas[0].in_flight = 5
    assert [r.name for r in router.route()] == ["r1", "r0"]


def test_replica_hang_watchdog_fires_router_retries_next():
    """A seeded dispatch hang wedges replica r0's serving dispatcher;
    the watchdog fails the batch with DeadlineExceeded and the router
    answers from r1 — the caller never sees the hang."""
    registry = _registry()
    schedule = ChaosSchedule(seed=7,
                             rules={"dispatch.ecrecover_addresses": 1})
    hung = ServingSigBackend(
        ChaosSigBackend(PythonSigBackend(), schedule, hang_s=1.5),
        ServingConfig(flush_us=200, watchdog_s=0.15),
        registry=registry)
    healthy = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=200),
                                registry=registry)
    router = FleetRouter(
        [Replica("r0", hung, probe=None, registry=registry),
         Replica("r1", healthy, probe=None, registry=registry)],
        health_interval_s=0.0, registry=registry)
    back = RouterSigBackend(router)
    (digest, sig, want), = _ecdsa_cases(1)
    try:
        t0 = time.monotonic()
        assert back.ecrecover_addresses([digest], [sig]) == [want]
        assert time.monotonic() - t0 < 1.2  # did not sit out the hang
        assert registry.counter("fleet/replica/r0/failures").value == 1
        assert registry.counter("fleet/router/failovers").value == 1
        assert schedule.injected.get("dispatch.ecrecover_addresses") == 1
    finally:
        hung.close()
        healthy.close()


def test_breaker_trip_drains_probe_repromotes_and_reenters():
    """Seeded chaos faults trip r0's breaker mid-traffic: every answer
    stays correct (fallback-served), the router marks r0 draining, and
    after the cooldown the router's probe runs the half-open
    differential — r0 re-enters only once the breaker re-closes."""
    registry = _registry()
    schedule = ChaosSchedule(seed=7,
                             rules={"backend.ecrecover_addresses": 3})
    serving0 = ServingSigBackend(
        ChaosSigBackend(PythonSigBackend(), schedule),
        ServingConfig(flush_us=200), registry=registry)
    serving1 = ServingSigBackend(PythonSigBackend(),
                                 ServingConfig(flush_us=200),
                                 registry=registry)
    breaker0 = CircuitBreaker(name="fleet-r0", fault_threshold=3,
                              reset_s=0.2, registry=registry)
    r0 = Replica("r0",
                 FailoverSigBackend(serving0, PythonSigBackend(),
                                    breaker=breaker0, registry=registry),
                 registry=registry)
    r1 = Replica("r1",
                 FailoverSigBackend(serving1, PythonSigBackend(),
                                    breaker=CircuitBreaker(
                                        name="fleet-r1",
                                        registry=registry),
                                    registry=registry),
                 registry=registry)
    router = FleetRouter([r0, r1], health_interval_s=0.0,
                         registry=registry)
    back = RouterSigBackend(router)
    cases = _ecdsa_cases(8)
    try:
        # keyless traffic prefers idle r0: the first three calls eat the
        # three seeded faults (each served correctly from the scalar
        # fallback), tripping the breaker
        for digest, sig, want in cases[:3]:
            assert back.ecrecover_addresses([digest], [sig]) == [want]
        assert breaker0.state_name == "open"
        router.refresh(force=True)
        assert r0.state == "draining"
        assert r0.drain_events == 1
        # drained: traffic lands on r1, still correct
        for digest, sig, want in cases[3:6]:
            assert back.ecrecover_addresses([digest], [sig]) == [want]
        assert r0.state == "draining"  # cooldown not elapsed
        # cooldown elapses; the router's refresh-side probe becomes the
        # half-open differential, matches, and re-promotes the primary
        time.sleep(0.25)
        deadline = time.monotonic() + 5
        while r0.state != "healthy" and time.monotonic() < deadline:
            router.refresh(force=True)
            time.sleep(0.02)
        assert r0.state == "healthy"
        assert breaker0.state_name == "closed"
        assert r0.reentries == 1
        for digest, sig, want in cases[6:]:
            assert back.ecrecover_addresses([digest], [sig]) == [want]
        assert schedule.injected.get("backend.ecrecover_addresses") == 3
    finally:
        serving0.close()
        serving1.close()


def test_all_replicas_draining_is_typed_and_fast():
    registry = _registry()
    router = FleetRouter(_plain_replicas(2, registry),
                         health_interval_s=0.0, registry=registry)
    router.drain("r0")
    router.drain("r1")
    with pytest.raises(AllReplicasDraining):
        router.call("ecrecover_addresses", [keccak256(b"x")],
                    [b"\x00" * 65])
    assert registry.counter("fleet/router/all_draining").value >= 1


def test_overloaded_replica_spills_to_next():
    """A shed on one replica's admission queue is routing information:
    the router retries the next replica instead of failing the caller."""
    registry = _registry()
    # r0's serving tier: zero-capacity-ish shed policy with a wedged
    # dispatcher brake so the queue stays full
    slow = SlowBackend(PythonSigBackend(), delay_s=0.2)
    serving0 = ServingSigBackend(
        slow, ServingConfig(max_batch=1, flush_us=0, queue_cap=1,
                            policy="shed"),
        registry=registry)
    serving1 = ServingSigBackend(PythonSigBackend(),
                                 ServingConfig(flush_us=200),
                                 registry=registry)
    router = FleetRouter(
        [Replica("r0", serving0, probe=None, registry=registry),
         Replica("r1", serving1, probe=None, registry=registry)],
        health_interval_s=0.0, registry=registry)
    back = RouterSigBackend(router)
    (digest, sig, want), = _ecdsa_cases(1)
    try:
        # wedge r0: fill the dispatcher, the double-buffer slot and the
        # queue until its shed policy fires — the flusher drains the
        # 1-row queue into the pipeline, so a few submits are needed
        # before admission actually refuses
        filler, wedged = [], False
        for i in range(8):
            try:
                filler.append(serving0.submit(
                    "ecrecover_addresses", [keccak256(b"fill-%d" % i)],
                    [b"\x00" * 65]))
            except ServingOverloadError:
                wedged = True
                break
        assert wedged, "r0 never reached its shed point"
        # route: r0 preferred (idle by in_flight), sheds, spills to r1
        assert back.ecrecover_addresses([digest], [sig]) == [want]
        assert registry.counter("serving/ecrecover/shed").value >= 2
        assert registry.counter("fleet/router/failovers").value >= 1
        for future in filler:
            future.result(timeout=10)
    finally:
        serving0.close()
        serving1.close()


# == chain_server drain lifecycle ===========================================


def test_rpc_server_drain_refuses_new_work_and_strands_no_caller():
    """`shard_drain` flips the replica to draining: health reports it,
    new verification RPCs fail with the typed 'replica draining' error,
    and already-queued serving futures resolve or fail cleanly."""
    from gethsharding_tpu.rpc.client import RPCClient, RPCError
    from gethsharding_tpu.rpc import codec
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    serving = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=500),
                                registry=_registry())
    failover = FailoverSigBackend(serving, PythonSigBackend(),
                                  breaker=CircuitBreaker(
                                      name="drain-test",
                                      registry=_registry()),
                                  registry=_registry())
    server = RPCServer(SimulatedMainchain(), sig_backend=failover)
    server.start()
    client = RPCClient(*server.address)
    try:
        (digest, sig, want), = _ecdsa_cases(1)
        out = client.call("shard_ecrecover", [codec.enc_bytes(digest)],
                          [codec.enc_bytes(sig)])
        assert out == [codec.enc_bytes(want)]
        health = client.call("shard_health")
        assert health["draining"] is False
        assert health["breaker"] == "closed"
        assert health["serving"] is not None

        drained = client.call("shard_drain")
        assert drained["draining"] is True
        assert client.call("shard_health")["draining"] is True
        with pytest.raises(RPCError, match="replica draining"):
            client.call("shard_ecrecover", [codec.enc_bytes(digest)],
                        [codec.enc_bytes(sig)])
        # non-verification RPCs still answer during the drain
        assert isinstance(client.call("shard_blockNumber"), int)
    finally:
        client.close()
        server.stop()
        serving.close()


def test_rpc_tenant_only_param_still_charges_quota():
    """A caller passing `tenant` WITHOUT `klass` on shard_ecrecover
    must still be charged against its quota (regression: the tenant tag
    used to be dropped unless a class rode along)."""
    from gethsharding_tpu.rpc.client import RPCClient, RPCError
    from gethsharding_tpu.rpc import codec
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    # a LONG flush deadline keeps the filler row sitting in the queue
    # (no full flush at max_batch 128, no deadline flush for ~1 s), so
    # the tenant's quota occupancy is deterministic — no pipeline race
    serving = ServingSigBackend(
        PythonSigBackend(),
        ServingConfig(max_batch=128, flush_us=1_000_000, queue_cap=64,
                      tenant_quota_rows=1),
        registry=_registry())
    server = RPCServer(SimulatedMainchain(), sig_backend=serving)
    server.start()
    client = RPCClient(*server.address)
    try:
        (digest, sig, _), = _ecdsa_cases(1)
        filler = serving.submit("ecrecover_addresses",
                                [keccak256(b"qf")], [b"\x00" * 65],
                                tenant="t9")
        queue = serving.batcher._queues["ecrecover_addresses"]
        assert queue.tenant_rows("t9") == 1
        with pytest.raises(RPCError, match="quota"):
            client.call("shard_ecrecover", [codec.enc_bytes(digest)],
                        [codec.enc_bytes(sig)], None, "t9")
        # a different tenant is admitted (and coalesces with the filler
        # once the deadline flush fires)
        out = client.call("shard_ecrecover", [codec.enc_bytes(digest)],
                          [codec.enc_bytes(sig)], None, "other")
        assert out is not None
        filler.result(timeout=10)
    finally:
        client.close()
        server.stop()
        serving.close()


def test_router_over_rpc_replicas_drains_and_fails_over():
    """Cross-process shape: two RPCServer replicas behind
    `RpcReplicaBackend`s; draining one routes traffic to the other
    (the typed draining refusal is retried, not surfaced)."""
    from gethsharding_tpu.fleet.router import RpcReplicaBackend
    from gethsharding_tpu.rpc.server import RPCServer
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    registry = _registry()
    servers, replicas = [], []
    for i in range(2):
        serving = ServingSigBackend(PythonSigBackend(),
                                    ServingConfig(flush_us=500),
                                    registry=_registry())
        server = RPCServer(SimulatedMainchain(), sig_backend=serving)
        server.start()
        servers.append((server, serving))
        backend = RpcReplicaBackend.dial(*server.address)
        replicas.append(Replica(f"rpc{i}", backend,
                                health=backend.health, probe=None,
                                registry=registry))
    router = FleetRouter(replicas, health_interval_s=0.0,
                         registry=registry)
    back = RouterSigBackend(router)
    cases = _ecdsa_cases(4)
    try:
        for digest, sig, want in cases[:2]:
            assert back.ecrecover_addresses(
                [digest], [sig],) == [want]
        # drain replica 0 THROUGH the control plane
        replicas[0].backend.drain()
        router.refresh(force=True)
        assert replicas[0].state == "draining"
        for digest, sig, want in cases[2:]:
            assert back.ecrecover_addresses([digest], [sig]) == [want]
        # the drained replica took nothing new
        assert servers[0][0].draining is True
    finally:
        for replica in replicas:
            replica.backend.close()
        for server, serving in servers:
            server.stop()
            serving.close()


# == the long traffic-model soak (slow tier) ================================


@pytest.mark.slow  # ~25 s: the full diurnal/hot-shard/herd traffic model
def test_fleet_traffic_model_soak_slow():
    """The scripts/serving_stress.py traffic-model soak, end to end:
    diurnal load curve, hot-shard skew, a thundering-herd burst and a
    seeded mid-soak breaker trip — exit 0 means zero divergence, zero
    interactive sheds, SLOs held, and the tripped replica re-entered."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "serving_stress.py"),
         "--replicas", "3", "--clients", "16", "--duration", "18",
         "--max-batch", "16", "--queue-cap", "16", "--policy", "shed",
         "--classes", "interactive=8,bulk_audit=4,catchup_replay=4",
         "--chaos-trip", "10", "--hot-shard", "0.9", "--diurnal-s", "8",
         "--herd-at", "6", "--slo-interactive-ms", "8000"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json as _json

    summary = _json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["divergences"] == 0
    assert summary["drain_events"] >= 1 and summary["reentered"]


# == the closed-loop acceptance soak ========================================


def test_closed_loop_drain_soak_acceptance():
    """The ISSUE 8 acceptance bar, end to end: a 3-replica fleet under
    mixed-class traffic rides a seeded chaos schedule that trips one
    replica's breaker mid-soak. Asserts: the router marks it draining;
    ZERO requests are lost or mis-answered (every interactive result
    verified against the known signer); interactive p99 stays within
    its SLO and sees zero sheds while catchup_replay is shed first;
    and the replica re-enters after half-open re-promotion."""
    registry = _registry()
    n_replicas = 3
    # r0's chaos: a seeded run of consecutive device-dispatch faults a
    # little into the soak — each absorbed by the fallback (answers stay
    # correct), together tripping the breaker. The window is wider than
    # the fault threshold because caller-side outcomes interleave across
    # threads: a pre-window dispatch resolving late can reset the
    # consecutive count once, not eight times.
    schedule = ChaosSchedule(
        seed=11,
        rules={"backend.ecrecover_addresses":
               lambda idx: 10 <= idx < 18})
    servings, replicas = [], []
    for i in range(n_replicas):
        inner = SlowBackend(PythonSigBackend(), delay_s=0.002)
        if i == 0:
            inner = ChaosSigBackend(inner, schedule)
        serving = ServingSigBackend(
            inner,
            ServingConfig(max_batch=16, flush_us=300, queue_cap=16,
                          policy="shed"),
            registry=_registry())
        servings.append(serving)
        breaker = CircuitBreaker(name=f"soak-r{i}", fault_threshold=3,
                                 reset_s=0.3, registry=registry)
        replicas.append(Replica(
            f"r{i}",
            FailoverSigBackend(serving, PythonSigBackend(),
                               breaker=breaker, registry=registry),
            registry=registry))
    router = FleetRouter(replicas, health_interval_s=0.05,
                         registry=registry)
    back = RouterSigBackend(router)

    cases = _ecdsa_cases(64)
    divergences: list = []
    interactive_lat: list = []
    interactive_shed = [0]
    catchup_shed = [0]
    stop = threading.Event()

    def interactive_client(c: int) -> None:
        for r in range(30):
            digest, sig, want = cases[(c * 30 + r) % len(cases)]
            t0 = time.monotonic()
            try:
                got = back.ecrecover_addresses([digest], [sig])
            except ServingOverloadError:
                interactive_shed[0] += 1
                continue
            interactive_lat.append(time.monotonic() - t0)
            if got != [want]:
                divergences.append((c, r, got))
                stop.set()
                return
            time.sleep(0.002)

    def catchup_flood() -> None:
        # bursty backfill with hot-shard skew: 8-row requests, many
        # concurrent threads, all keyed to ONE affinity — the hot
        # replica's 16-row queue overflows and catchup sheds first (the
        # retry ladder spills survivors to the colder replicas)
        for r in range(12):
            if stop.is_set():
                return
            rows = [cases[(r + j) % len(cases)] for j in range(8)]
            try:
                router.call(
                    "ecrecover_addresses",
                    [c[0] for c in rows], [c[1] for c in rows],
                    affinity="hot-shard", klass=CLASS_CATCHUP)
            except (ServingOverloadError, AllReplicasDraining):
                catchup_shed[0] += 1

    threads = ([threading.Thread(target=interactive_client, args=(c,))
                for c in range(4)]
               + [threading.Thread(target=catchup_flood)
                  for _ in range(6)])
    try:
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "hung client"
        assert divergences == [], divergences

        r0 = replicas[0]
        # the seeded faults fired and tripped r0 into draining mid-soak
        assert schedule.injected.get("backend.ecrecover_addresses", 0) >= 3
        assert r0.drain_events >= 1, router.states()

        # r0 re-enters after its half-open differential re-promotion
        deadline = time.monotonic() + 10
        while r0.state != "healthy" and time.monotonic() < deadline:
            router.refresh(force=True)
            time.sleep(0.05)
        assert r0.state == "healthy", router.states()
        assert r0.reentries >= 1

        # deterministic overload coda: the organic flood's pressure is
        # timing-dependent — on a fast host (or when an early r0 trip
        # spaces arrivals behind retry backoffs) 12 rounds can drain
        # without ever overflowing a 16-row queue, and an overload
        # phase that never overloaded would flake the shed assertions
        # instead of testing them. If nothing shed organically, drive
        # one concentrated catchup burst at a single replica beyond its
        # queue + double-buffer capacity (16 queued + 16 slotted + 16
        # executing = 48 rows; 10x8 = 80 arriving at once MUST shed),
        # so shed-by-class is always exercised.
        if sum(s.batcher.shed_by_class()[CLASS_CATCHUP]
               for s in servings) + catchup_shed[0] == 0:
            def burst(k: int) -> None:
                rows = [cases[(k + j) % len(cases)] for j in range(8)]
                try:
                    servings[1].classed(CLASS_CATCHUP).ecrecover_addresses(
                        [c[0] for c in rows], [c[1] for c in rows])
                except ServingOverloadError:
                    catchup_shed[0] += 1
            burst_threads = [threading.Thread(target=burst, args=(k,))
                             for k in range(10)]
            for thread in burst_threads:
                thread.start()
            for thread in burst_threads:
                thread.join(timeout=60)

        # shed-by-class: interactive rode through untouched; the
        # catchup flood absorbed the overload
        replica_sheds = {
            klass: sum(s.batcher.shed_by_class()[klass]
                       for s in servings)
            for klass in (CLASS_INTERACTIVE, CLASS_BULK_AUDIT,
                          CLASS_CATCHUP)}
        assert interactive_shed[0] == 0
        assert replica_sheds[CLASS_INTERACTIVE] == 0, replica_sheds
        # the overload evidence can land replica-side (displacement /
        # arrival shed) or caller-side (the retry ladder exhausted) —
        # the same either-side form bench.py --fleet gates on
        assert replica_sheds[CLASS_CATCHUP] + catchup_shed[0] > 0, \
            (replica_sheds, catchup_shed)

        # interactive latency SLO (generous for hermetic CPU: the bench
        # --fleet gate owns the tight number)
        interactive_lat.sort()
        p99 = interactive_lat[int(0.99 * (len(interactive_lat) - 1))]
        assert p99 < 2.0, f"interactive p99 {p99:.3f}s"

        # zero lost: every interactive request either verified or was
        # counted shed (and interactive sheds were zero)
        assert len(interactive_lat) == 4 * 30
    finally:
        stop.set()
        for serving in servings:
            serving.close()
