"""General EVM interpreter (core/vm.py, byzantium rules): opcode
semantics, gas accounting, call-context rules, precompiles — the
tooling-tier executor behind `evm` (phase-1 consensus stays on the
native SMC kernels)."""

import pytest

from gethsharding_tpu.core.vm import (
    Account, EVM, Env, StateDB, UINT_MAX, execute)
from gethsharding_tpu.crypto import bn256, secp256k1
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.utils.rlp import rlp_encode


def _asm(*parts) -> bytes:
    """Tiny assembler: ints are opcodes, bytes are literal, ('push', v)
    emits the smallest PUSHn."""
    out = bytearray()
    for part in parts:
        if isinstance(part, tuple):
            _, v = part
            blob = v.to_bytes(max(1, (v.bit_length() + 7) // 8), "big")
            out.append(0x60 + len(blob) - 1)
            out.extend(blob)
        elif isinstance(part, bytes):
            out.extend(part)
        else:
            out.append(part)
    return bytes(out)


def _run(code, **kw):
    res, vm = execute(code, **kw)
    return res, vm


def test_arithmetic_and_stack_semantics():
    # (7 + 5) * 3 - 1 = 35, returned as a 32-byte word
    code = _asm(("push", 5), ("push", 7), 0x01,   # ADD -> 12
                ("push", 3), 0x02,                # MUL -> 36
                ("push", 1), 0x90, 0x03,          # SWAP1; SUB -> 35
                ("push", 0), 0x52,                # MSTORE @0
                ("push", 32), ("push", 0), 0xF3)  # RETURN
    res, _ = _run(code)
    assert res.success
    assert int.from_bytes(res.output, "big") == 35


@pytest.mark.parametrize("code,want", [
    # SDIV: -8 / 3 == -2 (truncated toward zero)
    (_asm(("push", 3), ("push", UINT_MAX - 7), 0x05), UINT_MAX - 1),
    # SMOD: -8 % 3 == -2
    (_asm(("push", 3), ("push", UINT_MAX - 7), 0x07), UINT_MAX - 1),
    # DIV by zero = 0
    (_asm(("push", 0), ("push", 12), 0x04), 0),
    # SIGNEXTEND byte 0 of 0xFF -> -1
    (_asm(("push", 0xFF), ("push", 0), 0x0B), UINT_MAX),
    # BYTE 31 of 0x..01 -> 1
    (_asm(("push", 1), ("push", 31), 0x1A), 1),
    # SLT: -1 < 1
    (_asm(("push", 1), ("push", UINT_MAX), 0x12), 1),
    # EXP 2^10
    (_asm(("push", 10), ("push", 2), 0x0A), 1024),
])
def test_opcode_values(code, want):
    full = code + _asm(("push", 0), 0x52, ("push", 32), ("push", 0), 0xF3)
    res, _ = _run(full)
    assert res.success
    assert int.from_bytes(res.output, "big") == want


def test_keccak_and_calldata():
    # return keccak256(calldata[0:4])
    code = _asm(("push", 4), ("push", 0), ("push", 0), 0x37,  # CALLDATACOPY
                ("push", 4), ("push", 0), 0x20,               # KECCAK256
                ("push", 0), 0x52, ("push", 32), ("push", 0), 0xF3)
    res, _ = _run(code, data=b"abcd")
    assert res.output == keccak256(b"abcd")


def test_loop_sums_to_100_and_gas_is_exact_for_straightline():
    # straight-line gas check: PUSH1 PUSH1 ADD STOP = 3+3+3+0
    res, _ = _run(_asm(("push", 1), ("push", 2), 0x01, 0x00), gas=100)
    assert res.success and res.gas_left == 100 - 9
    # a JUMPI loop: sum 1..10 in storage slot 0 via memory counter
    code = _asm(
        ("push", 0), ("push", 0), 0x52,            # mem[0] = 0 (i)
        ("push", 0), ("push", 32), 0x52,           # mem[32] = 0 (acc)
        0x5B,                                      # loop: JUMPDEST @10
        ("push", 0), 0x51, ("push", 1), 0x01,      # i+1
        0x80, ("push", 0), 0x52,                   # mem[0] = i+1 (dup)
        ("push", 32), 0x51, 0x01,                  # acc += i+1
        ("push", 32), 0x52,
        ("push", 10), ("push", 0), 0x51, 0x10,     # i < 10 ?
        ("push", 10), 0x57,                        # JUMPI loop
        ("push", 32), 0x51, ("push", 0), 0x55,     # SSTORE 0, acc
        0x00)
    res, vm = _run(code, gas=200_000)
    assert res.success
    assert vm.state.get(b"\xc0" * 20).storage[0] == 55


def test_sstore_gas_and_refund_rules():
    addr = b"\xc0" * 20
    # zero -> nonzero: 20000; nonzero -> nonzero: 5000;
    # nonzero -> zero: 5000 + 15000 refund
    code = _asm(("push", 1), ("push", 0), 0x55, 0x00)
    res, vm = _run(code, gas=30_000)
    assert res.success and res.gas_left == 30_000 - 3 - 3 - 20000
    state = vm.state
    code2 = _asm(("push", 2), ("push", 0), 0x55, 0x00)
    res2, vm2 = _run(code2, state=state, gas=30_000)
    assert res2.gas_left == 30_000 - 3 - 3 - 5000
    # clearing refunds 15000, CAPPED at gas_used // 2 (= 2503 here)
    code3 = _asm(("push", 0), ("push", 0), 0x55, 0x00)
    res3, vm3 = _run(code3, state=state, gas=30_000)
    used = 3 + 3 + 5000
    assert res3.gas_left == 30_000 - used + used // 2
    assert 0 not in state.get(addr).storage


def test_out_of_gas_consumes_frame_and_reverts_state():
    code = _asm(("push", 1), ("push", 0), 0x55, 0x00)  # SSTORE needs 20006
    res, vm = _run(code, gas=10_000)
    assert not res.success and res.gas_left == 0
    assert vm.state.get(b"\xc0" * 20).storage == {}


def test_invalid_jump_and_stack_underflow_fail_loudly():
    res, _ = _run(_asm(("push", 3), 0x56, 0x00))  # JUMP to non-JUMPDEST
    assert not res.success and res.gas_left == 0
    res, _ = _run(bytes([0x01]))                  # ADD on empty stack
    assert not res.success
    # jump INTO push data must be rejected
    res, _ = _run(_asm(("push", 1), 0x56))        # dest 1 = inside PUSH
    assert not res.success


def test_revert_returns_data_and_restores_state():
    # SSTORE then REVERT("xy")
    code = _asm(("push", 9), ("push", 5), 0x55,
                ("push", 0x7879), ("push", 0), 0x52,
                ("push", 2), ("push", 30), 0xFD)
    res, vm = _run(code, gas=50_000)
    assert not res.success
    assert res.output == b"xy"
    assert res.gas_left > 0  # REVERT refunds remaining gas
    assert vm.state.get(b"\xc0" * 20).storage == {}


def _install(vm_state, addr, code, balance=0):
    acct = vm_state.get(addr)
    acct.code = code
    acct.balance = balance


def test_call_value_transfer_and_returndata():
    state = StateDB()
    callee = b"\x11" * 20
    # callee: return CALLVALUE
    _install(state, callee, _asm(0x34, ("push", 0), 0x52,
                                 ("push", 32), ("push", 0), 0xF3))
    # caller: CALL(gas, callee, value=7, in 0/0, out 0/32); return mem[0]
    code = _asm(("push", 32), ("push", 0), ("push", 0), ("push", 0),
                ("push", 7), ("push", int.from_bytes(callee, "big")),
                ("push", 100_000), 0xF1,
                ("push", 0), 0x52,  # store success flag
                ("push", 32), ("push", 0), 0xF3)
    state.get(b"\xc0" * 20).balance = 100
    res, vm = _run(code, state=state, gas=500_000)
    assert res.success
    # the call returned CALLVALUE=7 into mem[0]; then we overwrote with
    # the success flag (1)
    assert int.from_bytes(res.output, "big") == 1
    assert vm.state.get(callee).balance == 7
    assert vm.state.get(b"\xc0" * 20).balance == 93


def test_delegatecall_keeps_context_and_moves_no_balance():
    state = StateDB()
    lib = b"\x22" * 20
    # library code: SSTORE(0, CALLER); SSTORE(1, CALLVALUE)
    _install(state, lib, _asm(0x33, ("push", 0), 0x55,
                              0x34, ("push", 1), 0x55, 0x00))
    caller_addr = b"\xc0" * 20
    code = _asm(("push", 0), ("push", 0), ("push", 0), ("push", 0),
                ("push", int.from_bytes(lib, "big")),
                ("push", 200_000), 0xF4,
                ("push", 0), 0x52, ("push", 32), ("push", 0), 0xF3)
    state.get(caller_addr).balance = 50
    state.get(b"\xca" * 20).balance = 13  # top-level call transfers it
    res, vm = _run(code, state=state, gas=500_000, value=13,
                   caller=b"\xca" * 20)
    assert res.success and int.from_bytes(res.output, "big") == 1
    stored = vm.state.get(caller_addr).storage
    # storage written in the CALLER's account, caller/value inherited
    assert stored[0] == int.from_bytes(b"\xca" * 20, "big")
    assert stored[1] == 13
    assert vm.state.get(lib).storage == {}
    assert vm.state.get(lib).balance == 0


def test_staticcall_blocks_writes():
    state = StateDB()
    writer = b"\x33" * 20
    _install(state, writer, _asm(("push", 1), ("push", 0), 0x55, 0x00))
    code = _asm(("push", 0), ("push", 0), ("push", 0), ("push", 0),
                ("push", int.from_bytes(writer, "big")),
                ("push", 100_000), 0xFA,
                ("push", 0), 0x52, ("push", 32), ("push", 0), 0xF3)
    res, vm = _run(code, state=state, gas=500_000)
    assert res.success
    assert int.from_bytes(res.output, "big") == 0  # inner call failed
    assert vm.state.get(writer).storage == {}


def test_create_address_and_code_deposit():
    # initcode: returns 2 bytes of runtime code (0x00 0x00)
    initcode = _asm(("push", 2), ("push", 0), 0xF3)
    code = _asm(("push", len(initcode)),
                ("push", 32 - len(initcode)),  # offset of code in mem word
                ("push", 0), 0xF0,
                ("push", 0), 0x52, ("push", 32), ("push", 0), 0xF3)
    # place initcode into memory first: MSTORE a word whose tail is it
    word = int.from_bytes(initcode.rjust(32, b"\x00"), "big")
    full = _asm(("push", word), ("push", 0), 0x52) + code
    res, vm = _run(full, gas=500_000)
    assert res.success
    created = int.from_bytes(res.output, "big")
    want = keccak256(rlp_encode([b"\xc0" * 20, 0]))[12:]
    assert created == int.from_bytes(want, "big")
    assert vm.state.get(want).code == b"\x00\x00"
    assert vm.state.get(b"\xc0" * 20).nonce == 1


def test_selfdestruct_moves_balance():
    state = StateDB()
    victim = b"\x44" * 20
    heir = b"\x55" * 20
    _install(state, victim,
             _asm(("push", int.from_bytes(heir, "big")), 0xFF), balance=77)
    code = _asm(("push", 0), ("push", 0), ("push", 0), ("push", 0),
                ("push", 0), ("push", int.from_bytes(victim, "big")),
                ("push", 100_000), 0xF1, 0x00)
    res, vm = _run(code, state=state, gas=500_000)
    assert res.success
    assert vm.state.get(heir).balance == 77
    assert vm.state.get(victim).balance == 0
    assert vm.state.get(victim).code == b""


def test_logs_are_emitted_and_reverted_with_the_frame():
    code = _asm(("push", 0xAB), ("push", 0), 0x52,
                ("push", 0xBEEF),                  # topic
                ("push", 32), ("push", 0), 0xA1,   # LOG1(mem[0:32])
                0x00)
    res, vm = _run(code, gas=100_000)
    assert res.success and len(res.logs) == 1
    addr, topics, data = res.logs[0]
    assert topics == [0xBEEF] and data[-1] == 0xAB
    # a reverting frame keeps no logs
    code_rev = _asm(("push", 0), ("push", 0), 0xA0, ("push", 0),
                    ("push", 0), 0xFD)
    res2, vm2 = _run(code_rev, gas=100_000)
    assert not res2.success and vm2.logs == []


# -- precompiles ------------------------------------------------------------


def _call_precompile(pid, data, gas=10_000_000):
    vm = EVM()
    return vm.call(b"\xca" * 20, pid.to_bytes(20, "big"), 0, data, gas)


def test_precompile_ecrecover_matches_our_secp256k1():
    priv = 0xB0B
    digest = keccak256(b"vm-ecrecover")
    sig = secp256k1.sign(digest, priv)
    data = (digest + (27 + sig.v).to_bytes(32, "big")
            + sig.r.to_bytes(32, "big") + sig.s.to_bytes(32, "big"))
    res = _call_precompile(1, data)
    assert res.success
    assert res.output[12:] == bytes(secp256k1.priv_to_address(priv))
    # corrupted digest recovers a DIFFERENT address (or nothing)
    res_bad = _call_precompile(1, b"\x01" * 32 + data[32:])
    assert res_bad.output != res.output


def test_precompile_sha256_identity_modexp():
    res = _call_precompile(2, b"abc")
    import hashlib

    assert res.output == hashlib.sha256(b"abc").digest()
    res = _call_precompile(4, b"zzz")
    assert res.output == b"zzz"
    # modexp: 3^5 mod 7 = 5
    data = ((1).to_bytes(32, "big") + (1).to_bytes(32, "big")
            + (1).to_bytes(32, "big") + b"\x03" + b"\x05" + b"\x07")
    res = _call_precompile(5, data)
    assert res.output == b"\x05"


def test_precompile_bn256_trio_matches_our_curve_stack():
    g = bn256.G1_GEN
    g2 = bn256.g1_mul(2, g)
    data = (g[0].to_bytes(32, "big") + g[1].to_bytes(32, "big")
            + g[0].to_bytes(32, "big") + g[1].to_bytes(32, "big"))
    res = _call_precompile(6, data)          # G + G
    assert res.success
    assert res.output == (g2[0].to_bytes(32, "big")
                          + g2[1].to_bytes(32, "big"))
    res = _call_precompile(7, data[:64] + (3).to_bytes(32, "big"))  # 3·G
    g3 = bn256.g1_mul(3, g)
    assert res.output == (g3[0].to_bytes(32, "big")
                          + g3[1].to_bytes(32, "big"))
    # pairing: e(aP, Q)·e(-P, aQ) == 1
    a = 777
    p1 = bn256.g1_mul(a, g)
    q1 = bn256.G2_GEN
    p2 = bn256.g1_neg(g)
    q2 = bn256.g2_mul(a, q1)

    def enc_pair(p, q):
        (qx, qy) = q
        return (p[0].to_bytes(32, "big") + p[1].to_bytes(32, "big")
                + qx.b.to_bytes(32, "big") + qx.a.to_bytes(32, "big")
                + qy.b.to_bytes(32, "big") + qy.a.to_bytes(32, "big"))

    res = _call_precompile(8, enc_pair(p1, q1) + enc_pair(p2, q2))
    assert res.success
    assert int.from_bytes(res.output, "big") == 1
    # tampered pairing fails the check (returns 0, still succeeds)
    res_bad = _call_precompile(8, enc_pair(p1, q1) + enc_pair(g, q2))
    assert res_bad.success
    assert int.from_bytes(res_bad.output, "big") == 0
    # a not-on-curve point is a precompile FAILURE, not a false result
    bad = b"\x01" * 64 + enc_pair(p1, q1)[64:]
    res_err = _call_precompile(8, bad + enc_pair(p2, q2))
    assert not res_err.success


def test_call_gas_uses_63_64_rule():
    state = StateDB()
    spender = b"\x66" * 20
    # callee burns all its gas in an infinite loop
    _install(state, spender, _asm(0x5B, ("push", 0), 0x56))
    code = _asm(("push", 0), ("push", 0), ("push", 0), ("push", 0),
                ("push", 0), ("push", int.from_bytes(spender, "big")),
                ("push", UINT_MAX), 0xF1,   # request ALL gas
                ("push", 0), 0x52, ("push", 32), ("push", 0), 0xF3)
    res, _ = _run(code, state=state, gas=300_000)
    # the callee fails (out of gas) but the caller retains its 1/64
    assert res.success
    assert int.from_bytes(res.output, "big") == 0


def test_delegatecall_to_precompile_runs_the_precompile():
    """geth checks the precompile set before any code lookup — the
    identity precompile must answer DELEGATECALL/CALLCODE too."""
    code = _asm(("push", 0x61626364), ("push", 0), 0x52,   # mem = ..abcd
                ("push", 0), ("push", 0),                  # out 0/0
                ("push", 4), ("push", 28),                 # in 28/4
                ("push", 4),                               # address 0x04
                ("push", 100_000), 0xF4,                   # DELEGATECALL
                0x50,                                      # POP success
                0x3D, ("push", 0), 0x52,                   # RETURNDATASIZE
                ("push", 32), ("push", 0), 0xF3)
    res, _ = _run(code, gas=500_000)
    assert res.success
    assert int.from_bytes(res.output, "big") == 4


def test_selfdestruct_to_fresh_heir_charges_newaccount():
    state = StateDB()
    victim = b"\x44" * 20
    heir = b"\x77" * 20  # does not exist
    _install(state, victim,
             _asm(("push", int.from_bytes(heir, "big")), 0xFF), balance=5)
    vm = EVM(state=state)
    res = vm.call(b"\xca" * 20, victim, 0, b"", 100_000)
    assert res.success
    # PUSH20 (3) + SELFDESTRUCT 5000 + 25000 new-account surcharge
    assert 100_000 - res.gas_left == 3 + 5000 + 25000
    assert state.get(heir).balance == 5


def test_memory_expansion_gas_is_quadratic_exact():
    # MSTORE at 992 expands to 32 words: mem cost 3*32 + 32*32//512 = 98.
    # The second MSTORE at offset 0 fits inside the already-paid region,
    # so it must charge ZERO memory gas — expansion is charged on the
    # delta, not re-charged per touch.
    code = _asm(("push", 1), ("push", 992), 0x52,
                ("push", 1), ("push", 0), 0x52, 0x00)
    res, _ = _run(code, gas=10_000)
    mem = 3 * 32 + 32 * 32 // 512
    want = (3 + 3 + 3 + mem) + (3 + 3 + 3)  # second MSTORE: no mem gas
    assert res.success and 10_000 - res.gas_left == want


def test_callcode_uses_callers_storage():
    state = StateDB()
    lib = b"\x88" * 20
    _install(state, lib, _asm(("push", 42), ("push", 0), 0x55, 0x00))
    me = b"\xc0" * 20
    code = _asm(("push", 0), ("push", 0), ("push", 0), ("push", 0),
                ("push", 0), ("push", int.from_bytes(lib, "big")),
                ("push", 100_000), 0xF2, 0x00)
    res, vm = _run(code, state=state, gas=500_000)
    assert res.success
    assert vm.state.get(me).storage.get(0) == 42    # OUR storage
    assert vm.state.get(lib).storage == {}


def test_blockhash_window_and_env():
    env = Env(number=300, timestamp=777)
    # BLOCKHASH(number-1), BLOCKHASH far outside the 256 window -> 0,
    # NUMBER and TIMESTAMP straight from the env
    code = _asm(("push", 299), 0x40, ("push", 0), 0x52,
                ("push", 43), 0x40, ("push", 32), 0x52,
                0x43, ("push", 64), 0x52,        # NUMBER
                0x42, ("push", 96), 0x52,        # TIMESTAMP
                ("push", 128), ("push", 0), 0xF3)
    res, _ = _run(code, env=env, gas=100_000)
    assert res.success
    assert res.output[:32] == env.blockhash(299)
    assert res.output[32:64] == b"\x00" * 32   # outside the 256 window
    assert int.from_bytes(res.output[64:96], "big") == 300
    assert int.from_bytes(res.output[96:128], "big") == 777


def test_returndatacopy_out_of_bounds_is_exceptional():
    # no prior call: returndata is empty; copying 1 byte must abort
    code = _asm(("push", 1), ("push", 0), ("push", 0), 0x3E, 0x00)
    res, _ = _run(code, gas=100_000)
    assert not res.success and res.gas_left == 0


def test_extcodecopy_and_extcodesize():
    state = StateDB()
    other = b"\x99" * 20
    _install(state, other, b"\xde\xad\xbe\xef")
    code = _asm(("push", int.from_bytes(other, "big")), 0x3B,  # EXTCODESIZE
                ("push", 0), 0x52,
                ("push", 4), ("push", 0), ("push", 60),
                ("push", int.from_bytes(other, "big")), 0x3C,  # EXTCODECOPY
                ("push", 64), ("push", 0), 0xF3)
    res, _ = _run(code, state=state, gas=100_000)
    assert res.success
    assert int.from_bytes(res.output[:32], "big") == 4
    assert res.output[32 + 28:32 + 32] == b"\xde\xad\xbe\xef"


def test_modexp_zero_modulus_and_empty_output():
    # modulus 0 -> zero-filled output of m_len
    data = ((1).to_bytes(32, "big") + (1).to_bytes(32, "big")
            + (4).to_bytes(32, "big") + b"\x03" + b"\x05"
            + b"\x00\x00\x00\x00")
    res = _call_precompile(5, data)
    assert res.success and res.output == b"\x00" * 4
    # m_len 0 -> EMPTY output (not a zero word)
    data = ((1).to_bytes(32, "big") + (1).to_bytes(32, "big")
            + (0).to_bytes(32, "big") + b"\x03" + b"\x05")
    res = _call_precompile(5, data)
    assert res.success and res.output == b""


def test_stack_limit_enforced():
    code = _asm(*[("push", 1)] * 1025)
    res, _ = _run(code, gas=10_000)
    assert not res.success


def test_create_inside_staticcall_is_blocked():
    state = StateDB()
    creator = b"\xaa" * 20
    _install(state, creator,
             _asm(("push", 0), ("push", 0), ("push", 0), 0xF0, 0x00))
    code = _asm(("push", 0), ("push", 0), ("push", 0), ("push", 0),
                ("push", int.from_bytes(creator, "big")),
                ("push", 200_000), 0xFA,
                ("push", 0), 0x52, ("push", 32), ("push", 0), 0xF3)
    res, vm = _run(code, state=state, gas=500_000)
    assert res.success
    assert int.from_bytes(res.output, "big") == 0  # inner frame aborted
    assert vm.state.get(creator).nonce == 0        # no CREATE happened
