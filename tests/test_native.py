"""Native runtime component tests: the C keccak + bulk MPT builder vs
their pure-Python twins (differential, randomized, plus the 1 MiB body
the scalability fix exists for)."""

import time

import numpy as np
import pytest

from gethsharding_tpu import native
from gethsharding_tpu.core.derive_sha import chunk_root, derive_sha
from gethsharding_tpu.core.trie import Trie
from gethsharding_tpu.crypto.keccak import keccak256, keccak256_py
from gethsharding_tpu.utils.rlp import int_to_big_endian, rlp_encode

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def test_keccak_native_matches_python():
    rng = np.random.default_rng(5)
    for length in (0, 1, 55, 56, 135, 136, 137, 272, 1000):
        data = bytes(rng.integers(0, 255, length, dtype=np.uint8))
        assert native.keccak256(data) == keccak256_py(data), length


def test_keccak_batch():
    rng = np.random.default_rng(6)
    msgs = rng.integers(0, 255, (64, 96), dtype=np.uint8)
    out = native.keccak256_batch(msgs)
    for i in range(64):
        assert bytes(out[i]) == keccak256_py(bytes(msgs[i]))


def _python_trie_root(pairs):
    trie = Trie()
    for k, v in pairs:
        trie.update(k, v)
    return trie.root_hash()


def test_mpt_root_matches_python_trie_randomized():
    rng = np.random.default_rng(7)
    for trial in range(8):
        n = int(rng.integers(1, 600))
        pairs = {}
        for _ in range(n):
            klen = int(rng.integers(1, 9))
            key = bytes(rng.integers(0, 255, klen, dtype=np.uint8))
            val = bytes(rng.integers(0, 255, int(rng.integers(1, 65)),
                                     dtype=np.uint8))
            pairs[key] = val
        items = list(pairs.items())
        got = native.mpt_root([k for k, _ in items], [v for _, v in items])
        assert got == _python_trie_root(items), f"trial {trial}"


def test_mpt_root_long_string_values():
    """Values of 56-64 bytes need RLP's long-string form inside nodes."""
    for vlen in (55, 56, 60, 64):
        pairs = [(bytes([i]), bytes([i]) * vlen) for i in range(5)]
        got = native.mpt_root([k for k, _ in pairs], [v for _, v in pairs])
        assert got == _python_trie_root(pairs), vlen


def test_mpt_root_duplicate_keys_last_wins():
    keys = [b"\x01", b"\x02", b"\x01"]
    vals = [b"a", b"b", b"c"]
    got = native.mpt_root(keys, vals)
    assert got == _python_trie_root([(b"\x01", b"c"), (b"\x02", b"b")])


def test_mpt_root_empty_and_single():
    from gethsharding_tpu.core.trie import EMPTY_ROOT

    assert native.mpt_root([], []) == EMPTY_ROOT
    assert native.mpt_root([b"\x80"], [b"\x05"]) == _python_trie_root(
        [(b"\x80", b"\x05")])


def test_derive_sha_native_matches_python_across_sizes():
    # crosses every rlp(uint) key-shape boundary (1/2/3-byte keys)
    for n in (1, 2, 64, 127, 128, 129, 255, 256, 300):
        items = [rlp_encode(bytes([i % 256])) for i in range(n)]
        keys = [rlp_encode(int_to_big_endian(i)) for i in range(n)]
        assert native.mpt_root(keys, items) == _python_trie_root(
            list(zip(keys, items))), n


@pytest.mark.slow  # ~5 s capacity case; derive_sha parity across sizes stays fast
def test_chunk_root_one_mebibyte_body():
    """The protocol's collation size cap (collation.go:45) is now
    computable in seconds instead of minutes."""
    body = bytes(range(256)) * (2 ** 20 // 256)
    t0 = time.monotonic()
    root = chunk_root(body)
    elapsed = time.monotonic() - t0
    assert len(root) == 32
    assert elapsed < 30, f"1 MiB chunk root took {elapsed:.1f}s"
    # spot-check against the python path on a prefix (full python would
    # take minutes — exactly the trap this fixes)
    prefix = body[:2048]
    import os

    items = [rlp_encode(int(b)) for b in prefix]
    keys = [rlp_encode(int_to_big_endian(i)) for i in range(len(prefix))]
    assert chunk_root(prefix) == _python_trie_root(list(zip(keys, items)))


def test_native_scrypt_romix_matches_openssl():
    """The native ROMix composed with PBKDF2 outer layers must equal
    hashlib.scrypt wherever OpenSSL accepts the parameters — the
    differential that licenses it for the parameter sets OpenSSL
    rejects (keystore.scrypt_kdf's wiki/light profile)."""
    import hashlib

    import pytest

    from gethsharding_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    for (n, r, p) in ((1024, 8, 1), (16, 1, 1), (256, 4, 2), (64, 2, 4)):
        want = hashlib.scrypt(b"pw", salt=b"salt123", n=n, r=r, p=p,
                              dklen=64, maxmem=2**31 - 1)
        blocks = hashlib.pbkdf2_hmac("sha256", b"pw", b"salt123", 1,
                                     p * 128 * r)
        mixed = native.scrypt_romix(blocks, p, n, r)
        got = hashlib.pbkdf2_hmac("sha256", b"pw", mixed, 1, 64)
        assert got == want, (n, r, p)
