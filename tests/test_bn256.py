"""bn256 pairing: curve/tower sanity, bilinearity, PairingCheck, BLS votes.

Kept intentionally small: the pure-Python final exponentiation costs seconds
per call. The TPU kernels are differential-tested against these primitives.
"""

import pytest

from gethsharding_tpu.crypto.bn256 import (
    ATE_LOOP_COUNT,
    Fp2,
    G1_GEN,
    G2_GEN,
    N,
    P,
    U,
    bls_aggregate_sigs,
    bls_keygen,
    bls_sign,
    bls_verify,
    bls_verify_aggregate,
    g1_add,
    g1_is_on_curve,
    g1_mul,
    g1_neg,
    g2_add,
    g2_is_on_curve,
    g2_mul,
    hash_to_g1,
    pairing_check,
)


def test_curve_parameters():
    # BN family relations pin u, p, n together
    assert P == 36 * U**4 + 36 * U**3 + 24 * U**2 + 6 * U + 1
    assert N == 36 * U**4 + 36 * U**3 + 18 * U**2 + 6 * U + 1
    assert ATE_LOOP_COUNT == 6 * U * U


def test_generators_on_curve_with_correct_order():
    # raw (unreduced) scalar muls — g1_mul/g2_mul reduce mod N, which would
    # make these assertions vacuous
    from gethsharding_tpu.crypto.bn256 import g1_mul_raw, g2_mul_raw

    assert g1_is_on_curve(G1_GEN)
    assert g2_is_on_curve(G2_GEN)
    assert g1_mul_raw(N, G1_GEN) is None
    assert g2_mul_raw(N, G2_GEN) is None


def test_group_arithmetic():
    a = g1_mul(7, G1_GEN)
    b = g1_mul(11, G1_GEN)
    assert g1_add(a, b) == g1_mul(18, G1_GEN)
    qa = g2_mul(7, G2_GEN)
    qb = g2_mul(11, G2_GEN)
    assert g2_add(qa, qb) == g2_mul(18, G2_GEN)


def test_fp2_arithmetic():
    x = Fp2(3, 5)
    assert (x * x.inv()) == Fp2.one()
    assert (x + x.neg()).is_zero()


def test_pairing_degenerate_identity():
    # e(P, Q)·e(-P, Q) == 1 — the canonical precompile self-check
    assert pairing_check([(G1_GEN, G2_GEN), (g1_neg(G1_GEN), G2_GEN)])


def test_pairing_bilinearity():
    # e(aP, bQ)·e(-abP, Q) == 1  <=>  e(aP,bQ) == e(P,Q)^(ab)
    a, b = 6, 7
    assert pairing_check(
        [(g1_mul(a, G1_GEN), g2_mul(b, G2_GEN)),
         (g1_neg(g1_mul(a * b, G1_GEN)), G2_GEN)]
    )


def test_pairing_nondegenerate():
    # e(P, Q) != 1 for generators
    assert not pairing_check([(G1_GEN, G2_GEN)])


def test_pairing_infinity_contributes_identity():
    assert pairing_check([(None, G2_GEN), (G1_GEN, None)])


def test_pairing_rejects_off_curve():
    with pytest.raises(ValueError, match="not on curve"):
        pairing_check([((1, 3), G2_GEN)])


def test_hash_to_g1_on_curve_and_deterministic():
    h1 = hash_to_g1(b"header hash")
    h2 = hash_to_g1(b"header hash")
    assert h1 == h2
    assert g1_is_on_curve(h1)
    assert hash_to_g1(b"other") != h1


def test_bls_single_vote():
    sk, pk = bls_keygen(b"notary-0")
    msg = b"collation header 0x42"
    sig = bls_sign(msg, sk)
    assert bls_verify(msg, sig, pk)
    assert not bls_verify(b"forged header", sig, pk)


def test_bls_aggregate_votes():
    # 4 notaries vote on the same header; one aggregated pair-check verifies
    msg = b"canonical header"
    keys = [bls_keygen(bytes([i])) for i in range(4)]
    sigs = [bls_sign(msg, sk) for sk, _ in keys]
    agg = bls_aggregate_sigs(sigs)
    assert bls_verify_aggregate(msg, agg, [pk for _, pk in keys])
    # dropping a signer's sig breaks the aggregate
    bad = bls_aggregate_sigs(sigs[:3])
    assert not bls_verify_aggregate(msg, bad, [pk for _, pk in keys])


def test_bls_rejects_infinity_and_empty_committee():
    # regression: infinity sig/pk or an empty committee must never verify
    assert not bls_verify(b"m", None, None)
    assert not bls_verify(b"m", None, G2_GEN)
    assert not bls_verify(b"m", G1_GEN, None)
    assert not bls_verify_aggregate(b"m", bls_aggregate_sigs([]), [])


def test_pairing_rejects_non_subgroup_g2():
    # Find a point on the twist curve but outside the order-n subgroup by
    # scanning x and taking an Fp2 square root of x^3 + b'. The twist has
    # order n*(2p-n), so almost every curve point is outside the subgroup.
    from gethsharding_tpu.crypto.bn256 import B2, g2_is_on_curve, g2_mul_raw

    half = pow(2, P - 2, P)
    for xi in range(1, 200):
        x = Fp2(xi, 0)
        rhs = x * x * x + B2
        a, b = rhs.a, rhs.b
        norm = (a * a + b * b) % P
        s = pow(norm, (P + 1) // 4, P)
        if s * s % P != norm:
            continue
        c2 = (a + s) * half % P
        c = pow(c2, (P + 1) // 4, P)
        if c * c % P != c2 or c == 0:
            c2 = (a - s) * half % P
            c = pow(c2, (P + 1) // 4, P)
            if c * c % P != c2 or c == 0:
                continue
        d = b * half % P * pow(c, P - 2, P) % P
        cand = (x, Fp2(c, d))
        if g2_is_on_curve(cand) and g2_mul_raw(N, cand) is not None:
            with pytest.raises(ValueError, match="subgroup"):
                pairing_check([(G1_GEN, cand)])
            return
    pytest.fail("no non-subgroup twist point found in scan range")


def test_bls_verify_rejects_malformed_points_without_crashing():
    # network-supplied garbage must be a rejection, not an exception
    assert not bls_verify(b"m", (1, 3), G2_GEN)  # off-curve G1
    bad_g2 = (Fp2(1, 2), Fp2(3, 4))
    assert not bls_verify(b"m", G1_GEN, bad_g2)


def test_bls_proof_of_possession():
    from gethsharding_tpu.crypto.bn256 import (
        bls_prove_possession,
        bls_verify_possession,
        g2_add,
        g2_neg,
    )

    sk, pk = bls_keygen(b"honest")
    pop = bls_prove_possession(sk, pk)
    assert bls_verify_possession(pk, pop)
    # rogue key pk' = sk2*G2 - pk has no provable secret: its owner cannot
    # produce a valid PoP with any sk it knows
    sk2, pk2 = bls_keygen(b"attacker")
    rogue = g2_add(pk2, g2_neg(pk))
    assert not bls_verify_possession(rogue, bls_prove_possession(sk2, rogue))


@pytest.mark.slow  # ~4 s host scalar pairing; the BLS verify tests exercise the same path fast
def test_optimal_ate_check_parity():
    """pairing_check_optimal (6u+2 loop + frobenius lines, the batched
    kernel's scalar twin) agrees with the plain-ate pairing_check."""
    from gethsharding_tpu.crypto.bn256 import (
        G1_GEN,
        G2_GEN,
        g1_mul,
        g1_neg,
        g2_mul,
        pairing_check,
        pairing_check_optimal,
    )

    a = 987654321
    accept = [(g1_mul(a, G1_GEN), G2_GEN), (g1_neg(G1_GEN), g2_mul(a, G2_GEN))]
    reject = [(g1_mul(a + 1, G1_GEN), G2_GEN),
              (g1_neg(G1_GEN), g2_mul(a, G2_GEN))]
    assert pairing_check_optimal(accept) is pairing_check(accept) is True
    assert pairing_check_optimal(reject) is pairing_check(reject) is False
    # infinity pairs contribute identity in both variants
    assert pairing_check_optimal([(None, G2_GEN), (G1_GEN, None)]) is True
