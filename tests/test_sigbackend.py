"""The sigbackend seam: python (scalar) and jax (batched TPU kernels)
backends must agree on every output — the framework's equivalent of the
reference's cgo-vs-pure-Go crypto build matrix.

Also covers the notary's proposer-signature gate through both backends.
"""

import numpy as np
import pytest

from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.sigbackend import get_backend


def _ecdsa_cases():
    digests, sigs, expected = [], [], []
    for i in range(4):
        priv = int.from_bytes(keccak256(b"sb" + bytes([i])), "big") % ecdsa.N
        msg = keccak256(b"m" + bytes([i]))
        sig = ecdsa.sign(msg, priv)
        digests.append(msg)
        sigs.append(sig.to_bytes65())
        expected.append(ecdsa.priv_to_address(priv))
    # invalid rows: truncated sig, zeroed r
    digests.append(keccak256(b"x"))
    sigs.append(b"\x00" * 10)
    expected.append(None)
    digests.append(keccak256(b"y"))
    sigs.append(b"\x00" * 64 + b"\x00")
    expected.append(None)
    return digests, sigs, expected


@pytest.mark.parametrize("name", ["python", "jax"])
def test_ecrecover_addresses(name):
    backend = get_backend(name)
    digests, sigs, expected = _ecdsa_cases()
    got = backend.ecrecover_addresses(digests, sigs)
    assert got == expected


@pytest.mark.parametrize("name", ["python", "jax"])
def test_bls_aggregate(name):
    backend = get_backend(name)
    header = b"header"
    keys = [bls.bls_keygen(bytes([i])) for i in range(3)]
    agg_sig = bls.bls_aggregate_sigs(
        [bls.bls_sign(header, sk) for sk, _ in keys])
    agg_pk = bls.bls_aggregate_pks([pk for _, pk in keys])
    tampered = bls.g1_add(agg_sig, bls.G1_GEN)
    got = backend.bls_verify_aggregates(
        [header, header, header],
        [agg_sig, tampered, None],
        [agg_pk, agg_pk, agg_pk])
    assert got == [True, False, False]


def test_backends_agree_on_random_batch():
    digests, sigs, _ = _ecdsa_cases()
    py = get_backend("python").ecrecover_addresses(digests, sigs)
    jx = get_backend("jax").ecrecover_addresses(digests, sigs)
    assert py == jx


def test_notary_rejects_bad_proposer_signature():
    """End-to-end through the actor: a record whose signature does not
    recover to the proposer address must be rejected before voting."""
    from gethsharding_tpu.core.types import CollationHeader
    from gethsharding_tpu.smc.state_machine import CollationRecord
    from gethsharding_tpu.utils.hexbytes import Address20, Hash32
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.params import ETHER

    chain = SimulatedMainchain()
    client = SMCClient(backend=chain)
    chain.fund(client.account(), 2000 * ETHER)
    notary = Notary(client=client, shard=Shard(0, MemoryKV()))

    priv = 0xBEEF
    proposer = ecdsa.priv_to_address(priv)
    root = Hash32(keccak256(b"root"))
    unsigned = CollationHeader(shard_id=0, chunk_root=root, period=1,
                               proposer_address=proposer)
    good_sig = ecdsa.sign(bytes(unsigned.hash()), priv).to_bytes65()
    bad_sig = ecdsa.sign(bytes(unsigned.hash()), priv + 1).to_bytes65()

    good = CollationRecord(chunk_root=root, proposer=proposer,
                           signature=good_sig)
    bad = CollationRecord(chunk_root=root, proposer=proposer,
                          signature=bad_sig)
    results = notary.verify_proposer_signatures(
        [(0, 1, good), (0, 1, bad)])
    assert results == [True, False]


@pytest.mark.parametrize("name", ["python", "jax"])
def test_bls_committee_rows(name):
    """Committee-level verification: aggregation + pairing in one call.

    Rows cover: honest multi-voter, single voter, duplicate pubkey
    (doubling path), empty committee (reject), tampered message, and a
    signature from a key outside the pk row."""
    backend = get_backend(name)
    msgs, sig_rows, pk_rows = [], [], []

    def committee(tag, n, dup=False):
        keys = [bls.bls_keygen(tag + bytes([j])) for j in range(n)]
        if dup and n >= 2:
            keys[1] = keys[0]
        sigs = [bls.bls_sign(tag, sk) for sk, _ in keys]
        return sigs, [pk for _, pk in keys]

    s, p = committee(b"row0", 5)
    msgs.append(b"row0"); sig_rows.append(s); pk_rows.append(p)
    s, p = committee(b"row1", 1)
    msgs.append(b"row1"); sig_rows.append(s); pk_rows.append(p)
    s, p = committee(b"row2", 4, dup=True)
    msgs.append(b"row2"); sig_rows.append(s); pk_rows.append(p)
    msgs.append(b"row3"); sig_rows.append([]); pk_rows.append([])
    s, p = committee(b"row4", 3)
    msgs.append(b"not-row4"); sig_rows.append(s); pk_rows.append(p)
    s, p = committee(b"row5", 3)
    s[0] = bls.bls_sign(b"row5", bls.bls_keygen(b"outsider")[0])
    msgs.append(b"row5"); sig_rows.append(s); pk_rows.append(p)

    got = backend.bls_verify_committees(msgs, sig_rows, pk_rows)
    assert got == [True, True, True, False, False, False]


def test_bls_committee_backends_agree():
    msgs, sig_rows, pk_rows = [], [], []
    for i in range(3):
        tag = b"agree-%d" % i
        keys = [bls.bls_keygen(tag + bytes([j])) for j in range(i + 1)]
        sig_rows.append([bls.bls_sign(tag, sk) for sk, _ in keys])
        pk_rows.append([pk for _, pk in keys])
        msgs.append(tag)
    py = get_backend("python").bls_verify_committees(msgs, sig_rows, pk_rows)
    jx = get_backend("jax").bls_verify_committees(msgs, sig_rows, pk_rows)
    assert py == jx == [True, True, True]


def test_bls_committee_u16_wire_verdict_identical(monkeypatch):
    """GETHSHARDING_TPU_WIRE=u16 ships limb planes as uint16 and widens
    on device — verdicts must be identical to the int32 wire, including
    the tampered-row reject."""
    from gethsharding_tpu.sigbackend import JaxSigBackend

    monkeypatch.setenv("GETHSHARDING_TPU_WIRE", "u16")
    backend = JaxSigBackend()
    assert backend._wire_u16
    msgs, sig_rows, pk_rows = [], [], []
    for i in range(3):
        tag = b"wire-%d" % i
        keys = [bls.bls_keygen(tag + bytes([j])) for j in range(4)]
        sigs = [bls.bls_sign(tag, sk) for sk, _ in keys]
        if i == 1:
            sigs[2] = bls.bls_sign(b"tampered", keys[2][0])
        sig_rows.append(sigs)
        pk_rows.append([pk for _, pk in keys])
        msgs.append(tag)
    got = backend.bls_verify_committees(msgs, sig_rows, pk_rows)
    # oracle: the scalar python backend — get_backend("jax") here would
    # construct (and cache process-wide) a u16-wired singleton while the
    # env var is active, comparing u16 against itself
    want = get_backend("python").bls_verify_committees(
        msgs, sig_rows, pk_rows)
    assert got == want == [True, False, True]
    # pk-row cache under the u16 wire: entries are stored uint16 at miss
    # time; the hit path must return identical verdicts
    keys = [f"wire-row-{i}" for i in range(3)]
    miss = backend.bls_verify_committees(msgs, sig_rows, pk_rows,
                                         pk_row_keys=keys)
    hit = backend.bls_verify_committees(msgs, sig_rows, pk_rows,
                                        pk_row_keys=keys)
    assert miss == hit == want
    assert backend._pk_row_cache[keys[0]][0].dtype.name == "uint16"


def test_bls_committee_pk_row_cache_consistency():
    """The pubkey-row limb cache (jax backend): warm calls with row keys
    return byte-identical verdicts to the keyless path, a changed row
    under a NEW key is marshalled fresh, and the python backend accepts
    the same signature."""
    backend = get_backend("jax")
    msgs, sig_rows, pk_rows = [], [], []
    for i in range(3):
        tag = b"rowcache-%d" % i
        keys = [bls.bls_keygen(tag + bytes([j])) for j in range(2 + i)]
        sig_rows.append([bls.bls_sign(tag, sk) for sk, _ in keys])
        pk_rows.append([pk for _, pk in keys])
        msgs.append(tag)
    row_keys = [("rc", i) for i in range(3)]

    cold = backend.bls_verify_committees(msgs, sig_rows, pk_rows,
                                         pk_row_keys=row_keys)
    warm = backend.bls_verify_committees(msgs, sig_rows, pk_rows,
                                         pk_row_keys=row_keys)
    keyless = backend.bls_verify_committees(msgs, sig_rows, pk_rows)
    assert cold == warm == keyless == [True, True, True]

    # a forged signature still fails on the warm (cached-pk) path
    forged = [list(r) for r in sig_rows]
    forged[1][0] = bls.bls_sign(b"forged", bls.bls_keygen(b"evil")[0])
    got = backend.bls_verify_committees(msgs, forged, pk_rows,
                                        pk_row_keys=row_keys)
    assert got == [True, False, True]

    # new committee under a NEW key: marshalled fresh, verdict correct
    keys2 = [bls.bls_keygen(b"fresh-row" + bytes([j])) for j in range(4)]
    msgs2 = [b"fresh-msg"]
    sigs2 = [[bls.bls_sign(b"fresh-msg", sk) for sk, _ in keys2]]
    pks2 = [[pk for _, pk in keys2]]
    assert backend.bls_verify_committees(
        msgs2, sigs2, pks2, pk_row_keys=[("rc", "new")]) == [True]

    # python backend accepts (and ignores) the keys
    assert get_backend("python").bls_verify_committees(
        msgs, sig_rows, pk_rows, pk_row_keys=row_keys) == [True, True, True]
