"""race-guard + racecheck: lockset data-race analysis, cross-validated.

Four layers of coverage:

- the LIVE TREE: the race model over the real repo is non-vacuous (the
  threaded planes really are seen, the known-guarded attributes really
  classify as guarded) — the zero-new-findings gate itself rides
  tests/test_analysis.py's shardlint gate, which now includes
  ``race-guard`` and ``layering``;
- per-IDIOM fixtures: one known-bad and one known-good snippet per
  idiom the rule models (guarded, init-only, snapshot publication,
  double-checked lazy init, cross-thread future handoff, atomic
  types, entry-lockset helpers, typed container elements);
- the RUNTIME sanitizer: a seeded injected race across real threads
  must be caught (shared attr, empty lockset), a guarded fixture must
  record its lock, and the static/runtime cross-check must flag a
  runtime-unguarded write the static map calls guarded;
- REGRESSIONS for the true races this PR fixed: concurrent hammers on
  the previously-unguarded counters must now count exactly.
"""

import json
import textwrap
import threading
from pathlib import Path

import pytest

from gethsharding_tpu.analysis import Corpus, run_rules
from gethsharding_tpu.analysis.__main__ import main as cli_main
from gethsharding_tpu.analysis.races import (
    AttrVerdict,
    RaceModel,
    build_race_model,
)

REPO = Path(__file__).resolve().parents[1]


def make_corpus(tmp_path, files):
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src), encoding="utf-8")
    return Corpus.load(tmp_path)


def idents(findings, rule=None):
    return {f.ident for f in findings if rule is None or f.rule == rule}


# -- the live tree -----------------------------------------------------------

@pytest.fixture(scope="module")
def live_model():
    return build_race_model(Corpus.load(REPO))


def test_live_model_sees_the_threaded_planes(live_model):
    """Non-vacuity: the closure really marks the serving/fleet/slo/
    tracing/rpc component classes thread-shared — a rule that sees no
    threads reports no races and proves nothing."""
    assert len(live_model.scoped_threaded) >= 20
    expect = {
        ("gethsharding_tpu/serving/queue.py", "AdmissionQueue"),
        ("gethsharding_tpu/serving/batcher.py", "MicroBatcher"),
        ("gethsharding_tpu/serving/pipeline.py", "PipelinedDispatcher"),
        ("gethsharding_tpu/fleet/router.py", "Replica"),
        ("gethsharding_tpu/fleet/router.py", "FleetRouter"),
        ("gethsharding_tpu/resilience/breaker.py", "CircuitBreaker"),
        ("gethsharding_tpu/resilience/watchdog.py", "DispatchWatchdog"),
        ("gethsharding_tpu/slo/tracker.py", "SLOTracker"),
        ("gethsharding_tpu/slo/tracker.py", "_Series"),
        ("gethsharding_tpu/tracing/tracer.py", "Tracer"),
        ("gethsharding_tpu/metrics.py", "Counter"),
        ("gethsharding_tpu/rpc/server.py", "RPCServer"),
        ("gethsharding_tpu/rpc/client.py", "RPCClient"),
    }
    assert expect <= live_model.scoped_threaded, \
        sorted(expect - live_model.scoped_threaded)


def test_live_model_classifies_known_attributes(live_model):
    """The model's verdicts on hand-audited attributes: the guards are
    REAL lock nodes (shared with the lock-order site map), the idioms
    classify as designed."""
    def cls_of(key):
        return live_model.attrs[key].classification

    # guarded: the admission queue's accounting under its lock
    rows = live_model.attrs[
        "gethsharding_tpu/serving/queue.py::AdmissionQueue._rows"]
    assert rows.classification == "guarded"
    assert rows.guards == frozenset(
        {"gethsharding_tpu/serving/queue.py::AdmissionQueue._lock"})
    # guarded through the ENTRY lockset: _set_state_locked is only
    # ever called under Replica._lock — the fixpoint must see it
    state = live_model.attrs[
        "gethsharding_tpu/fleet/router.py::Replica.state"]
    assert state.classification == "guarded"
    assert state.guards == frozenset(
        {"gethsharding_tpu/fleet/router.py::Replica._lock"})
    # guarded via a typed-local receiver: the SLO ring mutations behind
    # `with series.lock:` in SLOTracker.record
    assert cls_of("gethsharding_tpu/slo/tracker.py::_Series.good") \
        == "guarded"
    # snapshot publication: atomic rebinds stay findings-free
    assert cls_of("gethsharding_tpu/metrics.py::Gauge._value") \
        == "publication"
    assert cls_of(
        "gethsharding_tpu/fleet/router.py::Replica.last_metrics") \
        == "publication"
    # atomic-by-convention types
    assert cls_of(
        "gethsharding_tpu/fleet/router.py::FleetRouter._stop_sweeper") \
        == "atomic-type"
    # this PR's fixes hold: previously-racy counters are now guarded
    for fixed in (
            "gethsharding_tpu/rpc/server.py::RPCServer.p2p_relayed_sends",
            "gethsharding_tpu/serving/batcher.py::"
            "MicroBatcher.dispatch_counts",
            "gethsharding_tpu/rpc/client.py::RPCClient._head_subscribers",
            "gethsharding_tpu/slo/tracker.py::_Series.last_gauge",
            "gethsharding_tpu/slo/tracker.py::_Series.breached",
            "gethsharding_tpu/slo/tracker.py::SLOTracker._hooks",
            "gethsharding_tpu/metrics.py::InfluxLineExporter.pushes"):
        assert cls_of(fixed) == "guarded", fixed


def test_live_racy_findings_are_exactly_the_baselined_ones(live_model):
    racy = {k for k, v in live_model.attrs.items()
            if v.classification == "racy"}
    data = json.loads(
        (REPO / "gethsharding_tpu/analysis/baseline.json").read_text())
    baselined = {key.split("::", 1)[1] for key in data["findings"]
                 if key.startswith("race-guard::")}
    assert racy == baselined, (racy, baselined)


# -- per-idiom fixtures ------------------------------------------------------

_THREADED_PREAMBLE = """
    import threading

    class Svc:
        def __init__(self):
            self._lock = threading.Lock()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self.count = 0
            self.snapshot = ()

        def _run(self):
            pass
"""


def test_race_guard_flags_unguarded_rmw(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/bad.py":
                                    _THREADED_PREAMBLE + """
        def bump(self):
            self.count += 1
    """})
    got = idents(run_rules(corpus, ["race-guard"]))
    assert got == {"Svc.count"}


def test_race_guard_guarded_rmw_is_clean(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/good.py":
                                    _THREADED_PREAMBLE + """
        def bump(self):
            with self._lock:
                self.count += 1
    """})
    assert run_rules(corpus, ["race-guard"]) == []


def test_race_guard_entry_lockset_helper_is_clean(tmp_path):
    """A private helper only ever called under the lock inherits the
    guard through the caller-intersection fixpoint."""
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/entry.py":
                                    _THREADED_PREAMBLE + """
        def bump(self):
            with self._lock:
                self._bump_locked()

        def poke(self):
            with self._lock:
                self._bump_locked()

        def _bump_locked(self):
            self.count += 1
    """})
    assert run_rules(corpus, ["race-guard"]) == []


def test_race_guard_helper_with_one_unlocked_caller_is_flagged(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/leak.py":
                                    _THREADED_PREAMBLE + """
        def bump(self):
            with self._lock:
                self._bump_locked()

        def oops(self):
            self._bump_locked()

        def _bump_locked(self):
            self.count += 1
    """})
    assert idents(run_rules(corpus, ["race-guard"])) == {"Svc.count"}


def test_race_guard_init_only_is_clean(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/init.py": """
        import threading

        class Svc:
            def __init__(self):
                self._thread = threading.Thread(target=self._run)
                self.config = {"a": 1}
                self.config["b"] = 2

            def _run(self):
                return self.config
    """})
    assert run_rules(corpus, ["race-guard"]) == []


def test_race_guard_snapshot_publication_is_clean(tmp_path):
    """The repo's snapshot-swap idiom: rebinding a fresh immutable
    value is an atomic publication under the GIL, not a race."""
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/snap.py":
                                    _THREADED_PREAMBLE + """
        def publish(self, rows):
            self.snapshot = tuple(rows)
    """})
    assert run_rules(corpus, ["race-guard"]) == []


def test_race_guard_unguarded_lazy_init_is_flagged(tmp_path):
    """`if self._cache is None: self._cache = ...` with no lock is the
    double-checked idiom MINUS the check that makes it safe."""
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/lazy.py":
                                    _THREADED_PREAMBLE + """
        def cache(self):
            if self.snapshot is None:
                self.snapshot = self._build()
            return self.snapshot

        def _build(self):
            return ()
    """})
    assert idents(run_rules(corpus, ["race-guard"])) == {"Svc.snapshot"}


def test_race_guard_double_checked_lazy_init_is_clean(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/dcheck.py":
                                    _THREADED_PREAMBLE + """
        def cache(self):
            if self.snapshot is None:
                with self._lock:
                    if self.snapshot is None:
                        self.snapshot = self._build()
            return self.snapshot

        def _build(self):
            return ()
    """})
    assert run_rules(corpus, ["race-guard"]) == []


def test_race_guard_mutating_call_is_flagged(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/mut.py": """
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = threading.Thread(target=self._run)
                self.subs = []

            def _run(self):
                pass

            def register(self, cb):
                self.subs.append(cb)
    """})
    assert idents(run_rules(corpus, ["race-guard"])) == {"Svc.subs"}


def test_race_guard_atomic_types_are_exempt(tmp_path):
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/atom.py": """
        import queue
        import threading

        class Svc:
            def __init__(self):
                self._thread = threading.Thread(target=self._run)
                self._stop = threading.Event()
                self._work = queue.Queue()

            def _run(self):
                pass

            def restart(self):
                self._stop = threading.Event()
                self._work = queue.Queue()
    """})
    assert run_rules(corpus, ["race-guard"]) == []


def test_race_guard_cross_thread_future_handoff_is_clean(tmp_path):
    """The serving tier's core idiom: a request object created by the
    caller, stamped by the flusher, resolved by the dispatch thread —
    writes to ANOTHER object's plain data attributes are out of the
    self-state model on purpose (the future's own lock serializes the
    visible handoff)."""
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/serving/hand.py": """
        import threading
        from concurrent.futures import Future

        class Request:
            def __init__(self):
                self.future = Future()
                self.t_taken = 0.0

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = threading.Thread(target=self._run)
                self._batch = []

            def _run(self):
                with self._lock:
                    batch = list(self._batch)
                for request in batch:
                    request.t_taken = 1.0
                    request.future.set_result([])

            def submit(self, request):
                with self._lock:
                    self._batch.append(request)
                return request.future
    """})
    assert run_rules(corpus, ["race-guard"]) == []


def test_race_guard_typed_container_elements_are_modeled(tmp_path):
    """The Replica idiom: the router mutates its replicas' attributes
    through a `List[Replica]`-annotated container — a read-modify-write
    there is a finding ON Replica even though the write site lives in
    Router."""
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/fleet/pool.py": """
        import threading
        from typing import List

        class Member:
            def __init__(self):
                self.hits = 0

        class Pool:
            def __init__(self, members: List[Member]):
                self.members = list(members)
                self._thread = threading.Thread(target=self._sweep)

            def _sweep(self):
                for member in self.members:
                    member.hits += 1
    """})
    assert idents(run_rules(corpus, ["race-guard"])) == {"Member.hits"}


def test_race_guard_lock_owner_without_threads_is_threaded(tmp_path):
    """A scoped class that allocates a lock declares itself shared —
    unguarded writes in it are findings even with no Thread ctor in
    sight (the CircuitBreaker shape: threads live in its callers)."""
    corpus = make_corpus(tmp_path, {"gethsharding_tpu/resilience/br.py": """
        import threading

        class Breaker:
            def __init__(self):
                self._lock = threading.Lock()
                self.faults = 0

            def record(self):
                self.faults += 1
    """})
    assert idents(run_rules(corpus, ["race-guard"])) == {"Breaker.faults"}


# -- the runtime sanitizer ---------------------------------------------------

@pytest.fixture
def racecheck_env():
    from gethsharding_tpu.analysis import lockcheck, racecheck

    if racecheck.active() or lockcheck.active():
        # session mode (GETHSHARDING_RACECHECK/LOCKCHECK=1): the
        # conftest recorder owns the patches with repo-only record
        # paths, so fixture locks created in tests/ would carry no
        # labels — these tests need an exclusive install
        pytest.skip("recorder session mode active; sanitizer tests "
                    "need an exclusive install")
    racecheck.install(classes=(),
                      record_paths=("gethsharding_tpu", "tests"))
    try:
        yield racecheck
    finally:
        racecheck.uninstall()


class _Unguarded:
    def __init__(self):
        self.counter = 0


class _Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self.counter = 0

    def bump(self):
        with self._lock:
            self.counter += 1


def _hammer(fn, threads=4, per_thread=200, seed=1234):
    """Seeded concurrent schedule: every thread performs a
    deterministic (seeded) number of calls, synchronized on a barrier
    so the interleaving really overlaps."""
    import random

    rng = random.Random(seed)
    counts = [per_thread + rng.randrange(8) for _ in range(threads)]
    barrier = threading.Barrier(threads)

    def work(n):
        barrier.wait()
        for _ in range(n):
            fn()

    workers = [threading.Thread(target=work, args=(n,)) for n in counts]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    return sum(counts)


def test_racecheck_catches_injected_race(racecheck_env):
    """The acceptance regression: a seeded multi-thread schedule over
    an unguarded counter must surface as a shared attribute with an
    EMPTY lockset — a race witness even though no value was provably
    corrupted this run."""
    racecheck_env.register(_Unguarded)
    obj = _Unguarded()
    _hammer(lambda: setattr(obj, "counter", obj.counter + 1))
    key = racecheck_env.class_key(_Unguarded) + ".counter"
    record = racecheck_env.report()[key]
    assert record.shared
    assert record.unguarded
    assert len(record.writer_threads) >= 2
    assert "test_races.py:" in record.first_shared_site


def test_racecheck_guarded_writes_record_their_lock(racecheck_env):
    racecheck_env.register(_Guarded)
    obj = _Guarded()
    expected = _hammer(obj.bump)
    assert obj.counter == expected  # the lock really guards
    record = racecheck_env.report()[
        racecheck_env.class_key(_Guarded) + ".counter"]
    assert record.shared
    assert not record.unguarded
    assert record.lockset and all("test_races" in label
                                  for label in record.lockset)


def test_racecheck_verify_flags_static_overpromise(racecheck_env):
    """A runtime-unguarded shared write to an attribute the static
    model calls guarded is a VIOLATION — the cross-validation's whole
    point."""
    racecheck_env.register(_Unguarded)
    obj = _Unguarded()
    _hammer(lambda: setattr(obj, "counter", obj.counter + 1))
    key = racecheck_env.class_key(_Unguarded) + ".counter"
    model = RaceModel()
    model.attrs[key] = AttrVerdict(key, "guarded",
                                   guards=frozenset({"NODE"}))
    verdict = racecheck_env.verify_against_static(model)
    assert not verdict.ok
    assert len(verdict.violations) == 1
    assert "over-promised" in verdict.violations[0]


def test_racecheck_verify_flags_init_only_written_shared(racecheck_env):
    racecheck_env.register(_Unguarded)
    obj = _Unguarded()
    _hammer(lambda: setattr(obj, "counter", 7))
    key = racecheck_env.class_key(_Unguarded) + ".counter"
    model = RaceModel()
    model.attrs[key] = AttrVerdict(key, "init-only")
    verdict = racecheck_env.verify_against_static(model)
    assert len(verdict.violations) == 1
    assert "init-only" in verdict.violations[0]


def test_racecheck_verify_confirmations_and_gaps(racecheck_env):
    racecheck_env.register(_Unguarded)
    obj = _Unguarded()
    _hammer(lambda: setattr(obj, "counter", obj.counter + 1))
    key = racecheck_env.class_key(_Unguarded) + ".counter"
    ghost = racecheck_env.class_key(_Unguarded) + ".never_driven"
    model = RaceModel()
    model.attrs[key] = AttrVerdict(key, "racy")
    model.attrs[ghost] = AttrVerdict(ghost, "racy")
    verdict = racecheck_env.verify_against_static(
        model, baseline_keys={key})
    assert verdict.ok
    assert len(verdict.confirmations) == 1
    assert "baselined" in verdict.confirmations[0]
    assert any("never_driven" in gap for gap in verdict.coverage_gaps)


def test_racecheck_matching_guard_is_clean(racecheck_env):
    """Runtime lockset mapped through the site map onto the SAME node
    the static model claims -> no violation (the happy path)."""
    racecheck_env.register(_Guarded)
    obj = _Guarded()
    _hammer(obj.bump)
    key = racecheck_env.class_key(_Guarded) + ".counter"
    record = racecheck_env.report()[key]
    (label,) = record.lockset
    rel, _, line = label.rpartition(":")
    model = RaceModel(site_map={(rel, int(line)): "GUARD_NODE"})
    model.attrs[key] = AttrVerdict(key, "guarded",
                                   guards=frozenset({"GUARD_NODE"}))
    verdict = racecheck_env.verify_against_static(model)
    assert verdict.ok and not verdict.violations


def test_racecheck_init_reset_defeats_id_reuse(racecheck_env):
    """Review regression: a fresh instance allocated at a dead
    instance's address must NOT inherit its writer-thread history —
    construction resets the record, so init writes never look
    shared."""
    racecheck_env.register(_Unguarded)

    def make_and_touch():
        obj = _Unguarded()  # same-address reallocation is likely here
        obj.counter = 1

    for _ in range(64):
        t = threading.Thread(target=make_and_touch)
        t.start()
        t.join()
    record = racecheck_env.report()[
        racecheck_env.class_key(_Unguarded) + ".counter"]
    assert not record.shared


def test_racecheck_uninstall_restores_classes():
    from gethsharding_tpu.analysis import racecheck

    if racecheck.active():
        pytest.skip("racecheck session mode active")
    original = _Unguarded.__init__
    racecheck.install(classes=())
    racecheck.register(_Unguarded)
    assert _Unguarded.__init__ is not original
    racecheck.uninstall()
    assert _Unguarded.__init__ is original
    assert "__setattr__" not in _Unguarded.__dict__


# -- regressions for the true races this PR fixed ----------------------------

def _session_racecheck_active() -> bool:
    from gethsharding_tpu.analysis import racecheck

    return racecheck.active()


@pytest.mark.skipif(
    _session_racecheck_active(),
    reason="builds a partial RPCServer via __new__ with a test-created "
           "lock the session recorder cannot label — its writes would "
           "look unguarded to the cross-validator")
def test_fixed_race_rpcserver_relayed_sends_counts_exactly():
    from gethsharding_tpu.rpc.server import RPCServer

    server = RPCServer.__new__(RPCServer)
    server._sub_lock = threading.Lock()
    server._p2p_peers = {}
    server.p2p_relayed_sends = 0
    total = _hammer(lambda: server.rpc_p2pSend(1, 2, "k", None),
                    threads=8, per_thread=500)
    assert server.p2p_relayed_sends == total


def test_fixed_race_slo_breach_fires_exactly_once():
    """Concurrent recorders all crossing the breach threshold must
    increment the breach counter ONCE (the breached flag flip is a
    check-then-act; it now happens under the ring lock)."""
    from gethsharding_tpu import metrics
    from gethsharding_tpu.slo.tracker import Objective, SLOTracker

    registry = metrics.Registry()
    tracker = SLOTracker(
        objectives={"klass": Objective("klass", availability=0.5)},
        registry=registry, breach_fast=1.1, breach_slow=1.1,
        min_events=4)
    fired = []
    tracker.on_breach(lambda name, fast, slow: fired.append(name))
    now = 1000.0

    def record_bad():
        # same logical instant: every thread sees the throttle window
        # open and the burn over threshold
        tracker.record("klass", ok=False, now=now)

    barrier = threading.Barrier(8)

    def work():
        barrier.wait()
        for _ in range(50):
            record_bad()

    workers = [threading.Thread(target=work) for _ in range(8)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    tracker.sweep(now)
    assert registry.counter("slo/klass/breaches").value == 1
    assert fired == ["klass"]


def test_fixed_race_influx_pushes_count_exactly(tmp_path):
    from gethsharding_tpu import metrics

    registry = metrics.Registry()
    registry.counter("x").inc()
    exporter = metrics.InfluxLineExporter(
        registry=registry, path=str(tmp_path / "lines.txt"))
    total = _hammer(exporter.push, threads=4, per_thread=50)
    assert exporter.pushes == total


def test_fixed_race_rpcclient_registration_is_locked():
    """Concurrent hook/subscriber registration must not lose entries
    (list.append raced list scans before the fix)."""
    from gethsharding_tpu.rpc.client import RPCClient

    client = RPCClient.__new__(RPCClient)
    client._pending_lock = threading.Lock()
    client._head_subscribers = []
    client._notification_hooks = {}
    n = [0]
    lock = threading.Lock()

    def register():
        with lock:
            n[0] += 1
            i = n[0]
        client.on_notification(f"m{i}", lambda p: None)

    total = _hammer(register, threads=8, per_thread=100)
    assert len(client._notification_hooks) == total


def test_fixed_race_batcher_dispatch_counts_exact():
    """Two dispatch threads can overlap after a watchdog restart: the
    per-op dispatch count is now locked and must count exactly."""
    from gethsharding_tpu.serving.batcher import MicroBatcher
    from gethsharding_tpu.serving.queue import Request

    class _Inner:
        name = "inner"

        def ecrecover_addresses(self, digests, sigs):
            return [None] * len(digests)

    batcher = MicroBatcher(_Inner(), flush_us=0.0)
    try:
        def one_batch():
            request = Request("ecrecover_addresses",
                              ([b"x" * 32], [b"y" * 65]), 1)
            batcher._run_batch("ecrecover_addresses", [request],
                               ([b"x" * 32], [b"y" * 65]), 1)
            assert request.future.result(timeout=5) == [None]

        total = _hammer(one_batch, threads=8, per_thread=100)
        assert batcher.dispatch_counts["ecrecover_addresses"] == total
    finally:
        batcher.close()


# -- layering fixtures -------------------------------------------------------

_LAYERS_OK = {
    "_comment": "fixture DAG",
    "units": {
        "serving": {"imports": ["metrics"], "lazy": ["resilience"]},
        "metrics": {"imports": [], "lazy": []},
        "resilience": {"imports": [], "lazy": []},
        "analysis": {"imports": [], "lazy": []},
    },
}


def _layering_tree(layers):
    return {
        "gethsharding_tpu/analysis/layers.json": json.dumps(layers),
        "gethsharding_tpu/serving/__init__.py": "",
        "gethsharding_tpu/metrics.py": "X = 1\n",
        "gethsharding_tpu/resilience/__init__.py": "",
        "gethsharding_tpu/serving/core.py": """
            from gethsharding_tpu import metrics

            def f():
                from gethsharding_tpu import resilience
                return metrics, resilience
        """,
    }


def test_layering_declared_edges_pass(tmp_path):
    corpus = make_corpus(tmp_path, _layering_tree(_LAYERS_OK))
    assert run_rules(corpus, ["layering"]) == []


def test_layering_flags_undeclared_and_scope_violations(tmp_path):
    layers = json.loads(json.dumps(_LAYERS_OK))
    layers["units"]["serving"] = {"imports": [], "lazy": []}
    corpus = make_corpus(tmp_path, _layering_tree(layers))
    got = idents(run_rules(corpus, ["layering"]))
    assert "undeclared-import:serving->metrics" in got
    assert "undeclared-lazy:serving->resilience" in got


def test_layering_lazy_only_edge_must_stay_lazy(tmp_path):
    layers = json.loads(json.dumps(_LAYERS_OK))
    layers["units"]["serving"] = {"imports": [],
                                  "lazy": ["metrics", "resilience"]}
    corpus = make_corpus(tmp_path, _layering_tree(layers))
    got = idents(run_rules(corpus, ["layering"]))
    # module-scope metrics import not allowed when declared lazy-only
    assert "undeclared-import:serving->metrics" in got
    findings = run_rules(corpus, ["layering"])
    msg = next(f.message for f in findings
               if f.ident == "undeclared-import:serving->metrics")
    assert "lazy-only" in msg


def test_layering_flags_stale_and_undeclared_unit(tmp_path):
    layers = json.loads(json.dumps(_LAYERS_OK))
    layers["units"]["metrics"]["imports"] = ["resilience"]  # stale
    del layers["units"]["serving"]  # now undeclared
    corpus = make_corpus(tmp_path, _layering_tree(layers))
    got = idents(run_rules(corpus, ["layering"]))
    assert "stale-layer:metrics->resilience" in got
    assert "undeclared-unit:serving" in got


def test_layering_structural_bans(tmp_path):
    layers = json.loads(json.dumps(_LAYERS_OK))
    layers["units"]["analysis"]["imports"] = ["serving"]
    layers["units"]["serving"]["lazy"].append("node")
    corpus = make_corpus(tmp_path, _layering_tree(layers))
    got = idents(run_rules(corpus, ["layering"]))
    assert "analysis-not-leaf:serving" in got
    assert "node-inversion:serving" in got
    # stale entries for the granted-but-unused edges fire too; the
    # bans themselves are what this test pins
    assert "stale-lazy:serving->node" in got


def test_layering_relative_imports_resolve_to_their_unit(tmp_path):
    """Review regression: `from ..metrics import X` inside serving/ is
    a cross-unit edge and must hit the DAG exactly like the absolute
    spelling — a relative import must not slip the rule."""
    layers = json.loads(json.dumps(_LAYERS_OK))
    layers["units"]["serving"] = {"imports": [], "lazy": []}
    tree = _layering_tree(layers)
    tree["gethsharding_tpu/__init__.py"] = ""
    tree["gethsharding_tpu/serving/core.py"] = """
        from .. import metrics

        def f():
            from ..resilience import errors
            return metrics, errors
    """
    tree["gethsharding_tpu/resilience/errors.py"] = "E = 1\n"
    corpus = make_corpus(tmp_path, tree)
    got = idents(run_rules(corpus, ["layering"]))
    assert "undeclared-import:serving->metrics" in got
    assert "undeclared-lazy:serving->resilience" in got
    # declared, the same relative edges pass
    layers["units"]["serving"] = {"imports": ["metrics"],
                                  "lazy": ["resilience"]}
    tree["gethsharding_tpu/analysis/layers.json"] = json.dumps(layers)
    corpus = make_corpus(tmp_path, tree)
    assert run_rules(corpus, ["layering"]) == []


def test_layering_missing_file_is_a_finding(tmp_path):
    tree = _layering_tree(_LAYERS_OK)
    del tree["gethsharding_tpu/analysis/layers.json"]
    corpus = make_corpus(tmp_path, tree)
    assert idents(run_rules(corpus, ["layering"])) \
        == {"missing-layers-json"}


def test_layering_live_tree_is_clean_and_nonvacuous():
    from gethsharding_tpu.analysis.layering import collect_import_edges

    corpus = Corpus.load(REPO)
    assert run_rules(corpus, ["layering"]) == []
    top, lazy = collect_import_edges(corpus)
    # the structural facts the ROADMAP refactor leans on
    assert ("serving", "node") not in top and ("serving", "node") not in lazy
    assert ("fleet", "node") not in top and ("fleet", "node") not in lazy
    assert ("sigbackend", "serving") not in top  # lazy-only by design
    assert ("sigbackend", "serving") in lazy
    assert not any(unit == "analysis" for (unit, _) in
                   list(top) + list(lazy))


# -- prune-baseline CLI ------------------------------------------------------

def test_cli_prune_baseline_drops_only_stale(tmp_path, capsys):
    (tmp_path / "gethsharding_tpu").mkdir()
    (tmp_path / "gethsharding_tpu/svc.py").write_text(textwrap.dedent("""
        import threading

        class S:
            def start(self):
                threading.Thread(target=print, daemon=True).start()
    """))
    (tmp_path / "README.md").write_text("nothing\n")
    baseline = tmp_path / "baseline.json"
    argv = ["--root", str(tmp_path), "--baseline", str(baseline)]
    assert cli_main(argv + ["--write-baseline"]) == 0
    data = json.loads(baseline.read_text())["findings"]
    live_key = next(k for k in data if "thread-lifecycle" in k)
    # add a dead entry, then prune: only the dead one goes
    data["thread-lifecycle::gethsharding_tpu/gone.py::x"] = "obsolete"
    baseline.write_text(json.dumps({"findings": data}))
    assert cli_main(argv + ["--prune-baseline"]) == 0
    out = capsys.readouterr().out
    assert "dropped 1" in out
    kept = json.loads(baseline.read_text())["findings"]
    assert live_key in kept
    assert "thread-lifecycle::gethsharding_tpu/gone.py::x" not in kept
    # idempotent: nothing stale on a second pass
    assert cli_main(argv + ["--prune-baseline"]) == 0
    assert "nothing stale" in capsys.readouterr().out


def test_cli_prune_baseline_still_gates_new_findings(tmp_path, capsys):
    """Review regression: a prune invocation on a dirty tree must not
    exit green — new findings gate exactly like a plain run."""
    (tmp_path / "gethsharding_tpu").mkdir()
    (tmp_path / "gethsharding_tpu/svc.py").write_text(textwrap.dedent("""
        import threading

        class S:
            def start(self):
                threading.Thread(target=print, daemon=True).start()
    """))
    (tmp_path / "README.md").write_text("nothing\n")
    baseline = tmp_path / "baseline.json"
    assert cli_main(["--root", str(tmp_path), "--baseline", str(baseline),
                     "--prune-baseline"]) == 1
    assert "NEW finding(s) remain" in capsys.readouterr().out


def test_fixed_race_influx_stop_straggler_cannot_reopen_socket(tmp_path):
    """Review regression: a reporter push racing past stop()'s bounded
    join must not lazily re-create (and leak) the closed socket."""
    from gethsharding_tpu import metrics

    registry = metrics.Registry()
    registry.counter("x").inc()
    exporter = metrics.InfluxLineExporter(
        registry=registry, udp=("127.0.0.1", 9))
    exporter.push()
    assert exporter._sock is not None
    exporter.stop()  # final flush, then closed
    assert exporter._sock is None
    before = exporter.pushes
    exporter.push()  # the straggler: must be a no-op now
    assert exporter._sock is None
    assert exporter.pushes == before


def test_cli_prune_baseline_refuses_partial_runs(tmp_path):
    (tmp_path / "gethsharding_tpu").mkdir()
    assert cli_main(["--root", str(tmp_path), "--rule", "race-guard",
                     "--prune-baseline"]) == 2
