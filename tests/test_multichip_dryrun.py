"""The multi-chip dryrun's rc/tail contract, as a test instead of JSON.

The driver used to snapshot ``dryrun_multichip`` child output into raw
``MULTICHIP_r0x.json`` files whose tails carried an alarming-looking
XLA:CPU AOT loader error (``cpu_aot_loader.cc``: machine-feature
mismatch, "could lead to execution errors such as SIGILL") next to
``rc: 0`` — benign in every observed run, but nothing ASSERTED that.
The root snapshots are retired: their rc/ok/tail history now lives in
``perf_ledger.jsonl`` as the ``multichip_dryrun`` workload (imported by
``scripts/ledger_import.py``), and these tests pin the contract down:

* the classifier in ``parallel.virtual`` recognizes exactly that noise
  class (checked against the imported snapshot tails themselves), and
  never excuses a nonzero rc;
* the dryrun child, run the same way the driver runs it (clean
  subprocess, forced virtual CPU platform), exits 0 with every stderr
  line either classified warn-only or ordinary log noise — no raw JSON
  snapshot needed as evidence.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from gethsharding_tpu.parallel.virtual import (
    assert_aot_warn_only,
    build_virtual_env,
    is_aot_mismatch_line,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Verbatim (truncated) lines from the MULTICHIP_r05.json tail — the
# shape of the noise this classifier exists for.
AOT_LINES = (
    "E0802 02:06:29.925595   20031 cpu_aot_loader.cc:210] Loading "
    "XLA:CPU AOT result. Target machine feature +prefer-no-gather is "
    "not  supported on the host machine.",
    "Machine type used for XLA:CPU compilation doesn't match the host "
    "machine. This could lead to execution errors such as SIGILL.",
)

# Lines that must NOT be classified away (from the r01 failure tail and
# ordinary jax logging).
REAL_LINES = (
    "Traceback (most recent call last):",
    "ValueError: requested 8 devices, only 1 visible",
    "WARNING:2026-07-29 20:51:57,630:jax._src.xla_bridge:905: Platform "
    "'axon' is experimental and not all JAX functionality may be "
    "correctly supported!",
)


def test_classifier_recognizes_aot_mismatch_lines():
    for line in AOT_LINES:
        assert is_aot_mismatch_line(line), line
    for line in REAL_LINES:
        assert not is_aot_mismatch_line(line), line


def test_classifier_covers_recorded_snapshot_tails():
    """Every OK run's recorded tail is fully explained by the warn-only
    class — the evidence that made rc-decides-and-tail-is-noise the
    contract in the first place. The tails live in the committed perf
    ledger (workload ``multichip_dryrun``, imported from the retired
    MULTICHIP_r0x.json snapshots by scripts/ledger_import.py); the
    repo-root path is explicit because conftest points the default
    ledger at a per-run temp file."""
    from gethsharding_tpu.perfwatch.ledger import Ledger

    ledger = Ledger(os.path.join(REPO, "perf_ledger.jsonl"))
    recs = ledger.records(workload="multichip_dryrun")
    assert recs, "multichip_dryrun history missing from perf_ledger.jsonl"
    checked = 0
    for rec in recs:
        extra = rec.get("extra") or {}
        if not extra.get("ok") or rec.get("metrics", {}).get("rc") != 0:
            continue
        src = extra.get("imported_from", rec.get("ts"))
        for line in extra.get("tail", "").splitlines():
            if line.strip():
                assert is_aot_mismatch_line(line), (src, line)
                checked += 1
    if not checked:
        pytest.skip("no ok-run snapshot tails to check")


def test_warn_only_never_excuses_failure():
    tail = "\n".join(AOT_LINES)
    assert assert_aot_warn_only(0, tail) == list(AOT_LINES)
    assert assert_aot_warn_only(0, "") == []
    with pytest.raises(RuntimeError, match="warn-only"):
        assert_aot_warn_only(1, tail)
    with pytest.raises(RuntimeError):
        assert_aot_warn_only(-11, "")  # e.g. an actual SIGSEGV/SIGILL


@pytest.mark.slow
def test_dryrun_child_rc_and_tail():
    """Run the dryrun child exactly as the driver does — clean
    subprocess, virtual CPU platform forced via env — and assert the
    rc/tail contract instead of snapshotting it to JSON."""
    env = build_virtual_env(2)
    env["GETHSHARDING_DRYRUN_REEXEC"] = "1"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(2)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=840,
    )
    matched = assert_aot_warn_only(proc.returncode, proc.stderr)
    # Whatever stderr remains after the warn-only class must be ordinary
    # log noise (jax/absl WARNING|I|E-prefixed), never a traceback.
    leftovers = [ln for ln in proc.stderr.splitlines()
                 if ln.strip() and ln not in matched]
    for line in leftovers:
        assert "Traceback" not in line and "Error" not in line.split(
            ":", 1)[0], proc.stderr[-4000:]
