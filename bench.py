"""Driver benchmark: the five BASELINE.md configs on real hardware.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", "extra"}.

Headline metric (BASELINE config 3): aggregate notary-signature
verifications/sec across one 100-shard period. The workload is produced
by the PROTOCOL, not synthesized: a chain with 135 notaries registered
through the real registration path (derived BLS keys + proofs of
possession), 100 collation records added per period, and every committee
slot's vote BLS-signed over the real vote digest with the voter's real
key. What is measured is the live notary's `audit_period` — the
production code path that aggregates the period's votes and verifies all
shards in ONE batched pairing dispatch. (The reference's sampling quirk
yields ~1 eligible voter per shard per period; the bench populates all
135 committee slots per the protocol's documented committee intent.)

Extras: config 1 (single PairingCheck micro), config 2 (one 135-vote
aggregate), config 4 (collation replay, 1 shard), config 5 (the fused
1024-shard stress step) — skipped automatically when the backend is too
slow to fit the budget (hermetic CPU runs).

The kernel has build-time knobs whose best setting depends on the
backend (GETHSHARDING_TPU_LIMB_FORM = wide|exact, GETHSHARDING_TPU_CARRY
= scan|assoc, GETHSHARDING_TPU_CONV = shift|slices|gather|onehot|mxu8,
GETHSHARDING_TPU_PAIRCONV = xla|pallas, GETHSHARDING_TPU_PALLAS,
all read at import): the bench AUTOTUNES by re-executing itself
per configuration in a subprocess and reports the fastest, caching the
winner per backend in .bench_autotune.json. Signing workloads are cached
in .bench_workload.npz (first build ~3 min of host-side scalar crypto).

`bench.py --serving` measures the verification SERVING tier instead: M
concurrent clients x single-item requests coalesced into shared
dispatches vs the same clients driving the backend directly
(scripts/serving_stress.py is the open-ended soak form).

`bench.py --trace [--trace-out PATH]` runs the serving benchmark with
the span tracer on and writes a Chrome trace-event JSON (Perfetto):
per-request queue_wait / batch_assembly / device_dispatch attribution.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time

import numpy as np

SHARDS, COMMITTEE = 100, 135
REPO = os.path.dirname(os.path.abspath(__file__))

# ordered by prior: exact/scan won the r2 TPU sweep (then measured with
# the one-hot conv; `shift` — the module default — replaced it after CPU
# profiling showed gather memory-bound and onehot doing redundant MACs,
# but shift/slices have NOT yet been measured on TPU: the tunnel was down
# for the rest of r2, so this sweep decides). The assoc carry and the
# Pallas fused-normalize lost on TPU in r2 but stay as probes — backends
# change. If the sweep budget runs out, the best config measured so far
# wins.
CONFIGS = [
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan"},
    # r4: the final-exponentiation mega-kernel (ops/pallas_finalexp.py) —
    # the whole ~250-op final exp as ONE pallas_call; the lever sized to
    # the latency-bound gap (VERDICT r3 #1). Probed right after the
    # champion, composed with the champion's ambient knobs and with
    # relaxed normalize for the Miller side.
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_FINALEXP": "mega"},
    # the two-launch pairing check: Miller AND final exp each one kernel
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega"},
    # the four-launch audit dispatch: aggregation kernels too
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega",
     "GETHSHARDING_TPU_AGG": "mega"},
    # mega kernels composed over the slices conv ambient (the r4 TPU
    # sweep's non-mega champion) — the non-pairing remainder of the
    # dispatch also runs its fastest measured form
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_CONV": "slices",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega",
     "GETHSHARDING_TPU_AGG": "mega"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_CONV": "slices",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega"},
    # the uint16 wire format: halves host->device transfer bytes (12-bit
    # limbs in int32 waste 20 bits); widened on device, value-identical
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega",
     "GETHSHARDING_TPU_WIRE": "u16"},
    # r5: in-kernel slice-accumulate conv (no shifted-concat copies per
    # schoolbook MAC) — the in-kernel analog of the XLA-land slices
    # winner, composed under the two-launch champion
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega",
     "GETHSHARDING_TPU_MEGA_CONV": "slices"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed",
     "GETHSHARDING_TPU_FINALEXP": "mega"},
    # r3 additions, probed right after the champion: the statically
    # unrolled carry (straight-line fused code instead of an XLA While
    # per normalize), the fused Pallas pair-conv (never materializes the
    # product tensor in HBM), alone, + fused-normalize, and the
    # int8-plane MXU column contraction
    {"GETHSHARDING_TPU_LIMB_FORM": "exact",
     "GETHSHARDING_TPU_CARRY": "unroll"},
    # relaxed normalize: no exact carry ripple anywhere in the field ops
    # (wide form only; quasi-canonical limbs, see ops/limb.py)
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed",
     "GETHSHARDING_TPU_SCAN_UNROLL": "8"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "unroll",
     "GETHSHARDING_TPU_SCAN_UNROLL": "8"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_PAIRCONV": "pallas"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_PAIRCONV": "pallas", "GETHSHARDING_TPU_PALLAS": "1"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_CONV": "mxu8"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_CONV": "slices"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_PAIRCONV": "pallas"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_CARRY": "scan"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_CONV": "onehot"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "assoc"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_PALLAS": "1"},
    # LAST on purpose: the fully inlined PAIR_UNROLL kernels compile for
    # >35 min on XLA:CPU and may not fit the per-config probe timeout on
    # any backend — the watcher's queue probes them with long timeouts
    # instead; in a sweep they only run if budget remains
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed",
     "GETHSHARDING_TPU_PAIR_UNROLL": "finalexp"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "unroll",
     "GETHSHARDING_TPU_PAIR_UNROLL": "1"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_PAIR_UNROLL": "1"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed",
     "GETHSHARDING_TPU_PAIR_UNROLL": "1"},
]

SWEEP_BUDGET_S = float(os.environ.get("GETHSHARDING_BENCH_BUDGET_S", "1200"))

# Optional ABSOLUTE wall-clock deadline (epoch seconds). Callers running
# under an outer `timeout` (scripts/tpu_experiments/89_finalize_winner.sh)
# set it so every stage's subprocess timeout derives from the REMAINING
# wall clock — the extras pass, retry, and sweep can then never cascade
# past the window and get SIGTERMed mid-write.
_DEADLINE_TS = float(os.environ.get("GETHSHARDING_BENCH_DEADLINE_TS", "0"))


def _remaining() -> "float | None":
    return None if not _DEADLINE_TS else _DEADLINE_TS - time.time()


def _enable_compile_cache() -> None:
    # persistent compile cache: first run pays ~1 min, repeats don't.
    # Host-keyed (entries from another machine can segfault on load);
    # one shared definition with tests/dryrun.
    from gethsharding_tpu.parallel.virtual import configure_compile_cache

    configure_compile_cache()


# == protocol-generated workload (host scalar crypto, disk-cached) =========


def _workload_path() -> str:
    return os.path.join(REPO, ".bench_workload.npz")


def _point_to_bytes(p) -> np.ndarray:
    return np.frombuffer(p[0].to_bytes(32, "big") + p[1].to_bytes(32, "big"),
                         np.uint8)


def _point_from_bytes(b) -> tuple:
    raw = bytes(b)
    return (int.from_bytes(raw[:32], "big"), int.from_bytes(raw[32:], "big"))


def _bench_root(s: int, p: int):
    """The deterministic per-(shard, period) collation root — ONE formula
    shared by the identity builder and the cache-readiness gate (period 1
    keeps the original single-period formula so old caches stay valid)."""
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.utils.hexbytes import Hash32

    return Hash32(keccak256(b"bench-root-%d" % s if p == 1
                            else b"bench-root-%d-p%d" % (s, p)))


def _bench_identities(k_periods: int = 1):
    """The deterministic identities + per-shard vote digests shared by the
    cache builder and the chain builder (single source of truth: a drift
    would silently invalidate the signature cache). With k_periods > 1
    the workload spans periods 1..K (the `audit_periods` catch-up form:
    BASELINE's protocol-level batching lever); period 1 keeps its
    original root formula so existing signature caches stay valid."""
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.smc.state_machine import vote_digest

    manager = AccountManager()
    accounts = [manager.new_account(seed=b"bench-notary-%d" % i)
                for i in range(COMMITTEE)]
    periods = list(range(1, k_periods + 1))
    roots, digests = {}, {}
    for p in periods:
        roots[p] = [_bench_root(s, p) for s in range(SHARDS)]
        digests[p] = [bytes(vote_digest(s, p, roots[p][s]))
                      for s in range(SHARDS)]
    return manager, accounts, roots, digests, periods


def _sig_cache_keys(p: int) -> tuple:
    """npz keys for period p's signature block (period 1 keeps the
    original single-period keys so pre-existing caches stay valid)."""
    return (("vote_sigs", "digest0") if p == 1
            else (f"vote_sigs_p{p}", f"digest0_p{p}"))


def _sig_cache_entry_ok(cache, p: int, digest0: bytes) -> bool:
    """ONE validity rule for a cached period (key presence + protocol
    shape + pinned digest), shared by the loader and the readiness gate —
    a drift between the two would either silently skip K-period coverage
    or start the ~20-min rebuild inside a tunnel window. `cache` is any
    mapping of npz keys to arrays (dict or an open NpzFile)."""
    skey, dkey = _sig_cache_keys(p)
    if skey not in cache or dkey not in cache:
        return False
    return (cache[skey].shape == (SHARDS, COMMITTEE, 64)
            and bytes(cache[dkey]) == digest0)


def _load_or_build_vote_sigs(accounts, manager, digests) -> dict:
    """{period: (SHARDS, COMMITTEE, 64) uint8} — every committee slot's
    signature per shard digest, signed with the notary's real derived
    vote key. Cached per period (period 1 under the original npz keys, so
    pre-existing single-period caches are reused verbatim; building K=8
    extends a K=4 cache instead of restarting it)."""
    path = _workload_path()
    data: dict = {}
    try:
        with np.load(path) as cached:
            data = {key: cached[key] for key in cached.files}
    except (OSError, ValueError):
        data = {}
    out, dirty = {}, False
    for p in sorted(digests):
        dg = digests[p]
        skey, dkey = _sig_cache_keys(p)
        if _sig_cache_entry_ok(data, p, dg[0]):
            out[p] = data[skey]
            continue
        print(f"# building vote-signature workload for period {p} "
              f"({SHARDS}x{COMMITTEE} BLS signs, ~3 min once)...",
              file=sys.stderr)
        sigs = np.zeros((SHARDS, COMMITTEE, 64), np.uint8)
        for s in range(SHARDS):
            for i, acct in enumerate(accounts):
                sig = manager.bls_sign(acct.address, dg[s])
                sigs[s, i] = _point_to_bytes(sig)
        data[skey] = sigs
        data[dkey] = np.frombuffer(dg[0], np.uint8)
        out[p] = sigs
        dirty = True
    if dirty:
        try:
            np.savez_compressed(path, **data)
        except OSError:
            pass
    return out


def build_audit_workload(k_periods: int = 1):
    """A real chain at the end of K full 100-shard periods: registry,
    records, and signed votes all built through protocol objects. Returns
    (notary, periods) ready for repeated audit_period(s) calls."""
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.sigbackend import get_backend
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.smc.state_machine import VoteSig

    config = Config()  # protocol-scale: 100 shards, committee 135
    chain = SimulatedMainchain(config=config)
    manager, accounts, roots, digests, periods = _bench_identities(k_periods)
    for acct in accounts:
        chain.fund(acct.address, 2000 * ETHER)
        chain.register_notary(
            acct.address, bls_pubkey=acct.bls_pubkey,
            bls_pop=manager.bls_proof_of_possession(acct.address))
    sig_bytes = _load_or_build_vote_sigs(accounts, manager, digests)
    proposer = manager.new_account(seed=b"bench-proposer")
    for period in periods:
        chain.fast_forward(1)
        assert chain.current_period() == period, "identity/digest drift"
        for s in range(SHARDS):
            chain.add_header(proposer.address, s, period, roots[period][s])
        for s in range(SHARDS):
            record = chain.smc.collation_records[(s, period)]
            for i, acct in enumerate(accounts):
                record.vote_sigs[i] = VoteSig(
                    sig=_point_from_bytes(sig_bytes[period][s, i]),
                    signer=acct.address)
            record.vote_count = COMMITTEE
            record.is_elected = True
            chain.smc.last_approved_collation[s] = period
    chain.fast_forward(1)  # close the last period

    client = SMCClient(backend=chain, accounts=manager, account=accounts[0],
                       config=config)
    notary = Notary(client=client, shard=Shard(shard_id=0, shard_db=MemoryKV()),
                    config=config, sig_backend=get_backend("jax"))
    return notary, periods


# == measurements ==========================================================


def measure_single() -> dict:
    """Measure under the CURRENT env config; prints one stats JSON line."""
    _setup_bench_env()

    import jax

    notary, periods = build_audit_workload()
    period = periods[-1]

    # warm-up (compiles the bucketed batch shape) + correctness gate
    assert notary.audit_period(period) is True, "audit must be consistent"
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        assert notary.audit_period(period) is True
    wall = (time.perf_counter() - t0) / iters
    # the verification dispatch itself (the BASELINE metric) — the audit
    # timer records only the sig-backend call
    dispatch = notary.m_audit_latency.percentile(0.5)
    sig_rate = SHARDS * COMMITTEE / dispatch

    stats = {
        "platform": jax.devices()[0].platform,
        "sig_rate": round(sig_rate, 1),
        "dispatch_s": round(dispatch, 4),
        "audit_wall_s": round(wall, 4),
        # GETHSHARDING_SIG_TIMING=1: host-marshal / transfer / device
        # split of the last dispatch (see sigbackend.last_timing)
        **({"sig_timing": notary.sig_backend.last_timing}
           if os.environ.get("GETHSHARDING_SIG_TIMING") == "1" else {}),
        # the per-dispatch wire ledger rides in EVERY config's extras so
        # probe-42 transfer attribution is comparable across rounds
        # instead of living only in one-off probe artifacts
        **_wire_stats(notary.sig_backend),
        "knobs": _knob_snapshot(),
    }
    if os.environ.get("GETHSHARDING_BENCH_EXTRAS") == "1":
        # configs 1/2/4/5 run only for the sweep winner (main() re-invokes
        # with this flag) — not in every autotune subprocess
        stats.update(_measure_extras(dispatch))
    return stats


def _wire_stats(backend) -> dict:
    """The last dispatch's wire ledger (always on, no device sync):
    bytes over the host->device link + pk device-cache hit ratio."""
    wire = getattr(backend, "last_wire", None)
    if not wire:
        return {}
    return {
        "wire_bytes_per_dispatch": wire["wire_bytes"],
        "g2_wire_bytes_per_dispatch": wire["g2_wire_bytes"],
        "pk_cache_hit_ratio": round(
            wire["pk_hit_rows"] / max(1, wire["pk_rows"]), 4),
        "pk_resident": wire["resident"],
    }


def _kperiod_cache_ready(max_k: int = 8) -> bool:
    """True only when every period's cached signature block EXISTS, has
    the current (SHARDS, COMMITTEE, 64) shape, and its pinned digest
    matches the current identity formula — a stale cache (drifted seed /
    digest scheme / protocol shape) must read as not-ready, or the extras
    pass would start the ~20-min rebuild inside a tunnel window (the same
    checks _load_or_build_vote_sigs uses to decide a rebuild)."""
    from gethsharding_tpu.smc.state_machine import vote_digest

    try:
        with np.load(_workload_path()) as cached:
            for p in range(1, max_k + 1):
                if not _sig_cache_entry_ok(
                        cached, p, bytes(vote_digest(0, p,
                                                     _bench_root(0, p)))):
                    return False
    except (OSError, ValueError):
        return False
    return True


def _kperiod_headroom(min_s: float) -> bool:
    """Enough wall-clock left before BOTH deadlines (the finalize
    window's GETHSHARDING_BENCH_DEADLINE_TS and the extras subprocess's
    advertised kill timer) for more K-period work? Standalone --kperiod
    probes set neither and always proceed (their own timeout governs)."""
    rem = _remaining()
    if rem is not None and rem < min_s:
        return False
    child = float(
        os.environ.get("GETHSHARDING_BENCH_CHILD_DEADLINE_TS", "0"))
    if child and child - time.time() < min_s:
        return False
    return True


def _setup_bench_env() -> None:
    """The shared measurement preamble (CPU forcing + compile cache) —
    one definition so --single and --kperiod captures stay comparable."""
    if os.environ.get("GETHSHARDING_BENCH_CPU") == "1":
        # hermetic/offline runs: force the CPU backend before any init
        from gethsharding_tpu.parallel.virtual import force_virtual_cpu_devices

        force_virtual_cpu_devices(1)
    _enable_compile_cache()


def _knob_snapshot() -> dict:
    """The active kernel knobs, so probe outputs are self-describing
    (scripts/tpu_pick_winner.py rebuilds the autotune cache from the
    best probe)."""
    return {key: val for key, val in os.environ.items()
            if key.startswith("GETHSHARDING_TPU_")}


def measure_kperiod(ks=None) -> dict:
    """sigs/sec vs K for the `audit_periods` K-period catch-up batch —
    the protocol-level lever (PERF.md): K periods' rows share ONE
    signature dispatch, so on a latency-bound kernel K periods cost
    nearly one. Reports the honest aggregate rate AND the per-dispatch /
    per-period latency for every K so the batching's latency cost is
    never hidden behind the throughput number."""
    _setup_bench_env()

    import jax

    if ks is None:
        ks = [int(x) for x in os.environ.get(
            "GETHSHARDING_BENCH_KLIST", "1,4,8").split(",")]
    ks = sorted(set(ks))
    notary, periods = build_audit_workload(max(ks))
    timer = notary.m_audit_latency
    sweep = []
    for k in ks:
        if sweep and not _kperiod_headroom(1800):
            # a truncated sweep (first K measured) beats a SIGKILLed
            # child that loses every extra already measured
            print(f"# kperiod sweep truncated before K={k}: deadline "
                  f"near", file=sys.stderr)
            break
        ps = periods[:k]
        res = notary.audit_periods(ps)  # warm-up compile + correctness gate
        assert all(res[p] is True for p in ps), "audit must be consistent"
        # isolate THIS K's dispatch samples: the registry timer is shared
        # across the whole sweep (reservoir 1024 >> samples taken here,
        # so the ring never wraps and the slice below is exact)
        base = len(timer._samples)
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            res = notary.audit_periods(ps)
            assert all(res[p] is True for p in ps)
        wall = (time.perf_counter() - t0) / iters
        new = sorted(timer._samples[base:])
        dispatch = new[len(new) // 2]
        sweep.append({
            "k": k,
            "dispatch_s": round(dispatch, 4),
            "per_period_s": round(dispatch / k, 4),
            "audit_wall_s": round(wall, 4),
            "sig_rate": round(k * SHARDS * COMMITTEE / dispatch, 1),
            **_wire_stats(notary.sig_backend),
        })
        print(f"# K={k}: {sweep[-1]['sig_rate']:.1f} sigs/sec aggregate, "
              f"dispatch {dispatch:.4f} s ({sweep[-1]['per_period_s']:.4f} "
              f"s/period)", file=sys.stderr)
    best = max(sweep, key=lambda r: r["sig_rate"])
    return {
        "platform": jax.devices()[0].platform,
        "sig_rate": best["sig_rate"],
        "dispatch_s": best["dispatch_s"],
        "audit_wall_s": best["audit_wall_s"],
        "k_periods": best["k"],
        "per_period_dispatch_s": best["per_period_s"],
        "kperiod_sweep": sweep,
        "knobs": _knob_snapshot(),
    }


def _measure_extras(dispatch_s: float) -> dict:
    """Configs 1, 2, 4 (+5 when the backend is fast enough)."""
    import jax
    import jax.numpy as jnp

    from gethsharding_tpu.crypto import bn256 as ref
    from gethsharding_tpu.ops import bn256_jax as k
    # checked_pull: the block-vs-pull self-checked device->host pull —
    # a no-op block under the tunnel plugin lands on the timer_suspect
    # counter and flags this run's ledger record invalid
    from gethsharding_tpu.perfwatch import checked_pull

    out = {}

    # config 1: single PairingCheck (e(aP,Q)e(-P,aQ) == 1), batch 1
    a = 1234567
    p1, q1 = ref.g1_mul(a, ref.G1_GEN), ref.G2_GEN
    p2, q2 = ref.g1_neg(ref.G1_GEN), ref.g2_mul(a, ref.G2_GEN)
    px, py, _ = k.g1_to_limbs([[p1, p2][i] for i in range(2)])
    qx, qy, _ = k.g2_to_limbs([[q1, q2][i] for i in range(2)])
    fn = jax.jit(k.pairing_check)
    args = (jnp.asarray(px)[None], jnp.asarray(py)[None],
            jnp.asarray(qx)[None], jnp.asarray(qy)[None],
            jnp.ones((1, 2), bool))
    assert bool(np.asarray(fn(*args))[0])
    t0 = time.perf_counter()
    for _ in range(3):
        r = fn(*args)
    checked_pull(r, op="bench/config1")  # real pull, self-checked
    out["config1_pairing_check_s"] = round((time.perf_counter() - t0) / 3, 4)

    # config 2: ONE 135-vote aggregate (batch 1 of the BLS kernel)
    header = b"bench-config2"
    keys = [ref.bls_keygen(bytes([i])) for i in range(4)]
    agg_sig = ref.bls_aggregate_sigs([ref.bls_sign(header, sk)
                                      for sk, _ in keys])
    agg_pk = ref.bls_aggregate_pks([pk for _, pk in keys])
    hx, hy, _ = k.g1_to_limbs([ref.hash_to_g1(header)])
    sx, sy, _ = k.g1_to_limbs([agg_sig])
    pkx, pky, _ = k.g2_to_limbs([agg_pk])
    fn2 = jax.jit(k.bls_verify_aggregate_batch)
    args2 = tuple(jnp.asarray(x) for x in (hx, hy, sx, sy, pkx, pky)) + (
        jnp.ones(1, bool),)
    assert bool(np.asarray(fn2(*args2))[0])
    t0 = time.perf_counter()
    for _ in range(3):
        r = fn2(*args2)
    checked_pull(r, op="bench/config2")  # real pull, self-checked
    out["config2_aggregate_verify_s"] = round((time.perf_counter() - t0) / 3,
                                              4)

    # config 4: collation replay, 1 shard x 64 txs
    from gethsharding_tpu.core import state_processor as sp
    from gethsharding_tpu.core.types import Transaction
    from gethsharding_tpu.crypto import secp256k1
    from gethsharding_tpu.ops import replay_jax

    n_txs = 64
    priv = 0xB0B
    sender = secp256k1.priv_to_address(priv)
    to = secp256k1.priv_to_address(0xA11CE)
    txs = [sp.sign_transaction(
        Transaction(nonce=i, gas_price=1, gas_limit=30000, to=to, value=1,
                    payload=b"x"), priv) for i in range(n_txs)]
    inp = replay_jax.build_replay_inputs(
        [txs], [{sender: sp.AccountState(balance=10 ** 12)}], [to])
    out4 = replay_jax.replay_batch(inp)
    assert bool(np.asarray(out4.statuses).all())
    t0 = time.perf_counter()
    for _ in range(3):
        out4 = replay_jax.replay_batch(inp)
    # the tiny statuses plane first as the self-checked barrier, then
    # the full-output transfer the HISTORICAL records timed — the
    # extra bool-plane RTT is noise next to the balances plane, while
    # changing the transferred volume would make every new
    # config4_replay_txs_per_s incomparable to the imported baseline
    checked_pull(out4.statuses, op="bench/config4")
    jax.device_get(out4)
    dt = (time.perf_counter() - t0) / 3
    out["config4_replay_txs_per_s"] = round(n_txs / dt, 1)

    # config 5: the fused 1024-shard stress step (addHeader + votes + BLS
    # + replay + all-reduce) — only when the backend is fast enough for
    # the 10x batch within the budget
    if dispatch_s < 2.0:
        from gethsharding_tpu.parallel.stress import (
            StressPipeline, build_stress_inputs)
        from gethsharding_tpu.params import Config

        n_shards = 1024
        inputs, pool, bh, sample_size, _ = build_stress_inputs(
            n_shards, votes_per_shard=2, txs_per_shard=1,
            committee_size=COMMITTEE)
        pipe = StressPipeline(config=Config(), mesh=None)
        res = pipe.run(inputs, pool, bh, 1, sample_size)
        jax.device_get(res.roots)
        t0 = time.perf_counter()
        res = pipe.run(inputs, pool, bh, 1, sample_size)
        checked_pull(res.roots, op="bench/config5")  # self-checked pull
        dt = time.perf_counter() - t0
        out["config5_stress_shards_per_s"] = round(n_shards / dt, 1)

    # the protocol-level lever (audit_periods K-period catch-up batching):
    # measured only when the K-period signature workload is ALREADY on
    # disk — the build is ~20 min of host scalar crypto, too much to
    # spend inside a tunnel window (scripts/tpu_experiments/03e and the
    # cache pre-builder create it) — and when enough window remains
    # the K sweep needs a fresh 8-period chain + up to two cold heavy
    # compiles (one cold heavy compile alone budgets 1800 s elsewhere):
    # enter only with headroom for at least the first K before BOTH
    # deadlines (finalize window + this subprocess's advertised kill
    # timer), and measure_kperiod rechecks between Ks — so a slow sweep
    # truncates instead of SIGKILLing away the extras already measured
    if dispatch_s < 2.0 and _kperiod_headroom(2400):
        if _kperiod_cache_ready(8):
            try:
                kstats = measure_kperiod(ks=[4, 8])
                out["kperiod_sweep"] = kstats["kperiod_sweep"]
                out["kperiod_best_sig_rate"] = kstats["sig_rate"]
            except Exception as exc:  # extras must never sink the winner
                print(f"# kperiod extra failed: {exc!r}", file=sys.stderr)
    return out


# == device residency + overlap (bench.py --resident / --overlap) =========


def measure_resident() -> dict:
    """Transfer attribution for the device-resident pk planes: the same
    audit dispatched cold (empty device cache) then warm. With
    GETHSHARDING_TPU_RESIDENT on (the default) the warm path must ship
    ZERO G2 pubkey bytes — the steady-state acceptance ledger; with it
    off the cold/warm bytes are equal, giving the A/B for how much of
    the dispatch the transfer share is. Hermetic on CPU (the ledger is
    platform-independent); the 05_resident probe runs it on TPU where
    the byte saving becomes tunnel time."""
    _setup_bench_env()

    import jax

    notary, periods = build_audit_workload()
    period = periods[-1]
    backend = notary.sig_backend

    # first dispatch: compile + cold-cache transfer
    assert notary.audit_period(period) is True, "audit must be consistent"
    cold = dict(backend.last_wire or {})
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        assert notary.audit_period(period) is True
    wall = (time.perf_counter() - t0) / iters
    warm = dict(backend.last_wire or {})
    dispatch = notary.m_audit_latency.percentile(0.5)
    resident = bool(warm.get("resident"))
    if resident:
        # the ISSUE-4 acceptance bar: a steady-state audit with a warm
        # device cache transfers zero G2 pubkey bytes
        assert warm.get("g2_wire_bytes") == 0, (
            f"warm device cache must ship zero G2 bytes: {warm}")
    return {
        "platform": jax.devices()[0].platform,
        "sig_rate": round(SHARDS * COMMITTEE / dispatch, 1),
        "dispatch_s": round(dispatch, 4),
        "audit_wall_s": round(wall, 4),
        "resident": resident,
        "wire_bytes_cold": cold.get("wire_bytes"),
        "wire_bytes_warm": warm.get("wire_bytes"),
        "g2_wire_bytes_cold": cold.get("g2_wire_bytes"),
        "g2_wire_bytes_warm": warm.get("g2_wire_bytes"),
        "pk_hit_bytes_warm": warm.get("pk_hit_bytes"),
        "pk_cache_hit_ratio_warm": round(
            warm.get("pk_hit_rows", 0) / max(1, warm.get("pk_rows", 0)), 4),
        "knobs": _knob_snapshot(),
    }


def measure_overlap() -> dict:
    """Sequential vs overlapped K-period audit pipeline. Sequential:
    marshal period N+1 only after N's verdict returned (one
    `audit_period` per period). Overlapped: `audit_periods(...,
    overlap=True)` — the async backend face launches N's dispatch and
    returns, so N+1 marshals/stages while N executes on device.
    overlap_ratio = seq_wall / overlap_wall; the acceptance bar on
    hermetic CPU is 'no slower' (>= ~1.0 — host/device concurrency is
    core-bound there); on TPU the ratio bounds how much host marshal
    the dispatch hides."""
    _setup_bench_env()

    import jax

    k = int(os.environ.get("GETHSHARDING_BENCH_OVERLAP_K", "4"))
    notary, periods = build_audit_workload(k)
    ps = periods[:k]

    # warm-up: compile the per-period shape + correctness gate both ways
    seq_res = {p: notary.audit_period(p) for p in ps}
    assert all(v is True for v in seq_res.values()), "audit inconsistent"
    ov_res = notary.audit_periods(ps, overlap=True)
    assert ov_res == seq_res, "overlapped verdicts must be identical"

    iters = 2
    t0 = time.perf_counter()
    for _ in range(iters):
        for p in ps:
            assert notary.audit_period(p) is True
    seq_wall = (time.perf_counter() - t0) / iters
    t0 = time.perf_counter()
    for _ in range(iters):
        res = notary.audit_periods(ps, overlap=True)
        assert all(res[p] is True for p in ps)
    ov_wall = (time.perf_counter() - t0) / iters
    return {
        "platform": jax.devices()[0].platform,
        "k_periods": k,
        "seq_wall_s": round(seq_wall, 4),
        "overlap_wall_s": round(ov_wall, 4),
        "overlap_ratio": round(seq_wall / ov_wall, 4),
        "sig_rate": round(k * SHARDS * COMMITTEE / ov_wall, 1),
        **_wire_stats(notary.sig_backend),
        "knobs": _knob_snapshot(),
    }


# == mesh-parallel committee audit (bench.py --mesh) =======================


def measure_mesh() -> dict:
    """The multi-chip audit closed loop: the SAME seeded committee
    workload through the scalar reference, the single-device jax
    backend, and the D-device mesh backend — verdicts must be
    bit-identical all three ways (sync AND async), the compiled mesh
    step must contain exactly ONE cross-device collective (the
    vote-total allreduce, counted from the AOT HLO), and the per-device
    cache shards must own DISJOINT buffer sets in the devscope census.
    Hermetic on the virtual CPU mesh (bit-identity and the collective
    count are platform-independent); on a real slice the same loop
    measures the interconnect instead of simulating it."""
    from gethsharding_tpu.parallel.virtual import force_virtual_cpu_devices

    n_devices = int(os.environ.get("GETHSHARDING_BENCH_MESH_DEVICES", "8"))
    force_virtual_cpu_devices(n_devices)

    import jax

    from gethsharding_tpu import devscope
    from gethsharding_tpu.crypto import bn256 as bls
    from gethsharding_tpu.sigbackend import PythonSigBackend
    from gethsharding_tpu.sigbackend.dispatch import JaxSigBackend

    # every device gets pointful rows (rows == bucket, divisible by D);
    # committees stay small so the scalar reference pairing loop is
    # tractable inside the bench budget
    rows = 3 * n_devices
    committee = 3
    msgs = [bytes([7, i % 251]) * 16 for i in range(rows)]
    kps = [[bls.bls_keygen(bytes([i, j, 13]) * 8) for j in range(committee)]
           for i in range(rows)]
    pk_rows = [[pk for _, pk in row] for row in kps]
    sig_rows = [[bls.bls_sign(m, sk) for sk, _ in row]
                for m, row in zip(msgs, kps)]
    # adversarial rows: one empty committee (must reject) and one forged
    # vote (must reject) — bit-identity must hold on rejections too
    pk_rows[1], sig_rows[1] = [], []
    sig_rows[rows - 2] = list(sig_rows[rows - 2])
    sig_rows[rows - 2][0] = bls.bls_sign(b"\xde\xad" * 16,
                                         kps[rows - 2][0][0])
    keys = [f"mesh-row-{i}" for i in range(rows)]

    ref = PythonSigBackend().bls_verify_committees(msgs, sig_rows, pk_rows)
    single = JaxSigBackend(mesh_devices=1)
    got_single = single.bls_verify_committees(msgs, sig_rows, pk_rows,
                                              pk_row_keys=keys)
    mesh = JaxSigBackend(mesh_devices=n_devices)
    got_mesh = mesh.bls_verify_committees(msgs, sig_rows, pk_rows,
                                          pk_row_keys=keys)
    got_async = mesh.bls_verify_committees_async(
        msgs, sig_rows, pk_rows, pk_row_keys=keys).result()
    assert ref == got_single == got_mesh == got_async, (
        "mesh audit verdicts must be bit-identical to the single-device "
        f"and scalar paths: ref={ref} single={got_single} "
        f"mesh={got_mesh} async={got_async}")
    info = dict(mesh.last_mesh or {})
    # the transfer-ledger acceptance bar: ONE collective (the vote-total
    # allreduce) per compiled step, verdict plane really sharded
    assert info.get("collectives") == 1, (
        f"mesh step must contain exactly one cross-device collective: "
        f"{info}")
    assert info.get("verdict_devices") == n_devices, (
        f"verdict plane must shard over all {n_devices} devices: {info}")
    assert info.get("vote_total") == sum(ref), (
        f"psum vote total must equal the verdict sum: {info} vs "
        f"{sum(ref)}")

    # per-device cache shards: every shard owns buffers, registered
    # under its own census owner, and ownership is DISJOINT
    owner_names = [f"pk_plane_lru_shard{i}" for i in range(n_devices)]
    registered = set(devscope.owners())
    assert all(name in registered for name in owner_names), (
        f"every mesh shard must register a census owner: {registered}")
    shard_buf_ids = [
        {id(buf) for buf in mesh._mesh_shard_buffers(i)}
        for i in range(n_devices)]
    assert all(shard_buf_ids), "every shard must hold resident buffers"
    for i in range(n_devices):
        for j in range(i + 1, n_devices):
            overlap = shard_buf_ids[i] & shard_buf_ids[j]
            assert not overlap, (
                f"cache shards {i} and {j} share {len(overlap)} "
                f"buffers — per-device ownership must be disjoint")
    census = devscope.poller().census()
    owners_census = {name: census["owners"].get(name, {})
                     for name in owner_names}

    # steady-state rate: the memoized mesh batch repeats every period
    iters = int(os.environ.get("GETHSHARDING_BENCH_MESH_ITERS", "5"))
    t0 = time.perf_counter()
    for _ in range(iters):
        res = mesh.bls_verify_committees(msgs, sig_rows, pk_rows,
                                         pk_row_keys=keys)
    wall = (time.perf_counter() - t0) / iters
    assert res == ref, "steady-state mesh verdicts drifted"
    warm_wire = dict(mesh.last_wire or {})
    return {
        "platform": jax.devices()[0].platform,
        "backend": f"jax-mesh{n_devices}",
        "n_devices": n_devices,
        "rows": rows,
        "committee_width": committee,
        "sig_rate": round(rows * committee / wall, 1),
        "audits_per_s": round(1.0 / wall, 2),
        "audit_wall_s": round(wall, 5),
        "collectives_per_step": info["collectives"],
        "verdict_devices": info["verdict_devices"],
        "vote_total": info["vote_total"],
        "bucket": info["bucket"],
        "g2_wire_bytes_warm": warm_wire.get("g2_wire_bytes"),
        "pk_hit_rows_warm": warm_wire.get("pk_hit_rows"),
        "shard_census": {
            name: {"claimed_bytes": entry.get("claimed_bytes"),
                   "buffers": entry.get("buffers"),
                   "drifted": entry.get("drifted")}
            for name, entry in owners_census.items()},
        "knobs": _knob_snapshot(),
    }


# == fixed-base precomputation closed loop (bench.py --precomp) ============


def measure_precomp() -> dict:
    """The fixed-base pairing-precomputation closed loop: the SAME
    seeded committee workload through the scalar reference, the jax
    backend with GETHSHARDING_PRECOMP=1 (line tables resident in the
    device LRU) and with =0 (today's recompute path) — verdicts
    bit-identical on every path, sync AND async, hostile rows included
    (an empty committee, a forged vote, and a pk aggregate cancelled to
    INFINITY); the warm precomp audit ships ZERO G2 bytes; and the
    compiled precomp executable's HLO op census carries far fewer
    `multiply` ops than the recompute twin — proof the fixed-argument
    Miller point arithmetic is really absent from the warm dispatch,
    not merely hidden. Hermetic on CPU (bit-identity and the census are
    platform-independent); the 05_precomp probe runs the same loop on
    TPU where the skipped work becomes sigs/sec."""
    _setup_bench_env()

    import jax
    import jax.numpy as jnp

    from gethsharding_tpu.crypto import bn256 as bls
    from gethsharding_tpu.ops import bn256_jax as k
    from gethsharding_tpu.sigbackend import PythonSigBackend
    from gethsharding_tpu.sigbackend.dispatch import JaxSigBackend
    from gethsharding_tpu.sigbackend.layout import count_ops

    rows, committee = 8, 3
    msgs = [bytes([19, i % 251]) * 16 for i in range(rows)]
    kps = [[bls.bls_keygen(bytes([i + 1, j + 1, 37]) * 8)
            for j in range(committee)] for i in range(rows)]
    pk_rows = [[pk for _, pk in row] for row in kps]
    sig_rows = [[bls.bls_sign(m, sk) for sk, _ in row]
                for m, row in zip(msgs, kps)]
    # hostile rows: an empty committee, a forged vote, and a pk
    # aggregate cancelled to INFINITY (pk + (-pk)) — every rejection
    # must be identical on every path (the line table of a cancelled
    # aggregate is the infinity-marked zero table, never a stale accept)
    pk_rows[1], sig_rows[1] = [], []
    sig_rows[3] = list(sig_rows[3])
    sig_rows[3][0] = bls.bls_sign(b"some other collation header!!!!!",
                                  kps[3][0][0])
    pk_rows[5] = [pk_rows[5][0], bls.g2_neg(pk_rows[5][0])]
    sig_rows[5] = sig_rows[5][:2]
    keys = [f"precomp-row-{i}" for i in range(rows)]

    want = PythonSigBackend().bls_verify_committees(msgs, sig_rows, pk_rows)
    assert want[1] is False and want[3] is False and want[5] is False, (
        f"hostile rows must reject on the scalar reference: {want}")

    on = JaxSigBackend()  # GETHSHARDING_PRECOMP defaults on
    assert on._precomp, "precomp must default ON for the jax backend"
    got_cold = on.bls_verify_committees(msgs, sig_rows, pk_rows,
                                        pk_row_keys=keys)
    cold = dict(on.last_wire or {})
    got_warm = on.bls_verify_committees(msgs, sig_rows, pk_rows,
                                        pk_row_keys=keys)
    warm = dict(on.last_wire or {})
    got_async = on.bls_verify_committees_async(
        msgs, sig_rows, pk_rows, pk_row_keys=keys).result()
    prev = os.environ.get("GETHSHARDING_PRECOMP")
    os.environ["GETHSHARDING_PRECOMP"] = "0"
    try:
        off = JaxSigBackend()
    finally:
        if prev is None:
            del os.environ["GETHSHARDING_PRECOMP"]
        else:
            os.environ["GETHSHARDING_PRECOMP"] = prev
    got_off = off.bls_verify_committees(msgs, sig_rows, pk_rows,
                                        pk_row_keys=keys)
    assert want == got_cold == got_warm == got_async == got_off, (
        f"precomp verdicts must be bit-identical to the scalar + "
        f"recompute paths: ref={want} cold={got_cold} warm={got_warm} "
        f"async={got_async} recompute={got_off}")
    assert cold.get("precomp") is True and warm.get("precomp") is True
    assert off.last_wire.get("precomp") is False
    assert cold.get("g2_wire_bytes", 0) > 0, f"cold must ship G2: {cold}"
    # THE acceptance bar: a warm precomp audit ships zero G2 bytes AND
    # skips the point-arithmetic half of the Miller loop (census below)
    assert warm.get("g2_wire_bytes") == 0, (
        f"warm line tables must ship zero G2 bytes: {warm}")
    assert warm.get("pk_hit_rows") == sum(1 for r in pk_rows if r), warm

    # the op census: AOT-compile the precomp kernel and its recompute
    # twin at one small shape and compare `multiply` counts — the
    # fixed-argument point arithmetic (dbl/madd per schedule step +
    # the on-device G2 aggregation) must be absent from the warm
    # executable (same contract as the mesh collective count: counted
    # from the optimized HLO text, no hand-claimed speedup)
    nl = k.NLIMBS
    steps = k.LINE_TABLE_SHAPE[0]
    b, w = 1, 2
    z32 = functools.partial(jnp.zeros, dtype=jnp.int32)
    pre_args = (z32((b, nl)), z32((b, nl)),
                z32((b, w, nl)), z32((b, w, nl)), jnp.zeros((b, w), bool),
                z32((b, steps, 3, 2, nl)),
                jnp.zeros((b,), bool), jnp.zeros((b,), bool))
    rec_args = (z32((b, nl)), z32((b, nl)),
                z32((b, w, nl)), z32((b, w, nl)), jnp.zeros((b, w), bool),
                z32((b, w, 2, nl)), z32((b, w, 2, nl)),
                jnp.zeros((b, w), bool), jnp.zeros((b,), bool))
    pre_mul = count_ops(jax.jit(k.bls_verify_committee_precomp_batch)
                        .lower(*pre_args).compile().as_text(), "multiply")
    rec_mul = count_ops(jax.jit(k.bls_aggregate_verify_committee_batch)
                        .lower(*rec_args).compile().as_text(), "multiply")
    assert 0 < pre_mul < 0.7 * rec_mul, (
        f"precomp executable must drop the fixed-argument point "
        f"arithmetic: {pre_mul} multiplies vs recompute {rec_mul}")

    # steady-state warm rate (each dispatch DeviceTimer-stamped inside
    # the backend; a lying pull lands on the suspect counter and
    # invalidates this run's ledger record via _emit)
    n_sigs = sum(len(r) for r in sig_rows)
    iters = int(os.environ.get("GETHSHARDING_BENCH_PRECOMP_ITERS", "5"))
    t0 = time.perf_counter()
    for _ in range(iters):
        res = on.bls_verify_committees(msgs, sig_rows, pk_rows,
                                       pk_row_keys=keys)
    wall = (time.perf_counter() - t0) / iters
    assert res == want, "steady-state precomp verdicts drifted"
    t0 = time.perf_counter()
    for _ in range(iters):
        res = off.bls_verify_committees(msgs, sig_rows, pk_rows,
                                        pk_row_keys=keys)
    recompute_wall = (time.perf_counter() - t0) / iters
    assert res == want, "steady-state recompute verdicts drifted"

    stats = {
        "platform": jax.devices()[0].platform,
        "backend": "jax-precomp",
        "rows": rows,
        "n_sigs": n_sigs,
        "sig_rate": round(n_sigs / wall, 1),
        "audit_wall_s": round(wall, 5),
        "recompute_wall_s": round(recompute_wall, 5),
        "precomp_speedup": round(recompute_wall / wall, 4),
        "blocks": warm.get("blocks"),
        "g2_wire_bytes_cold": cold.get("g2_wire_bytes"),
        "g2_wire_bytes_warm": warm.get("g2_wire_bytes"),
        "pk_hit_rows_warm": warm.get("pk_hit_rows"),
        "hlo_multiplies_precomp": pre_mul,
        "hlo_multiplies_recompute": rec_mul,
        "hlo_multiply_ratio": round(pre_mul / rec_mul, 4),
        "knobs": _knob_snapshot(),
    }
    stats.update(_measure_precomp_stress())
    return stats


def _measure_precomp_stress() -> dict:
    """The config-5-style stress rider of the precomp loop: one fused
    multi-shard stress step (addHeader + votes + BLS + replay +
    all-reduce) under the precomp-era tree, sized down on CPU so the
    hermetic probe finishes inside its budget (the TPU probe runs the
    full 1024-shard shape). Failures never sink the closed loop — the
    stress record is a rider, the bit-identity loop is the contract."""
    import jax

    from gethsharding_tpu.perfwatch import checked_pull

    if os.environ.get("GETHSHARDING_BENCH_PRECOMP_STRESS", "1") != "1":
        return {}
    try:
        from gethsharding_tpu.parallel.stress import (StressPipeline,
                                                      build_stress_inputs)
        from gethsharding_tpu.params import Config

        on_tpu = jax.devices()[0].platform == "tpu"
        n_shards = int(os.environ.get(
            "GETHSHARDING_BENCH_PRECOMP_SHARDS",
            "1024" if on_tpu else "32"))
        committee_size = COMMITTEE if on_tpu else 8
        inputs, pool, bh, sample_size, _ = build_stress_inputs(
            n_shards, votes_per_shard=2, txs_per_shard=1,
            committee_size=committee_size)
        cfg = Config() if committee_size == Config().committee_size \
            else Config(committee_size=committee_size,
                        quorum_size=max(1, (2 * committee_size) // 3))
        pipe = StressPipeline(config=cfg, mesh=None)
        res = pipe.run(inputs, pool, bh, 1, sample_size)
        jax.device_get(res.roots)  # compile + warm-up
        t0 = time.perf_counter()
        res = pipe.run(inputs, pool, bh, 1, sample_size)
        checked_pull(res.roots, op="bench/precomp_config5")
        dt = time.perf_counter() - t0
        return {"config5_shards": n_shards,
                "config5_committee": committee_size,
                "config5_stress_shards_per_s": round(n_shards / dt, 1)}
    except Exception as exc:  # noqa: BLE001 - rider, not the contract
        print(f"# precomp config5 stress rider failed: {exc!r}",
              file=sys.stderr)
        return {}


def measure_composed() -> dict:
    """Resident + overlap (+ precomp) COMPOSED: the K-period overlapped
    audit pipeline running against warm device-resident pk planes and
    line tables — the steady-state production shape all three levers
    stack into, queued since PR 3. Asserts overlapped-vs-sequential
    verdict identity and the warm zero-G2 wire under composition, then
    reports the composed rate (the 05_resident/05_overlap/05_precomp
    probes emit this as the `composed_audit` workload)."""
    _setup_bench_env()

    import jax

    k_periods = int(os.environ.get("GETHSHARDING_BENCH_COMPOSED_K", "3"))
    notary, periods = build_audit_workload(k_periods)
    ps = periods[:k_periods]
    backend = notary.sig_backend

    # compile + cold-cache pass, then the overlap identity gate
    seq = {p: notary.audit_period(p) for p in ps}
    assert all(v is True for v in seq.values()), "audit inconsistent"
    ov = notary.audit_periods(ps, overlap=True)
    assert ov == seq, "overlapped verdicts must equal sequential"
    warm = dict(backend.last_wire or {})
    if warm.get("resident"):
        assert warm.get("g2_wire_bytes") == 0, (
            f"composed warm audits must ship zero G2 bytes: {warm}")

    iters = 2
    t0 = time.perf_counter()
    for _ in range(iters):
        res = notary.audit_periods(ps, overlap=True)
        assert all(res[p] is True for p in ps)
    wall = (time.perf_counter() - t0) / iters
    return {
        "platform": jax.devices()[0].platform,
        "k_periods": k_periods,
        "precomp": warm.get("precomp"),
        "resident": warm.get("resident"),
        "sig_rate": round(k_periods * SHARDS * COMMITTEE / wall, 1),
        "composed_wall_s": round(wall, 4),
        "g2_wire_bytes_warm": warm.get("g2_wire_bytes"),
        "pk_hit_rows_warm": warm.get("pk_hit_rows"),
        "knobs": _knob_snapshot(),
    }


# == serving-tier amortization (bench.py --serving) ========================


def measure_serving() -> dict:
    """M concurrent clients x small requests through the serving tier vs
    the same clients driving the backend directly — the dispatch-
    amortization claim measured, not asserted. Hermetic by default
    (python inner backend: the coalescing win is dispatch-count
    amortization, visible on any backend; set
    GETHSHARDING_BENCH_SERVING_BACKEND=jax on a live accelerator)."""
    import threading

    from gethsharding_tpu.crypto import secp256k1 as ecdsa
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
    from gethsharding_tpu.sigbackend import get_backend

    clients = int(os.environ.get("GETHSHARDING_BENCH_SERVING_CLIENTS", "32"))
    per_client = int(os.environ.get("GETHSHARDING_BENCH_SERVING_REQS", "16"))
    inner = get_backend(
        os.environ.get("GETHSHARDING_BENCH_SERVING_BACKEND", "python"))

    cases = []
    for i in range(clients * per_client):
        priv = int.from_bytes(keccak256(b"serve-%d" % i), "big") % ecdsa.N
        digest = keccak256(b"serve-msg-%d" % i)
        cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                      ecdsa.priv_to_address(priv)))

    def drive(recover) -> float:
        """Each client thread issues `per_client` single-item requests;
        returns wall seconds. Divergence is a hard failure."""
        errors: list = []

        def client(c: int) -> None:
            for r in range(per_client):
                digest, sig, want = cases[c * per_client + r]
                if recover([digest], [sig]) != [want]:
                    errors.append((c, r))

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, f"result divergence at {errors[:4]}"
        return time.perf_counter() - t0

    total = clients * per_client
    direct_s = drive(inner.ecrecover_addresses)

    serving = ServingSigBackend(inner, ServingConfig(
        max_batch=int(os.environ.get("GETHSHARDING_SERVING_MAX_BATCH",
                                     "128")),
        flush_us=float(os.environ.get("GETHSHARDING_SERVING_FLUSH_US",
                                      "2000"))))
    try:
        serving_s = drive(serving.ecrecover_addresses)
        dispatches = serving.dispatch_count
    finally:
        serving.close()

    return {
        "backend": inner.name,
        "clients": clients,
        "requests": total,
        "serving_rate": round(total / serving_s, 1),
        "direct_rate": round(total / direct_s, 1),
        "speedup": round(direct_s / serving_s, 3),
        "dispatches": dispatches,
        "coalesce_ratio": round(total / max(1, dispatches), 1),
    }


def measure_fleet() -> dict:
    """The fleet-serving acceptance run: 3 breaker-guarded serving
    replicas behind the shard router, driven by the traffic-model soak
    (diurnal curve, hot-shard skew, thundering-herd burst, mixed
    admission classes) while a seeded chaos schedule trips replica
    r0's breaker mid-soak. Asserts the ISSUE 8 closed-loop bar:

    - zero divergences (every result verified against the known
      signer) and zero hung clients — nothing lost or mis-answered;
    - r0 drained at least once and RE-ENTERED through half-open
      re-promotion;
    - interactive saw ZERO sheds and held its p99 SLO, while the
      catchup_replay flood was shed first (replica-level counters).

    Hermetic by default (python replicas — the SLO default is
    calibrated for scalar host crypto; tighten
    GETHSHARDING_FLEET_SLO_INTERACTIVE_MS on an accelerator)."""
    duration = float(os.environ.get("GETHSHARDING_BENCH_FLEET_S", "12"))
    slo_ms = float(os.environ.get(
        "GETHSHARDING_FLEET_SLO_INTERACTIVE_MS", "8000"))
    backend = os.environ.get("GETHSHARDING_BENCH_FLEET_BACKEND", "python")
    clients = int(os.environ.get("GETHSHARDING_BENCH_FLEET_CLIENTS", "16"))
    cmd = [sys.executable,
           os.path.join(REPO, "scripts", "serving_stress.py"),
           "--replicas", "3", "--clients", str(clients),
           "--duration", str(duration), "--backend", backend,
           "--max-batch", "16", "--queue-cap", "16", "--policy", "shed",
           "--classes", "interactive=8,bulk_audit=4,catchup_replay=4",
           "--chaos-trip", "10", "--hot-shard", "0.9",
           "--diurnal-s", str(max(4.0, duration / 2)),
           "--herd-at", str(duration / 3),
           "--slo-interactive-ms", str(slo_ms)]
    env = {**os.environ}
    if backend == "python":
        env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=duration * 20 + 120, cwd=REPO, env=env)
    lines = [line for line in proc.stdout.strip().splitlines()
             if line.startswith("{")]
    assert lines, f"no soak output (rc {proc.returncode}): {proc.stderr}"
    summary = json.loads(lines[-1])
    assert summary.get("summary") and summary.get("fleet"), summary
    # the closed-loop acceptance assertions (the soak gates these too —
    # rc != 0 means one of them failed inside the run)
    assert proc.returncode == 0, (summary, proc.stderr[-2000:])
    assert summary["divergences"] == 0, summary
    assert summary["hung_clients"] == 0, summary
    assert summary["interactive_shed"] == 0, summary
    assert summary["drain_events"] >= 1, summary
    assert summary["reentered"], summary
    assert summary["chaos_injected"] >= 3, summary
    sheds = summary["replica_shed_by_class"]
    caller = summary["caller_shed"]
    assert sheds["interactive"] == 0, summary
    assert sheds["catchup_replay"] + caller["catchup_replay"] > 0, (
        "the catchup flood never shed — the overload phase tested "
        "nothing", summary)
    assert summary["p99_ms"]["interactive"] <= slo_ms, summary

    # -- the SLO-layer overhead gate (the PR 2 tracer-budget shape) --------
    # the serving hot path now records one SLO event per request (and a
    # routed request records a second at the router); both together must
    # cost <2% of a serving request. Measured, not assumed: a real
    # serving request's latency vs the amortized cost of
    # SLOTracker.record on a warm tracker.
    from gethsharding_tpu.metrics import Registry
    from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
    from gethsharding_tpu.sigbackend import PythonSigBackend
    from gethsharding_tpu.slo import SLOTracker

    serving = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=500.0),
                                registry=Registry())
    try:
        serving.ecrecover_addresses([], [])  # warm the threads
        n = 100
        t0 = time.perf_counter()
        for i in range(n):
            serving.ecrecover_addresses(
                [bytes([i % 251]) * 32], [b"\x00" * 65])
        per_request_s = (time.perf_counter() - t0) / n
    finally:
        serving.close()
    tracker = SLOTracker(registry=Registry())
    m = 20_000
    t0 = time.perf_counter()
    for _ in range(m):
        tracker.record("interactive", ok=True, latency_s=0.001)
    record_s = (time.perf_counter() - t0) / m
    slo_overhead_pct = 100.0 * 2 * record_s / per_request_s
    assert slo_overhead_pct < 2.0, (
        f"SLO layer overhead {slo_overhead_pct:.3f}% of a serving "
        f"request ({record_s * 1e6:.3f}us x2 vs "
        f"{per_request_s * 1e6:.1f}us) breaches the 2% budget")
    return {
        "replicas": 3,
        "clients": clients,
        "backend": backend,
        "platform": "cpu" if backend == "python"
        else (_probe_backend() or "cpu"),
        "duration_s": duration,
        "p99_ms": summary["p99_ms"],
        "slo_ms": summary["slo_ms"],
        "done": summary["done"],
        "replica_shed_by_class": sheds,
        "caller_shed": caller,
        "drain_events": summary["drain_events"],
        "reentries": summary["reentries"],
        "chaos_injected": summary["chaos_injected"],
        "states": summary["states"],
        "slo_record_us": round(record_s * 1e6, 3),
        "slo_overhead_pct": round(slo_overhead_pct, 4),
    }


def measure_elastic() -> dict:
    """The elastic-fleet acceptance run (ISSUE 20): the cross-process
    closed-loop soak (scripts/serving_stress.py --elastic) — 2
    chain_server replicas behind TWO peered frontend processes,
    frontend A running the SLO-driven autoscaler, FrontendPool clients
    riding a 10x diurnal swing, frontend B killed -9 mid-swing.
    Asserts the closed loop END TO END:

    - zero incorrect verdicts and zero hung clients through membership
      churn, autoscale spawns/retires, and the frontend kill;
    - the actors failed over to the surviving frontend (pool failover
      counter >= 1 — the kill was actually felt and survived);
    - the autoscaler was observed acting in BOTH directions, countered
      via frontend A's shard_fleetStatus: scale-OUT at the peak
      (sustained federated queue depth) AND scale-IN at the trough;
    - interactive p99 held its SLO across the whole swing.

    The soak itself appends the `fleet_elastic` workload record to the
    perf ledger through `perfwatch.record_bench` (noise-aware gate);
    this wrapper re-emits the headline number with the bench stamp."""
    duration = float(os.environ.get("GETHSHARDING_BENCH_ELASTIC_S", "16"))
    slo_ms = float(os.environ.get(
        "GETHSHARDING_FLEET_SLO_INTERACTIVE_MS", "8000"))
    clients = int(os.environ.get("GETHSHARDING_BENCH_FLEET_CLIENTS", "16"))
    cmd = [sys.executable,
           os.path.join(REPO, "scripts", "serving_stress.py"),
           "--elastic", "--clients", str(clients),
           "--duration", str(duration),
           "--slo-interactive-ms", str(slo_ms)]
    env = {**os.environ}
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=duration * 20 + 180, cwd=REPO, env=env)
    lines = [line for line in proc.stdout.strip().splitlines()
             if line.startswith("{")]
    assert lines, f"no soak output (rc {proc.returncode}): {proc.stderr}"
    summary = json.loads(lines[-1])
    assert summary.get("summary") and summary.get("elastic"), summary
    assert proc.returncode == 0, (summary, proc.stderr[-2000:])
    assert summary["divergences"] == 0, summary
    assert summary["hung_clients"] == 0, summary
    assert summary["frontend_killed"], summary
    assert summary["failovers"] >= 1, summary
    assert summary["scale_out"] >= 1, summary
    assert summary["scale_in"] >= 1, summary
    assert summary["epoch"] >= 2, summary  # one add + one remove
    assert not summary["slo_breach"], summary
    summary["platform"] = "cpu (hermetic)"
    return summary


def measure_hedge() -> dict:
    """The request-hedging closed loop (ISSUE 15 acceptance): a
    3-replica fleet where replica r0's TRANSPORT is chaos-delayed 10x
    (seeded ``fleet.transport`` delay rule: ~8% of its calls stall
    0.12 s vs the ~ms scalar baseline), driven by keyed interactive
    traffic twice — hedging OFF, then hedging ON with the same seed.
    Asserts, not reports:

    - interactive p99 improves >= 2x with hedging on (the tail IS the
      delayed replica; the hedge answers from the next affinity
      replica after the floor delay);
    - wasted duplicate dispatches stay <= 15% of all dispatches
      (hedges fire on the delayed tail, not on every call);
    - zero divergences in either phase (every verdict checked against
      the known signer).
    """
    from gethsharding_tpu.crypto import secp256k1 as ecdsa
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.fleet import FleetRouter, Replica
    from gethsharding_tpu.metrics import Registry
    from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                                   TransportChaos)
    from gethsharding_tpu.sigbackend import PythonSigBackend

    calls = int(os.environ.get("GETHSHARDING_BENCH_HEDGE_CALLS", "400"))
    delay_s = float(os.environ.get("GETHSHARDING_BENCH_HEDGE_DELAY_S",
                                   "0.12"))
    rate = float(os.environ.get("GETHSHARDING_BENCH_HEDGE_RATE", "0.08"))
    # the fleet-wide flag may be exported as 0 (hedging off in prod);
    # the CLOSED LOOP always hedges — a non-positive ambient value
    # falls back to the bench default instead of un-arming the gate
    hedge_ms = float(os.environ.get("GETHSHARDING_FLEET_HEDGE_MS")
                     or 0) or 15.0
    if hedge_ms <= 0:
        hedge_ms = 15.0
    cases = []
    for i in range(64):
        priv = int.from_bytes(keccak256(b"hedge-%d" % i), "big") % ecdsa.N
        digest = keccak256(b"hedge-msg-%d" % i)
        cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                      ecdsa.priv_to_address(priv)))

    def run_phase(hedge_on: bool) -> dict:
        registry = Registry()
        schedule = ChaosSchedule(
            seed=29, rules={"fleet.transport": rate},
            modes={"fleet.transport": "delay"}, delay_s=delay_s)
        replicas = [
            Replica("r0", TransportChaos(PythonSigBackend(), schedule),
                    probe=None, registry=registry),
            Replica("r1", PythonSigBackend(), probe=None,
                    registry=registry),
            Replica("r2", PythonSigBackend(), probe=None,
                    registry=registry),
        ]
        router = FleetRouter(replicas, health_interval_s=0.0,
                             hedge_ms=hedge_ms if hedge_on else 0,
                             registry=registry)
        lat, divergences = [], 0
        try:
            for i in range(calls):
                digest, sig, want = cases[i % len(cases)]
                t0 = time.perf_counter()
                got = router.call("ecrecover_addresses", [digest], [sig],
                                  affinity=f"shard-{i % 64}")
                lat.append(time.perf_counter() - t0)
                if got != [want]:
                    divergences += 1
            time.sleep(delay_s + 0.2)  # let hedge losers finish
        finally:
            router.close()
        lat.sort()
        stats = router.hedge_stats()
        return {
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
            "p99_ms": round(lat[int(0.99 * (len(lat) - 1))] * 1e3, 2),
            "divergences": divergences,
            "hedge": stats,
            "dispatches": calls + stats["issued"],
        }

    base = run_phase(hedge_on=False)
    hedged = run_phase(hedge_on=True)
    assert base["divergences"] == 0 and hedged["divergences"] == 0, (
        base, hedged)
    improvement = base["p99_ms"] / max(hedged["p99_ms"], 1e-9)
    assert improvement >= 2.0, (
        f"hedging bought only {improvement:.2f}x on interactive p99 "
        f"({base['p99_ms']} ms -> {hedged['p99_ms']} ms) — the "
        f"acceptance bar is 2x", base, hedged)
    wasted_pct = 100.0 * hedged["hedge"]["wasted"] / hedged["dispatches"]
    assert wasted_pct <= 15.0, (
        f"hedging wasted {wasted_pct:.1f}% of dispatches "
        f"(bar: <=15%)", hedged)
    assert hedged["hedge"]["issued"] > 0, (
        "the delayed tail never triggered a hedge — the phase tested "
        "nothing", hedged)
    return {
        "calls": calls,
        "delay_s": delay_s,
        "delay_rate": rate,
        "hedge_ms": hedge_ms,
        "p99_ms_no_hedge": base["p99_ms"],
        "p99_ms_hedged": hedged["p99_ms"],
        "p50_ms_hedged": hedged["p50_ms"],
        "improvement": round(improvement, 2),
        "hedges_issued": hedged["hedge"]["issued"],
        "hedges_won": hedged["hedge"]["won"],
        "hedges_wasted": hedged["hedge"]["wasted"],
        "wasted_pct": round(wasted_pct, 2),
    }


def measure_partition() -> dict:
    """The partition/kill soak (ISSUE 15 acceptance): mixed interactive
    traffic over a hedged 3-replica fleet while, mid-soak, replica r0
    is KILLED (its serving tier closed — every later call fails
    typed) and replica r1 is PARTITIONED for a seeded window
    (``fleet.transport`` partition rule: the wire raises the retryable
    transport fault, the router's consecutive-failure path trips it,
    and it re-enters after the window through the ordinary
    cooldown+health path). Asserted, not reported: ZERO incorrect
    verdicts, every caller-visible failure TYPED
    (shed/drain/deadline), and the partitioned replica re-entered."""
    import threading

    from gethsharding_tpu.crypto import secp256k1 as ecdsa
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.fleet import (AllReplicasDraining, FleetRouter,
                                        Replica)
    from gethsharding_tpu.metrics import Registry
    from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                                   TransportChaos)
    from gethsharding_tpu.resilience.errors import DeadlineExceeded
    from gethsharding_tpu.serving import (ServingConfig,
                                          ServingOverloadError,
                                          ServingSigBackend)
    from gethsharding_tpu.sigbackend import PythonSigBackend

    registry = Registry()
    # r1's partition window: wire calls 30..110 are refused (the
    # schedule is per-seam-call, so the window length covers the soak's
    # middle even with retries consuming slots)
    schedule = ChaosSchedule(
        seed=31, rules={"fleet.transport": lambda idx: 30 <= idx < 110},
        modes={"fleet.transport": "partition"})
    serving0 = ServingSigBackend(PythonSigBackend(),
                                 ServingConfig(flush_us=200),
                                 registry=registry)
    replicas = [
        Replica("r0", serving0, probe=None, registry=registry),
        Replica("r1", TransportChaos(PythonSigBackend(), schedule),
                probe=None, registry=registry,
                trip_cooldown_s=0.3),
        Replica("r2", PythonSigBackend(), probe=None, registry=registry),
    ]
    router = FleetRouter(replicas, health_interval_s=0.05, hedge_ms=10,
                         registry=registry)
    cases = []
    for i in range(32):
        priv = int.from_bytes(keccak256(b"part-%d" % i), "big") % ecdsa.N
        digest = keccak256(b"part-msg-%d" % i)
        cases.append((digest, ecdsa.sign(digest, priv).to_bytes65(),
                      ecdsa.priv_to_address(priv)))
    typed = (ServingOverloadError, AllReplicasDraining, DeadlineExceeded)
    divergences: list = []
    untyped: list = []
    typed_losses = {"shed": 0, "drain": 0, "deadline": 0}
    completed = [0]
    rounds = int(os.environ.get("GETHSHARDING_BENCH_PARTITION_ROUNDS",
                                "50"))
    kill_at = rounds // 3

    def client(c: int) -> None:
        for r in range(rounds):
            digest, sig, want = cases[(c * rounds + r) % len(cases)]
            try:
                got = router.call("ecrecover_addresses", [digest], [sig],
                                  affinity=f"shard-{(c + r) % 24}")
            except typed as exc:
                if isinstance(exc, AllReplicasDraining):
                    typed_losses["drain"] += 1
                elif isinstance(exc, DeadlineExceeded):
                    typed_losses["deadline"] += 1
                else:
                    typed_losses["shed"] += 1
                continue
            except Exception as exc:  # noqa: BLE001 - the gate itself
                untyped.append(repr(exc))
                continue
            completed[0] += 1
            if got != [want]:
                divergences.append((c, r, got))
            time.sleep(0.004)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(4)]
    for t in threads:
        t.start()
    # mid-soak kill: r0's serving tier closes under traffic — queued
    # futures fail typed, later calls refuse typed, the router retries
    # the survivors
    time.sleep(kill_at * 0.004 * 2)
    serving0.close()
    for t in threads:
        t.join(timeout=120)
    hung = [t for t in threads if t.is_alive()]
    # the partitioned replica's window is over: it re-enters through
    # cooldown + the background sweep
    deadline = time.monotonic() + 10
    while replicas[1].state != "healthy" and time.monotonic() < deadline:
        router.refresh(force=True)
        time.sleep(0.05)
    stats = router.hedge_stats()
    states = router.states()
    router.close()
    assert not hung, "hung soak client"
    assert divergences == [], divergences[:3]
    assert untyped == [], untyped[:5]
    assert completed[0] > 0
    assert replicas[1].state == "healthy", states
    assert replicas[1].reentries >= 1, states
    return {
        "rounds": rounds,
        "clients": 4,
        "completed": completed[0],
        "typed_losses": typed_losses,
        "untyped_losses": 0,
        "divergences": 0,
        "r1_trips_reentries": replicas[1].reentries,
        "hedge": stats,
        "states": {name: s["state"] for name, s in states.items()},
    }


def measure_chaos() -> dict:
    """Failover availability under a seeded chaos schedule: N ecrecover
    calls through `FailoverSigBackend` while the primary backend is hit
    by deterministic injected faults. The metric is the fraction of
    calls answered CORRECTLY (fallback-covered faults included) — the
    paper's always-vote contract, measured. Also reports the breaker's
    full cycle (trips, probes, re-close) under the schedule. Hermetic
    by default (python primary); GETHSHARDING_BENCH_CHAOS_BACKEND=jax
    runs the real device path on an accelerator (the 06_failover
    probe).

    GETHSHARDING_CHAOS_MODE=corrupt switches the schedule to SILENT
    corruption (wrong answers, no exceptions) with the soundness
    spot-checker (rate GETHSHARDING_SOUNDNESS_RATE) composed inside
    the failover slot; the report's detected-vs-undetected corruption
    counts say how much of the injected corruption the audit caught
    (detected corruption is served from the fallback and stays
    correct; undetected corruption is a wrong answer)."""
    from gethsharding_tpu.crypto import secp256k1 as ecdsa
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.metrics import Registry
    from gethsharding_tpu.resilience.breaker import (
        CLOSED, CircuitBreaker, FailoverSigBackend)
    from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                                   ChaosSigBackend)
    from gethsharding_tpu.sigbackend import PythonSigBackend, get_backend

    seed = int(os.environ.get("GETHSHARDING_CHAOS_SEED", "42"))
    rate = float(os.environ.get("GETHSHARDING_CHAOS_RATE", "0.3"))
    calls = int(os.environ.get("GETHSHARDING_BENCH_CHAOS_CALLS", "60"))
    rows = int(os.environ.get("GETHSHARDING_BENCH_CHAOS_ROWS", "8"))
    primary_name = os.environ.get("GETHSHARDING_BENCH_CHAOS_BACKEND",
                                  "python")
    mode = os.environ.get("GETHSHARDING_CHAOS_MODE", "fault")
    import random

    # faults only for the first 2/3 of the run: the tail is the recovery
    # window where the breaker must probe its way back to closed
    fault_calls = (calls * 2) // 3

    def fault_rule(idx: int) -> bool:
        return (idx < fault_calls
                and random.Random(f"{seed}:bench:{idx}").random() < rate)

    schedule = ChaosSchedule(
        seed=seed, rules={"backend.ecrecover_addresses": fault_rule},
        modes=({"backend.ecrecover_addresses": "corrupt"}
               if mode == "corrupt" else None))
    registry = Registry()
    breaker = CircuitBreaker(name="bench", fault_threshold=2,
                             reset_s=0.002, registry=registry)
    primary = ChaosSigBackend(get_backend(primary_name), schedule)
    if mode == "corrupt":
        # silent corruption is invisible to the breaker's exception
        # path: only the spot-checker can turn it into a fault
        from gethsharding_tpu.resilience.soundness import (
            SpotCheckSigBackend)

        primary = SpotCheckSigBackend(primary, registry=registry)
    backend = FailoverSigBackend(
        primary, PythonSigBackend(), breaker=breaker, registry=registry)

    batches = []
    for b in range(calls):
        digests, sigs, wants = [], [], []
        for r in range(rows):
            priv = int.from_bytes(
                keccak256(b"chaos-%d-%d" % (b, r)), "big") % ecdsa.N
            digest = keccak256(b"chaos-msg-%d-%d" % (b, r))
            digests.append(digest)
            sigs.append(ecdsa.sign(digest, priv).to_bytes65())
            wants.append(ecdsa.priv_to_address(priv))
        batches.append((digests, sigs, wants))

    correct = answered = 0
    t0 = time.perf_counter()
    for digests, sigs, wants in batches:
        try:
            got = backend.ecrecover_addresses(digests, sigs)
            answered += 1
            correct += int(got == wants)
        except Exception:  # noqa: BLE001 - an escape IS the finding
            pass
        time.sleep(0.004)  # let open-state cooldowns elapse
    wall_s = time.perf_counter() - t0

    def count(metric: str) -> int:
        return registry.counter(f"resilience/breaker/bench/{metric}").value

    injected = schedule.injected.get("backend.ecrecover_addresses", 0)
    # corrupt-mode accounting: a corruption the spot-checker caught
    # became a SoundnessViolation (served correct from the fallback);
    # one it missed is a silently wrong answer
    detected = registry.counter(
        "resilience/soundness/ecrecover_addresses/mismatches").value
    undetected = answered - correct if mode == "corrupt" else 0
    return {
        "primary": primary_name,
        "seed": seed,
        "rate": rate,
        "mode": mode,
        "calls": calls,
        "rows": rows,
        "chaos_availability": round(correct / calls, 4),
        "answered": answered,
        "injected_faults": injected if mode != "corrupt" else 0,
        "corruptions_injected": injected if mode == "corrupt" else 0,
        "corruptions_detected": detected,
        "corruptions_undetected": undetected,
        "breaker_trips": count("trips"),
        "breaker_probes": count("probes"),
        "breaker_closes": count("closes"),
        "fallback_calls": count("fallback_calls"),
        "breaker_reclosed": breaker.state == CLOSED,
        "wall_s": round(wall_s, 3),
        "platform": _chaos_platform(primary_name),
    }


def _chaos_platform(primary_name: str) -> str:
    if "jax" not in primary_name:
        return "host"
    import jax

    return jax.devices()[0].platform


def measure_soundness() -> dict:
    """The continuous soundness audit's two acceptance numbers in one
    run (bench.py --soundness):

    1. **Overhead** at the DEFAULT sample rate: the audit work per
       dispatch (always-on invariant sweep + rate-amortized sampled
       scalar re-verification) measured directly against the cost of a
       real-signature ecrecover dispatch — asserted <2%, the same
       budget-guard shape as the tracing and closed-breaker guards.
    2. **Closed-loop detection**: an every-dispatch silent corruptor
       (chaos mode=corrupt — wrong answers, no exceptions) must trip
       the failover breaker within the dispatch budget
       `dispatches_to_detect` predicts at 99.9% confidence.

    Hermetic by default (python primary);
    GETHSHARDING_BENCH_SOUNDNESS_BACKEND=jax times the real device
    dispatch (the 08_soundness probe)."""
    from gethsharding_tpu.crypto import secp256k1 as ecdsa
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.metrics import Registry
    from gethsharding_tpu.resilience.breaker import (
        OPEN, CircuitBreaker, FailoverSigBackend)
    from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                                   ChaosSigBackend)
    from gethsharding_tpu.resilience.soundness import (
        DEFAULT_RATE, DEFAULT_ROWS, SpotCheckSigBackend,
        detection_probability, dispatches_to_detect, soundness_table)
    from gethsharding_tpu.sigbackend import PythonSigBackend, get_backend

    seed = int(os.environ.get("GETHSHARDING_SOUNDNESS_SEED", "0"))
    rows = int(os.environ.get("GETHSHARDING_BENCH_SOUNDNESS_ROWS", "32"))
    primary_name = os.environ.get("GETHSHARDING_BENCH_SOUNDNESS_BACKEND",
                                  "python")
    primary = get_backend(primary_name)

    # -- part 1: audit overhead against a real-signature dispatch ----------
    digests, sigs = [], []
    for r in range(rows):
        priv = int.from_bytes(
            keccak256(b"soundness-%d" % r), "big") % ecdsa.N
        digest = keccak256(b"soundness-msg-%d" % r)
        digests.append(digest)
        sigs.append(ecdsa.sign(digest, priv).to_bytes65())
    cols = (digests, sigs)

    reps = 2 if primary_name == "python" else 8
    t0 = time.perf_counter()
    for _ in range(reps):
        out = primary.ecrecover_addresses(digests, sigs)
    per_dispatch_s = (time.perf_counter() - t0) / reps

    spot = SpotCheckSigBackend(primary, rate=DEFAULT_RATE,
                               rows=DEFAULT_ROWS, seed=seed,
                               registry=Registry())
    m = 50
    t0 = time.perf_counter()
    for _ in range(m):
        spot._check_invariants("ecrecover_addresses", cols, out)
        spot._tick("ecrecover_addresses")
    invariant_s = (time.perf_counter() - t0) / m
    k = 3
    t0 = time.perf_counter()
    for i in range(k):
        spot._spot_check("ecrecover_addresses", cols, out, idx=i)
    spotcheck_s = (time.perf_counter() - t0) / k
    # what one dispatch pays on average: the always-on sweep plus the
    # rate-amortized sampled re-verification
    audit_s = invariant_s + DEFAULT_RATE * spotcheck_s
    overhead_pct = 100.0 * audit_s / per_dispatch_s
    assert overhead_pct < 2.0, (
        f"soundness audit overhead {overhead_pct:.3f}% of a "
        f"{rows}-row dispatch ({audit_s * 1e6:.1f}us vs "
        f"{per_dispatch_s * 1e6:.1f}us) breaches the 2% budget")

    # -- part 2: closed-loop detection within the predicted budget ---------
    # an ambient GETHSHARDING_SOUNDNESS_RATE=0 (the node's off switch)
    # must not crash the closed loop — detection at rate 0 has no
    # budget, so the run falls back to the demonstration rate
    check_rate = float(os.environ.get("GETHSHARDING_SOUNDNESS_RATE",
                                      "0.25") or 0)
    if check_rate <= 0:
        check_rate = 0.25
    chaos_rows = 8
    budget = dispatches_to_detect(check_rate, DEFAULT_ROWS, chaos_rows,
                                  corrupt_rows=1, confidence=0.999)
    schedule = ChaosSchedule(
        seed=seed, rules={"backend.ecrecover_addresses": True},
        modes={"backend.ecrecover_addresses": "corrupt"})
    registry = Registry()
    breaker = CircuitBreaker(name="soundness", fault_threshold=1,
                             reset_s=60.0, registry=registry)
    backend = FailoverSigBackend(
        SpotCheckSigBackend(ChaosSigBackend(PythonSigBackend(), schedule),
                            rate=check_rate, rows=DEFAULT_ROWS, seed=seed,
                            registry=registry),
        PythonSigBackend(), breaker=breaker, registry=registry)
    garbage = ([b"\x11" * 32] * chaos_rows, [b"\x22" * 65] * chaos_rows)
    dispatches_to_trip = None
    for i in range(budget):
        backend.ecrecover_addresses(*garbage)
        if breaker.state == OPEN:
            dispatches_to_trip = i + 1
            break
    detected = dispatches_to_trip is not None
    assert detected, (
        f"silent corruption NOT detected within the predicted "
        f"{budget}-dispatch budget (rate {check_rate}, "
        f"{DEFAULT_ROWS}/{chaos_rows} rows)")

    return {
        "primary": primary_name,
        "rows": rows,
        "overhead_pct": round(overhead_pct, 4),
        "default_rate": DEFAULT_RATE,
        "rows_per_check": DEFAULT_ROWS,
        "per_dispatch_us": round(per_dispatch_s * 1e6, 1),
        "audit_us_per_dispatch": round(audit_s * 1e6, 2),
        "invariant_us": round(invariant_s * 1e6, 2),
        "spot_check_us": round(spotcheck_s * 1e6, 1),
        "detection_rate": check_rate,
        "dispatches_to_trip": dispatches_to_trip,
        "predicted_budget_p999": budget,
        "p_detect_per_dispatch": round(detection_probability(
            check_rate, DEFAULT_ROWS, chaos_rows), 4),
        "soundness_mismatches": registry.counter(
            "resilience/soundness/ecrecover_addresses/mismatches").value,
        "soundness_table_64": soundness_table(64, DEFAULT_ROWS),
        "platform": _chaos_platform(primary_name),
    }


# == data-availability sampling (bench.py --das) ===========================


def measure_das() -> dict:
    """Full-fetch vs sampled availability: bytes per collation, plus
    batched sample-verify throughput.

    Part 1 is the END-TO-END acceptance run: a proposer publishes
    erasure-extended bodies, a notary in sampled DA mode votes across
    several periods over a live shardp2p hub, and the harness asserts
    (a) not one CollationBodyRequest left the notary and (b) fetched
    bytes per collation stay within k·chunk_size + proof overhead —
    against the full-fetch baseline of body_size bytes per collation.

    Part 2 measures `das_verify_samples` rows/sec: the scalar python
    reference vs the batched backend (GETHSHARDING_BENCH_DAS_BACKEND,
    default jax), verdict-checked bit-for-bit. Hermetic on CPU; the
    07_das probe runs the same thing against the real chip."""
    import random as _random

    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.actors.proposer import create_collation
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.core.types import Transaction
    from gethsharding_tpu.das.erasure import DAS_CHUNK_SIZE, extend_body
    from gethsharding_tpu.das.proofs import (MAX_PROOF_DEPTH, chunk_leaf,
                                             merkle_levels, merkle_proof)
    from gethsharding_tpu.das.sampler import detection_probability
    from gethsharding_tpu.das.service import DASService
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.p2p.messages import CollationBodyRequest
    from gethsharding_tpu.p2p.service import Hub, P2PServer
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.sigbackend import get_backend
    from gethsharding_tpu.smc.chain import SimulatedMainchain

    body_size = int(os.environ.get("GETHSHARDING_BENCH_DAS_BODY",
                                   str(256 * 1024)))
    k_samples = int(os.environ.get("GETHSHARDING_BENCH_DAS_SAMPLES", "16"))
    n_periods = int(os.environ.get("GETHSHARDING_BENCH_DAS_PERIODS", "3"))
    backend_name = os.environ.get("GETHSHARDING_BENCH_DAS_BACKEND", "jax")

    # -- part 1: the sampled-notary acceptance run -------------------------
    config = Config(quorum_size=1, period_length=4)
    chain = SimulatedMainchain(config=config)
    prop_client = SMCClient(backend=chain, config=config)
    not_client = SMCClient(backend=chain, config=config)
    chain.fund(prop_client.account(), 2000 * ETHER)
    chain.fund(not_client.account(), 2000 * ETHER)
    hub = Hub()
    watch = P2PServer(hub)
    watch.start()  # must be hub-attached or broadcasts never reach it
    body_watch = watch.subscribe(CollationBodyRequest)
    svc_prop = DASService(client=prop_client, p2p=P2PServer(hub),
                          samples=k_samples)
    svc_not = DASService(client=not_client, p2p=P2PServer(hub),
                         samples=k_samples)
    svc_prop.start()
    svc_not.start()
    notary = Notary(client=not_client, shard=Shard(0, MemoryKV()),
                    p2p=svc_not.p2p, config=config, deposit_flag=True,
                    all_shards=False, sig_backend=get_backend("python"),
                    das=svc_not, da_mode="sampled")
    notary.start()
    chain.fast_forward(1)
    rng = _random.Random(1)
    try:
        for _ in range(n_periods):
            period = chain.current_period()
            collation = create_collation(
                prop_client, 0, period,
                [Transaction(nonce=period,
                             payload=bytes(rng.randrange(256)
                                           for _ in range(body_size)))])
            svc_prop.publish(0, period, collation.header.chunk_root,
                             collation.body)
            prop_client.add_header(0, period,
                                   collation.header.chunk_root,
                                   collation.header.proposer_signature)
            chain.commit()
            notary.notarize_collations(head=chain.block_number)
            while chain.current_period() == period:
                chain.commit()
        assert notary.votes_submitted == n_periods, notary.errors
        assert body_watch.try_get() is None, \
            "a CollationBodyRequest left the sampled notary"
        sampled_bytes = svc_not.bytes_fetched / n_periods
        budget = k_samples * (DAS_CHUNK_SIZE + 32 * MAX_PROOF_DEPTH + 40)
        assert sampled_bytes <= budget, (sampled_bytes, budget)
    finally:
        notary.stop()
        svc_prop.stop()
        svc_not.stop()
        watch.stop()

    # -- part 2: batched verify throughput ---------------------------------
    xb = extend_body(bytes(rng.randrange(256)
                           for _ in range(body_size)), 0.5)
    levels = merkle_levels([chunk_leaf(c) for c in xb.chunks])
    das_root = levels[-1][0]
    rows = int(os.environ.get("GETHSHARDING_BENCH_DAS_ROWS", "128"))
    idx = [rng.randrange(xb.n) for _ in range(rows)]
    chunks = [xb.chunks[i] for i in idx]
    prfs = [merkle_proof(levels, i) for i in idx]
    roots = [das_root] * rows
    scalar = get_backend("python")
    batched = get_backend(backend_name)
    want = scalar.das_verify_samples(chunks, idx, prfs, roots)
    assert all(want)
    got = batched.das_verify_samples(chunks, idx, prfs, roots)  # compile
    assert got == want, "batched verdicts diverge from scalar"
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        batched.das_verify_samples(chunks, idx, prfs, roots)
    batched_s = (time.perf_counter() - t0) / iters
    t0 = time.perf_counter()
    scalar.das_verify_samples(chunks, idx, prfs, roots)
    scalar_s = time.perf_counter() - t0
    ledger = getattr(batched, "last_wire", None) or {}

    import jax

    return {
        "platform": jax.devices()[0].platform,
        "body_bytes": body_size,
        "k_samples": k_samples,
        "periods": n_periods,
        "votes": n_periods,
        "full_fetch_bytes_per_collation": body_size,
        "sampled_bytes_per_collation": round(sampled_bytes, 1),
        "bytes_ratio": round(sampled_bytes / body_size, 4),
        "sample_budget_bytes": budget,
        "detection_probability": round(
            detection_probability(k_samples, xb.n, xb.k), 6),
        "verify_rows": rows,
        "verify_backend": backend_name,
        "verify_rows_per_sec": round(rows / batched_s, 1),
        "scalar_rows_per_sec": round(rows / scalar_s, 1),
        "verify_speedup": round(scalar_s / batched_s, 3),
        "sample_wire_bytes_per_dispatch": ledger.get("sample_wire_bytes"),
    }


# == polynomial-multiproof DAS (bench.py --das-poly) =======================


def measure_das_poly() -> dict:
    """Constant-size multiproofs vs merkle paths: proof bytes per
    sampled collation, plus batched multiproof-verify throughput.

    Part 1 is the proof-size acceptance check: at the default sampling
    shape (k sampled chunks per collation) the polynomial multiproof
    is ONE 64-byte G1 point where the merkle mode ships k sibling
    paths — the run asserts the ≥5× byte cut the scheme exists for,
    and that the proof stays 64 bytes as k grows.

    Part 2 measures `das_verify_multiproofs` rows/sec: the scalar PCS
    reference (one two-pair pairing per row, host python) vs the
    batched backend (GETHSHARDING_BENCH_DAS_BACKEND, default jax)
    folding every row into one fixed-shape pairing dispatch,
    verdict-checked bit-for-bit. Hermetic on CPU."""
    import random as _random

    from gethsharding_tpu.das import pcs
    from gethsharding_tpu.das.erasure import extend_body
    from gethsharding_tpu.das.sampler import proof_bytes, sample_indices
    from gethsharding_tpu.sigbackend import get_backend

    body_size = int(os.environ.get("GETHSHARDING_BENCH_DAS_BODY",
                                   str(256 * 1024)))
    k_samples = int(os.environ.get("GETHSHARDING_BENCH_DAS_SAMPLES", "16"))
    rows = int(os.environ.get("GETHSHARDING_BENCH_DAS_POLY_ROWS", "6"))
    backend_name = os.environ.get("GETHSHARDING_BENCH_DAS_BACKEND", "jax")
    rng = _random.Random(1)

    # -- part 1: proof bytes per sampled collation -------------------------
    merkle_bytes = proof_bytes(k_samples, "merkle")
    poly_bytes = proof_bytes(k_samples, "poly")
    xb = extend_body(bytes(rng.randrange(256)
                           for _ in range(body_size)), 0.5)
    values = [pcs.chunk_value(c) for c in xb.chunks]
    indices = sample_indices(rng.randbytes(32), k_samples, xb.n)
    proof, _evals = pcs.open_multi(values, indices)
    assert len(pcs.g1_to_bytes(proof)) == poly_bytes == 64
    assert merkle_bytes >= 5 * poly_bytes, (merkle_bytes, poly_bytes)
    # constant in k: doubling the sample count moves the merkle cost,
    # not the poly cost
    wide = sample_indices(rng.randbytes(32), 2 * k_samples, xb.n)
    wide_proof, _ = pcs.open_multi(values, wide)
    assert len(pcs.g1_to_bytes(wide_proof)) == poly_bytes

    # -- part 2: batched verify throughput ---------------------------------
    commitments, index_rows, eval_rows, proofs, ns = [], [], [], [], []
    for row in range(rows):
        row_values = [rng.randrange(pcs.N) for _ in range(xb.n)]
        row_indices = sample_indices(rng.randbytes(32), k_samples, xb.n)
        row_proof, row_evals = pcs.open_multi(row_values, row_indices)
        commitments.append(pcs.g1_to_bytes(pcs.commit(row_values)))
        index_rows.append(row_indices)
        eval_rows.append(row_evals)
        proofs.append(pcs.g1_to_bytes(row_proof))
        ns.append(xb.n)
    scalar = get_backend("python")
    batched = get_backend(backend_name)
    t0 = time.perf_counter()
    want = scalar.das_verify_multiproofs(commitments, index_rows,
                                         eval_rows, proofs, ns)
    scalar_s = time.perf_counter() - t0
    assert all(want)
    got = batched.das_verify_multiproofs(commitments, index_rows,
                                         eval_rows, proofs, ns)  # compile
    assert got == want, "batched multiproof verdicts diverge from scalar"
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        batched.das_verify_multiproofs(commitments, index_rows,
                                       eval_rows, proofs, ns)
    batched_s = (time.perf_counter() - t0) / iters
    ledger = getattr(batched, "last_wire", None) or {}

    import jax

    return {
        "platform": jax.devices()[0].platform,
        "body_bytes": body_size,
        "k_samples": k_samples,
        "n_chunks": xb.n,
        "merkle_proof_bytes_per_collation": merkle_bytes,
        "poly_proof_bytes_per_collation": poly_bytes,
        "proof_bytes_cut": round(merkle_bytes / poly_bytes, 2),
        "verify_rows": rows,
        "verify_backend": backend_name,
        "verify_rows_per_sec": round(rows / batched_s, 2),
        "scalar_rows_per_sec": round(rows / scalar_s, 2),
        "verify_speedup": round(scalar_s / batched_s, 3),
        "wire_bytes_per_dispatch": ledger.get("wire_bytes"),
    }


# == perfwatch closed-loop acceptance (bench.py --perfwatch) ===============


def measure_perfwatch() -> dict:
    """The measurement substrate's own acceptance run, closed-loop:

    1. **Gate trips on a real slowdown.** Seed a fresh ledger with
       clean CPU-quick micro-suite runs, assert the gate passes, inject
       a 1.3x slowdown into one registered microbenchmark and assert
       `--check` flags exactly that workload, then assert a clean rerun
       passes again (the injected record does not poison the median).
    2. **The timer cannot be lied to.** A simulated no-op
       `block_until_ready` (the r4 tunnel-plugin hazard) must increment
       `perfwatch/timer_suspect` and flag the enclosing ledger record
       invalid.
    3. **The black box is complete.** A chaos-injected dispatch hang
       under the serving watchdog must produce a flight-recorder bundle
       containing the event ring (with the watchdog_timeout and
       chaos_decision events), the finished-span ring, a metrics
       snapshot, and the ledger tail.
    4. **It all stays cheap.** DeviceTimer + recorder ring appends per
       dispatch are measured against a real serving request and
       asserted <2% — the same budget bar as the tracing and SLO
       layers."""
    import tempfile
    import threading

    import numpy as _np

    from gethsharding_tpu import metrics as _metrics
    from gethsharding_tpu import perfwatch
    from gethsharding_tpu.perfwatch import gate as pgate
    from gethsharding_tpu.perfwatch import registry as pregistry
    from gethsharding_tpu.perfwatch.ledger import Ledger
    from gethsharding_tpu.perfwatch.recorder import RECORDER
    from gethsharding_tpu.perfwatch.timer import DeviceTimer

    out: dict = {}
    tmp = tempfile.mkdtemp(prefix="bench_perfwatch_")
    ledger = Ledger(os.path.join(tmp, "ledger.jsonl"))

    # -- part 1: the regression gate, tripped by an honest 1.3x ------------
    # the drill lane is the deterministic clock-spin reference bench:
    # the REAL workload benches drift ~20% with host load on a shared
    # box (their gating belongs to a quiet CI lane, with the band
    # doing the noise absorption), but the acceptance contract here —
    # "1.3x trips, clean reruns do not" — must hold on ANY machine,
    # so it is asserted on the bench whose wall the clock controls
    target = "clock_spin_5ms"
    lane = [f"micro/{target}"]
    clean_runs = 4
    for _ in range(clean_runs):
        pregistry.run_suite(ledger=ledger, quick=True, inject={})
    full = pgate.check(ledger)  # the whole-suite face, reported below
    clean = pgate.check(ledger, workloads=lane)
    assert not clean.failed, [vars(v) for v in clean.regressions]
    pregistry.run_suite(ledger=ledger, quick=True,
                        inject={target: 1.3})
    tripped = pgate.check(ledger, workloads=lane)
    flagged = {v.workload for v in tripped.regressions}
    assert tripped.failed and f"micro/{target}" in flagged, (
        f"injected 1.3x slowdown on {target} did not trip the gate: "
        f"{[vars(v) for v in tripped.verdicts]}")
    pregistry.run_suite(ledger=ledger, quick=True, inject={})
    healed = pgate.check(ledger, workloads=lane)
    assert not healed.failed, (
        "clean rerun after the injected record still trips",
        [vars(v) for v in healed.regressions])
    out["gate_clean_runs"] = clean_runs
    out["gate_metrics_checked"] = len(full.verdicts)
    out["gate_tripped_on"] = sorted(flagged)

    # -- part 2: the simulated no-op block_until_ready ---------------------
    class _NoopBlockValue:
        """block_until_ready returns instantly; the REAL pull takes the
        dispatch latency — exactly the r4 tunnel-plugin behavior."""

        def block_until_ready(self):
            return self

        def __array__(self, dtype=None, copy=None):
            time.sleep(0.3)  # the "real" dispatch the block hid —
            # above the 0.25 s suspect floor, like the r4 0.455 s case
            return _np.zeros(4, dtype=dtype or _np.int32)

    suspects_before = perfwatch.suspect_count()
    dt = DeviceTimer("bench/suspect_demo")
    dt.dispatched()
    dt.pull(_NoopBlockValue())
    dt.done()
    assert dt.suspect, "no-op block_until_ready went undetected"
    assert perfwatch.suspect_count() == suspects_before + 1
    # ... and a record taken over the suspect window is stamped invalid
    rec = perfwatch.record_bench(
        metric="suspect_demo", value=dt.device_s, unit="s", extra={},
        suspects=perfwatch.suspect_count() - suspects_before,
        ledger=ledger)
    assert rec["valid"] is False, rec
    out["timer_suspects"] = perfwatch.suspect_count() - suspects_before
    out["suspect_record_valid"] = rec["valid"]

    # -- part 3: chaos hang -> watchdog -> complete black-box bundle -------
    from gethsharding_tpu.resilience.chaos import (ChaosSchedule,
                                                   ChaosSigBackend)
    from gethsharding_tpu.resilience.errors import DeadlineExceeded
    from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
    from gethsharding_tpu.sigbackend import PythonSigBackend

    old_env = {k: os.environ.get(k) for k in
               ("GETHSHARDING_PERFWATCH_DIR", "GETHSHARDING_PERFWATCH_DUMP_S",
                "GETHSHARDING_PERFWATCH_LEDGER")}
    os.environ["GETHSHARDING_PERFWATCH_DIR"] = os.path.join(tmp, "blackbox")
    os.environ["GETHSHARDING_PERFWATCH_DUMP_S"] = "0"
    os.environ["GETHSHARDING_PERFWATCH_LEDGER"] = ledger.path
    try:
        schedule = ChaosSchedule(
            seed=7, rules={"dispatch.ecrecover_addresses": 1})
        serving = ServingSigBackend(
            ChaosSigBackend(PythonSigBackend(), schedule, hang_s=2.0),
            ServingConfig(flush_us=200.0, watchdog_s=0.2))
        try:
            try:
                serving.ecrecover_addresses([b"\x11" * 32], [b"\x22" * 65])
                raise AssertionError("hung dispatch did not fail")
            except DeadlineExceeded:
                pass  # the watchdog fired — the trigger under test
            deadline = time.monotonic() + 10.0
            bundle = None
            while time.monotonic() < deadline:
                RECORDER.flush()
                base = os.environ["GETHSHARDING_PERFWATCH_DIR"]
                dirs = sorted(os.listdir(base)) if os.path.isdir(base) \
                    else []
                if dirs:
                    bundle = os.path.join(base, dirs[-1])
                    break
                time.sleep(0.05)
            assert bundle is not None, "watchdog fired but no bundle"
            required = ("manifest.json", "events.json", "spans.json",
                        "metrics.json", "wire.json", "ledger_tail.jsonl")
            present = sorted(os.listdir(bundle))
            missing = [f for f in required if f not in present]
            assert not missing, f"bundle incomplete: missing {missing}"
            events = json.load(open(os.path.join(bundle, "events.json")))
            kinds = {e["kind"] for e in events}
            assert "watchdog_timeout" in kinds, kinds
            assert "chaos_decision" in kinds, kinds
            snapshot = json.load(open(os.path.join(bundle,
                                                   "metrics.json")))
            assert "resilience/watchdog/timeouts" in snapshot
            tail = [json.loads(line) for line in
                    open(os.path.join(bundle, "ledger_tail.jsonl"))]
            assert tail, "ledger tail empty in the bundle"
            out["bundle"] = bundle
            out["bundle_files"] = present
            out["bundle_events"] = sorted(kinds)
            out["bundle_ledger_tail"] = len(tail)
        finally:
            serving.close()
    finally:
        for key, val in old_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val

    # -- part 4: the hot-path overhead budget ------------------------------
    serving = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=500.0))
    try:
        serving.ecrecover_addresses([], [])  # warm the threads
        n = 100
        t0 = time.perf_counter()
        for i in range(n):
            serving.ecrecover_addresses(
                [bytes([i % 251]) * 32], [b"\x00" * 65])
        per_request_s = (time.perf_counter() - t0) / n
    finally:
        serving.close()
    arr = _np.zeros(8, _np.int32)
    wire = {"wire_bytes": 1024, "g2_wire_bytes": 0, "pk_hit_bytes": 1024,
            "pk_rows": 100, "pk_hit_rows": 100, "resident": True,
            "wire": "i32"}
    m = 20_000
    t0 = time.perf_counter()
    for _ in range(m):
        dt = DeviceTimer("overhead_probe")
        dt.dispatched()
        dt.pull(arr)
        dt.done()
        RECORDER.record_wire("overhead_probe", wire)
    per_dispatch_s = (time.perf_counter() - t0) / m
    overhead_pct = 100.0 * per_dispatch_s / per_request_s
    assert overhead_pct < 2.0, (
        f"perfwatch timer+recorder overhead {overhead_pct:.3f}% of a "
        f"serving request ({per_dispatch_s * 1e6:.2f}us vs "
        f"{per_request_s * 1e6:.1f}us) breaches the 2% budget")
    out["overhead_pct"] = round(overhead_pct, 4)
    out["per_dispatch_us"] = round(per_dispatch_s * 1e6, 3)
    out["per_request_us"] = round(per_request_s * 1e6, 1)
    out["platform"] = "host"
    assert threading.active_count() < 100  # no thread leak from the loop
    # the suspect DRILL above (part 2) incremented the process-global
    # timer_suspect counter on purpose; resync the emitter's mark so
    # the headline record of this mode is not stamped invalid by its
    # own demonstration
    global _SUSPECT_MARK
    _SUSPECT_MARK = perfwatch.suspect_count()
    return out


# == devscope closed-loop acceptance (bench.py --devscope) =================


def measure_devscope() -> dict:
    """The device-introspection plane's acceptance run, closed-loop:

    1. **The storm detector fires exactly once.** An injected recompile
       storm (unbucketed traffic widening the compiled-shape set past
       the window threshold) must raise ONE `recompile_storm` recorder
       event and one `storms` tick — not one per fresh shape — while a
       steady-state stream of cache hits plus the occasional genuinely
       new bucket raises nothing.
    2. **A near-OOM leaves a census.** A simulated device at 95% HBM
       utilization must fire the flight recorder's dump path, and the
       resulting bundle's event ring must contain the `hbm_near_oom`
       event WITH the buffer census attributing live buffers to their
       registered owner.
    3. **It all stays cheap.** The sampling profiler's per-tick cost ×
       its rate plus the memory poller's per-poll cost ÷ its interval —
       the fraction of wall time the plane consumes while a serving
       request runs — is measured against a real serving request and
       asserted <2% (the same budget bar as tracing/SLO/perfwatch)."""
    import tempfile

    from gethsharding_tpu import devscope
    from gethsharding_tpu import metrics as _metrics
    from gethsharding_tpu.devscope import (CompileWatch, MemoryPoller,
                                           SamplingProfiler)
    from gethsharding_tpu.perfwatch.recorder import RECORDER

    out: dict = {}
    tmp = tempfile.mkdtemp(prefix="bench_devscope_")
    # the drills run against ISOLATED metric registries: an injected
    # storm or a fake 15-GiB device must exercise the detectors without
    # latching this process's real devscope/* rows (recorder events
    # stay global on purpose — they ARE the acceptance evidence)
    drill_reg = _metrics.Registry()

    # -- part 1: the recompile-storm detector, exactly once ---------------
    def _storm_events() -> int:
        return sum(1 for e in RECORDER.events()
                   if e["kind"] == "recompile_storm")

    watch = CompileWatch(storm_shapes=8, storm_window_s=30.0,
                         registry=drill_reg)
    events_before = _storm_events()
    for _ in range(64):  # steady state: the same bucketed shape, hits
        watch.saw("bls_committee", (128, 144), False)
    watch.saw("bls_committee", (160, 144), True)  # one honest new bucket
    assert watch.storms == 0, "a single fresh shape must not be a storm"
    assert _storm_events() == events_before
    for i in range(16):  # the storm: unbucketed widths flooding in
        watch.saw("bls_committee", (100 + i, 144), True)
    assert watch.storms == 1, (
        f"injected recompile storm raised {watch.storms} times, want 1")
    for i in range(16, 32):  # an ONGOING storm must not re-raise
        watch.saw("bls_committee", (100 + i, 144), True)
    assert watch.storms == 1, "ongoing storm re-raised the detector"
    storm_events = _storm_events() - events_before
    assert storm_events == 1, (
        f"{storm_events} recompile_storm recorder events, want exactly 1")
    assert watch.storm_active(), "storm gauge should still be latched"
    out["storm_raised"] = watch.storms
    out["storm_recorder_events"] = storm_events
    out["storm_fresh_shapes"] = 33

    # -- part 2: simulated near-OOM -> bundle with the buffer census ------
    class _Buf:
        def __init__(self, nbytes, shape):
            self.nbytes = nbytes
            self.shape = shape
            self.dtype = "int32"

    bufs = [_Buf(48 << 20, (1024, 135, 2, 25)),
            _Buf(16 << 20, (1024, 135, 2, 25)),
            _Buf(4 << 20, (128, 144))]

    class _HotDevice:
        id = 0
        platform = "tpu"

        def memory_stats(self):
            return {"bytes_in_use": int(15.2 * (1 << 30)),
                    "peak_bytes_in_use": int(15.4 * (1 << 30)),
                    "bytes_limit": 16 << 30}

    devscope.register_owner(
        "bench_demo_plane",
        claimed_fn=lambda: sum(b.nbytes for b in bufs),
        buffers_fn=lambda: list(bufs))
    old_env = {k: os.environ.get(k) for k in
               ("GETHSHARDING_PERFWATCH_DIR", "GETHSHARDING_PERFWATCH_DUMP_S")}
    os.environ["GETHSHARDING_PERFWATCH_DIR"] = os.path.join(tmp, "blackbox")
    os.environ["GETHSHARDING_PERFWATCH_DUMP_S"] = "0"
    try:
        poller = MemoryPoller(interval_s=60.0,
                              devices_fn=lambda: [_HotDevice()],
                              buffers_fn=lambda: list(bufs),
                              registry=drill_reg)
        readings = poller.poll_once()
        assert readings["d0"]["limit"] == 16 << 30
        deadline = time.monotonic() + 10.0
        bundle = None
        while time.monotonic() < deadline:
            RECORDER.flush()
            base = os.environ["GETHSHARDING_PERFWATCH_DIR"]
            dirs = sorted(os.listdir(base)) if os.path.isdir(base) else []
            if dirs:
                bundle = os.path.join(base, dirs[-1])
                break
            time.sleep(0.05)
        assert bundle is not None, "near-OOM fired but no bundle appeared"
        events = json.load(open(os.path.join(bundle, "events.json")))
        oom = [e for e in events if e["kind"] == "hbm_near_oom"]
        assert oom, f"no hbm_near_oom event in the bundle: " \
                    f"{sorted({e['kind'] for e in events})}"
        census = oom[-1]["detail"]["census"]
        assert census["live_buffers"] == len(bufs), census
        owner_slot = census["by_owner"].get("bench_demo_plane")
        assert owner_slot and owner_slot["bytes"] == sum(
            b.nbytes for b in bufs), census["by_owner"]
        assert not census["owners"]["bench_demo_plane"]["drifted"]
        # a second poll at the same utilization must NOT re-dump: the
        # episode latch holds until utilization clears the hysteresis
        near_oom_before = poller.describe()["near_oom_events"]
        poller.poll_once()
        assert poller.describe()["near_oom_events"] == near_oom_before, (
            "near-OOM re-fired inside one episode")
        out["bundle"] = bundle
        out["census_buffers"] = census["live_buffers"]
        out["census_owned_bytes"] = owner_slot["bytes"]
    finally:
        devscope.unregister_owner("bench_demo_plane")
        for key, val in old_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val

    # -- part 3: sampler + poller overhead vs a serving request -----------
    from gethsharding_tpu.serving import ServingConfig, ServingSigBackend
    from gethsharding_tpu.sigbackend import PythonSigBackend

    serving = ServingSigBackend(PythonSigBackend(),
                                ServingConfig(flush_us=500.0))
    try:
        serving.ecrecover_addresses([], [])  # warm the threads
        n = 100
        t0 = time.perf_counter()
        for i in range(n):
            serving.ecrecover_addresses(
                [bytes([i % 251]) * 32], [b"\x00" * 65])
        per_request_s = (time.perf_counter() - t0) / n

        # the sampler's per-tick cost, measured with the serving
        # threads live (a tick walks EVERY thread's stack — an idle
        # process would understate it)
        # default hz — the rate we charge; isolated registry (a probe
        # loop must not inflate the process sample counter)
        sampler = SamplingProfiler(registry=drill_reg)
        m = 500
        t0 = time.perf_counter()
        for _ in range(m):
            sampler.sample_once()
        tick_s = (time.perf_counter() - t0) / m
        assert sampler.collapsed(), "sampler collected no stacks"
    finally:
        serving.close()
    class _CoolDevice:
        # the overhead probe's device sits WELL below the near-OOM
        # threshold: part 2 already restored the perfwatch env, so a
        # 95% device here would dump real bundles into cwd and bill
        # the background dump thread to the poll-cost timing
        id = 0
        platform = "tpu"

        def memory_stats(self):
            return {"bytes_in_use": 8 << 30,
                    "peak_bytes_in_use": 9 << 30,
                    "bytes_limit": 16 << 30}

    idle_poller = MemoryPoller(interval_s=None,
                               devices_fn=lambda: [_CoolDevice()],
                               buffers_fn=lambda: [],
                               registry=drill_reg)
    m = 200
    t0 = time.perf_counter()
    for _ in range(m):
        idle_poller.poll_once()
    poll_s = (time.perf_counter() - t0) / m
    # the plane's duty cycle: fraction of any wall interval (and hence
    # of any serving request running through it) spent in devscope
    duty = sampler.hz * tick_s + poll_s / idle_poller.interval_s
    overhead_pct = 100.0 * duty
    assert overhead_pct < 2.0, (
        f"devscope sampler+poller overhead {overhead_pct:.3f}% of a "
        f"serving request (tick {tick_s * 1e6:.1f}us x {sampler.hz}Hz + "
        f"poll {poll_s * 1e6:.1f}us / {idle_poller.interval_s}s) "
        f"breaches the 2% budget")
    out["overhead_pct"] = round(overhead_pct, 4)
    out["sampler_tick_us"] = round(tick_s * 1e6, 2)
    out["sampler_hz"] = sampler.hz
    out["poll_us"] = round(poll_s * 1e6, 2)
    out["poll_interval_s"] = idle_poller.interval_s
    out["per_request_us"] = round(per_request_s * 1e6, 1)
    out["platform"] = "host"
    return out


# == fleettrace closed-loop acceptance (bench.py --fleettrace) =============


def _read_boot_line(proc, timeout_s: float = 60.0) -> dict:
    """Read the one-line {"host","port"} JSON a chain_server / fleet
    frontend prints once listening (bounded: a child that dies or never
    binds fails the bench instead of hanging it)."""
    import select

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [], 0.25)
        if not ready:
            assert proc.poll() is None, (
                f"child exited rc {proc.returncode} before binding")
            continue
        line = proc.stdout.readline()
        assert line, f"child closed stdout (rc {proc.poll()})"
        line = line.strip()
        if line.startswith(b"{"):
            return json.loads(line)
    raise AssertionError("child never printed its boot line")


def measure_fleettrace() -> dict:
    """The fleettrace closed-loop acceptance run, three processes end
    to end:

    1. **One request, one tree, three processes.** A fleet frontend
       (``--fleettrace``, owning the collector) balances 2 chain_server
       replicas (``--fleettrace-export`` back to the frontend); this
       bench process exports its own client spans the same way. One
       interactive ``shard_verifyAggregates`` must assemble into ONE
       trace whose spans carry >= 3 distinct pids, and the critical-
       path segments must sum to the INDEPENDENTLY measured end-to-end
       wall time within 10% (the self-time telescoping identity,
       checked against a clock the collector never saw).
    2. **A breach leaves a cross-process exemplar.** With the
       interactive latency target forced impossibly low, a burst of
       routed requests breaches the SLO in the frontend; the breach
       onset dumps a flight-recorder bundle whose ``exemplars.json``
       must contain an assembled >= 3-process trace.
    3. **Collection stays cheap.** Per-span record + encode + ingest
       cost (measured on isolated instruments) x the measured spans-
       per-request, as a fraction of the measured request, asserted
       under the 2% observability budget."""
    import socket
    import tempfile

    from gethsharding_tpu import fleettrace, metrics as _metrics, tracing
    from gethsharding_tpu.crypto import bn256 as bls
    from gethsharding_tpu.crypto import secp256k1 as ecdsa
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.rpc import codec
    from gethsharding_tpu.rpc.client import RPCClient

    out: dict = {}
    tmp = tempfile.mkdtemp(prefix="bench_fleettrace_")
    bundles = os.path.join(tmp, "blackbox")
    # reserve the frontend port up front: replicas need their export
    # endpoint BEFORE the frontend can exist (it dials them to boot),
    # and a failed export batch is absorbed + retried by design
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    fe_port = sock.getsockname()[1]
    sock.close()

    child_env = {**os.environ, "JAX_PLATFORMS": "cpu",
                 "GETHSHARDING_FLEETTRACE_INTERVAL_MS": "50"}
    fe_env = {**child_env,
              "GETHSHARDING_FLEETTRACE_SAMPLE": "1.0",
              "GETHSHARDING_FLEETTRACE_LINGER_S": "0.4",
              "GETHSHARDING_PERFWATCH_DIR": bundles,
              "GETHSHARDING_PERFWATCH_DUMP_S": "0",
              # impossible interactive latency target: every routed
              # request is budget-bad, so phase 2's burst breaches
              "GETHSHARDING_SLO_INTERACTIVE_P99_MS": "0.001"}
    old_env = {k: os.environ.get(k)
               for k in ("GETHSHARDING_FLEETTRACE_INTERVAL_MS",)}
    os.environ["GETHSHARDING_FLEETTRACE_INTERVAL_MS"] = "50"

    children = []
    client = None
    try:
        replicas = []
        for i in range(2):
            proc = subprocess.Popen(
                [sys.executable, "-m", "gethsharding_tpu.rpc.chain_server",
                 "--port", "0", "--sigbackend", "python",
                 "--fleettrace-export", f"127.0.0.1:{fe_port}"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                cwd=REPO, env=child_env)
            children.append(proc)
            replicas.append(_read_boot_line(proc))
        frontend = subprocess.Popen(
            [sys.executable, "-m", "gethsharding_tpu.fleet.frontend",
             "--port", str(fe_port), "--fleettrace",
             *sum((["--replica", f"{r['host']}:{r['port']}"]
                   for r in replicas), [])],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            cwd=REPO, env=fe_env)
        children.append(frontend)
        boot = _read_boot_line(frontend)
        assert boot["port"] == fe_port, boot

        # this process exports its own client spans to the collector:
        # the third process in every assembled tree
        fleettrace.boot_exporter(f"127.0.0.1:{fe_port}", label="bench")
        client = RPCClient("127.0.0.1", fe_port, timeout=60.0)

        # -- part 1: one interactive request -> one 3-process tree --------
        header = b"fleettrace-bench"
        keys = [bls.bls_keygen(bytes([i + 1])) for i in range(3)]
        agg_sig = bls.bls_aggregate_sigs(
            [bls.bls_sign(header, sk) for sk, _ in keys])
        agg_pk = bls.bls_aggregate_pks([pk for _, pk in keys])
        call_args = ([codec.enc_bytes(header)], [codec.enc_g1(agg_sig)],
                     [codec.enc_g2(agg_pk)], "interactive")
        for _ in range(2):  # warm replica dial + serving threads
            assert client.call("shard_verifyAggregates",
                               *call_args) == [True]
        with tracing.span("bench/fleettrace_request") as probe:
            t0 = time.perf_counter()
            got = client.call("shard_verifyAggregates", *call_args)
            wall_s = time.perf_counter() - t0
        assert got == [True], got
        trace_id = probe.trace_id
        fleettrace.EXPORTER.flush()

        exemplar = None
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and exemplar is None:
            for ex in client.call("shard_traceExemplars", 32):
                if ex["trace_id"] == trace_id:
                    exemplar = ex
                    break
            if exemplar is None:
                time.sleep(0.2)
        assert exemplar is not None, (
            "the measured request never assembled into a retained trace")
        pids = {span.get("pid") for span in exemplar["spans"]}
        pids.discard(None)
        assert len(pids) >= 3, (
            f"assembled trace spans {len(pids)} processes, want >= 3 "
            f"(bench + frontend + replica): {sorted(pids)}")
        attr = exemplar["attribution"]
        seg_sum_s = sum(attr["segments"].values())
        identity = abs(seg_sum_s - wall_s) / wall_s
        assert identity <= 0.10, (
            f"critical-path segments sum {seg_sum_s * 1e3:.2f} ms vs "
            f"measured wall {wall_s * 1e3:.2f} ms "
            f"({identity * 100:.1f}% apart, bar 10%) — "
            f"segments {attr['segments']}")
        tables = client.call("shard_traceAttribution")
        assert tables["classes"].get("interactive"), tables["classes"]
        assert tables["traces"]["assembled"] >= 1, tables
        out["processes"] = len(pids)
        out["spans_per_request"] = len(exemplar["spans"])
        out["wall_ms"] = round(wall_s * 1e3, 2)
        out["segment_sum_ms"] = round(seg_sum_s * 1e3, 2)
        out["identity_gap_pct"] = round(identity * 100, 2)
        out["segments_ms"] = {k: round(v * 1e3, 3)
                              for k, v in attr["segments"].items()
                              if v > 0}

        # -- part 2: SLO breach -> bundle with cross-process exemplar -----
        digests, sigs = [], []
        for i in range(4):
            priv = int.from_bytes(keccak256(b"ft-%d" % i), "big") % ecdsa.N
            digest = keccak256(b"ft-msg-%d" % i)
            digests.append(codec.enc_bytes(digest))
            sigs.append(codec.enc_bytes(
                ecdsa.sign(digest, priv).to_bytes65()))
        for _ in range(12):  # >= min_events inside one refresh window
            client.call("shard_ecrecover", digests, sigs, "interactive")
        time.sleep(1.1)  # the burn-gauge refresh is throttled to ~1/s
        for _ in range(3):
            client.call("shard_ecrecover", digests, sigs, "interactive")
        bundle = None
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and bundle is None:
            if os.path.isdir(bundles):
                for name in sorted(os.listdir(bundles)):
                    path = os.path.join(bundles, name)
                    if "slo_breach" in name and os.path.exists(
                            os.path.join(path, "exemplars.json")):
                        bundle = path
                        break
            if bundle is None:
                time.sleep(0.2)
        assert bundle is not None, (
            "the injected SLO breach never dumped a flight-recorder "
            "bundle with exemplars.json")
        exemplars = json.load(open(os.path.join(bundle, "exemplars.json")))
        cross = [ex for ex in exemplars
                 if len({s.get("pid") for s in ex["spans"]}
                        - {None}) >= 3]
        assert cross, (
            f"no cross-process exemplar in the breach bundle "
            f"({len(exemplars)} exemplars)")
        events = json.load(open(os.path.join(bundle, "events.json")))
        assert any(e["kind"] == "slo_breach" for e in events), (
            sorted({e["kind"] for e in events}))
        out["breach_bundle"] = bundle
        out["bundle_exemplars"] = len(exemplars)
        out["bundle_cross_process"] = len(cross)
    finally:
        if client is not None:
            client.close()
        fleettrace.shutdown()
        for proc in children:
            proc.terminate()
        for proc in children:
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                proc.kill()
        for key, val in old_env.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val

    # -- part 3: collection overhead vs the measured request ---------------
    # per-span costs on ISOLATED instruments (the probe loops must not
    # pollute the process tracer/collector), charged at the strictest
    # model — every span of the measured request pays record + encode +
    # ingest — against the request it observed
    tracer = tracing.Tracer(registry=_metrics.Registry())
    tracer.enabled = True
    tracer.enable_export(8192)
    n = 20_000
    t0 = time.perf_counter()
    for i in range(n):
        tracer.record("serving/bench/queue_wait", 0.0, 0.001,
                      trace_id=i, tags={"klass": "interactive"})
    record_s = (time.perf_counter() - t0) / n
    batch, _ = tracer.drain_export(512)
    t0 = time.perf_counter()
    for _ in range(16):
        rows = codec.enc_spans(batch)
    enc_s = (time.perf_counter() - t0) / (16 * len(batch))
    sink = fleettrace.TraceCollector(_metrics.Registry(),
                                     max_traces=65536, linger_s=3600.0,
                                     sample=0.0)
    payload = {"pid": os.getpid(), "label": "bench", "clock_offset_us": 0.0,
               "dropped": 0, "spans": rows}
    m = 16
    t0 = time.perf_counter()
    for _ in range(m):
        sink.ingest_payload(dict(payload))
    ingest_s = (time.perf_counter() - t0) / (m * len(batch))
    per_span_s = record_s + enc_s + ingest_s
    overhead_pct = (100.0 * out["spans_per_request"] * per_span_s
                    / wall_s)
    assert overhead_pct < 2.0, (
        f"fleettrace collection overhead {overhead_pct:.3f}% of the "
        f"measured request ({out['spans_per_request']} spans x "
        f"{per_span_s * 1e6:.2f}us vs {wall_s * 1e3:.2f} ms) breaches "
        f"the 2% budget")
    out["overhead_pct"] = round(overhead_pct, 4)
    out["record_us"] = round(record_s * 1e6, 3)
    out["encode_us"] = round(enc_s * 1e6, 3)
    out["ingest_us"] = round(ingest_s * 1e6, 3)
    out["platform"] = "host"
    return out


# == autotune orchestration ================================================


def _heavy_config(cfg: dict) -> bool:
    """Configs whose FIRST compile can legitimately exceed the normal
    per-probe timeout (mega-kernel Mosaic compiles, static unrolls).
    They get a longer probe window and are NEVER negative-cached — a
    budget-capped timeout is not evidence of a deterministic failure
    (the tunnel watcher probes them with 4800 s windows)."""
    return (cfg.get("GETHSHARDING_TPU_PAIR_UNROLL", "0") != "0"
            or "mega" in (cfg.get("GETHSHARDING_TPU_FINALEXP", ""),
                          cfg.get("GETHSHARDING_TPU_MILLER", ""),
                          cfg.get("GETHSHARDING_TPU_AGG", "")))


def _run_config(cfg: dict, extras: bool = False) -> dict | None:
    # the probe must measure cfg and ONLY cfg: ambient exported
    # GETHSHARDING_TPU_* knobs would leak into every subprocess, trip the
    # mutually-exclusive knob validations (ValueError at import), and get
    # the clean cfg permanently negative-cached under the wrong label
    env = {key: val for key, val in os.environ.items()
           if not key.startswith("GETHSHARDING_TPU_")}
    env.update(cfg)
    # the winner's extras pass (configs 1/2/4/5) compiles several extra
    # kernels — the r1 run lost its extras to the sweep-probe timeout, so
    # it gets a budget of its own, scaled with the run's overall budget
    # knob so a capped hermetic run stays capped; heavy configs get a
    # longer window for their first Mosaic compile
    if extras:
        timeout = min(4200, max(560, 1.25 * SWEEP_BUDGET_S))
        if _kperiod_cache_ready(8):
            # the extras pass will also attempt the K-period sweep (a
            # fresh 8-period chain + two cold batch shapes) — the
            # standalone 03e probe budgets 6900 s for the same work
            timeout = max(timeout, min(6000, 4 * SWEEP_BUDGET_S))
    else:
        timeout = min(1800 if _heavy_config(cfg) else 560, SWEEP_BUDGET_S)
    rem = _remaining()
    if rem is not None:
        if rem < 120:
            return None  # not enough window left to learn anything
        timeout = min(timeout, max(90, rem - 45))
    if extras:
        env["GETHSHARDING_BENCH_EXTRAS"] = "1"
        # let the child skip the K-period sweep when too little of THIS
        # timeout remains for it (finished extras must survive)
        env["GETHSHARDING_BENCH_CHILD_DEADLINE_TS"] = str(
            time.time() + timeout - 120)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--single"],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=REPO)
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                stats = json.loads(line)
                if "sig_rate" in stats:
                    return stats
            except json.JSONDecodeError:
                continue
    except (subprocess.TimeoutExpired, OSError):
        pass
    return None


def _sweep_fingerprint() -> str:
    """Identity of the config set: a cache written for a different sweep
    (older knob set) must not short-circuit the new sweep."""
    import hashlib

    return hashlib.sha256(
        json.dumps(CONFIGS, sort_keys=True).encode()).hexdigest()[:12]


def _cache_path() -> str:
    return os.path.join(REPO, ".bench_autotune.json")


def ensure_workload_cache() -> None:
    """Build the signing workload ONCE in the orchestrating process (host
    scalar crypto only, no accelerator) so each sweep subprocess loads it
    from disk instead of paying ~3 minutes."""
    k = int(os.environ.get("GETHSHARDING_BENCH_KPERIOD_MAX", "1"))
    manager, accounts, _roots, digests, _periods = _bench_identities(k)
    _load_or_build_vote_sigs(accounts, manager, digests)


_SUSPECT_MARK: "int | None" = None


def _emit(metric: str, value, unit: str, vs_baseline, extra: dict,
          workload: "str | None" = None, source: str = "bench") -> None:
    """THE one result emitter: prints the driver's JSON line AND appends
    the same measurement to the perfwatch benchmark ledger (one schema,
    one writer — per-mode extras dicts can no longer drift). A record
    taken while the device-timer self-check fired (`block_until_ready`
    no-oped under the measurement — the r4 hazard) is stamped invalid so
    the regression gate never baselines a lying timing."""
    global _SUSPECT_MARK
    print(json.dumps({"metric": metric, "value": value, "unit": unit,
                      "vs_baseline": vs_baseline, "extra": extra}))
    try:
        from gethsharding_tpu.perfwatch import record_bench, suspect_count

        suspects_now = suspect_count()
        suspects = suspects_now - (_SUSPECT_MARK or 0)
        _SUSPECT_MARK = suspects_now
        record_bench(metric=metric, value=value, unit=unit,
                     vs_baseline=vs_baseline, extra=extra,
                     workload=workload or metric, source=source,
                     suspects=suspects)
    except Exception as exc:  # noqa: BLE001 - the ledger is additive:
        # a read-only checkout must still print the driver line
        print(f"# perfwatch ledger write failed: {exc!r}", file=sys.stderr)


def _print_metric(sig_rate: float, stats: dict, knobs: str) -> None:
    """The headline metric line (single output contract for the
    autotuned and fallback paths), routed through `_emit`."""
    extra = {key: val for key, val in stats.items() if key != "sig_rate"}
    try:
        # code provenance: a replayed capture must be attributable to the
        # tree it actually measured
        extra["git"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True, timeout=10).stdout.strip()
    except (subprocess.SubprocessError, OSError):
        pass
    if extra.get("platform") == "axon":
        # the axon PJRT plugin IS the TPU chip behind the tunnel
        extra["platform"] = "tpu (axon)"
    # replayable provenance: _latest_capture refuses git-tracked captures
    # without an embedded stamp (checkout resets mtime), so every fresh
    # report carries its own capture time
    extra.setdefault("captured_at",
                     time.strftime("%Y-%m-%d %H:%M:%S", time.localtime()))
    _emit("notary_sig_verifications_per_sec", sig_rate,
          (f"sigs/sec (100-shard period audit, on-device 135-vote "
           f"BLS aggregation+verification, protocol-generated "
           f"workload, opt-ate bn256, {knobs})"),
          round(sig_rate / 100_000.0, 4), extra)


def _latest_capture() -> dict | None:
    """Newest mid-round TPU capture recorded by scripts/tpu_watch.sh.

    The accelerator tunnel dies for hours at a time (it was dead for the
    whole tail of r2, burying that round's kernels under a CPU-fallback
    number). When it is dead at report time, the honest best number is
    the live capture the watcher took earlier in the round — reported
    with explicit provenance (capture timestamp + a note), never
    fabricated: every capture is a real measured run of this repo's
    production audit path on the real chip."""
    import glob

    best = None
    live = glob.glob(os.path.join(REPO, ".tpu_results", "*.json"))
    tracked = glob.glob(os.path.join(REPO, "bench_results", "*.json"))
    for path in live + tracked:
        try:
            with open(path) as fh:
                rec = json.load(fh)
            mtime = os.path.getmtime(path)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict) or "value" not in rec:
            continue
        if rec.get("metric") != "notary_sig_verifications_per_sec":
            continue  # other experiments' records are not the headline
        if not str(rec.get("extra", {}).get("platform", "")).startswith("tpu"):
            continue
        # provenance: a record that already carries captured_at keeps it
        # (a replayed report must not be restamped as a fresh capture).
        # mtime is trusted as the capture time only for the watcher's own
        # untracked .tpu_results files — a git-tracked capture gets its
        # mtime reset by checkout, so without an embedded stamp it is
        # unusable, not "fresh"
        stamp = rec.get("extra", {}).get("captured_at")
        if stamp:
            try:
                when = time.mktime(time.strptime(stamp, "%Y-%m-%d %H:%M:%S"))
            except ValueError:
                continue
        elif path in live:
            when = mtime
        else:
            continue
        if time.time() - when > 24 * 3600:
            continue  # not this round's capture — stale evidence is worse
        if best is None or when > best[0]:
            best = (when, rec)
    if best is None:
        return None
    rec = dict(best[1])
    rec["extra"] = {
        **rec.get("extra", {}),
        "captured_at": time.strftime("%Y-%m-%d %H:%M:%S",
                                     time.localtime(best[0])),
        "note": ("live TPU capture from this round's tunnel watcher; "
                 "tunnel unreachable at report time"),
    }
    return rec


def _replay_capture(reason: str) -> bool:
    """Report this round's live TPU capture instead of a meaningless CPU
    number. Returns False when no (recent) capture exists.

    GETHSHARDING_BENCH_NO_REPLAY=1 disables replay entirely — the tunnel
    watcher's experiments set it so a mid-run tunnel death reads as
    failure (retry next window) instead of a replayed 'success'."""
    if os.environ.get("GETHSHARDING_BENCH_NO_REPLAY") == "1":
        return False
    captured = _latest_capture()
    if captured is None:
        return False
    print(f"# {reason}; reporting this round's live TPU capture",
          file=sys.stderr)
    # the replayed capture keeps its original line shape verbatim AND
    # lands in the ledger tagged as a replay (not a fresh measurement)
    print(json.dumps(captured))
    try:
        from gethsharding_tpu.perfwatch import record_bench

        record_bench(metric=captured["metric"], value=captured["value"],
                     unit=captured.get("unit"),
                     vs_baseline=captured.get("vs_baseline"),
                     extra=captured.get("extra"), source="replay")
    except Exception as exc:  # noqa: BLE001 - additive, never fatal
        print(f"# perfwatch ledger write failed: {exc!r}", file=sys.stderr)
    return True


def _probe_backend(timeout: float = 120.0):
    """Is an accelerator reachable? The TPU tunnel can die and then ANY
    jax backend init hangs forever — probe in a bounded subprocess so the
    driver's bench run always produces a number."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout, cwd=REPO)
        lines = proc.stdout.strip().splitlines()
        return lines[-1] if proc.returncode == 0 and lines else None
    except (subprocess.TimeoutExpired, OSError):
        return None


def main() -> None:
    # the device-introspection stamp: every ledger record this process
    # emits carries the peak-HBM watermark + cumulative compile cost
    # (devscope.ledger_fields, polled on demand at each append — no
    # background thread perturbing the measurements)
    try:
        from gethsharding_tpu import devscope as _devscope

        _devscope.boot(start_poller=False)
    except Exception:  # noqa: BLE001 - the stamp is additive
        pass

    if "--single" in sys.argv:
        print(json.dumps(measure_single()))
        return

    if "--trace" in sys.argv:
        # profile ONE serving benchmark run with the span tracer on:
        # every coalesced request's queue_wait / batch_assembly /
        # device_dispatch attribution lands in a Chrome trace-event JSON
        # (open in Perfetto) — the artifact that says WHERE a slow
        # request spent its time, which the aggregate timers cannot
        from gethsharding_tpu import tracing

        out_path = os.environ.get(
            "GETHSHARDING_TRACE_OUT", os.path.join(REPO, "bench_trace.json"))
        if "--trace-out" in sys.argv:
            idx = sys.argv.index("--trace-out")
            if idx + 1 < len(sys.argv):
                out_path = sys.argv[idx + 1]
        tracing.enable(ring_spans=65536)
        stats = measure_serving()
        events = tracing.write_chrome_trace(out_path)
        requests = sum(
            1 for rec in tracing.TRACER.recent_spans()
            if rec["name"].endswith("/request"))
        _emit("serving_trace_profile", stats["serving_rate"],
              (f"verifs/sec ({stats['clients']} concurrent clients, "
               f"span-traced serving run, {stats['backend']} "
               f"backend)"),
              round(stats["serving_rate"]
                    / max(stats["direct_rate"], 1e-9), 4),
              {**{k: v for k, v in stats.items() if k != "serving_rate"},
               "trace_out": out_path,
               "trace_events": events,
               "traced_requests": requests})
        return

    if "--resident" in sys.argv:
        # cold-vs-warm transfer attribution for the device-resident pk
        # planes: the warm G2 byte count is THE acceptance number (zero
        # when residency is on), the cold/warm delta is the per-dispatch
        # transfer the cache removes
        stats = measure_resident()
        _emit("audit_warm_wire_bytes_per_dispatch",
              stats["wire_bytes_warm"],
              (f"bytes over the host->device link per warm "
               f"100-shard audit dispatch (cold "
               f"{stats['wire_bytes_cold']} B; resident="
               f"{stats['resident']}, {stats['platform']})"),
              round(stats["wire_bytes_warm"]
                    / max(1, stats["wire_bytes_cold"]), 4),
              {k: v for k, v in stats.items() if k != "wire_bytes_warm"})
        return

    if "--overlap" in sys.argv:
        # sequential vs overlapped audit pipeline (marshal N+1 while N
        # executes); >= 1.0 means the overlap pays for itself
        stats = measure_overlap()
        _emit("audit_overlap_ratio", stats["overlap_ratio"],
              (f"sequential/overlapped wall ratio over "
               f"{stats['k_periods']} periods "
               f"({stats['platform']})"),
              stats["overlap_ratio"],
              {k: v for k, v in stats.items() if k != "overlap_ratio"})
        return

    if "--mesh" in sys.argv:
        # the multi-chip audit closed loop: tri-path bit-identity
        # (scalar / single-device / D-device mesh), exactly one
        # cross-device collective per compiled step, disjoint
        # per-device cache-shard ownership in the devscope census —
        # recorded as the `multichip_audit` workload group so the
        # noise-aware gate tracks the mesh rate like any other
        stats = measure_mesh()
        _emit("multichip_audit_sig_rate", stats["sig_rate"],
              (f"sigs/sec ({stats['rows']}-committee seeded audit on a "
               f"{stats['n_devices']}-device {stats['platform']} mesh, "
               f"one pjit step, {stats['collectives_per_step']} "
               f"collective/step, verdicts bit-identical to scalar + "
               f"single-device)"),
              round(stats["sig_rate"] / 100_000.0, 6),
              {k: v for k, v in stats.items() if k != "sig_rate"},
              workload="multichip_audit")
        return

    if "--precomp" in sys.argv:
        # the fixed-base precomputation closed loop: tri-path verdict
        # bit-identity (scalar / precomp / recompute, hostile rows
        # included), warm zero-G2 wire, and the HLO op census proving
        # the fixed-argument point arithmetic is absent — recorded as
        # the `precomp_audit` workload so the noise-aware gate tracks
        # the precomp rate like any other
        stats = measure_precomp()
        _emit("precomp_audit_sig_rate", stats["sig_rate"],
              (f"sigs/sec ({stats['rows']}-committee seeded audit, warm "
               f"fixed-base line tables, zero G2 wire bytes, "
               f"{stats['hlo_multiplies_precomp']} HLO multiplies vs "
               f"{stats['hlo_multiplies_recompute']} recompute, verdicts "
               f"bit-identical to scalar + recompute, "
               f"{stats['platform']})"),
              round(stats["sig_rate"] / 100_000.0, 6),
              {k: v for k, v in stats.items() if k != "sig_rate"},
              workload="precomp_audit")
        if stats.get("config5_stress_shards_per_s"):
            _emit("precomp_config5_stress_shards_per_s",
                  stats["config5_stress_shards_per_s"],
                  (f"shards/sec fused stress step "
                   f"({stats['config5_shards']} shards, committee "
                   f"{stats['config5_committee']}, precomp-era tree, "
                   f"{stats['platform']})"),
                  None,
                  {k: v for k, v in stats.items()
                   if k != "config5_stress_shards_per_s"},
                  workload="precomp_stress")
        return

    if "--composed" in sys.argv:
        # resident + overlap (+ precomp) composed: the K-period
        # overlapped pipeline over warm line tables — the composed
        # record the 05_* probes have queued since PR 3
        stats = measure_composed()
        _emit("composed_audit_sig_rate", stats["sig_rate"],
              (f"sigs/sec ({stats['k_periods']}-period overlapped "
               f"audit, resident={stats['resident']}, "
               f"precomp={stats['precomp']}, {stats['platform']})"),
              round(stats["sig_rate"] / 100_000.0, 6),
              {k: v for k, v in stats.items() if k != "sig_rate"},
              workload="composed_audit")
        return

    if "--chaos" in sys.argv:
        # failover availability under a seeded chaos schedule: the
        # value is the fraction of calls answered correctly while the
        # primary faults; extras carry the breaker's full open ->
        # half-open-probe -> closed cycle counters
        stats = measure_chaos()
        injected_desc = (
            f"{stats['corruptions_injected']} silent corruptions "
            f"({stats['corruptions_detected']} detected)"
            if stats["mode"] == "corrupt"
            else f"{stats['injected_faults']} injected faults")
        _emit("chaos_availability", stats["chaos_availability"],
              (f"fraction of {stats['calls']} calls answered "
               f"correctly under seeded chaos (rate "
               f"{stats['rate']}, {injected_desc}, "
               f"{stats['primary']} primary, "
               f"{stats['platform']})"),
              stats["chaos_availability"],
              {k: v for k, v in stats.items()
               if k != "chaos_availability"})
        return

    if "--soundness" in sys.argv:
        # the continuous integrity audit's two acceptance numbers:
        # audit overhead per dispatch (asserted <2% at the default
        # sample rate) and closed-loop silent-corruption detection
        # within the dispatch budget detection_probability predicts
        stats = measure_soundness()
        _emit("soundness_overhead_pct", stats["overhead_pct"],
              (f"% of a {stats['rows']}-row ecrecover dispatch "
               f"spent on the soundness audit at rate "
               f"{stats['default_rate']} (corruption tripped the "
               f"breaker in {stats['dispatches_to_trip']} of the "
               f"predicted {stats['predicted_budget_p999']} "
               f"dispatches, {stats['platform']})"),
              round(stats["overhead_pct"] / 2.0, 4),
              {k: v for k, v in stats.items() if k != "overhead_pct"})
        return

    if "--das" in sys.argv:
        # data-availability sampling: full-fetch vs sampled bytes per
        # collation (the bandwidth->compute trade), with the batched
        # sample-verify throughput riding in the extras. The run IS the
        # acceptance check: zero body fetches, bytes within the
        # k-sample budget, batched verdicts == scalar.
        stats = measure_das()
        _emit("das_sampled_bytes_per_collation",
              stats["sampled_bytes_per_collation"],
              (f"bytes fetched per {stats['body_bytes']}-byte "
               f"collation at k={stats['k_samples']} sampled "
               f"chunks (full fetch: "
               f"{stats['full_fetch_bytes_per_collation']} B; "
               f"{stats['platform']})"),
              stats["bytes_ratio"],
              {key: val for key, val in stats.items()
               if key != "sampled_bytes_per_collation"})
        return

    if "--das-poly" in sys.argv:
        # polynomial-multiproof DAS: the proof-byte cut vs merkle
        # paths (the run asserts the ≥5× acceptance floor and the
        # constant-in-k proof size), with batched-vs-scalar multiproof
        # verify throughput riding in the extras, bit-identical.
        stats = measure_das_poly()
        _emit("das_poly_proof_bytes_per_collation",
              stats["poly_proof_bytes_per_collation"],
              (f"proof bytes per collation at "
               f"k={stats['k_samples']} sampled chunks (merkle: "
               f"{stats['merkle_proof_bytes_per_collation']} B — a "
               f"{stats['proof_bytes_cut']}x cut; batched verify "
               f"{stats['verify_rows_per_sec']} rows/s vs scalar "
               f"{stats['scalar_rows_per_sec']}, "
               f"{stats['platform']})"),
              round(stats["poly_proof_bytes_per_collation"]
                    / stats["merkle_proof_bytes_per_collation"], 4),
              {key: val for key, val in stats.items()
               if key != "poly_proof_bytes_per_collation"})
        return

    if "--perfwatch" in sys.argv:
        # the measurement substrate's own acceptance gate: the
        # regression check trips on an injected 1.3x slowdown (and only
        # then), a simulated no-op block_until_ready is caught by the
        # timer self-check and invalidates its record, a chaos-injected
        # dispatch hang produces a COMPLETE flight-recorder bundle, and
        # the whole layer stays under the 2% hot-path budget
        stats = measure_perfwatch()
        _emit("perfwatch_overhead_pct", stats["overhead_pct"],
              (f"% of a serving request spent on the perfwatch device "
               f"timer + flight-recorder ring "
               f"({stats['per_dispatch_us']}us vs "
               f"{stats['per_request_us']}us; gate tripped on "
               f"{','.join(stats['gate_tripped_on'])}, bundle "
               f"{len(stats['bundle_files'])} files, host)"),
              round(stats["overhead_pct"] / 2.0, 4),
              {k: v for k, v in stats.items() if k != "overhead_pct"})
        return

    if "--devscope" in sys.argv:
        # the device-introspection plane's acceptance gate: the
        # recompile-storm detector raises exactly once on an injected
        # storm (silent on steady state), a simulated near-OOM leaves a
        # flight-recorder bundle containing the attributed buffer
        # census, and the sampler+poller duty cycle stays under the 2%
        # serving-request budget
        stats = measure_devscope()
        _emit("devscope_overhead_pct", stats["overhead_pct"],
              (f"% of a serving request spent on the devscope sampler "
               f"({stats['sampler_tick_us']}us/tick x "
               f"{stats['sampler_hz']}Hz) + memory poller "
               f"({stats['poll_us']}us / {stats['poll_interval_s']}s); "
               f"storm raised {stats['storm_raised']}x, census "
               f"{stats['census_buffers']} buffers, host)"),
              round(stats["overhead_pct"] / 2.0, 4),
              {k: v for k, v in stats.items() if k != "overhead_pct"})
        return

    if "--fleettrace" in sys.argv:
        # the cross-process tracing closed loop: one interactive
        # request through bench -> frontend -> replica assembles into
        # one >= 3-process trace whose critical-path segments sum to
        # the independently measured wall time, an injected SLO breach
        # dumps a bundle carrying a cross-process exemplar, and the
        # collection plane stays under the 2% observability budget
        stats = measure_fleettrace()
        _emit("fleettrace_overhead_pct", stats["overhead_pct"],
              (f"% of the measured fleet request spent on span "
               f"collection ({stats['spans_per_request']} spans x "
               f"record {stats['record_us']}us + encode "
               f"{stats['encode_us']}us + ingest {stats['ingest_us']}us "
               f"vs {stats['wall_ms']} ms; {stats['processes']}-process "
               f"trace, segment-sum gap {stats['identity_gap_pct']}%, "
               f"host)"),
              round(stats["overhead_pct"] / 2.0, 4),
              {k: v for k, v in stats.items() if k != "overhead_pct"})
        return

    if "--serving" in sys.argv:
        # the serving-tier extra: coalesced verifications/sec for M
        # concurrent small-request clients, with the direct-backend
        # baseline riding in the same JSON line
        stats = measure_serving()
        _emit("serving_coalesced_verifications_per_sec",
              stats["serving_rate"],
              (f"verifs/sec ({stats['clients']} concurrent clients x "
               f"single-item ecrecover through the serving tier, "
               f"{stats['backend']} backend)"),
              round(stats["serving_rate"]
                    / max(stats["direct_rate"], 1e-9), 4),
              {k: v for k, v in stats.items() if k != "serving_rate"})
        return

    if "--elastic" in sys.argv:
        # the elastic-fleet acceptance gate: the cross-process
        # closed-loop soak — diurnal swing, autoscaler out AND in,
        # frontend killed -9 with actor failover, zero incorrect
        # verdicts (asserted inside; the soak also appends its own
        # fleet_elastic workload record through record_bench)
        stats = measure_elastic()
        _emit("fleet_elastic_soak_p99_ms", stats["p99_ms"],
              (f"interactive p99 ms across a 10x diurnal swing over "
               f"{stats['replicas']} replicas + 2 peered frontends "
               f"(autoscaler out x{stats['scale_out']} / "
               f"in x{stats['scale_in']}, one frontend killed -9, "
               f"{stats['failovers']} pool failovers, "
               f"{stats['clients']} clients, {stats['platform']})"),
              round(stats["p99_ms"] / max(stats["slo_ms"], 1e-9), 4),
              {k: v for k, v in stats.items()
               if k not in ("summary", "p99_ms", "endpoints")},
              workload="fleet_elastic")
        return

    if "--fleet" in sys.argv:
        # the fleet-serving acceptance gate: the traffic-model soak
        # (scripts/serving_stress.py --replicas) under a seeded chaos
        # schedule that trips one replica's breaker mid-soak. The run
        # IS the check: zero lost/mis-answered requests, the router
        # drains and re-enters the tripped replica through half-open
        # re-promotion, catchup_replay sheds first while interactive
        # sees zero sheds and holds its p99 SLO.
        stats = measure_fleet()
        _emit("fleet_interactive_p99_ms", stats["p99_ms"]["interactive"],
              (f"interactive p99 ms over a {stats['replicas']}"
               f"-replica routed fleet (SLO "
               f"{stats['slo_ms']['interactive']} ms; mid-soak "
               f"breaker trip + drain + re-entry; "
               f"{stats['clients']} mixed-class clients, "
               f"{stats['platform']})"),
              round(stats["p99_ms"]["interactive"]
                    / max(stats["slo_ms"]["interactive"], 1e-9), 4),
              {k: v for k, v in stats.items() if k != "p99_ms"}
              | {"p99_ms": stats["p99_ms"]})
        # the hedging closed loop: one replica transport-delayed 10x,
        # interactive p99 must improve >= 2x at <= 15% wasted
        # dispatches (asserted inside)
        hedge = measure_hedge()
        _emit("fleet_hedge_p99_improvement", hedge["improvement"],
              (f"x interactive p99 cut by hedging "
               f"({hedge['p99_ms_no_hedge']} ms -> "
               f"{hedge['p99_ms_hedged']} ms; one replica delayed "
               f"{hedge['delay_s'] * 1e3:.0f} ms at rate "
               f"{hedge['delay_rate']}; wasted "
               f"{hedge['wasted_pct']}% of dispatches, bar <= 15%)"),
              round(hedge["improvement"] / 2.0, 4),
              {k: v for k, v in hedge.items() if k != "improvement"})
        # the partition/kill soak: zero incorrect verdicts, only typed
        # failures, the partitioned replica re-enters (asserted inside)
        part = measure_partition()
        _emit("fleet_partition_soak_completed", part["completed"],
              (f"verified calls through a fleet whose replica r0 was "
               f"KILLED and r1 PARTITIONED mid-soak "
               f"({part['clients']} clients x {part['rounds']} rounds; "
               f"0 incorrect verdicts, 0 untyped failures, "
               f"r1 re-entries {part['r1_trips_reentries']})"),
              None,
              {k: v for k, v in part.items() if k != "completed"})
        return

    if "--kperiod" in sys.argv:
        # the K-period catch-up sweep under the CURRENT env knobs; emits
        # the full metric line itself so a watcher probe's output is a
        # replayable capture (the aggregate metric is honest only next to
        # its per-period latency, which rides in extra.kperiod_sweep)
        stats = measure_kperiod()
        label = "/".join(
            f"{key.replace('GETHSHARDING_TPU_', '').lower()}={val}"
            for key, val in sorted(stats["knobs"].items())) or "defaults"
        _print_metric(
            stats["sig_rate"],
            {key: val for key, val in stats.items() if key != "sig_rate"},
            f"audit_periods K={stats['k_periods']} catch-up batch, "
            f"{label}, {stats['platform']}")
        return

    ensure_workload_cache()

    if os.environ.get("GETHSHARDING_BENCH_CPU") != "1":
        platform = _probe_backend()
        if platform is None:
            # the tunnel is dead NOW but may have been alive earlier in
            # the round: a real measured TPU number, with its capture
            # timestamp, beats a meaningless CPU figure
            if _replay_capture("accelerator unreachable"):
                return
            # dead accelerator tunnel: fall back to the hermetic CPU path
            # in-process (no sweep — CPU probes would eat the budget) so
            # the run still reports a real, correctness-gated number
            print("# accelerator unreachable; hermetic CPU fallback",
                  file=sys.stderr)
            os.environ["GETHSHARDING_BENCH_CPU"] = "1"
            # measured r3 on this host class (hermetic audit dispatch):
            # exact/scan + slices conv 742 sigs/s vs exact/scan 463 vs
            # the wide/shift defaults 387 — seed the fallback with the
            # CPU winner instead of paying for an in-fallback sweep
            os.environ.setdefault("GETHSHARDING_TPU_LIMB_FORM", "exact")
            os.environ.setdefault("GETHSHARDING_TPU_CARRY", "scan")
            os.environ.setdefault("GETHSHARDING_TPU_CONV", "slices")
            if SWEEP_BUDGET_S >= 900:
                # budget allows the configs 1/2/4 extras even on the CPU
                # fallback (config 5 self-skips on slow dispatch), so the
                # driver artifact records them in every round
                os.environ["GETHSHARDING_BENCH_EXTRAS"] = "1"
            stats = measure_single()
            knobs = "/".join([os.environ["GETHSHARDING_TPU_LIMB_FORM"],
                              os.environ["GETHSHARDING_TPU_CARRY"],
                              os.environ["GETHSHARDING_TPU_CONV"]])
            _print_metric(stats["sig_rate"], stats,
                          f"{knobs}, CPU FALLBACK - accelerator tunnel "
                          f"unreachable")
            return

    best_cfg, best = None, None
    cache_key = None
    failed: list = []
    try:
        cached = json.load(open(_cache_path()))
        if cached.get("sweep") == _sweep_fingerprint():
            # negative cache: configs that timed out / crashed in an
            # earlier sweep of THIS config set are not re-probed (a
            # deterministic too-slow compile would eat the tunnel window
            # every round)
            failed = [c for c in cached.get("failed", []) if c in CONFIGS]
            if all(key in cached for key in ("config", "platform")):
                cache_key = cached.get("platform")
                best_cfg = cached["config"]
    except Exception:
        pass

    def _save_cache(winner=None, platform=None):
        payload = {"sweep": _sweep_fingerprint(), "failed": failed}
        if winner is not None:
            payload.update({"config": winner, "platform": platform})
        try:
            json.dump(payload, open(_cache_path(), "w"))
        except OSError:
            pass

    if best_cfg is not None:
        stats = _run_config(best_cfg, extras=True)
        if stats is not None and stats.get("platform") == cache_key:
            best = stats
        else:
            # the extras pass compiles several extra kernels and can time
            # out on its own; before abandoning the cached winner for a
            # full re-sweep (which may not fit the caller's window —
            # 89_finalize's outer timeout), retry the winner WITHOUT
            # extras: a capture missing configs 1/2/4/5 beats no capture
            stats = _run_config(best_cfg)
            if stats is not None and stats.get("platform") == cache_key:
                print("# winner extras pass failed; reporting winner "
                      "without extras", file=sys.stderr)
                best = stats
            else:
                best_cfg = None

    if best_cfg is None:
        results = []
        sweep_failures: list = []
        sweep_start = time.monotonic()
        for i, cfg in enumerate(CONFIGS):
            if cfg in failed:
                print(f"# skipping config {cfg} (failed in an earlier "
                      f"sweep)", file=sys.stderr)
                continue
            elapsed = time.monotonic() - sweep_start
            rem = _remaining()
            if rem is not None and rem < 660:
                # break BEFORE starting a config the deadline would clamp:
                # a deadline-truncated probe failure must never be
                # negative-cached as a deterministic config failure
                print(f"# wall-clock deadline near; sweep stops after {i} "
                      f"configs", file=sys.stderr)
                break
            if elapsed > SWEEP_BUDGET_S and (
                    results or elapsed > SWEEP_BUDGET_S + 2 * 560):
                # past budget stop once something succeeded; with NOTHING
                # succeeded allow limited overtime (a couple of probe
                # timeouts) — an unbounded empty-results sweep against a
                # dead tunnel would run every config to its timeout and
                # blow the caller's window
                print(f"# sweep budget exhausted after {i} configs",
                      file=sys.stderr)
                break
            stats = _run_config(cfg)
            if stats is not None:
                results.append((cfg, stats))
                print(f"# config {cfg} -> {stats['sig_rate']:.1f} sigs/sec "
                      f"[{stats['platform']}]", file=sys.stderr)
            else:
                sweep_failures.append(cfg)
        if not results:
            # every sweep probe failed; before measuring in-process,
            # re-probe — the tunnel may have died MID-RUN, and an
            # in-process backend init against a dead tunnel hangs forever
            if (os.environ.get("GETHSHARDING_BENCH_CPU") != "1"
                    and _probe_backend() is None):
                if _replay_capture("accelerator died mid-run"):
                    return
                print("# accelerator died mid-run; hermetic CPU fallback",
                      file=sys.stderr)
                os.environ["GETHSHARDING_BENCH_CPU"] = "1"
            else:
                os.environ["GETHSHARDING_BENCH_EXTRAS"] = "1"
            best_cfg, best = {}, measure_single()
        else:
            best_cfg, best = max(results, key=lambda r: r[1]["sig_rate"])
            # persist failures only when the accelerator is STILL
            # reachable after the sweep — "something else succeeded" does
            # not make later failures deterministic (config 1 can succeed
            # and the tunnel die mid-sweep, which is this environment's
            # normal operating mode), so re-probe before blacklisting
            if sweep_failures and (
                    os.environ.get("GETHSHARDING_BENCH_CPU") == "1"
                    or _probe_backend() is not None):
                failed.extend(c for c in sweep_failures
                              if c not in failed and not _heavy_config(c))
            _save_cache(best_cfg, best["platform"])
            # one extra run of the winner for the config 1/2/4/5 numbers
            stats = _run_config(best_cfg, extras=True)
            if stats is not None:
                best = stats

    # label from the FULL winning config (any knob may decide the sweep)
    knobs = "/".join(
        [best_cfg.get("GETHSHARDING_TPU_LIMB_FORM", "wide"),
         best_cfg.get("GETHSHARDING_TPU_CARRY", "scan"),
         best_cfg.get("GETHSHARDING_TPU_CONV", "shift")]
        + (["pairconv-pallas"]
           if best_cfg.get("GETHSHARDING_TPU_PAIRCONV") == "pallas" else [])
        + ([f"pair-unroll-{best_cfg['GETHSHARDING_TPU_PAIR_UNROLL']}"]
           if best_cfg.get("GETHSHARDING_TPU_PAIR_UNROLL", "0") != "0"
           else [])
        + ([f"scan-unroll{best_cfg['GETHSHARDING_TPU_SCAN_UNROLL']}"]
           if best_cfg.get("GETHSHARDING_TPU_SCAN_UNROLL") else [])
        + (["norm-relaxed"]
           if best_cfg.get("GETHSHARDING_TPU_NORM") == "relaxed" else [])
        + (["pallas-norm"] if best_cfg.get("GETHSHARDING_TPU_PALLAS") == "1"
           else [])
        + (["finalexp-mega"]
           if best_cfg.get("GETHSHARDING_TPU_FINALEXP") == "mega" else [])
        + (["miller-mega"]
           if best_cfg.get("GETHSHARDING_TPU_MILLER") == "mega" else [])
        + (["agg-mega"]
           if best_cfg.get("GETHSHARDING_TPU_AGG") == "mega" else [])
        + ([f"mega-conv-{best_cfg['GETHSHARDING_TPU_MEGA_CONV']}"]
           if best_cfg.get("GETHSHARDING_TPU_MEGA_CONV") else []))
    _print_metric(best["sig_rate"], best, f"{knobs}, {best['platform']}")


if __name__ == "__main__":
    main()
