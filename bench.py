"""Driver benchmark: notary-vote BLS aggregate verification throughput.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

The workload is BASELINE.md config 3: one period of the 100-shard
sharding protocol — for every shard, verify the aggregate BLS committee
vote (135 signatures aggregated into one G1 point) on its collation
header via the batched optimal-ate pairing kernel (ops/bn256_jax):
one shared-accumulator Miller product + inversion-free final check per
shard, all as one jitted batch on the accelerator.

The kernel has two build-time knobs whose best setting depends on whether
the backend is latency- or throughput-bound (env vars read at import:
GETHSHARDING_TPU_LIMB_FORM = wide|exact, GETHSHARDING_TPU_CARRY =
scan|assoc). The benchmark AUTOTUNES: it re-executes itself in a
subprocess per configuration, measures each, and reports the fastest.
Results are cached in .bench_autotune.json keyed by backend so repeat
runs skip the sweep.

Metric: aggregate notary-signature verifications/sec = shards × committee
/ wall time. North star (BASELINE.md): ≥100k/sec on TPU v4-8 —
vs_baseline is rate / 100_000.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

SHARDS, COMMITTEE = 100, 135

# ordered by prior: exact/scan won the CPU sweep (throughput-bound), the
# wide/assoc pair minimizes sequential depth (latency-bound TPU); if the
# sweep budget runs out, the best of the configs measured so far wins
CONFIGS = [
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_CARRY": "assoc"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_CARRY": "scan"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "assoc"},
]

SWEEP_BUDGET_S = float(os.environ.get("GETHSHARDING_BENCH_BUDGET_S", "1200"))


def _enable_compile_cache() -> None:
    import jax

    try:  # persistent compile cache: first run pays ~1 min, repeats don't
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def measure_single() -> dict:
    """Measure the workload under the CURRENT env config; return stats."""
    if os.environ.get("GETHSHARDING_BENCH_CPU") == "1":
        # hermetic/offline runs: force the CPU backend before any init
        # (the TPU-tunnel plugin otherwise dials hardware that may be
        # absent); the driver's real-hardware runs never set this.
        from gethsharding_tpu.parallel.virtual import force_virtual_cpu_devices

        force_virtual_cpu_devices(1)

    import jax
    import jax.numpy as jnp

    _enable_compile_cache()

    from gethsharding_tpu.crypto import bn256 as ref
    from gethsharding_tpu.ops import bn256_jax as k

    # one real signed header, replicated across shards (throughput is
    # data-independent; correctness is pinned by tests/test_bn256_jax.py)
    header = b"collation-header"
    keys = [ref.bls_keygen(bytes([i % 256, i // 256])) for i in range(8)]
    agg_sig = ref.bls_aggregate_sigs(
        [ref.bls_sign(header, sk) for sk, _ in keys])
    agg_pk = ref.bls_aggregate_pks([pk for _, pk in keys])
    h = ref.hash_to_g1(header)

    hx, hy, _ = k.g1_to_limbs([h] * SHARDS)
    sx, sy, _ = k.g1_to_limbs([agg_sig] * SHARDS)
    pkx, pky, _ = k.g2_to_limbs([agg_pk] * SHARDS)
    args = [jnp.asarray(a) for a in (hx, hy, sx, sy, pkx, pky)]
    args.append(jnp.ones(SHARDS, bool))

    fn = jax.jit(k.bls_verify_aggregate_batch)
    out = fn(*args)
    out.block_until_ready()  # compile
    assert bool(np.asarray(out).all()), "verification must accept"

    iters, t0 = 3, time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    elapsed = (time.perf_counter() - t0) / iters

    return {
        "platform": jax.devices()[0].platform,
        "elapsed": elapsed,
        "sig_rate": SHARDS * COMMITTEE / elapsed,
    }


def _run_config(cfg: dict) -> dict | None:
    """Measure one config in a subprocess; None on failure/timeout."""
    env = dict(os.environ)
    env.update(cfg)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--single"],
            env=env, capture_output=True, text=True, timeout=560,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                stats = json.loads(line)
                if "sig_rate" in stats:
                    return stats
            except json.JSONDecodeError:
                continue
    except (subprocess.TimeoutExpired, OSError):
        pass
    return None


def _cache_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench_autotune.json")


def main() -> None:
    if "--single" in sys.argv:
        print(json.dumps(measure_single()))
        return

    best_cfg, best = None, None
    cache_key = None
    try:
        cached = json.load(open(_cache_path()))
        cache_key = cached.get("platform")
        if all(k in cached for k in ("config", "platform")):
            best_cfg = cached["config"]
    except Exception:
        pass

    if best_cfg is not None:
        # verify the cached winner still runs, then use it directly
        stats = _run_config(best_cfg)
        if stats is not None and stats.get("platform") == cache_key:
            best = stats
        else:
            best_cfg = None

    if best_cfg is None:
        results = []
        sweep_start = time.monotonic()
        for i, cfg in enumerate(CONFIGS):
            if results and time.monotonic() - sweep_start > SWEEP_BUDGET_S:
                print(f"# sweep budget exhausted after {i} configs",
                      file=sys.stderr)
                break
            stats = _run_config(cfg)
            if stats is not None:
                results.append((cfg, stats))
                print(f"# config {cfg} -> "
                      f"{stats['sig_rate']:.1f} sigs/sec "
                      f"[{stats['platform']}]", file=sys.stderr)
        if not results:
            # subprocess sweep impossible (e.g. no fork) — measure inline
            best_cfg, best = {}, measure_single()
        else:
            best_cfg, best = max(results, key=lambda r: r[1]["sig_rate"])
            try:
                json.dump({"config": best_cfg,
                           "platform": best["platform"]},
                          open(_cache_path(), "w"))
            except OSError:
                pass

    sig_rate = best["sig_rate"]
    form = best_cfg.get("GETHSHARDING_TPU_LIMB_FORM", "wide")
    carry = best_cfg.get("GETHSHARDING_TPU_CARRY", "scan")
    print(json.dumps({
        "metric": "notary_sig_verifications_per_sec",
        "value": round(sig_rate, 1),
        "unit": (f"sigs/sec (100 shards x 135-vote BLS aggregate, "
                 f"opt-ate bn256, {form}/{carry}, "
                 f"{best['platform']})"),
        "vs_baseline": round(sig_rate / 100_000.0, 4),
    }))


if __name__ == "__main__":
    main()
