"""Driver benchmark: the five BASELINE.md configs on real hardware.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", "extra"}.

Headline metric (BASELINE config 3): aggregate notary-signature
verifications/sec across one 100-shard period. The workload is produced
by the PROTOCOL, not synthesized: a chain with 135 notaries registered
through the real registration path (derived BLS keys + proofs of
possession), 100 collation records added per period, and every committee
slot's vote BLS-signed over the real vote digest with the voter's real
key. What is measured is the live notary's `audit_period` — the
production code path that aggregates the period's votes and verifies all
shards in ONE batched pairing dispatch. (The reference's sampling quirk
yields ~1 eligible voter per shard per period; the bench populates all
135 committee slots per the protocol's documented committee intent.)

Extras: config 1 (single PairingCheck micro), config 2 (one 135-vote
aggregate), config 4 (collation replay, 1 shard), config 5 (the fused
1024-shard stress step) — skipped automatically when the backend is too
slow to fit the budget (hermetic CPU runs).

The kernel has build-time knobs whose best setting depends on the
backend (GETHSHARDING_TPU_LIMB_FORM = wide|exact, GETHSHARDING_TPU_CARRY
= scan|assoc, GETHSHARDING_TPU_CONV = shift|slices|gather|onehot|mxu8,
GETHSHARDING_TPU_PAIRCONV = xla|pallas, GETHSHARDING_TPU_PALLAS,
all read at import): the bench AUTOTUNES by re-executing itself
per configuration in a subprocess and reports the fastest, caching the
winner per backend in .bench_autotune.json. Signing workloads are cached
in .bench_workload.npz (first build ~3 min of host-side scalar crypto).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

SHARDS, COMMITTEE = 100, 135
REPO = os.path.dirname(os.path.abspath(__file__))

# ordered by prior: exact/scan won the r2 TPU sweep (then measured with
# the one-hot conv; `shift` — the module default — replaced it after CPU
# profiling showed gather memory-bound and onehot doing redundant MACs,
# but shift/slices have NOT yet been measured on TPU: the tunnel was down
# for the rest of r2, so this sweep decides). The assoc carry and the
# Pallas fused-normalize lost on TPU in r2 but stay as probes — backends
# change. If the sweep budget runs out, the best config measured so far
# wins.
CONFIGS = [
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan"},
    # r4: the final-exponentiation mega-kernel (ops/pallas_finalexp.py) —
    # the whole ~250-op final exp as ONE pallas_call; the lever sized to
    # the latency-bound gap (VERDICT r3 #1). Probed right after the
    # champion, composed with the champion's ambient knobs and with
    # relaxed normalize for the Miller side.
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_FINALEXP": "mega"},
    # the two-launch pairing check: Miller AND final exp each one kernel
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega"},
    # the four-launch audit dispatch: aggregation kernels too
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega",
     "GETHSHARDING_TPU_AGG": "mega"},
    # mega kernels composed over the slices conv ambient (the r4 TPU
    # sweep's non-mega champion) — the non-pairing remainder of the
    # dispatch also runs its fastest measured form
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_CONV": "slices",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega",
     "GETHSHARDING_TPU_AGG": "mega"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_CONV": "slices",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega"},
    # the uint16 wire format: halves host->device transfer bytes (12-bit
    # limbs in int32 waste 20 bits); widened on device, value-identical
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_FINALEXP": "mega", "GETHSHARDING_TPU_MILLER": "mega",
     "GETHSHARDING_TPU_WIRE": "u16"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed",
     "GETHSHARDING_TPU_FINALEXP": "mega"},
    # r3 additions, probed right after the champion: the statically
    # unrolled carry (straight-line fused code instead of an XLA While
    # per normalize), the fused Pallas pair-conv (never materializes the
    # product tensor in HBM), alone, + fused-normalize, and the
    # int8-plane MXU column contraction
    {"GETHSHARDING_TPU_LIMB_FORM": "exact",
     "GETHSHARDING_TPU_CARRY": "unroll"},
    # relaxed normalize: no exact carry ripple anywhere in the field ops
    # (wide form only; quasi-canonical limbs, see ops/limb.py)
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed",
     "GETHSHARDING_TPU_SCAN_UNROLL": "8"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "unroll",
     "GETHSHARDING_TPU_SCAN_UNROLL": "8"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_PAIRCONV": "pallas"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_PAIRCONV": "pallas", "GETHSHARDING_TPU_PALLAS": "1"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_CONV": "mxu8"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_CONV": "slices"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_PAIRCONV": "pallas"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_CARRY": "scan"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_CONV": "onehot"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "assoc"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_PALLAS": "1"},
    # LAST on purpose: the fully inlined PAIR_UNROLL kernels compile for
    # >35 min on XLA:CPU and may not fit the per-config probe timeout on
    # any backend — the watcher's queue probes them with long timeouts
    # instead; in a sweep they only run if budget remains
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed",
     "GETHSHARDING_TPU_PAIR_UNROLL": "finalexp"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "unroll",
     "GETHSHARDING_TPU_PAIR_UNROLL": "1"},
    {"GETHSHARDING_TPU_LIMB_FORM": "exact", "GETHSHARDING_TPU_CARRY": "scan",
     "GETHSHARDING_TPU_PAIR_UNROLL": "1"},
    {"GETHSHARDING_TPU_LIMB_FORM": "wide", "GETHSHARDING_TPU_NORM": "relaxed",
     "GETHSHARDING_TPU_PAIR_UNROLL": "1"},
]

SWEEP_BUDGET_S = float(os.environ.get("GETHSHARDING_BENCH_BUDGET_S", "1200"))

# Optional ABSOLUTE wall-clock deadline (epoch seconds). Callers running
# under an outer `timeout` (scripts/tpu_experiments/89_finalize_winner.sh)
# set it so every stage's subprocess timeout derives from the REMAINING
# wall clock — the extras pass, retry, and sweep can then never cascade
# past the window and get SIGTERMed mid-write.
_DEADLINE_TS = float(os.environ.get("GETHSHARDING_BENCH_DEADLINE_TS", "0"))


def _remaining() -> "float | None":
    return None if not _DEADLINE_TS else _DEADLINE_TS - time.time()


def _enable_compile_cache() -> None:
    # persistent compile cache: first run pays ~1 min, repeats don't.
    # Host-keyed (entries from another machine can segfault on load);
    # one shared definition with tests/dryrun.
    from gethsharding_tpu.parallel.virtual import configure_compile_cache

    configure_compile_cache()


# == protocol-generated workload (host scalar crypto, disk-cached) =========


def _workload_path() -> str:
    return os.path.join(REPO, ".bench_workload.npz")


def _point_to_bytes(p) -> np.ndarray:
    return np.frombuffer(p[0].to_bytes(32, "big") + p[1].to_bytes(32, "big"),
                         np.uint8)


def _point_from_bytes(b) -> tuple:
    raw = bytes(b)
    return (int.from_bytes(raw[:32], "big"), int.from_bytes(raw[32:], "big"))


def _bench_identities():
    """The deterministic identities + per-shard vote digests shared by the
    cache builder and the chain builder (single source of truth: a drift
    would silently invalidate the signature cache)."""
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.mainchain.accounts import AccountManager
    from gethsharding_tpu.smc.state_machine import vote_digest
    from gethsharding_tpu.utils.hexbytes import Hash32

    period = 1  # build_audit_workload asserts the chain lands here
    manager = AccountManager()
    accounts = [manager.new_account(seed=b"bench-notary-%d" % i)
                for i in range(COMMITTEE)]
    roots = [Hash32(keccak256(b"bench-root-%d" % s)) for s in range(SHARDS)]
    digests = [bytes(vote_digest(s, period, roots[s])) for s in range(SHARDS)]
    return manager, accounts, roots, digests, period


def _load_or_build_vote_sigs(accounts, manager, digests) -> np.ndarray:
    """(SHARDS, COMMITTEE, 64) uint8 — every committee slot's signature
    per shard digest, signed with the notary's real derived vote key."""
    path = _workload_path()
    try:
        cached = np.load(path)
        sigs = cached["vote_sigs"]
        if (sigs.shape == (SHARDS, COMMITTEE, 64)
                and bytes(cached["digest0"]) == digests[0]):
            return sigs
    except (OSError, KeyError, ValueError):
        pass
    print("# building vote-signature workload "
          f"({SHARDS}x{COMMITTEE} BLS signs, ~3 min once)...", file=sys.stderr)
    sigs = np.zeros((SHARDS, COMMITTEE, 64), np.uint8)
    for s in range(SHARDS):
        for i, acct in enumerate(accounts):
            sig = manager.bls_sign(acct.address, digests[s])
            sigs[s, i] = _point_to_bytes(sig)
    try:
        np.savez_compressed(path, vote_sigs=sigs,
                            digest0=np.frombuffer(digests[0], np.uint8))
    except OSError:
        pass
    return sigs


def build_audit_workload():
    """A real chain at the end of a full 100-shard period: registry,
    records, and signed votes all built through protocol objects. Returns
    (notary, period) ready for repeated audit_period calls."""
    from gethsharding_tpu.actors.notary import Notary
    from gethsharding_tpu.core.shard import Shard
    from gethsharding_tpu.db.kv import MemoryKV
    from gethsharding_tpu.mainchain.client import SMCClient
    from gethsharding_tpu.params import Config, ETHER
    from gethsharding_tpu.sigbackend import get_backend
    from gethsharding_tpu.smc.chain import SimulatedMainchain
    from gethsharding_tpu.smc.state_machine import VoteSig

    config = Config()  # protocol-scale: 100 shards, committee 135
    chain = SimulatedMainchain(config=config)
    manager, accounts, roots, digests, period = _bench_identities()
    for acct in accounts:
        chain.fund(acct.address, 2000 * ETHER)
        chain.register_notary(
            acct.address, bls_pubkey=acct.bls_pubkey,
            bls_pop=manager.bls_proof_of_possession(acct.address))
    chain.fast_forward(1)
    assert chain.current_period() == period, "identity/digest drift"
    proposer = manager.new_account(seed=b"bench-proposer")
    for s in range(SHARDS):
        chain.add_header(proposer.address, s, period, roots[s])
    sig_bytes = _load_or_build_vote_sigs(accounts, manager, digests)
    for s in range(SHARDS):
        record = chain.smc.collation_records[(s, period)]
        for i, acct in enumerate(accounts):
            record.vote_sigs[i] = VoteSig(
                sig=_point_from_bytes(sig_bytes[s, i]), signer=acct.address)
        record.vote_count = COMMITTEE
        record.is_elected = True
        chain.smc.last_approved_collation[s] = period
    chain.fast_forward(1)  # close the period

    client = SMCClient(backend=chain, accounts=manager, account=accounts[0],
                       config=config)
    notary = Notary(client=client, shard=Shard(shard_id=0, shard_db=MemoryKV()),
                    config=config, sig_backend=get_backend("jax"))
    return notary, period


# == measurements ==========================================================


def measure_single() -> dict:
    """Measure under the CURRENT env config; prints one stats JSON line."""
    if os.environ.get("GETHSHARDING_BENCH_CPU") == "1":
        # hermetic/offline runs: force the CPU backend before any init
        from gethsharding_tpu.parallel.virtual import force_virtual_cpu_devices

        force_virtual_cpu_devices(1)

    import jax

    _enable_compile_cache()

    notary, period = build_audit_workload()

    # warm-up (compiles the bucketed batch shape) + correctness gate
    assert notary.audit_period(period) is True, "audit must be consistent"
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        assert notary.audit_period(period) is True
    wall = (time.perf_counter() - t0) / iters
    # the verification dispatch itself (the BASELINE metric) — the audit
    # timer records only the sig-backend call
    dispatch = notary.m_audit_latency.percentile(0.5)
    sig_rate = SHARDS * COMMITTEE / dispatch

    stats = {
        "platform": jax.devices()[0].platform,
        "sig_rate": round(sig_rate, 1),
        "dispatch_s": round(dispatch, 4),
        "audit_wall_s": round(wall, 4),
        # GETHSHARDING_SIG_TIMING=1: host-marshal / transfer / device
        # split of the last dispatch (see sigbackend.last_timing)
        **({"sig_timing": notary.sig_backend.last_timing}
           if os.environ.get("GETHSHARDING_SIG_TIMING") == "1" else {}),
        # the active kernel knobs, so probe outputs are self-describing
        # (scripts/tpu_pick_winner.py rebuilds the autotune cache from
        # the best probe)
        "knobs": {key: val for key, val in os.environ.items()
                  if key.startswith("GETHSHARDING_TPU_")},
    }
    if os.environ.get("GETHSHARDING_BENCH_EXTRAS") == "1":
        # configs 1/2/4/5 run only for the sweep winner (main() re-invokes
        # with this flag) — not in every autotune subprocess
        stats.update(_measure_extras(dispatch))
    return stats


def _measure_extras(dispatch_s: float) -> dict:
    """Configs 1, 2, 4 (+5 when the backend is fast enough)."""
    import jax
    import jax.numpy as jnp

    from gethsharding_tpu.crypto import bn256 as ref
    from gethsharding_tpu.ops import bn256_jax as k

    out = {}

    # config 1: single PairingCheck (e(aP,Q)e(-P,aQ) == 1), batch 1
    a = 1234567
    p1, q1 = ref.g1_mul(a, ref.G1_GEN), ref.G2_GEN
    p2, q2 = ref.g1_neg(ref.G1_GEN), ref.g2_mul(a, ref.G2_GEN)
    px, py, _ = k.g1_to_limbs([[p1, p2][i] for i in range(2)])
    qx, qy, _ = k.g2_to_limbs([[q1, q2][i] for i in range(2)])
    fn = jax.jit(k.pairing_check)
    args = (jnp.asarray(px)[None], jnp.asarray(py)[None],
            jnp.asarray(qx)[None], jnp.asarray(qy)[None],
            jnp.ones((1, 2), bool))
    assert bool(np.asarray(fn(*args))[0])
    t0 = time.perf_counter()
    for _ in range(3):
        r = fn(*args)
    np.asarray(r)  # device->host pull: block_until_ready can no-op
    out["config1_pairing_check_s"] = round((time.perf_counter() - t0) / 3, 4)

    # config 2: ONE 135-vote aggregate (batch 1 of the BLS kernel)
    header = b"bench-config2"
    keys = [ref.bls_keygen(bytes([i])) for i in range(4)]
    agg_sig = ref.bls_aggregate_sigs([ref.bls_sign(header, sk)
                                      for sk, _ in keys])
    agg_pk = ref.bls_aggregate_pks([pk for _, pk in keys])
    hx, hy, _ = k.g1_to_limbs([ref.hash_to_g1(header)])
    sx, sy, _ = k.g1_to_limbs([agg_sig])
    pkx, pky, _ = k.g2_to_limbs([agg_pk])
    fn2 = jax.jit(k.bls_verify_aggregate_batch)
    args2 = tuple(jnp.asarray(x) for x in (hx, hy, sx, sy, pkx, pky)) + (
        jnp.ones(1, bool),)
    assert bool(np.asarray(fn2(*args2))[0])
    t0 = time.perf_counter()
    for _ in range(3):
        r = fn2(*args2)
    np.asarray(r)  # device->host pull: block_until_ready can no-op
    out["config2_aggregate_verify_s"] = round((time.perf_counter() - t0) / 3,
                                              4)

    # config 4: collation replay, 1 shard x 64 txs
    from gethsharding_tpu.core import state_processor as sp
    from gethsharding_tpu.core.types import Transaction
    from gethsharding_tpu.crypto import secp256k1
    from gethsharding_tpu.ops import replay_jax

    n_txs = 64
    priv = 0xB0B
    sender = secp256k1.priv_to_address(priv)
    to = secp256k1.priv_to_address(0xA11CE)
    txs = [sp.sign_transaction(
        Transaction(nonce=i, gas_price=1, gas_limit=30000, to=to, value=1,
                    payload=b"x"), priv) for i in range(n_txs)]
    inp = replay_jax.build_replay_inputs(
        [txs], [{sender: sp.AccountState(balance=10 ** 12)}], [to])
    out4 = replay_jax.replay_batch(inp)
    assert bool(np.asarray(out4.statuses).all())
    t0 = time.perf_counter()
    for _ in range(3):
        out4 = replay_jax.replay_batch(inp)
    jax.device_get(out4)  # real pull: block_until_ready can no-op
    dt = (time.perf_counter() - t0) / 3
    out["config4_replay_txs_per_s"] = round(n_txs / dt, 1)

    # config 5: the fused 1024-shard stress step (addHeader + votes + BLS
    # + replay + all-reduce) — only when the backend is fast enough for
    # the 10x batch within the budget
    if dispatch_s < 2.0:
        from gethsharding_tpu.parallel.stress import (
            StressPipeline, build_stress_inputs)
        from gethsharding_tpu.params import Config

        n_shards = 1024
        inputs, pool, bh, sample_size, _ = build_stress_inputs(
            n_shards, votes_per_shard=2, txs_per_shard=1,
            committee_size=COMMITTEE)
        pipe = StressPipeline(config=Config(), mesh=None)
        res = pipe.run(inputs, pool, bh, 1, sample_size)
        jax.device_get(res.roots)
        t0 = time.perf_counter()
        res = pipe.run(inputs, pool, bh, 1, sample_size)
        jax.device_get(res.roots)  # real pull: block_until_ready can no-op
        dt = time.perf_counter() - t0
        out["config5_stress_shards_per_s"] = round(n_shards / dt, 1)
    return out


# == autotune orchestration ================================================


def _heavy_config(cfg: dict) -> bool:
    """Configs whose FIRST compile can legitimately exceed the normal
    per-probe timeout (mega-kernel Mosaic compiles, static unrolls).
    They get a longer probe window and are NEVER negative-cached — a
    budget-capped timeout is not evidence of a deterministic failure
    (the tunnel watcher probes them with 4800 s windows)."""
    return (cfg.get("GETHSHARDING_TPU_PAIR_UNROLL", "0") != "0"
            or "mega" in (cfg.get("GETHSHARDING_TPU_FINALEXP", ""),
                          cfg.get("GETHSHARDING_TPU_MILLER", ""),
                          cfg.get("GETHSHARDING_TPU_AGG", "")))


def _run_config(cfg: dict, extras: bool = False) -> dict | None:
    # the probe must measure cfg and ONLY cfg: ambient exported
    # GETHSHARDING_TPU_* knobs would leak into every subprocess, trip the
    # mutually-exclusive knob validations (ValueError at import), and get
    # the clean cfg permanently negative-cached under the wrong label
    env = {key: val for key, val in os.environ.items()
           if not key.startswith("GETHSHARDING_TPU_")}
    env.update(cfg)
    # the winner's extras pass (configs 1/2/4/5) compiles several extra
    # kernels — the r1 run lost its extras to the sweep-probe timeout, so
    # it gets a budget of its own, scaled with the run's overall budget
    # knob so a capped hermetic run stays capped; heavy configs get a
    # longer window for their first Mosaic compile
    timeout = min(4200, max(560, 1.25 * SWEEP_BUDGET_S)) if extras else min(
        1800 if _heavy_config(cfg) else 560, SWEEP_BUDGET_S)
    rem = _remaining()
    if rem is not None:
        if rem < 120:
            return None  # not enough window left to learn anything
        timeout = min(timeout, max(90, rem - 45))
    if extras:
        env["GETHSHARDING_BENCH_EXTRAS"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--single"],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=REPO)
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                stats = json.loads(line)
                if "sig_rate" in stats:
                    return stats
            except json.JSONDecodeError:
                continue
    except (subprocess.TimeoutExpired, OSError):
        pass
    return None


def _sweep_fingerprint() -> str:
    """Identity of the config set: a cache written for a different sweep
    (older knob set) must not short-circuit the new sweep."""
    import hashlib

    return hashlib.sha256(
        json.dumps(CONFIGS, sort_keys=True).encode()).hexdigest()[:12]


def _cache_path() -> str:
    return os.path.join(REPO, ".bench_autotune.json")


def ensure_workload_cache() -> None:
    """Build the signing workload ONCE in the orchestrating process (host
    scalar crypto only, no accelerator) so each sweep subprocess loads it
    from disk instead of paying ~3 minutes."""
    manager, accounts, _roots, digests, _period = _bench_identities()
    _load_or_build_vote_sigs(accounts, manager, digests)


def _print_metric(sig_rate: float, stats: dict, knobs: str) -> None:
    """THE one JSON line the driver records (single output contract for
    the autotuned and fallback paths)."""
    extra = {key: val for key, val in stats.items() if key != "sig_rate"}
    try:
        # code provenance: a replayed capture must be attributable to the
        # tree it actually measured
        extra["git"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True, timeout=10).stdout.strip()
    except (subprocess.SubprocessError, OSError):
        pass
    if extra.get("platform") == "axon":
        # the axon PJRT plugin IS the TPU chip behind the tunnel
        extra["platform"] = "tpu (axon)"
    # replayable provenance: _latest_capture refuses git-tracked captures
    # without an embedded stamp (checkout resets mtime), so every fresh
    # report carries its own capture time
    extra.setdefault("captured_at",
                     time.strftime("%Y-%m-%d %H:%M:%S", time.localtime()))
    print(json.dumps({
        "metric": "notary_sig_verifications_per_sec",
        "value": sig_rate,
        "unit": (f"sigs/sec (100-shard period audit, on-device 135-vote "
                 f"BLS aggregation+verification, protocol-generated "
                 f"workload, opt-ate bn256, {knobs})"),
        "vs_baseline": round(sig_rate / 100_000.0, 4),
        "extra": extra,
    }))


def _latest_capture() -> dict | None:
    """Newest mid-round TPU capture recorded by scripts/tpu_watch.sh.

    The accelerator tunnel dies for hours at a time (it was dead for the
    whole tail of r2, burying that round's kernels under a CPU-fallback
    number). When it is dead at report time, the honest best number is
    the live capture the watcher took earlier in the round — reported
    with explicit provenance (capture timestamp + a note), never
    fabricated: every capture is a real measured run of this repo's
    production audit path on the real chip."""
    import glob

    best = None
    live = glob.glob(os.path.join(REPO, ".tpu_results", "*.json"))
    tracked = glob.glob(os.path.join(REPO, "bench_results", "*.json"))
    for path in live + tracked:
        try:
            with open(path) as fh:
                rec = json.load(fh)
            mtime = os.path.getmtime(path)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict) or "value" not in rec:
            continue
        if rec.get("metric") != "notary_sig_verifications_per_sec":
            continue  # other experiments' records are not the headline
        if not str(rec.get("extra", {}).get("platform", "")).startswith("tpu"):
            continue
        # provenance: a record that already carries captured_at keeps it
        # (a replayed report must not be restamped as a fresh capture).
        # mtime is trusted as the capture time only for the watcher's own
        # untracked .tpu_results files — a git-tracked capture gets its
        # mtime reset by checkout, so without an embedded stamp it is
        # unusable, not "fresh"
        stamp = rec.get("extra", {}).get("captured_at")
        if stamp:
            try:
                when = time.mktime(time.strptime(stamp, "%Y-%m-%d %H:%M:%S"))
            except ValueError:
                continue
        elif path in live:
            when = mtime
        else:
            continue
        if time.time() - when > 24 * 3600:
            continue  # not this round's capture — stale evidence is worse
        if best is None or when > best[0]:
            best = (when, rec)
    if best is None:
        return None
    rec = dict(best[1])
    rec["extra"] = {
        **rec.get("extra", {}),
        "captured_at": time.strftime("%Y-%m-%d %H:%M:%S",
                                     time.localtime(best[0])),
        "note": ("live TPU capture from this round's tunnel watcher; "
                 "tunnel unreachable at report time"),
    }
    return rec


def _replay_capture(reason: str) -> bool:
    """Report this round's live TPU capture instead of a meaningless CPU
    number. Returns False when no (recent) capture exists.

    GETHSHARDING_BENCH_NO_REPLAY=1 disables replay entirely — the tunnel
    watcher's experiments set it so a mid-run tunnel death reads as
    failure (retry next window) instead of a replayed 'success'."""
    if os.environ.get("GETHSHARDING_BENCH_NO_REPLAY") == "1":
        return False
    captured = _latest_capture()
    if captured is None:
        return False
    print(f"# {reason}; reporting this round's live TPU capture",
          file=sys.stderr)
    print(json.dumps(captured))
    return True


def _probe_backend(timeout: float = 120.0):
    """Is an accelerator reachable? The TPU tunnel can die and then ANY
    jax backend init hangs forever — probe in a bounded subprocess so the
    driver's bench run always produces a number."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout, cwd=REPO)
        lines = proc.stdout.strip().splitlines()
        return lines[-1] if proc.returncode == 0 and lines else None
    except (subprocess.TimeoutExpired, OSError):
        return None


def main() -> None:
    if "--single" in sys.argv:
        print(json.dumps(measure_single()))
        return

    ensure_workload_cache()

    if os.environ.get("GETHSHARDING_BENCH_CPU") != "1":
        platform = _probe_backend()
        if platform is None:
            # the tunnel is dead NOW but may have been alive earlier in
            # the round: a real measured TPU number, with its capture
            # timestamp, beats a meaningless CPU figure
            if _replay_capture("accelerator unreachable"):
                return
            # dead accelerator tunnel: fall back to the hermetic CPU path
            # in-process (no sweep — CPU probes would eat the budget) so
            # the run still reports a real, correctness-gated number
            print("# accelerator unreachable; hermetic CPU fallback",
                  file=sys.stderr)
            os.environ["GETHSHARDING_BENCH_CPU"] = "1"
            # measured r3 on this host class (hermetic audit dispatch):
            # exact/scan + slices conv 742 sigs/s vs exact/scan 463 vs
            # the wide/shift defaults 387 — seed the fallback with the
            # CPU winner instead of paying for an in-fallback sweep
            os.environ.setdefault("GETHSHARDING_TPU_LIMB_FORM", "exact")
            os.environ.setdefault("GETHSHARDING_TPU_CARRY", "scan")
            os.environ.setdefault("GETHSHARDING_TPU_CONV", "slices")
            if SWEEP_BUDGET_S >= 900:
                # budget allows the configs 1/2/4 extras even on the CPU
                # fallback (config 5 self-skips on slow dispatch), so the
                # driver artifact records them in every round
                os.environ["GETHSHARDING_BENCH_EXTRAS"] = "1"
            stats = measure_single()
            knobs = "/".join([os.environ["GETHSHARDING_TPU_LIMB_FORM"],
                              os.environ["GETHSHARDING_TPU_CARRY"],
                              os.environ["GETHSHARDING_TPU_CONV"]])
            _print_metric(stats["sig_rate"], stats,
                          f"{knobs}, CPU FALLBACK - accelerator tunnel "
                          f"unreachable")
            return

    best_cfg, best = None, None
    cache_key = None
    failed: list = []
    try:
        cached = json.load(open(_cache_path()))
        if cached.get("sweep") == _sweep_fingerprint():
            # negative cache: configs that timed out / crashed in an
            # earlier sweep of THIS config set are not re-probed (a
            # deterministic too-slow compile would eat the tunnel window
            # every round)
            failed = [c for c in cached.get("failed", []) if c in CONFIGS]
            if all(key in cached for key in ("config", "platform")):
                cache_key = cached.get("platform")
                best_cfg = cached["config"]
    except Exception:
        pass

    def _save_cache(winner=None, platform=None):
        payload = {"sweep": _sweep_fingerprint(), "failed": failed}
        if winner is not None:
            payload.update({"config": winner, "platform": platform})
        try:
            json.dump(payload, open(_cache_path(), "w"))
        except OSError:
            pass

    if best_cfg is not None:
        stats = _run_config(best_cfg, extras=True)
        if stats is not None and stats.get("platform") == cache_key:
            best = stats
        else:
            # the extras pass compiles several extra kernels and can time
            # out on its own; before abandoning the cached winner for a
            # full re-sweep (which may not fit the caller's window —
            # 89_finalize's outer timeout), retry the winner WITHOUT
            # extras: a capture missing configs 1/2/4/5 beats no capture
            stats = _run_config(best_cfg)
            if stats is not None and stats.get("platform") == cache_key:
                print("# winner extras pass failed; reporting winner "
                      "without extras", file=sys.stderr)
                best = stats
            else:
                best_cfg = None

    if best_cfg is None:
        results = []
        sweep_failures: list = []
        sweep_start = time.monotonic()
        for i, cfg in enumerate(CONFIGS):
            if cfg in failed:
                print(f"# skipping config {cfg} (failed in an earlier "
                      f"sweep)", file=sys.stderr)
                continue
            elapsed = time.monotonic() - sweep_start
            rem = _remaining()
            if rem is not None and rem < 660:
                # break BEFORE starting a config the deadline would clamp:
                # a deadline-truncated probe failure must never be
                # negative-cached as a deterministic config failure
                print(f"# wall-clock deadline near; sweep stops after {i} "
                      f"configs", file=sys.stderr)
                break
            if elapsed > SWEEP_BUDGET_S and (
                    results or elapsed > SWEEP_BUDGET_S + 2 * 560):
                # past budget stop once something succeeded; with NOTHING
                # succeeded allow limited overtime (a couple of probe
                # timeouts) — an unbounded empty-results sweep against a
                # dead tunnel would run every config to its timeout and
                # blow the caller's window
                print(f"# sweep budget exhausted after {i} configs",
                      file=sys.stderr)
                break
            stats = _run_config(cfg)
            if stats is not None:
                results.append((cfg, stats))
                print(f"# config {cfg} -> {stats['sig_rate']:.1f} sigs/sec "
                      f"[{stats['platform']}]", file=sys.stderr)
            else:
                sweep_failures.append(cfg)
        if not results:
            # every sweep probe failed; before measuring in-process,
            # re-probe — the tunnel may have died MID-RUN, and an
            # in-process backend init against a dead tunnel hangs forever
            if (os.environ.get("GETHSHARDING_BENCH_CPU") != "1"
                    and _probe_backend() is None):
                if _replay_capture("accelerator died mid-run"):
                    return
                print("# accelerator died mid-run; hermetic CPU fallback",
                      file=sys.stderr)
                os.environ["GETHSHARDING_BENCH_CPU"] = "1"
            else:
                os.environ["GETHSHARDING_BENCH_EXTRAS"] = "1"
            best_cfg, best = {}, measure_single()
        else:
            best_cfg, best = max(results, key=lambda r: r[1]["sig_rate"])
            # persist failures only when the accelerator is STILL
            # reachable after the sweep — "something else succeeded" does
            # not make later failures deterministic (config 1 can succeed
            # and the tunnel die mid-sweep, which is this environment's
            # normal operating mode), so re-probe before blacklisting
            if sweep_failures and (
                    os.environ.get("GETHSHARDING_BENCH_CPU") == "1"
                    or _probe_backend() is not None):
                failed.extend(c for c in sweep_failures
                              if c not in failed and not _heavy_config(c))
            _save_cache(best_cfg, best["platform"])
            # one extra run of the winner for the config 1/2/4/5 numbers
            stats = _run_config(best_cfg, extras=True)
            if stats is not None:
                best = stats

    # label from the FULL winning config (any knob may decide the sweep)
    knobs = "/".join(
        [best_cfg.get("GETHSHARDING_TPU_LIMB_FORM", "wide"),
         best_cfg.get("GETHSHARDING_TPU_CARRY", "scan"),
         best_cfg.get("GETHSHARDING_TPU_CONV", "shift")]
        + (["pairconv-pallas"]
           if best_cfg.get("GETHSHARDING_TPU_PAIRCONV") == "pallas" else [])
        + ([f"pair-unroll-{best_cfg['GETHSHARDING_TPU_PAIR_UNROLL']}"]
           if best_cfg.get("GETHSHARDING_TPU_PAIR_UNROLL", "0") != "0"
           else [])
        + ([f"scan-unroll{best_cfg['GETHSHARDING_TPU_SCAN_UNROLL']}"]
           if best_cfg.get("GETHSHARDING_TPU_SCAN_UNROLL") else [])
        + (["norm-relaxed"]
           if best_cfg.get("GETHSHARDING_TPU_NORM") == "relaxed" else [])
        + (["pallas-norm"] if best_cfg.get("GETHSHARDING_TPU_PALLAS") == "1"
           else [])
        + (["finalexp-mega"]
           if best_cfg.get("GETHSHARDING_TPU_FINALEXP") == "mega" else [])
        + (["miller-mega"]
           if best_cfg.get("GETHSHARDING_TPU_MILLER") == "mega" else [])
        + (["agg-mega"]
           if best_cfg.get("GETHSHARDING_TPU_AGG") == "mega" else []))
    _print_metric(best["sig_rate"], best, f"{knobs}, {best['platform']}")


if __name__ == "__main__":
    main()
