"""Driver benchmark: notary-vote BLS aggregate verification throughput.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

The workload is BASELINE.md config 3: one period of the 100-shard
sharding protocol — for every shard, verify the aggregate BLS committee
vote (135 signatures aggregated into one G1 point) on its collation
header via the batched bn256 pairing kernel (ops/bn256_jax):
100 aggregate checks = 200 Miller loops + 100 final exponentiations,
all as one jitted batch on the accelerator.

Metric: aggregate notary-signature verifications/sec = shards × committee
/ wall time. North star (BASELINE.md): ≥100k/sec on TPU v4-8 —
vs_baseline is rate / 100_000.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    try:  # persistent compile cache: first run pays ~1 min, repeats don't
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass

    from gethsharding_tpu.crypto import bn256 as ref
    from gethsharding_tpu.ops import bn256_jax as k

    shards, committee = 100, 135

    # one real signed header, replicated across shards (throughput is
    # data-independent; correctness is pinned by tests/test_bn256_jax.py)
    header = b"collation-header"
    keys = [ref.bls_keygen(bytes([i % 256, i // 256])) for i in range(8)]
    agg_sig = ref.bls_aggregate_sigs(
        [ref.bls_sign(header, sk) for sk, _ in keys])
    agg_pk = ref.bls_aggregate_pks([pk for _, pk in keys])
    h = ref.hash_to_g1(header)

    hx, hy, _ = k.g1_to_limbs([h] * shards)
    sx, sy, _ = k.g1_to_limbs([agg_sig] * shards)
    pkx, pky, _ = k.g2_to_limbs([agg_pk] * shards)
    args = [jnp.asarray(a) for a in (hx, hy, sx, sy, pkx, pky)]
    args.append(jnp.ones(shards, bool))

    fn = jax.jit(k.bls_verify_aggregate_batch)
    out = fn(*args)
    out.block_until_ready()  # compile
    assert bool(np.asarray(out).all()), "verification must accept"

    iters, t0 = 3, time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    elapsed = (time.perf_counter() - t0) / iters

    sig_rate = shards * committee / elapsed
    print(json.dumps({
        "metric": "notary_sig_verifications_per_sec",
        "value": round(sig_rate, 1),
        "unit": "sigs/sec (100 shards x 135-vote BLS aggregate, bn256 pairing)",
        "vs_baseline": round(sig_rate / 100_000.0, 4),
    }))


if __name__ == "__main__":
    main()
