"""Driver benchmark: batched consensus-kernel throughput on real hardware.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Metric: aggregate 256-bit field multiplications/sec through the limb engine
(ops/limb.py) at the notary workload shape — 100 shards x 135 committee
members (BASELINE.md configs 2-3). This is the primitive under every
pairing/signature verification; the headline sig-verifs/sec metric lands
once ops/bn256_jax.py wires the full pairing on top.

vs_baseline: the reference publishes no measured numbers (BASELINE.md), so
the ratio is against the driver's north-star target expressed in this
primitive's units.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from gethsharding_tpu.crypto.bn256 import P as BN_P
    from gethsharding_tpu.ops.limb import ModArith

    arith = ModArith(BN_P)
    shards, committee = 100, 135
    batch = shards * committee  # 13500 field elements in flight

    muls_per_step = 8

    @jax.jit
    def step(x, y):
        for _ in range(muls_per_step):
            x = arith.mul(x, y)
        return x

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 1 << 12, (batch, 22), dtype=np.int32))
    y = jnp.asarray(rng.integers(0, 1 << 12, (batch, 22), dtype=np.int32))

    step(x, y).block_until_ready()  # compile

    iters = 20
    t0 = time.perf_counter()
    out = x
    for _ in range(iters):
        out = step(out, y)
    out.block_until_ready()
    elapsed = time.perf_counter() - t0

    total_muls = batch * muls_per_step * iters
    rate = total_muls / elapsed

    # North star: >=100k sig-verifs/sec. One BLS aggregate verify is two
    # pairings; one pairing ~ 1.5e4 field muls (Miller loop + final exp), so
    # the target in this unit is ~3e9 field muls/sec.
    baseline_rate = 3.0e9
    print(json.dumps({
        "metric": "field_mul_throughput_256bit",
        "value": round(rate, 1),
        "unit": "muls/sec",
        "vs_baseline": round(rate / baseline_rate, 4),
    }))


if __name__ == "__main__":
    main()
