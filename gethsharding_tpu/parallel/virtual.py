"""Virtual-device forcing: validate multi-chip layouts without real chips.

The driver environment exposes exactly one real TPU chip; multi-chip
shardings are validated on XLA's host-platform virtual CPU devices
(``--xla_force_host_platform_device_count``), per the environment contract
in SURVEY.md §7.5. This is the single shared implementation used by both
``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip`` so the two
cannot drift.

Forcing must happen before the first XLA client is created in the process:
XLA parses the flag once, and the environment's TPU-tunnel PJRT plugin
patches backend lookup to dial the tunnel even when ``JAX_PLATFORMS=cpu``
is set — dropping every non-cpu backend factory is the load-bearing step.
"""

from __future__ import annotations

import os
import re
from pathlib import Path
from typing import Optional

_COUNT_RE = re.compile(r"--xla_force_host_platform_device_count=(\d+)")

# The ONE known-benign stderr class of a virtual-mesh dryrun child:
# XLA:CPU's AOT loader logs E-severity machine-feature mismatch lines
# (cpu_aot_loader.cc) when a persistent-cache executable was compiled
# on a host with ISA features the executing host lacks ("Target
# machine feature +prefer-no-gather is not supported ... could lead to
# execution errors such as SIGILL"). Observed in every recorded
# dryrun tail (the `multichip_dryrun` ledger records) WITH rc=0 and
# bit-identical outputs: the loader recompiles/
# falls back safely, so the lines are WARN-ONLY — they must never fail
# a dryrun, and they must never excuse a real failure (rc != 0 fails
# regardless of what the tail says).
AOT_MISMATCH_MARKERS = (
    "cpu_aot_loader",
    "machine type used for xla:cpu compilation doesn't match",
    "target machine feature",
    "could lead to execution errors such as sigill",
)


def is_aot_mismatch_line(line: str) -> bool:
    """True when a stderr line belongs to the XLA:CPU AOT
    machine-feature mismatch class (see `AOT_MISMATCH_MARKERS`)."""
    low = line.lower()
    return any(marker in low for marker in AOT_MISMATCH_MARKERS)


def assert_aot_warn_only(rc: int, tail: str):
    """The dryrun child verdict: rc decides, the AOT mismatch lines in
    the captured tail are classified as warn-only noise. Returns the
    matched lines on success; raises ``RuntimeError`` on rc != 0 —
    explicitly even when mismatch lines are present, so the benign
    class can never mask a real crash (e.g. an actual SIGILL exits
    nonzero and fails here with the tail attached)."""
    matched = [line for line in tail.splitlines()
               if is_aot_mismatch_line(line)]
    if rc != 0:
        raise RuntimeError(
            f"virtual-mesh dryrun child failed (rc={rc}); the AOT "
            f"machine-feature mismatch warning is warn-only and never "
            f"excuses a failure. stderr tail:\n{tail[-4000:]}")
    return matched


def host_fingerprint() -> str:
    """Short stable id of THIS machine's CPU capabilities. The persistent
    compile cache stores AOT executables specialized to the compiling
    host's ISA extensions; loading an entry produced on a different
    machine can SIGILL or segfault inside the cache read (observed r2:
    a cache carried over from another host crashed the suite). Keying
    the cache directory by host makes cross-machine reuse impossible."""
    import hashlib
    import platform

    probe = platform.machine() + platform.processor()
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    probe += line
                    break
    except OSError:
        pass
    return hashlib.sha256(probe.encode()).hexdigest()[:10]


def default_cache_dir() -> str:
    """The repo-local host-keyed compile cache directory."""
    return str(Path(__file__).resolve().parents[2]
               / f".jax_cache-{host_fingerprint()}")


_cache_off_sticky = False


def configure_compile_cache(cache_dir=None, enabled: bool = True,
                            force: bool = False) -> None:
    """Point JAX's persistent compile cache at the host-keyed dir — the
    ONE definition shared by tests/dryrun (`force_virtual_cpu_devices`)
    and `bench.py`, so they can never drift onto different caches.

    `enabled=False` turns the cache off through the same seam AND makes
    the off-state STICKY: later default-enables (e.g. a test invoking
    `force_virtual_cpu_devices` mid-suite — the r3 full-suite segfault:
    the dryrun re-enabled the cache and a later cache READ crashed in
    XLA's executable deserializer) are ignored unless `force=True`.
    Multi-file pytest runs rely on this staying off for the whole
    process lifetime."""
    global _cache_off_sticky

    import jax

    if not enabled:
        _cache_off_sticky = True
    elif _cache_off_sticky and not force:
        return  # a multi-file run pinned the cache off: stay off
    elif force:
        _cache_off_sticky = False

    try:
        jax.config.update("jax_compilation_cache_dir",
                          str(cache_dir or default_cache_dir())
                          if enabled else None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # pragma: no cover - config name drift across jax
        pass


def requested_virtual_cpu_count() -> int:
    """Virtual CPU device count currently requested via XLA_FLAGS (0 if none)."""
    m = _COUNT_RE.search(os.environ.get("XLA_FLAGS", ""))
    return int(m.group(1)) if m else 0


def build_virtual_env(n: int, base_env=None) -> dict:
    """A copy of ``base_env`` (default: os.environ) with the virtual CPU
    platform forced for a CHILD process: JAX_PLATFORMS=cpu and the
    host-platform device-count flag rewritten to ``n``."""
    env = dict(os.environ if base_env is None else base_env)
    env["JAX_PLATFORMS"] = "cpu"
    flags = _COUNT_RE.sub("", env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    return env


def backend_initialized() -> bool:
    """True if any XLA backend client already exists in this process (at
    which point the device-count flag can no longer take effect)."""
    try:
        import jax._src.xla_bridge as xb

        return bool(getattr(xb, "_backends", {}))
    except Exception:  # pragma: no cover - jax-internal layout drift
        return False


def force_virtual_cpu_devices(n: int,
                              cache_dir: Optional[str] = None) -> None:
    """Force >= ``n`` visible JAX devices via the virtual CPU host platform.

    Idempotent; safe to call again in a process where it already ran (e.g.
    under pytest where conftest ran it at collection time). Must run before
    the first backend init to have any effect on the device count.

    Also points JAX's persistent compilation cache at the repo-local
    host-keyed ``.jax_cache-<fingerprint>`` via `configure_compile_cache`
    (the pairing kernels take minutes to compile cold on XLA:CPU; cache
    hits make repeat runs take seconds).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if requested_virtual_cpu_count() < n:
        flags = _COUNT_RE.sub("", flags)
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    configure_compile_cache(cache_dir)

    try:
        import jax._src.xla_bridge as xb

        # Drop PLUGIN factories (e.g. the TPU-tunnel PJRT plugin whose
        # patched backend lookup dials hardware even under JAX_PLATFORMS=
        # cpu) but keep the builtins: removing e.g. "tpu" from the factory
        # table also removes it from MLIR's known-platform registry, which
        # breaks importing jax.experimental.pallas.
        builtin = {"cpu", "tpu", "gpu", "cuda", "rocm", "metal",
                   "interpreter"}
        for name in list(getattr(xb, "_backend_factories", {})):
            if name not in builtin:
                xb._backend_factories.pop(name, None)
    except Exception:  # pragma: no cover - jax-internal layout drift
        pass
