"""Device mesh construction for the shard axis.

One logical axis — ``"shard"`` — carries the framework's only data-parallel
dimension (100 independent shard chains, `sharding_manager.sol:56`). Batch
work whose leading axis is shardID shards cleanly over it; per-period
cross-shard reductions (vote tallies, quorum counts) become `psum` over the
axis, which XLA lowers to ICI all-reduces on real TPU topologies.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the ``"shard"`` axis.

    ``n_devices=None`` uses every visible device; otherwise the first
    ``n_devices`` (the driver's dryrun passes an explicit count against a
    virtual CPU platform).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=("shard",))


def shard_axis_sharding(mesh: Mesh) -> NamedSharding:
    """NamedSharding that splits the leading (shardID) axis over ALL mesh
    axes (1-D "shard" meshes and 2-D ("dcn", "ici") meshes alike)."""
    return NamedSharding(mesh, PartitionSpec(tuple(mesh.axis_names)))


def make_multihost_mesh(n_hosts: Optional[int] = None,
                        devices_per_host: Optional[int] = None,
                        devices: Optional[Sequence] = None) -> Mesh:
    """A 2-D mesh ``("dcn", "ici")`` for multi-host deployments.

    The shard axis factors over both: a host owns a slab of shards split
    across its local chips. Reductions then lower hierarchically — a fast
    intra-host all-reduce over ICI, then one small inter-host all-reduce
    over DCN (the layout rule of SURVEY.md §5.8: collectives should ride
    ICI; only the reduced scalars cross DCN — exactly what
    `hierarchical_psum` emits).

    On a real multi-host pod pass `devices=jax.devices()` under
    `jax.distributed.initialize()` and the (process, local-device)
    structure gives the host axis; single-process callers (tests, the
    dryrun's virtual CPU platform) get an explicit factorization.
    """
    if devices is None:
        devices = jax.devices()
    # group by host FIRST: jax.devices() ordering is not guaranteed to be
    # process-contiguous, and a grid row mixing hosts would silently send
    # the "ici" reduce over DCN — the exact layout this mesh exists to
    # avoid
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    if n_hosts is None:
        n_hosts = max(d.process_index for d in devices) + 1
    if devices_per_host is None:
        if len(devices) % n_hosts:
            raise ValueError(
                f"{len(devices)} devices do not factor over {n_hosts} hosts")
        devices_per_host = len(devices) // n_hosts
    need = n_hosts * devices_per_host
    if need > len(devices):
        raise ValueError(
            f"requested {n_hosts}x{devices_per_host} devices, only "
            f"{len(devices)} visible")
    grid = np.asarray(devices[:need]).reshape(n_hosts, devices_per_host)
    return Mesh(grid, axis_names=("dcn", "ici"))


def hierarchical_psum(value, mesh: Mesh):
    """Sum over every mesh axis, innermost (ICI) first.

    Inside `shard_map` over a ``("dcn", "ici")`` mesh this emits the
    intra-host reduce before the cross-host one, so the DCN hop carries
    one already-reduced scalar per host."""
    for axis in reversed(mesh.axis_names):
        value = jax.lax.psum(value, axis_name=axis)
    return value
