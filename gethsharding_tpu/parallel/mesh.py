"""Device mesh construction for the shard axis.

One logical axis — ``"shard"`` — carries the framework's only data-parallel
dimension (100 independent shard chains, `sharding_manager.sol:56`). Batch
work whose leading axis is shardID shards cleanly over it; per-period
cross-shard reductions (vote tallies, quorum counts) become `psum` over the
axis, which XLA lowers to ICI all-reduces on real TPU topologies.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the ``"shard"`` axis.

    ``n_devices=None`` uses every visible device; otherwise the first
    ``n_devices`` (the driver's dryrun passes an explicit count against a
    virtual CPU platform).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=("shard",))


def shard_axis_sharding(mesh: Mesh) -> NamedSharding:
    """NamedSharding that splits the leading (shardID) axis over the mesh."""
    return NamedSharding(mesh, PartitionSpec("shard"))
