"""The per-period cross-shard pipeline: verify → tally → approve.

This is the framework's "training step": for every shard in a period,
verify the aggregate BLS committee vote on the shard's collation header
(batched pairing kernel), tally accepted votes, apply the quorum rule, and
all-reduce the period totals — laid out so the shard axis shards over a
`jax.sharding.Mesh` (BASELINE.md configs 3 and 5; SURVEY.md §2.2 row 1:
shard-level data parallelism is the reference's only scaling axis, here it
is the mesh axis and the tallies ride ICI collectives).

Two dispatch modes, same math:
- single-device: one jitted batch over all shards;
- mesh: `shard_map` with each device owning a contiguous shard slab and
  `psum` for the cross-shard reductions.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
try:  # re-exported at top level on newer jax; experimental on 0.4.x
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as PS

from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.ops import bn256_jax as bn
from gethsharding_tpu.params import Config, DEFAULT_CONFIG
from gethsharding_tpu.parallel.mesh import (
    hierarchical_psum, shard_axis_sharding)


class PeriodInputs(NamedTuple):
    """Device arrays for one period across S shards (leading axis = shard)."""

    hx: jnp.ndarray    # (S, 22) G1 hash-to-curve of each header
    hy: jnp.ndarray
    sx: jnp.ndarray    # (S, 22) aggregate committee signature
    sy: jnp.ndarray
    pkx: jnp.ndarray   # (S, 2, 22) aggregate committee public key
    pky: jnp.ndarray
    vote_count: jnp.ndarray  # (S,) int32 — votes aggregated per shard
    has_header: jnp.ndarray  # (S,) bool — shard has a submission this period


class PeriodOutputs(NamedTuple):
    verified: jnp.ndarray       # (S,) bool — aggregate signature valid
    approved: jnp.ndarray       # (S,) bool — verified & quorum reached
    total_votes: jnp.ndarray    # () int32 — Σ counted votes (all shards)
    total_approved: jnp.ndarray  # () int32 — Σ approved shards


def _tally(ok, counted, quorum: int, mesh: Optional[Mesh]) -> PeriodOutputs:
    """Quorum + period totals, reduced hierarchically over the mesh —
    the ONE tail shared by both pipeline granularities."""
    approved = ok & (counted >= quorum)
    total_votes = jnp.sum(counted)
    total_approved = jnp.sum(approved.astype(jnp.int32))
    if mesh is not None:
        total_votes = hierarchical_psum(total_votes, mesh)
        total_approved = hierarchical_psum(total_approved, mesh)
    return PeriodOutputs(ok, approved, total_votes, total_approved)


def _step(inp: PeriodInputs, quorum: int, mesh: Optional[Mesh]):
    ok = bn.bls_verify_aggregate_batch(
        inp.hx, inp.hy, inp.sx, inp.sy, inp.pkx, inp.pky, inp.has_header)
    return _tally(ok, jnp.where(ok, inp.vote_count, 0), quorum, mesh)


def _compile_step(step, quorum: int, mesh: Optional[Mesh], tuple_cls):
    """jit (single device) or shard_map-jit (mesh) of a period step over
    `tuple_cls` inputs; the leading shard axis splits over ALL mesh axes
    (1-D shard meshes and 2-D ("dcn", "ici") multi-host meshes alike),
    with tallies reduced hierarchically — ICI first, then DCN."""
    if mesh is None:
        return jax.jit(lambda inp: step(inp, quorum, None))
    n_fields = len(tuple_cls._fields)
    spec = PS(tuple(mesh.axis_names))
    return jax.jit(shard_map(
        lambda inp: step(inp, quorum, mesh),
        mesh=mesh,
        in_specs=(tuple_cls(*([spec] * n_fields)),),
        out_specs=PeriodOutputs(spec, spec, PS(), PS()),
    ))


def _run_padded(fn, mesh: Optional[Mesh], inputs, tuple_cls):
    """Run a compiled period step, padding the shard axis with masked
    zero rows (has_header False) to the next multiple of the mesh size
    and slicing the per-shard outputs back — masked rows contribute
    nothing to the psum tallies."""
    n = int(inputs[0].shape[0])
    if mesh is None:
        return fn(inputs)
    n_dev = mesh.devices.size
    padded = -(-n // n_dev) * n_dev
    if padded != n:
        pad = padded - n

        def pad_rows(a):
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return jnp.pad(a, widths)

        inputs = tuple_cls(*(pad_rows(a) for a in inputs))
    sharding = shard_axis_sharding(mesh)
    inputs = tuple_cls(*(jax.device_put(a, sharding) for a in inputs))
    out = fn(inputs)
    if padded != n:
        out = PeriodOutputs(
            verified=out.verified[:n], approved=out.approved[:n],
            total_votes=out.total_votes,
            total_approved=out.total_approved)
    return out


class PeriodPipeline:
    """Compiled per-period verifier over PRE-AGGREGATED committee points,
    optionally sharded over a mesh; uneven shard counts pad with masked
    rows (see `_run_padded`)."""

    def __init__(self, config: Config = DEFAULT_CONFIG,
                 mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh
        self._fn = _compile_step(_step, config.quorum_size, mesh,
                                 PeriodInputs)

    def run(self, inputs: PeriodInputs) -> PeriodOutputs:
        return _run_padded(self._fn, self.mesh, inputs, PeriodInputs)

    # -- host-side assembly -------------------------------------------------

    def build_inputs(self, headers: Sequence[Optional[bytes]],
                     agg_sigs: Sequence[Optional[bls.G1Point]],
                     agg_pks: Sequence[Optional[bls.G2Point]],
                     vote_counts: Sequence[int]) -> PeriodInputs:
        """Host records -> device arrays. `headers[i] is None` marks a
        shard with no submission this period (row masked out)."""
        hashes = [bls.hash_to_g1(h) if h is not None else None
                  for h in headers]
        hx, hy, hok = bn.g1_to_limbs(hashes)
        sx, sy, sok = bn.g1_to_limbs(list(agg_sigs))
        pkx, pky, pok = bn.g2_to_limbs(list(agg_pks))
        has_header = hok & sok & pok
        return PeriodInputs(
            hx=jnp.asarray(hx), hy=jnp.asarray(hy),
            sx=jnp.asarray(sx), sy=jnp.asarray(sy),
            pkx=jnp.asarray(pkx), pky=jnp.asarray(pky),
            vote_count=jnp.asarray(np.asarray(vote_counts, np.int32)),
            has_header=jnp.asarray(has_header),
        )


class CommitteePeriodInputs(NamedTuple):
    """Per-period inputs at COMMITTEE granularity (leading axis = shard):
    raw vote signatures and voter pubkeys, aggregated on device inside
    the verification dispatch (the production audit path)."""

    hx: jnp.ndarray        # (S, 22) G1 hash-to-curve of each header
    hy: jnp.ndarray
    sigx: jnp.ndarray      # (S, C, 22) per-vote signatures
    sigy: jnp.ndarray
    sig_mask: jnp.ndarray  # (S, C) bool — filled vote slots
    pkx: jnp.ndarray       # (S, C, 2, 22) voter pubkeys
    pky: jnp.ndarray
    pk_mask: jnp.ndarray   # (S, C) bool
    has_header: jnp.ndarray  # (S,) bool


def _committee_step(inp: CommitteePeriodInputs, quorum: int,
                    mesh: Optional[Mesh]):
    ok = bn.bls_aggregate_verify_committee_batch(
        inp.hx, inp.hy, inp.sigx, inp.sigy, inp.sig_mask,
        inp.pkx, inp.pky, inp.pk_mask, inp.has_header)
    # the vote count IS the filled signature slots — the device holds the
    # ground truth, so a stale/forged host-side count cannot inflate the
    # quorum
    counted = jnp.where(ok, jnp.sum(inp.sig_mask.astype(jnp.int32),
                                    axis=-1), 0)
    return _tally(ok, counted, quorum, mesh)


class CommitteePeriodPipeline:
    """The production period step: per-shard committee aggregation (masked
    projective tree reduction over the committee axis) + batched pairing
    verification + quorum tally, with the SHARD axis over the mesh and
    tallies riding `psum` — aggregation work stays device-local, only the
    two scalar totals cross the interconnect."""

    def __init__(self, config: Config = DEFAULT_CONFIG,
                 mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh
        self._fn = _compile_step(_committee_step, config.quorum_size, mesh,
                                 CommitteePeriodInputs)

    def run(self, inputs: CommitteePeriodInputs) -> PeriodOutputs:
        return _run_padded(self._fn, self.mesh, inputs,
                           CommitteePeriodInputs)

    def build_inputs(self, headers: Sequence[Optional[bytes]],
                     sig_rows: Sequence[Sequence[bls.G1Point]],
                     pk_rows: Sequence[Sequence[bls.G2Point]],
                     width: Optional[int] = None) -> CommitteePeriodInputs:
        """Host vote records -> committee-granular device arrays. The
        committee axis pads to `width` (default: the config committee
        size) so the compiled shape is period-invariant."""
        width = (width if width is not None
                 else self.config.committee_size)
        hashes = [bls.hash_to_g1(h) if h is not None else None
                  for h in headers]
        hx, hy, hok = bn.g1_to_limbs(hashes)
        sigx, sigy, sig_mask = bn.g1_committee_to_limbs(sig_rows, width)
        pkx, pky, pk_mask = bn.g2_committee_to_limbs(pk_rows, width)
        return CommitteePeriodInputs(
            hx=jnp.asarray(hx), hy=jnp.asarray(hy),
            sigx=jnp.asarray(sigx), sigy=jnp.asarray(sigy),
            sig_mask=jnp.asarray(sig_mask),
            pkx=jnp.asarray(pkx), pky=jnp.asarray(pky),
            pk_mask=jnp.asarray(pk_mask),
            has_header=jnp.asarray(hok),
        )
