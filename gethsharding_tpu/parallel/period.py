"""The per-period cross-shard pipeline: verify → tally → approve.

This is the framework's "training step": for every shard in a period,
verify the aggregate BLS committee vote on the shard's collation header
(batched pairing kernel), tally accepted votes, apply the quorum rule, and
all-reduce the period totals — laid out so the shard axis shards over a
`jax.sharding.Mesh` (BASELINE.md configs 3 and 5; SURVEY.md §2.2 row 1:
shard-level data parallelism is the reference's only scaling axis, here it
is the mesh axis and the tallies ride ICI collectives).

Two dispatch modes, same math:
- single-device: one jitted batch over all shards;
- mesh: `shard_map` with each device owning a contiguous shard slab and
  `psum` for the cross-shard reductions.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as PS

from gethsharding_tpu.crypto import bn256 as bls
from gethsharding_tpu.ops import bn256_jax as bn
from gethsharding_tpu.params import Config, DEFAULT_CONFIG
from gethsharding_tpu.parallel.mesh import shard_axis_sharding


class PeriodInputs(NamedTuple):
    """Device arrays for one period across S shards (leading axis = shard)."""

    hx: jnp.ndarray    # (S, 22) G1 hash-to-curve of each header
    hy: jnp.ndarray
    sx: jnp.ndarray    # (S, 22) aggregate committee signature
    sy: jnp.ndarray
    pkx: jnp.ndarray   # (S, 2, 22) aggregate committee public key
    pky: jnp.ndarray
    vote_count: jnp.ndarray  # (S,) int32 — votes aggregated per shard
    has_header: jnp.ndarray  # (S,) bool — shard has a submission this period


class PeriodOutputs(NamedTuple):
    verified: jnp.ndarray       # (S,) bool — aggregate signature valid
    approved: jnp.ndarray       # (S,) bool — verified & quorum reached
    total_votes: jnp.ndarray    # () int32 — Σ counted votes (all shards)
    total_approved: jnp.ndarray  # () int32 — Σ approved shards


def _step(inp: PeriodInputs, quorum: int, axis: Optional[str]):
    ok = bn.bls_verify_aggregate_batch(
        inp.hx, inp.hy, inp.sx, inp.sy, inp.pkx, inp.pky, inp.has_header)
    counted = jnp.where(ok, inp.vote_count, 0)
    approved = ok & (counted >= quorum)
    total_votes = jnp.sum(counted)
    total_approved = jnp.sum(approved.astype(jnp.int32))
    if axis is not None:
        total_votes = jax.lax.psum(total_votes, axis_name=axis)
        total_approved = jax.lax.psum(total_approved, axis_name=axis)
    return PeriodOutputs(ok, approved, total_votes, total_approved)


class PeriodPipeline:
    """Compiled per-period verifier, optionally sharded over a mesh.

    Uneven shard counts are handled transparently: `run` pads the batch
    with masked (has_header=False) rows up to the next multiple of the
    mesh axis size and slices the per-shard outputs back — masked rows
    contribute nothing to the `psum` tallies.
    """

    def __init__(self, config: Config = DEFAULT_CONFIG,
                 mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh
        quorum = config.quorum_size
        if mesh is None:
            self._fn = jax.jit(lambda inp: _step(inp, quorum, None))
        else:
            self._fn = jax.jit(shard_map(
                lambda inp: _step(inp, quorum, "shard"),
                mesh=mesh,
                in_specs=(PeriodInputs(*([PS("shard")] * 8)),),
                out_specs=PeriodOutputs(
                    PS("shard"), PS("shard"), PS(), PS()),
            ))

    def run(self, inputs: PeriodInputs) -> PeriodOutputs:
        n = int(inputs.hx.shape[0])
        if self.mesh is None:
            return self._fn(inputs)
        n_dev = self.mesh.devices.size
        padded = -(-n // n_dev) * n_dev
        if padded != n:
            pad = padded - n

            def pad_rows(a):
                widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
                return jnp.pad(a, widths)  # zeros: has_header rows False

            inputs = PeriodInputs(*(pad_rows(a) for a in inputs))
        sharding = shard_axis_sharding(self.mesh)
        inputs = PeriodInputs(
            *(jax.device_put(a, sharding) for a in inputs))
        out = self._fn(inputs)
        if padded != n:
            out = PeriodOutputs(
                verified=out.verified[:n], approved=out.approved[:n],
                total_votes=out.total_votes,
                total_approved=out.total_approved)
        return out

    # -- host-side assembly -------------------------------------------------

    def build_inputs(self, headers: Sequence[Optional[bytes]],
                     agg_sigs: Sequence[Optional[bls.G1Point]],
                     agg_pks: Sequence[Optional[bls.G2Point]],
                     vote_counts: Sequence[int]) -> PeriodInputs:
        """Host records -> device arrays. `headers[i] is None` marks a
        shard with no submission this period (row masked out)."""
        hashes = [bls.hash_to_g1(h) if h is not None else None
                  for h in headers]
        hx, hy, hok = bn.g1_to_limbs(hashes)
        sx, sy, sok = bn.g1_to_limbs(list(agg_sigs))
        pkx, pky, pok = bn.g2_to_limbs(list(agg_pks))
        has_header = hok & sok & pok
        return PeriodInputs(
            hx=jnp.asarray(hx), hy=jnp.asarray(hy),
            sx=jnp.asarray(sx), sy=jnp.asarray(sy),
            pkx=jnp.asarray(pkx), pky=jnp.asarray(pky),
            vote_count=jnp.asarray(np.asarray(vote_counts, np.int32)),
            has_header=jnp.asarray(has_header),
        )
