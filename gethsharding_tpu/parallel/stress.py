"""The multi-chip stress pipeline: BASELINE.md config 5.

One fused period step over S shards sharded across the ``"shard"`` mesh
axis, combining every per-period kernel the framework has:

  addHeader vote-plane reset  (ops/smc_jax.add_header_reset_masked)
  -> submitVote batch          (ops/smc_jax.submit_votes_batch:
                                committee sampling, bitfield, quorum)
  -> committee BLS aggregation + verification (ops/bn256_jax: masked
                                 projective tree sum + one Miller
                                 product per shard — the production
                                 audit dispatch)
  -> collation tx replay        (ops/replay_jax: batched ecrecover +
                                 ordered state transitions + state roots)
  -> period totals as `psum` over ICI (the all-reduce of the north star)

Each device owns a contiguous slab of shards with DISTINCT data; uneven
shard counts pad with masked rows (has_header=False, invalid attempts) —
`run` handles the padding transparently, like PeriodPipeline.

Every sub-kernel is differential-tested on its own elsewhere; the test
for this module checks mesh-vs-single-device bit identity, which is the
property the stress config exists to demonstrate.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
try:  # re-exported at top level on newer jax; experimental on 0.4.x
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as PS

from gethsharding_tpu.ops import bn256_jax as bn
from gethsharding_tpu.ops import replay_jax, secp256k1_jax, smc_jax
from gethsharding_tpu.params import Config, DEFAULT_CONFIG
from gethsharding_tpu.parallel.mesh import shard_axis_sharding


class StressInputs(NamedTuple):
    """Leading axis S = shards on every field except the replicated tail."""

    # SMC vote plane
    has_voted: jnp.ndarray       # (S, C) bool
    vote_count: jnp.ndarray      # (S,) int32
    last_submitted: jnp.ndarray  # (S,) int32
    last_approved: jnp.ndarray   # (S,) int32
    is_elected: jnp.ndarray      # (S,) bool
    chunk_root: jnp.ndarray      # (S, 32) uint8 — prior record roots
    # this period's headers
    new_header: jnp.ndarray      # (S,) bool
    new_chunk_root: jnp.ndarray  # (S, 32) uint8
    # vote attempts, V rows per shard (padded with valid=False)
    att_index: jnp.ndarray       # (S, V) int32
    att_pool_index: jnp.ndarray  # (S, V) int32
    att_sender: jnp.ndarray      # (S, V, 20) uint8
    att_chunk_root: jnp.ndarray  # (S, V, 32) uint8
    att_deposited: jnp.ndarray   # (S, V) bool
    att_valid: jnp.ndarray       # (S, V) bool
    # committee BLS votes per shard (aggregated ON DEVICE)
    hx: jnp.ndarray              # (S, NLIMBS)
    hy: jnp.ndarray
    sigx: jnp.ndarray            # (S, Cw, NLIMBS) raw vote signatures
    sigy: jnp.ndarray
    sig_mask: jnp.ndarray        # (S, Cw) bool
    pkx: jnp.ndarray             # (S, Cw, 2, NLIMBS) voter pubkeys
    pky: jnp.ndarray
    pk_mask: jnp.ndarray         # (S, Cw) bool
    agg_valid: jnp.ndarray       # (S,) bool
    # collation replay (see ops/replay_jax.ReplayInputs)
    addrs: jnp.ndarray
    nonces: jnp.ndarray
    balances: jnp.ndarray
    coinbase_ix: jnp.ndarray
    tx_e: jnp.ndarray
    tx_r: jnp.ndarray
    tx_s: jnp.ndarray
    tx_recid: jnp.ndarray
    tx_nonce: jnp.ndarray
    tx_gas_limit: jnp.ndarray
    tx_intrinsic: jnp.ndarray
    tx_price: jnp.ndarray
    tx_value: jnp.ndarray
    tx_to: jnp.ndarray
    tx_valid: jnp.ndarray


class StressOutputs(NamedTuple):
    accepted: jnp.ndarray        # (S, V) bool — accepted vote attempts
    vote_count: jnp.ndarray      # (S,) int32
    is_elected: jnp.ndarray      # (S,) bool
    agg_ok: jnp.ndarray          # (S,) bool — aggregate signature valid
    tx_status: jnp.ndarray       # (S, T) bool
    roots: jnp.ndarray           # (S, 32) uint8 — post-replay state roots
    total_votes: jnp.ndarray     # () int32  — psum over the mesh
    total_elected: jnp.ndarray   # () int32
    total_txs: jnp.ndarray       # () int32


def _step(inp: StressInputs, pool_addr, blockhash, period, sample_size,
          committee_size: int, quorum_size: int, axis,
          axis_sizes: tuple = ()):
    """`axis`: None (single device) or the mesh axis-name tuple. With a
    multi-axis mesh (("dcn", "ici")) the slab index linearizes over the
    axes in order and the tallies psum over all of them — ICI innermost
    (hierarchical_psum ordering)."""
    s_local, v = inp.att_index.shape
    t = inp.tx_recid.shape[1]

    # 1. addHeader resets
    state = smc_jax.VoteState(
        has_voted=inp.has_voted, vote_count=inp.vote_count,
        last_submitted=inp.last_submitted, last_approved=inp.last_approved,
        is_elected=inp.is_elected, chunk_root=inp.chunk_root)
    state = smc_jax.add_header_reset_masked(
        state, inp.new_header, period, inp.new_chunk_root)

    # 2. submitVote batch — attempts flattened to LOCAL slab indices for
    # state routing, with GLOBAL shard ids for the committee sampling
    flat = lambda x: x.reshape((s_local * v,) + x.shape[2:])
    shard_ids = jnp.repeat(jnp.arange(s_local, dtype=jnp.int32), v)
    if axis is not None:
        device_ix = jnp.int32(0)
        for name, size in zip(axis, axis_sizes):
            device_ix = (device_ix * size
                         + jax.lax.axis_index(name).astype(jnp.int32))
        base = device_ix * s_local
    else:
        base = jnp.int32(0)
    attempts = smc_jax.VoteAttempts(
        shard=shard_ids, index=flat(inp.att_index),
        pool_index=flat(inp.att_pool_index), sender=flat(inp.att_sender),
        chunk_root=flat(inp.att_chunk_root),
        deposited=flat(inp.att_deposited), valid=flat(inp.att_valid))
    state, accepted = smc_jax.submit_votes_batch(
        state, pool_addr, attempts, period=period, blockhash=blockhash,
        sample_size=sample_size, committee_size=committee_size,
        quorum_size=quorum_size, sample_shard=shard_ids + base)

    # 3. committee BLS aggregation + verification (masked projective tree
    # sum, then one shared-accumulator Miller product per local shard)
    agg_ok = bn.bls_aggregate_verify_committee_batch(
        inp.hx, inp.hy, inp.sigx, inp.sigy, inp.sig_mask,
        inp.pkx, inp.pky, inp.pk_mask, inp.agg_valid)

    # 4. collation replay (batched recovery + ordered transitions)
    tflat = lambda x: x.reshape((s_local * t,) + x.shape[2:])
    qx, qy, rec_ok = secp256k1_jax.ecrecover_batch(
        tflat(inp.tx_e), tflat(inp.tx_r), tflat(inp.tx_s),
        tflat(inp.tx_recid), tflat(inp.tx_valid))
    senders = replay_jax.pubkeys_to_addresses(qx, qy).reshape(s_local, t, 20)
    sender_ok = rec_ok.reshape(s_local, t)
    nonces, balances, tx_status, _ = jax.vmap(replay_jax._shard_replay)(
        inp.addrs, inp.nonces, inp.balances, inp.coinbase_ix, senders,
        sender_ok, inp.tx_nonce, inp.tx_gas_limit, inp.tx_intrinsic,
        inp.tx_price, inp.tx_value, inp.tx_to, inp.tx_valid)
    roots = replay_jax._state_root(inp.addrs, nonces, balances)

    # 5. period totals over the mesh
    total_votes = jnp.sum(accepted.astype(jnp.int32))
    total_elected = jnp.sum(state.is_elected.astype(jnp.int32))
    total_txs = jnp.sum(tx_status.astype(jnp.int32))
    if axis is not None:
        for name in reversed(axis):  # ICI first, then DCN (§5.8)
            total_votes = jax.lax.psum(total_votes, axis_name=name)
            total_elected = jax.lax.psum(total_elected, axis_name=name)
            total_txs = jax.lax.psum(total_txs, axis_name=name)

    return StressOutputs(
        accepted=accepted.reshape(s_local, v), vote_count=state.vote_count,
        is_elected=state.is_elected, agg_ok=agg_ok, tx_status=tx_status,
        roots=roots, total_votes=total_votes, total_elected=total_elected,
        total_txs=total_txs)


class StressPipeline:
    """Compiled config-5 step, single-device or mesh-sharded.

    Committee-sampling parity across layouts: the keccak sampling must see
    GLOBAL shard ids while state routing uses LOCAL slab indices under
    shard_map — `_step` derives the global ids from `lax.axis_index`.
    """

    def __init__(self, config: Config = DEFAULT_CONFIG,
                 mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh
        c, q = config.committee_size, config.quorum_size

        def run_fn(inp, pool_addr, blockhash, period, sample_size, axis,
                   axis_sizes=()):
            return _step(inp, pool_addr, blockhash, period, sample_size,
                         c, q, axis, axis_sizes)

        if mesh is None:
            self._fn = jax.jit(
                lambda inp, pool, bh, per, ss: run_fn(inp, pool, bh, per,
                                                      ss, None))
        else:
            # any mesh rank: 1-D ("shard",) and 2-D ("dcn", "ici") alike —
            # the shard axis splits over ALL mesh axes, tallies reduce
            # hierarchically
            axes = tuple(mesh.axis_names)
            sizes = tuple(mesh.shape[name] for name in axes)
            n_fields = len(StressInputs._fields)
            self._fn = jax.jit(shard_map(
                lambda inp, pool, bh, per, ss: run_fn(
                    inp, pool, bh, per, ss, axes, sizes),
                mesh=mesh,
                in_specs=(StressInputs(*([PS(axes)] * n_fields)),
                          PS(), PS(), PS(), PS()),
                out_specs=StressOutputs(
                    *([PS(axes)] * 6 + [PS()] * 3)),
            ))

    def run(self, inputs: StressInputs, pool_addr, blockhash, period,
            sample_size) -> StressOutputs:
        n = int(inputs.has_voted.shape[0])
        padded = n
        if self.mesh is not None:
            n_dev = self.mesh.devices.size
            padded = -(-n // n_dev) * n_dev
            if padded != n:
                pad = padded - n

                def pad_rows(a):
                    widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
                    return jnp.pad(a, widths)

                inputs = StressInputs(*(pad_rows(a) for a in inputs))
            sharding = shard_axis_sharding(self.mesh)
            inputs = StressInputs(
                *(jax.device_put(a, sharding) for a in inputs))
        out = self._fn(inputs, jnp.asarray(pool_addr),
                       jnp.asarray(blockhash), jnp.int32(period),
                       jnp.int32(sample_size))
        if padded != n:
            out = StressOutputs(
                *(a[:n] for a in out[:6]), *out[6:])
        return out


# == distinct-per-shard workload builder ===================================


def build_stress_inputs(n_shards: int, *, votes_per_shard: int = 3,
                        txs_per_shard: int = 2, committee_size: int = 135,
                        period: int = 1, seed: int = 7):
    """Distinct per-shard data for the stress step (host-side, scalar
    crypto): a notary pool, per-shard sampled vote attempts that the
    committee check will accept, per-shard aggregate BLS votes on the
    shard's own digest, and per-shard signed transfer transactions.

    Returns (inputs, pool_addr, blockhash, sample_size, expected) where
    `expected` carries host-computed acceptance data for assertions."""
    from gethsharding_tpu.core import state_processor as sp
    from gethsharding_tpu.core.types import Transaction
    from gethsharding_tpu.crypto import bn256 as bls
    from gethsharding_tpu.crypto import secp256k1
    from gethsharding_tpu.crypto.keccak import keccak256
    from gethsharding_tpu.smc.state_machine import vote_digest
    from gethsharding_tpu.utils.hexbytes import Address20, Hash32

    rng = np.random.default_rng(seed)
    pool_size = committee_size
    pool = [Address20(bytes(rng.integers(1, 255, 20, dtype=np.uint8)))
            for _ in range(pool_size)]
    pool_addr = np.stack([np.frombuffer(bytes(a), np.uint8) for a in pool])
    blockhash = bytes(rng.integers(0, 255, 32, dtype=np.uint8))
    sample_size = pool_size

    def sampled_slot(pool_index: int, shard: int) -> int:
        pre = (blockhash + pool_index.to_bytes(32, "big")
               + shard.to_bytes(32, "big"))
        return int.from_bytes(keccak256(pre), "big") % sample_size

    s = n_shards
    v = votes_per_shard
    t = txs_per_shard
    z = np.zeros
    roots = rng.integers(0, 255, (s, 32), dtype=np.uint8)

    att_index = z((s, v), np.int32)
    att_pool_index = z((s, v), np.int32)
    att_sender = z((s, v, 20), np.uint8)
    att_root = np.repeat(roots[:, None, :], v, axis=1)
    att_deposited = np.ones((s, v), bool)
    att_valid = np.ones((s, v), bool)
    for shard in range(s):
        for j in range(v):
            # attempt j claims pool slot j; its sender must be the member
            # the committee sampling selects for (j, shard)
            att_index[shard, j] = j
            att_pool_index[shard, j] = j
            att_sender[shard, j] = pool_addr[sampled_slot(j, shard)]

    # distinct committee BLS votes per shard, aggregated ON DEVICE (small
    # committee for host build speed; the pairing cost per shard is
    # committee-size-invariant and the tree cost is measured by the
    # committee width knob)
    keys = [bls.bls_keygen(bytes([seed % 256, i])) for i in range(2)]
    h_pts, sig_rows, pk_rows = [], [], []
    for shard in range(s):
        digest = vote_digest(shard, period, Hash32(bytes(roots[shard])))
        h_pts.append(bls.hash_to_g1(digest))
        sig_rows.append([bls.bls_sign(digest, sk) for sk, _ in keys])
        pk_rows.append([pk for _, pk in keys])
    hx, hy, hok = bn.g1_to_limbs(h_pts)
    sigx, sigy, sig_mask = bn.g1_committee_to_limbs(sig_rows, len(keys))
    pkx, pky, pk_mask = bn.g2_committee_to_limbs(pk_rows, len(keys))

    # distinct replay data per shard: one funded sender pays a recipient
    priv = [(int(rng.integers(1, 2 ** 31)) * 2663 + shard) % secp256k1.N or 1
            for shard in range(s)]
    shard_txs, genesis, coinbases = [], [], []
    coinbase = Address20(b"\xc0" * 20)
    for shard in range(s):
        sender_addr = secp256k1.priv_to_address(priv[shard])
        recipient = Address20(bytes(rng.integers(1, 255, 20, dtype=np.uint8)))
        txs = [sp.sign_transaction(
            Transaction(nonce=k, gas_price=1, gas_limit=30000, to=recipient,
                        value=1000 + shard, payload=bytes([shard % 256])),
            priv[shard]) for k in range(t)]
        shard_txs.append(txs)
        genesis.append({sender_addr: sp.AccountState(balance=10 ** 9)})
        coinbases.append(coinbase)
    rep = replay_jax.build_replay_inputs(shard_txs, genesis, coinbases,
                                         pad_txs=t)

    inputs = StressInputs(
        has_voted=jnp.zeros((s, committee_size), bool),
        vote_count=jnp.zeros(s, jnp.int32),
        last_submitted=jnp.zeros(s, jnp.int32),
        last_approved=jnp.zeros(s, jnp.int32),
        is_elected=jnp.zeros(s, bool),
        chunk_root=jnp.zeros((s, 32), jnp.uint8),
        new_header=jnp.ones(s, bool),
        new_chunk_root=jnp.asarray(roots),
        att_index=jnp.asarray(att_index),
        att_pool_index=jnp.asarray(att_pool_index),
        att_sender=jnp.asarray(att_sender),
        att_chunk_root=jnp.asarray(att_root),
        att_deposited=jnp.asarray(att_deposited),
        att_valid=jnp.asarray(att_valid),
        hx=jnp.asarray(hx), hy=jnp.asarray(hy),
        sigx=jnp.asarray(sigx), sigy=jnp.asarray(sigy),
        sig_mask=jnp.asarray(sig_mask),
        pkx=jnp.asarray(pkx), pky=jnp.asarray(pky),
        pk_mask=jnp.asarray(pk_mask),
        agg_valid=jnp.asarray(hok),
        addrs=rep.addrs, nonces=rep.nonces, balances=rep.balances,
        coinbase_ix=rep.coinbase_ix,
        tx_e=rep.tx_e, tx_r=rep.tx_r, tx_s=rep.tx_s,
        tx_recid=rep.tx_recid, tx_nonce=rep.tx_nonce,
        tx_gas_limit=rep.tx_gas_limit, tx_intrinsic=rep.tx_intrinsic,
        tx_price=rep.tx_price, tx_value=rep.tx_value, tx_to=rep.tx_to,
        tx_valid=rep.tx_valid,
    )
    return inputs, pool_addr, np.frombuffer(blockhash, np.uint8), \
        sample_size, {"shard_txs": shard_txs}
