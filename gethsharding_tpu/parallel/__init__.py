"""parallel — multi-chip scaling: Mesh construction + shard_map pipelines.

The reference scales by running one OS process per shard actor and letting
the SMC serialize everything (SURVEY.md §2.2: shard-level data parallelism
is the only axis). Here the same workload — per-shard vote verification,
tallying, and quorum — is laid out over a `jax.sharding.Mesh` so that the
per-shard work rides the VPU/MXU in lockstep and the cross-shard reductions
ride ICI collectives (`psum` under `shard_map`), per the north star
(SURVEY.md §5.8).

Tests exercise these paths on a virtual 8-device CPU mesh
(`tests/conftest.py` sets xla_force_host_platform_device_count), matching
how the driver dry-runs `__graft_entry__.dryrun_multichip`.
"""

from gethsharding_tpu.parallel.mesh import make_mesh, shard_axis_sharding

__all__ = ["make_mesh", "shard_axis_sharding"]
