"""Light client: SMC-anchored on-demand chunk retrieval with proofs.

The `les/` + `light/` role (ODR — on-demand retrieval, `les/odr.go`,
`light/odr.go`) mapped to the sharding domain: a light client holds NO
shard data. Its root of trust is the SMC (exactly as the reference's
light client trusts the header chain): it reads the canonical
(shard, period) chunk root from the contract, then samples body bytes
from peers over shardp2p — each response carries a merkle proof that is
verified locally against the anchored root (`trie/proof.go
VerifyProof`), so a lying peer cannot forge content and a peer that
cannot prove availability of sampled indices fails the check.

This is also the data-availability-sampling intent behind the 32-byte
chunk design (SURVEY.md §5.7): `availability_check` samples K
pseudorandom indices; all proofs verifying == the body is available at
those points without downloading it.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

from gethsharding_tpu import metrics
from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.core.derive_sha import verify_chunk
from gethsharding_tpu.core.trie import EMPTY_ROOT
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.p2p.messages import ChunkProofRequest, ChunkProofResponse
from gethsharding_tpu.p2p.service import P2PServer
from gethsharding_tpu.utils.hexbytes import Hash32


class LightClient(Service):
    """Proof-verified byte sampling against SMC-anchored chunk roots."""

    name = "light"
    supervisable = True

    def __init__(self, client: SMCClient, p2p: P2PServer, das=None):
        super().__init__()
        self.client = client
        self.p2p = p2p
        # DAS face (gethsharding_tpu/das): when a DASService is
        # attached, `das_check` samples whole erasure-extended chunks
        # against the proposer's commitment — the chunk-granular,
        # parity-aware successor of the per-byte `availability_check`
        self.das = das
        self.samples_verified = 0
        self.proofs_rejected = 0
        self._sub = None
        self.m_sample_latency = metrics.timer("light/sample_latency")

    def on_start(self) -> None:
        self._sub = self.p2p.subscribe(ChunkProofResponse)

    def on_stop(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()

    # -- anchoring ---------------------------------------------------------

    def canonical_chunk_root(self, shard_id: int,
                             period: int) -> Optional[Hash32]:
        """The root of trust: the SMC's collation record for the pair."""
        record = self.client.collation_record(shard_id, period)
        return None if record is None else record.chunk_root

    # -- sampling ----------------------------------------------------------

    def sample(self, shard_id: int, period: int, indices: Sequence[int],
               timeout: float = 5.0) -> Dict[int, Optional[int]]:
        """Retrieve + verify body bytes at `indices` from peers.

        Broadcasts one ChunkProofRequest per index, collects responses,
        and verifies every proof against the SMC-anchored chunk root.
        Returns an entry per RESOLVED index: the proven byte value, or
        None for a PROVEN absence (index outside the body). Missing
        entries = no peer answered in time. A response with an invalid
        proof is counted, logged and discarded — never returned."""
        root = self.canonical_chunk_root(shard_id, period)
        if root is None:
            raise ValueError(
                f"no canonical collation for shard {shard_id} "
                f"period {period}")
        if self._sub is None:
            raise RuntimeError("light client is not started")
        out, _ = self._sample(root, shard_id, period, indices, timeout)
        return out

    def _sample(self, root: Hash32, shard_id: int, period: int,
                indices: Sequence[int], timeout: float):
        """Request + verify against an already-resolved root; returns
        (resolved dict, last verified responder's body-length claim)."""
        pending = set(indices)
        for index in sorted(pending):
            self.p2p.broadcast(ChunkProofRequest(
                chunk_root=root, shard_id=shard_id, period=period,
                index=index))
        out: Dict[int, Optional[int]] = {}
        len_claim: Optional[int] = None
        deadline = time.monotonic() + timeout
        with self.m_sample_latency.time():
            while pending and time.monotonic() < deadline:
                msg = self._sub.try_get()
                if msg is None:
                    if self.wait(0.01):
                        break
                    continue
                response: ChunkProofResponse = msg.data
                if (bytes(response.chunk_root) != bytes(root)
                        or response.index not in pending):
                    continue
                try:
                    value = verify_chunk(bytes(root), response.index,
                                         response.proof)
                except ValueError as exc:
                    self.proofs_rejected += 1
                    self.record_error(
                        f"peer {msg.peer.peer_id} sent an invalid proof "
                        f"for index {response.index}: {exc}")
                    continue
                out[response.index] = value
                len_claim = response.body_len
                pending.discard(response.index)
                self.samples_verified += 1
        return out, len_claim

    def proven_length(self, shard_id: int, period: int,
                      timeout: float = 5.0) -> Optional[int]:
        """PROVE the body length: take a peer's length claim L, then
        verify a presence proof at L-1 and an absence proof at L. A
        lying claim fails one of the two. None = could not prove
        (no peers, or dishonest claims)."""
        root = self.canonical_chunk_root(shard_id, period)
        if root is None:
            return None
        return self._proven_length(root, shard_id, period, timeout)

    def _proven_length(self, root: Hash32, shard_id: int, period: int,
                       timeout: float) -> Optional[int]:
        if bytes(root) == EMPTY_ROOT:
            return 0  # the empty body's DeriveSha root
        first, claim = self._sample(root, shard_id, period, [0], timeout)
        if first.get(0) is None:  # unanswered, or 'absent' for index 0
            return None
        if not claim or claim <= 0:
            return None
        boundary, _ = self._sample(root, shard_id, period,
                                   [claim - 1, claim], timeout)
        if (boundary.get(claim - 1) is not None and claim in boundary
                and boundary[claim] is None):
            return claim
        return None

    def availability_check(self, shard_id: int, period: int, k: int = 16,
                           timeout: float = 5.0,
                           seed: Optional[bytes] = None) -> bool:
        """Data-availability sampling (the intent of the 32-byte chunk
        design): prove the body length, then sample K pseudorandom
        in-range indices. `seed` defaults to a FRESH random value — a
        withholding peer must not be able to precompute which indices
        every checker will ask for (DAS soundness); pass an explicit
        seed only for auditable replay of a specific check. True iff
        the length is proven and EVERY sampled index verifies."""
        import secrets

        root = self.canonical_chunk_root(shard_id, period)
        if root is None:
            return False
        length = self._proven_length(root, shard_id, period, timeout)
        if length is None:
            return False
        if length == 0:
            return True  # empty body: trivially available
        if seed is None:
            seed = secrets.token_bytes(32)
        digest = keccak256(bytes(root) + seed)
        indices, counter = set(), 0
        while len(indices) < min(k, length) and counter < 8 * k:
            digest = keccak256(digest + counter.to_bytes(4, "big"))
            indices.add(int.from_bytes(digest[:4], "big") % length)
            counter += 1
        got, _ = self._sample(root, shard_id, period, sorted(indices),
                              timeout)
        return all(got.get(i) is not None for i in indices)

    # -- erasure-coded DAS (gethsharding_tpu/das) --------------------------

    def das_check(self, shard_id: int, period: int,
                  k: Optional[int] = None,
                  seed: Optional[bytes] = None) -> bool:
        """Chunk-granular data-availability sampling against the
        proposer's erasure-extension commitment.

        Fetches the signed commitment (validated against the
        SMC-anchored record: chunk_root binding + proposer signature),
        draws k indices from a FRESH random seed (a light client's
        selection must not be precomputable — `das/sampler.py`
        documents the soundness split), pulls chunk+proof samples over
        shardp2p and verifies them with the scalar reference (a light
        client has no device). True iff every sampled chunk proves.

        Under ``--da-proofs=poly`` the k samples arrive under ONE
        constant-size polynomial multiproof instead of k sibling paths
        (das/pcs.py) — `fetch_multiproof` verifies it against the
        signed poly commitment before admission, so delivery IS the
        verdict and the wire cost per check drops from k paths to one
        64-byte point."""
        if self.das is None:
            raise RuntimeError("light client has no DAS service attached")
        import secrets

        from gethsharding_tpu.das.proofs import verify_sample
        from gethsharding_tpu.das.sampler import sample_indices

        record = self.client.collation_record(shard_id, period)
        if record is None:
            return False
        with self.m_sample_latency.time():
            commitment = self.das.fetch_commitment(
                shard_id, period, record.chunk_root, record.proposer)
            if commitment is None:
                return False
            if seed is None:
                seed = secrets.token_bytes(32)
            k = self.das.samples if k is None else k
            indices = sample_indices(
                keccak256(seed + bytes(commitment.das_root)), k,
                commitment.n)
            if getattr(self.das, "proof_mode", "merkle") == "poly":
                got = self.das.fetch_multiproof(commitment, indices)
                verdicts = [got is not None] * len(indices)
            else:
                fetched = self.das.fetch_samples(commitment, indices)
                verdicts = []
                for index in indices:
                    chunk, proof = fetched.get(index, (b"", ()))
                    verdicts.append(verify_sample(commitment.das_root,
                                                  index, chunk, proof))
            self.samples_verified += sum(verdicts)
            self.proofs_rejected += len(verdicts) - sum(verdicts)
            self.das.note_verdicts(verdicts)
        return bool(verdicts) and all(verdicts)
