"""Proposer actor: packages transactions into collations, registers headers.

Parity: `sharding/proposer/service.go` (proposeCollations :72,
createCollation :93) and `proposer.go` (createCollation pure :55, AddHeader
:20, checkHeaderAdded :98): subscribe to the txpool feed, build a collation
per tx batch (serialize -> chunkRoot -> sign with the node account), save
it to the shardDB, and submit `addHeader` to the SMC when the period has no
submission yet.
"""

from __future__ import annotations

from typing import List, Optional

from gethsharding_tpu import tracing
from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.core.shard import Shard
from gethsharding_tpu.core.types import (
    Collation,
    CollationHeader,
    Transaction,
    serialize_txs_to_blob,
)
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.actors.txpool import TXPool
from gethsharding_tpu.params import Config, DEFAULT_CONFIG


def create_collation(client: SMCClient, shard_id: int, period: int,
                     txs: List[Transaction]) -> Collation:
    """Pure collation construction (parity: proposer.go:55 createCollation):
    validate shard/period, serialize txs, merklize the chunk root, sign the
    header hash with the node account."""
    if not (0 <= shard_id < client.shard_count()):
        raise ValueError(f"shard id {shard_id} out of range")
    body = serialize_txs_to_blob(txs)
    header = CollationHeader(
        shard_id=shard_id,
        period=period,
        proposer_address=client.account(),
    )
    collation = Collation(header=header, body=body, transactions=list(txs))
    collation.calculate_chunk_root()
    signature = client.sign(bytes(header.hash()))
    header.add_sig(signature)
    return collation


def check_header_added(client: SMCClient, shard_id: int, period: int) -> bool:
    """True if this period still has no submitted header (proposer.go:98)."""
    return client.last_submitted_collation(shard_id) < period


class Proposer(Service):
    name = "proposer"
    supervisable = True

    def __init__(self, client: SMCClient, txpool: TXPool, shard: Shard,
                 config: Config = DEFAULT_CONFIG,
                 poll_interval: float = 0.05,
                 das=None):
        super().__init__()
        self.client = client
        self.txpool = txpool
        self.shard = shard
        self.config = config
        self.poll_interval = poll_interval
        # data-availability sampling (gethsharding_tpu/das): when a
        # DASService is attached, every created collation is erasure-
        # extended and its parity chunks + signed commitment published,
        # so sampled notaries can vote without fetching the body
        self.das = das
        self.collations_proposed = 0
        self.das_published = 0
        self._sub = None

    def on_start(self) -> None:
        self._sub = self.txpool.transactions_feed.subscribe()
        self.spawn(self._propose_collations)

    def on_stop(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()

    # -- the loop (parity: proposeCollations service.go:72-90) -------------

    def _propose_collations(self) -> None:
        while not self.stopped():
            tx = self._sub.try_get()
            if tx is None:
                if self.wait(self.poll_interval):
                    return
                continue
            try:
                # a feed event wakes the proposer; the collation packs the
                # pool's full price-ordered pending selection (the feed tx
                # was admitted to the pool before publication), which the
                # pool then drops as included — core/tx_pool Pending +
                # mined-drop semantics
                batch = self.txpool.take_pending()
                self.create_and_submit(batch if batch else [tx])
            except Exception as exc:
                self.record_error(f"create collation failed: {exc}")

    def create_and_submit(self, txs: List[Transaction]) -> Optional[Collation]:
        # the collation lifecycle trace root: create (serialize ->
        # chunk root -> sign -> persist) then addHeader on-chain
        with tracing.span("proposer/propose", txs=len(txs)):
            # the addHeader tx executes in the pending block; derive the
            # period from it so headers never straddle a period boundary
            period = ((self.client.block_number + 1)
                      // self.config.period_length)
            with tracing.span("proposer/create"):
                collation = create_collation(self.client,
                                             self.shard.shard_id,
                                             period, txs)
                # persist locally regardless; only one header per
                # (shard, period) can go on-chain (service.go:93)
                self.shard.save_collation(collation)
            if self.das is not None:
                # extend + publish BEFORE addHeader: by the time the
                # header is on-chain, sampled notaries can already pull
                # the commitment and chunks. A publish failure (e.g. an
                # injected das.parity_publish fault) must not lose the
                # collation itself — full-fetch peers still serve it.
                try:
                    self.das.publish(collation.header.shard_id, period,
                                     collation.header.chunk_root,
                                     collation.body)
                    self.das_published += 1
                except Exception as exc:  # noqa: BLE001 - chaos seam
                    self.record_error(f"das publish failed: {exc}")
            self.collations_proposed += 1
            self.log.info(
                "Saved collation with header hash %s",
                collation.header.hash().hex_str,
            )
            if check_header_added(self.client, self.shard.shard_id, period):
                self.add_header(collation)
            return collation

    def add_header(self, collation: Collation) -> None:
        """Submit the header to the SMC (proposer.go:20 AddHeader)."""
        header = collation.header
        with tracing.span("proposer/add_header", shard=header.shard_id,
                          period=header.period):
            self.client.add_header(
                header.shard_id, header.period, header.chunk_root,
                header.proposer_signature,
            )
        self.log.info("Added header to SMC: shard %s period %s",
                      header.shard_id, header.period)
