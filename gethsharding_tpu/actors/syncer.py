"""Syncer: serves collation bodies over shardp2p.

Parity: `sharding/syncer/service.go` (handleCollationBodyRequests :73) and
`handlers.go` (RespondCollationBody :19, RequestCollationBody :49):
subscribe to CollationBodyRequest messages, reconstruct + sign the header
from the request tuple, fetch the collation from the shardDB, and reply to
the requesting peer with a CollationBodyResponse. Where the reference's
final `p2p.Send` is a no-op stub, this syncer actually delivers — and on
the receiving side stores the body + availability bit.
"""

from __future__ import annotations

from typing import Optional

from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.core.shard import Shard, ShardError
from gethsharding_tpu.core.types import CollationHeader
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.p2p.messages import (
    ChunkProofRequest, ChunkProofResponse, CollationBodyRequest,
    CollationBodyResponse)
from gethsharding_tpu.p2p.service import Message, P2PServer


def request_collation_body(caller, shard_id: int,
                           period: int) -> Optional[CollationBodyRequest]:
    """Build a body request from the SMC record (handlers.go:49)."""
    record = caller.collation_record(shard_id, period)
    if record is None or bytes(record.chunk_root) == b"\x00" * 32:
        return None
    return CollationBodyRequest(
        chunk_root=record.chunk_root,
        shard_id=shard_id,
        period=period,
        proposer=record.proposer,
    )


class Syncer(Service):
    name = "syncer"
    supervisable = True

    # chunk proofs are served from a Python-built per-body trie; an
    # UNTRUSTED request stream cycling distinct large roots could pin
    # the proof thread rebuilding O(body) tries (cache thrash DoS), so
    # proof serving is capped — light clients needing bigger bodies use
    # the full CollationBodyRequest path instead
    PROOF_BODY_CAP = 1 << 16

    def __init__(self, client: SMCClient, shard: Shard, p2p: P2PServer,
                 poll_interval: float = 0.05):
        super().__init__()
        self.client = client
        self.shard = shard
        self.p2p = p2p
        self.poll_interval = poll_interval
        self.responses_sent = 0
        self.bodies_stored = 0
        self.proofs_served = 0
        self._req_sub = None
        self._resp_sub = None
        self._proof_sub = None

    def on_start(self) -> None:
        self._req_sub = self.p2p.subscribe(CollationBodyRequest)
        self._resp_sub = self.p2p.subscribe(CollationBodyResponse)
        self._proof_sub = self.p2p.subscribe(ChunkProofRequest)
        self.spawn(self._handle_requests, name="syncer-requests")
        self.spawn(self._handle_responses, name="syncer-responses")
        self.spawn(self._handle_proof_requests, name="syncer-proofs")

    def on_stop(self) -> None:
        for sub in (self._req_sub, self._resp_sub, self._proof_sub):
            if sub is not None:
                sub.unsubscribe()

    # -- request side ------------------------------------------------------

    def _handle_requests(self) -> None:
        while not self.stopped():
            msg = self._req_sub.try_get()
            if msg is None:
                if self.wait(self.poll_interval):
                    return
                continue
            try:
                self.respond_collation_body(msg)
            except Exception as exc:
                self.record_error(f"could not construct response: {exc}")

    def respond_collation_body(self, msg: Message) -> None:
        """RespondCollationBody (handlers.go:19)."""
        request: CollationBodyRequest = msg.data
        header = CollationHeader(
            shard_id=request.shard_id,
            chunk_root=request.chunk_root,
            period=request.period,
            proposer_address=request.proposer,
        )
        signature = self.client.sign(bytes(header.hash()))
        header.add_sig(signature)
        try:
            collation = self.shard.collation_by_header_hash(header.hash())
        except ShardError:
            # try by chunk root alone: votes reconstruct unsigned headers
            try:
                body = self.shard.body_by_chunk_root(request.chunk_root)
            except ShardError:
                return  # we don't have it either
            response = CollationBodyResponse(
                header_hash=header.hash(), body=body
            )
            self.p2p.send(response, msg.peer)
            self.responses_sent += 1
            return
        response = CollationBodyResponse(
            header_hash=collation.header.hash(), body=collation.body
        )
        self.p2p.send(response, msg.peer)
        self.responses_sent += 1

    # -- on-demand chunk proofs (the les/light ODR serving side) -----------

    def _handle_proof_requests(self) -> None:
        while not self.stopped():
            msg = self._proof_sub.try_get()
            if msg is None:
                if self.wait(self.poll_interval):
                    return
                continue
            try:
                self.respond_chunk_proof(msg)
            except Exception as exc:
                self.record_error(f"could not construct proof: {exc}")

    def respond_chunk_proof(self, msg: Message) -> None:
        """Serve a merkle proof for one body byte under its chunk root —
        what an les/light server's ODR handler does for trie nodes
        (`les/odr_requests.go` role). The per-body proof trie is
        LRU-cached in core/derive_sha, so a light client sampling many
        indices of one root builds it once."""
        from gethsharding_tpu.core.derive_sha import chunk_proof

        request: ChunkProofRequest = msg.data
        try:
            body = self.shard.body_by_chunk_root(request.chunk_root)
        except ShardError:
            return  # we don't have the body; another peer may
        if request.index < 0 or len(body) > self.PROOF_BODY_CAP:
            return
        self.p2p.send(ChunkProofResponse(
            chunk_root=request.chunk_root, index=request.index,
            proof=tuple(chunk_proof(body, request.index)),
            body_len=len(body)), msg.peer)
        self.proofs_served += 1

    # -- response side -----------------------------------------------------

    def _handle_responses(self) -> None:
        while not self.stopped():
            msg = self._resp_sub.try_get()
            if msg is None:
                if self.wait(self.poll_interval):
                    return
                continue
            response: CollationBodyResponse = msg.data
            try:
                self.shard.save_body(response.body)
                self.bodies_stored += 1
            except ShardError as exc:
                self.record_error(f"could not store synced body: {exc}")
