"""Service base: lifecycle + background loops + error funnel.

Parity with the `sharding.Service` contract (`sharding/interfaces.go:30`)
and `utils.HandleServiceErrors` (`sharding/utils/service.go:11`): services
start loops on threads, report failures to an error list (logged, never
fatal), and stop via a shared shutdown event.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional


class Service:
    """Base lifecycle: start() spawns registered loops, stop() joins them."""

    name = "service"
    # restart-as-fresh-instance eligibility (node/service.go:78-83: "New
    # instance of the service will be constructed" on restart). Leaf actor
    # services opt in; infrastructure services other services hold direct
    # references to (DB, client, txpool) stay False — replacing them would
    # leave dependents pointing at the dead instance.
    supervisable = False

    def __init__(self):
        self._threads: List[threading.Thread] = []
        self._shutdown = threading.Event()
        self.errors: List[str] = []
        self.log = logging.getLogger(f"sharding.{self.name}")
        self._started = False
        self._crashed = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._shutdown.clear()
        self.log.info("Starting %s service", self.name)
        self.on_start()

    def stop(self) -> None:
        if not self._started:
            return
        self.log.info("Stopping %s service", self.name)
        self._shutdown.set()
        self.on_stop()
        for thread in self._threads:
            thread.join(timeout=5.0)
        self._threads.clear()
        self._started = False

    def on_start(self) -> None:  # override
        pass

    def on_stop(self) -> None:  # override
        pass

    @property
    def running(self) -> bool:
        return self._started

    # -- helpers -----------------------------------------------------------

    def spawn(self, target: Callable[[], None], name: Optional[str] = None) -> None:
        thread = threading.Thread(
            target=self._guard(target), name=name or f"{self.name}-loop",
            daemon=True,
        )
        self._threads.append(thread)
        thread.start()

    def _guard(self, target: Callable[[], None]) -> Callable[[], None]:
        def runner():
            try:
                target()
            except Exception as exc:  # funnel, never crash the node
                self.record_error(f"{self.name} loop crashed: {exc!r}")
                self._crashed = True

        return runner

    @property
    def crashed(self) -> bool:
        """True when a background loop died on an exception (the signal a
        supervisor restarts on); cleared only by a fresh instance."""
        return self._crashed

    # -- callback-driven failure detection ---------------------------------
    # Services without their own loops (head-subscription actors like the
    # notary) funnel per-callback errors; a run of consecutive failures
    # with no success in between marks the service crashed so the
    # supervisor treats it like a dead loop.

    FAILURE_THRESHOLD = 5

    def record_failure(self, message: str) -> None:
        self.record_error(message)
        self._consecutive_failures = getattr(
            self, "_consecutive_failures", 0) + 1
        if self._consecutive_failures >= self.FAILURE_THRESHOLD:
            self._crashed = True

    def record_success(self) -> None:
        self._consecutive_failures = 0

    def record_error(self, message: str) -> None:
        self.errors.append(message)
        self.log.error(message)

    def stopped(self) -> bool:
        return self._shutdown.is_set()

    def wait(self, timeout: float) -> bool:
        """Sleep that wakes early on shutdown; True if shutting down."""
        return self._shutdown.wait(timeout)
