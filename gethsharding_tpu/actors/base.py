"""Service base: lifecycle + background loops + error funnel.

Parity with the `sharding.Service` contract (`sharding/interfaces.go:30`)
and `utils.HandleServiceErrors` (`sharding/utils/service.go:11`): services
start loops on threads, report failures to an error list (logged, never
fatal), and stop via a shared shutdown event.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional


class Service:
    """Base lifecycle: start() spawns registered loops, stop() joins them."""

    name = "service"

    def __init__(self):
        self._threads: List[threading.Thread] = []
        self._shutdown = threading.Event()
        self.errors: List[str] = []
        self.log = logging.getLogger(f"sharding.{self.name}")
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._shutdown.clear()
        self.log.info("Starting %s service", self.name)
        self.on_start()

    def stop(self) -> None:
        if not self._started:
            return
        self.log.info("Stopping %s service", self.name)
        self._shutdown.set()
        self.on_stop()
        for thread in self._threads:
            thread.join(timeout=5.0)
        self._threads.clear()
        self._started = False

    def on_start(self) -> None:  # override
        pass

    def on_stop(self) -> None:  # override
        pass

    @property
    def running(self) -> bool:
        return self._started

    # -- helpers -----------------------------------------------------------

    def spawn(self, target: Callable[[], None], name: Optional[str] = None) -> None:
        thread = threading.Thread(
            target=self._guard(target), name=name or f"{self.name}-loop",
            daemon=True,
        )
        self._threads.append(thread)
        thread.start()

    def _guard(self, target: Callable[[], None]) -> Callable[[], None]:
        def runner():
            try:
                target()
            except Exception as exc:  # funnel, never crash the node
                self.record_error(f"{self.name} loop crashed: {exc!r}")

        return runner

    def record_error(self, message: str) -> None:
        self.errors.append(message)
        self.log.error(message)

    def stopped(self) -> bool:
        return self._shutdown.is_set()

    def wait(self, timeout: float) -> bool:
        """Sleep that wakes early on shutdown; True if shutting down."""
        return self._shutdown.wait(timeout)
