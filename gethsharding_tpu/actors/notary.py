"""Notary actor: joins the pool, watches heads, votes on data availability.

Parity: `sharding/notary/service.go` (Start :31, notarizeCollations :44)
and `notary.go` (subscribeBlockHeaders :28, checkSMCForNotary :62,
joinNotaryPool :267, leaveNotaryPool :318, releaseNotary :365, submitVote
:413, verifyNotary :245, isLockUpOver :129). The vote path — which the
reference only exercises from tests — is wired into the head loop here:

  head -> in pool? -> per shard: sampled for committee? -> collation record
  exists for this period? -> chunk-root/availability check (requesting the
  body over shardp2p if missing) -> submitVote at our poolIndex -> on
  quorum, set the header canonical in the shardDB.

The `sig_backend` seam is where batched TPU verification plugs in: votes
for all shards in a period are verified as one batch (see
`gethsharding_tpu.ops` and BASELINE.md configs 2-3).
"""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional, Tuple

from gethsharding_tpu import metrics, tracing
from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.core.shard import Shard, ShardError
from gethsharding_tpu.core.types import CollationHeader
from gethsharding_tpu.serving.classes import CLASS_BULK_AUDIT, admission_class
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.p2p.messages import CollationBodyRequest
from gethsharding_tpu.p2p.service import P2PServer
from gethsharding_tpu.params import Config, DEFAULT_CONFIG
from gethsharding_tpu.resilience.errors import FetchAborted, TransientError
from gethsharding_tpu.resilience.policy import (POLL_MISS, RetryExecutor,
                                                RetryPolicy, poll_probe)
from gethsharding_tpu.sigbackend import SigBackend, get_backend
from gethsharding_tpu.smc.state_machine import SMCRevert, vote_digest


class _BodyUnavailable(TransientError):
    """A collation body did not arrive within one fetch attempt."""


class Notary(Service):
    name = "notary"
    supervisable = True

    def __init__(self, client: SMCClient, shard: Shard,
                 p2p: Optional[P2PServer] = None,
                 config: Config = DEFAULT_CONFIG,
                 deposit_flag: bool = False,
                 all_shards: bool = True,
                 sig_backend: Optional[SigBackend] = None,
                 mirror=None,
                 journal=None,
                 das=None,
                 da_mode: str = "full"):
        super().__init__()
        self.client = client
        self.shard = shard
        self.p2p = p2p
        # data-availability sampling (--da-mode=sampled + a DASService):
        # the availability verdict comes from k sampled chunk proofs
        # verified in ONE batched das_verify_samples dispatch across all
        # candidate shards — the notary never fetches a collation body
        self.das = das
        self.da_mode = da_mode
        # positive sampled verdicts are cached per (shard, period): a
        # collation's chunks are immutable content, so once k samples
        # verified, re-entering the head loop (or the windback walk)
        # must NOT re-fetch k chunks — the acceptance bound is
        # k·chunk_size + proof overhead PER COLLATION. Negative
        # verdicts are never cached (late-arriving samples may still
        # flip them). Bounded by pruning below _DA_CACHE_MAX.
        self._da_verdicts: dict = {}
        # crash-safe vote journal (resilience/journal.VoteJournal): a
        # restarted notary recovers its submitted (shard, period) votes
        # and the audit high-water mark on on_start, so it neither
        # double-votes nor re-audits finished periods. None = process
        # memory only (the pre-resilience behavior).
        self.journal = journal
        # eth/downloader analog (mainchain/mirror.StateMirror): when set,
        # the per-head phase-1 scan reads records/watermarks/committee
        # context from ONE bulk snapshot pull instead of O(shards) client
        # round trips — the difference between 1 and ~300 RPC calls per
        # head for a remote (--endpoint) notary
        self.mirror = mirror
        self.config = config
        self.deposit_flag = deposit_flag
        # notaries watch every shard (the reference scans 0..shardCount)
        self.all_shards = all_shards
        self.sig_backend = sig_backend or get_backend("python")
        self.votes_submitted = 0
        self.canonical_set = 0
        self.signatures_rejected = 0
        self.audits_run = 0
        self.audit_mismatches = 0
        self.aggregate_sigs_verified = 0
        self._last_audited_period = 0
        self._unsubscribe = None
        # the two BASELINE metrics (SURVEY.md §7.8): aggregate notary
        # signature verifications/sec and collation validate latency
        self.m_sigs_verified = metrics.counter(
            "notary/aggregate_sig_verifications")
        self.m_validate_latency = metrics.timer("notary/validate_latency")
        self.m_audit_latency = metrics.timer("notary/period_audit_latency")
        self.m_votes = metrics.counter("notary/votes_submitted")
        self.m_audit_mismatch = metrics.counter("notary/audit_mismatches")
        self.m_windback_checks = metrics.counter("notary/windback_checks")
        # body-fetch retry seam (resilience/policy): each attempt
        # re-broadcasts the shardp2p request and polls briefly — a lost
        # request frame costs one backoff, not the whole availability
        # verdict
        self._body_retry = RetryExecutor(
            "collation_body",
            RetryPolicy(attempts=3, base_s=0.05, cap_s=0.2,
                        retryable=(_BodyUnavailable,)))

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        if self.journal is not None:
            # a journal AHEAD of the chain belongs to a previous chain
            # lifetime (wiped devnet, fresh simulated chain under an
            # old datadir): replaying it would mute the notary until
            # the new chain catches up to the stale watermark. An
            # unreachable chain keeps the journal — surviving exactly
            # that outage is what the journal is for.
            try:
                current = self.client.current_period()
            except Exception:  # noqa: BLE001 - chain down at boot
                current = None
            if current is not None \
                    and self.journal.invalidate_if_reset(current):
                self.log.warning(
                    "vote journal was ahead of the chain (period %d): "
                    "chain reset assumed, journal cleared", current)
            # recovery replay: a restart must not re-audit periods the
            # crashed instance already finished (the vote-side replay is
            # per (shard, period) in submit_vote). The journal records
            # the audited period itself (None = never audited);
            # `_last_audited_period = N` means "period N-1 audited",
            # hence the +1.
            high_water = self.journal.audit_high_water()
            if high_water is not None \
                    and high_water + 1 > self._last_audited_period:
                self._last_audited_period = high_water + 1
            recovered = sum(1 for _ in self.journal.votes())
            if recovered or high_water is not None:
                self.log.info(
                    "vote journal recovered: %d submitted votes, audit "
                    "high-water period %s", recovered, high_water)
        if self.deposit_flag:
            try:
                self.join_notary_pool()
            except Exception as exc:
                self.record_error(f"joining notary pool failed: {exc}")
        self._unsubscribe = self.client.subscribe_new_head(self._on_head)

    def on_stop(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()

    # -- pool membership (notary.go:267,318,365) ---------------------------

    def join_notary_pool(self) -> None:
        registry = self.client.notary_registry()
        if registry is not None and registry.deposited:
            self.log.info("Already joined notary pool")
            return
        self.client.register_notary()
        self.log.info("Joined notary pool: %s", self.client.account().hex_str)

    def leave_notary_pool(self) -> None:
        self.client.deregister_notary()

    def release_notary(self) -> None:
        registry = self.client.notary_registry()
        if registry is None or registry.deregistered_period == 0:
            raise RuntimeError("account has not deregistered")
        if not self.is_lockup_over(registry):
            raise RuntimeError("lockup period is not over")
        self.client.release_notary()

    def is_lockup_over(self, registry) -> bool:
        """isLockUpOver (notary.go:129)."""
        return (self.client.current_period()
                > registry.deregistered_period + self.config.notary_lockup_length)

    def is_account_in_notary_pool(self) -> bool:
        registry = self.client.notary_registry()
        return registry is not None and registry.deposited

    # -- the hot loop (notarizeCollations / checkSMCForNotary) -------------

    def _on_head(self, block) -> None:
        try:
            self.notarize_collations(head=block.number)
            self.record_success()
        except Exception as exc:
            # a run of consecutive head failures marks the service crashed
            # for the supervisor (callback actors have no loop to die)
            self.record_failure(
                f"notarize failed at head {block.number}: {exc}")

    def _head_snapshot(self, head: Optional[int]):
        """The mirror snapshot for this head, refreshed if the mirror has
        not caught up yet (ONE bulk pull); None = read via the client."""
        if self.mirror is None:
            return None
        if head is None:
            head = self.client.block_number
        try:
            snap = self.mirror.snapshot()
            if snap is None or (snap["block_number"] or 0) < head:
                snap = self.mirror.refresh()
        except Exception:
            return None  # degraded mirror: fall back to direct reads
        if snap is None or (snap["block_number"] or 0) < head:
            return None
        return snap

    def notarize_collations(self, head: Optional[int] = None) -> None:
        # the per-head trace root: fetch -> recover -> vote phases below
        # parent under it, and (with --serving) the recovery dispatch's
        # serving/... request spans stitch to the recover phase
        with tracing.span("notary/notarize"):
            self._notarize_collations(head)

    def _notarize_collations(self, head: Optional[int]) -> None:
        if not self.is_account_in_notary_pool():
            return
        snap = self._head_snapshot(head)
        if snap is not None:
            period = snap["period"]
            block_number = snap["block_number"]
            shard_count = snap["shard_count"]
        else:
            period = self.client.current_period()
            block_number = self.client.block_number
            shard_count = self.client.shard_count()
        # audit the previous period's aggregate votes once, in one batched
        # device dispatch (the re-architected hot loop; see audit_period).
        # With overlap on (GETHSHARDING_NOTARY_OVERLAP, default), the
        # dispatch is FIRED here and the verdict pulled only after the
        # vote phases: the device verifies period N-1 while this thread
        # fetches candidates, recovers proposer signatures and votes —
        # the host pull stays off the critical path until the verdict
        # is actually needed (the audit counters/mismatch report).
        finish_audit: Optional[Callable[[], None]] = None
        prev_audited = self._last_audited_period
        if period > 0 and self._last_audited_period < period:
            if self._overlap_enabled():
                finish_audit = self._begin_period_audit(period - 1)
            else:
                self.audit_period(period - 1)
            self._last_audited_period = period
        try:
            self._vote_phases(snap, period, block_number, shard_count)
        except Exception:
            # the vote-phase failure wins; still collect the audit
            # verdict (its device work is done — dropping the future
            # would silently skip the mismatch checks for this period)
            if finish_audit is not None:
                try:
                    finish_audit()
                except Exception as audit_exc:
                    # transient collect failure: rewind the watermark so
                    # the NEXT head retries this period's audit (the
                    # sync path's retry semantics)
                    self._last_audited_period = prev_audited
                    self.record_error(
                        f"period audit failed behind a vote-phase "
                        f"error: {audit_exc}")
            raise
        if finish_audit is not None:
            try:
                finish_audit()
            except Exception:
                self._last_audited_period = prev_audited  # retry next head
                raise

    def _vote_phases(self, snap, period: int, block_number: int,
                     shard_count: int) -> None:
        # a vote submitted now executes in the PENDING block; if that block
        # already belongs to the next period the SMC will revert with
        # "period is not current" — skip and wait for the new period's head
        pending_period = (block_number + 1) // self.config.period_length
        if pending_period != period:
            return
        shard_ids = (range(shard_count)
                     if self.all_shards else [self.shard.shard_id])

        # phase 1: collect every eligible (shard, record) pair this period
        # — from the snapshot (zero extra round trips) when mirrored
        candidates: List[Tuple[int, int, object]] = []
        with tracing.span("notary/fetch"):
            for shard_id in self._eligible_shards(shard_ids, snap):
                if snap is not None:
                    from gethsharding_tpu.mainchain.mirror import (
                        decode_record)

                    if snap["last_submitted"].get(shard_id) != period:
                        continue
                    rec = snap["records"].get(shard_id)
                    record = None if rec is None else decode_record(rec)
                else:
                    record = self.client.collation_record(shard_id, period)
                    if (record is not None and self.client
                            .last_submitted_collation(shard_id) != period):
                        record = None
                if record is None:
                    continue
                candidates.append((shard_id, period, record))
        if not candidates:
            return

        # phase 2: ONE batched proposer-signature verification across all
        # candidate shards (with sigbackend 'jax' this is a single vmapped
        # recovery-ladder dispatch, replacing the per-shard batch-of-1)
        signed = [c for c in candidates if c[2].signature]
        sig_ok = {}
        if signed:
            with tracing.span("notary/recover", rows=len(signed)):
                submit = getattr(self.sig_backend, "submit", None)
                if submit is not None:
                    # serving backend (--serving): the recovery batch runs
                    # on the serving tier's dispatch thread while THIS
                    # thread fires body-request broadcasts for
                    # not-yet-local collations — the syncer round trips
                    # overlap the device dispatch instead of queueing
                    # behind it. Fire-and-forget only: the authoritative
                    # (polling) availability check stays in submit_vote,
                    # so this adds zero stalls. (Requests for rows that
                    # then fail the signature gate are speculative but
                    # harmless: body fetches carry no vote authority.)
                    from gethsharding_tpu.serving.batcher import (
                        observe_future_wake)

                    digests, sigs = self._proposer_sig_inputs(signed)
                    future = submit("ecrecover_addresses", digests, sigs)
                    for shard_id, p, record in candidates:
                        self._prefetch_availability(shard_id, p, record)
                    recovered = future.result()
                    observe_future_wake(future)
                    results = self._match_proposers(recovered, signed)
                else:
                    results = self.verify_proposer_signatures(signed)
                for (shard_id, _, _), good in zip(signed, results):
                    sig_ok[shard_id] = good

        # phase 3: availability checks + signed vote submission per shard.
        # In sampled mode the checks happen FIRST, for every candidate at
        # once: k samples × all shards marshalled into ONE batched
        # das_verify_samples dispatch (the samples × shards plane), so
        # per-shard submit_vote reads a precomputed verdict instead of
        # issuing its own dispatch-of-k
        sampled_ok = (self._sampled_verdicts(candidates)
                      if self._sampled() else None)
        with tracing.span("notary/vote", candidates=len(candidates)):
            for shard_id, p, record in candidates:
                if record.signature and not sig_ok.get(shard_id, False):
                    self.signatures_rejected += 1
                    self.record_error(
                        f"proposer signature invalid: shard {shard_id} "
                        f"period {p}")
                    continue
                with self.m_validate_latency.time():
                    self.submit_vote(
                        shard_id, p, record, proposer_sig_checked=True,
                        availability=(None if sampled_ok is None
                                      else sampled_ok.get(shard_id,
                                                          False)))

    def _eligible_shards(self, shard_ids, snap=None) -> List[int]:
        """Committee eligibility for ALL shards from one sampling-context
        view: the reference issues an eth_call per shard per head
        (`notary.go:62`, the network-bound hot loop SURVEY.md §3.1 flags);
        here the keccak sampling runs locally over the fetched context —
        taken from the mirror snapshot when one is current, so a remote
        notary spends zero extra round trips on it. Falls back to
        per-shard calls when the backend lacks the view."""
        from gethsharding_tpu.crypto.keccak import keccak256

        if snap is not None:
            from gethsharding_tpu.mainchain.mirror import (
                decode_committee_context)

            ctx = decode_committee_context(snap["committee_context"])
        else:
            ctx = self.client.committee_context()
        me = self.client.account()
        if ctx is None:
            return [s for s in shard_ids
                    if self.client.get_notary_in_committee(s) == me]
        sample_size = ctx["sample_size"]
        if sample_size <= 0:
            return []
        registry = self.client.notary_registry()
        pool_index = registry.pool_index if registry is not None else 0
        prefix = ctx["blockhash"] + pool_index.to_bytes(32, "big")
        pool = ctx["pool"]
        me_raw = bytes(me)
        out = []
        for shard_id in shard_ids:
            digest = keccak256(prefix + shard_id.to_bytes(32, "big"))
            slot = int.from_bytes(digest, "big") % sample_size
            member = pool[slot] if slot < len(pool) else None
            if member is not None and member == me_raw:
                out.append(shard_id)
        return out

    # -- voting (notary.go:413 submitVote) ---------------------------------

    def submit_vote(self, shard_id: int, period: int, record,
                    proposer_sig_checked: bool = False,
                    availability: Optional[bool] = None) -> bool:
        registry = self.client.notary_registry()
        if registry is None or not registry.deposited:
            self.record_error("cannot vote: not a deposited notary")
            return False
        if registry.pool_index >= self.config.committee_size:
            self.record_error(
                f"invalid pool index {registry.pool_index}: exceeds committee "
                f"size {self.config.committee_size}"
            )
            return False
        # the crash-safe journal gate FIRST: it answers "did this
        # process lineage already submit (shard, period)?" locally, so
        # a restarted notary cannot double-vote even while its view of
        # the chain (or the chain connection itself) is catching up
        if self.journal is not None and self.journal.has_vote(shard_id,
                                                              period):
            return False
        if self.client.has_voted(shard_id, registry.pool_index):
            if self.journal is not None:
                # the chain knows but the journal missed it (vote landed
                # in the crash window): sync so the NEXT check is local
                self.journal.record_vote(shard_id, period)
            return False

        # proposer-signature check through the sig backend (the reference's
        # native-crypto seam). The period flow pre-verifies ALL candidate
        # records in one batch (notarize_collations phase 2); this single
        # check covers direct callers. An unsigned record (empty sig) is
        # accepted for parity with the reference flow, where header
        # signatures are not yet enforced on-chain — but a PRESENT
        # signature must recover to the proposer.
        if record.signature and not proposer_sig_checked:
            if not self.verify_proposer_signatures(
                    [(shard_id, period, record)])[0]:
                self.signatures_rejected += 1
                self.record_error(
                    f"proposer signature invalid: shard {shard_id} "
                    f"period {period}")
                return False

        # data-availability check: full mode checks the local shardDB and
        # fetches the body over shardp2p when missing (the reference's
        # syncer round-trip); sampled mode (--da-mode=sampled) verifies k
        # sampled chunk proofs against the proposer's erasure-extension
        # commitment instead — zero body bytes. The period flow passes a
        # precomputed batched verdict via `availability`; direct callers
        # compute their own here.
        with tracing.span("notary/verify", shard=shard_id):
            if availability is None:
                availability = (
                    self._check_sampled(shard_id, period, record)
                    if self._sampled()
                    else self._check_availability(shard_id, period,
                                                  record))
            if not availability:
                self.record_error(
                    f"collation body unavailable for shard {shard_id} "
                    f"period {period}"
                )
                return False

            # enforced windback (sharding/README.md): the previous W
            # periods' collations on this shard chain must also be
            # available before we extend it with a vote
            if not self._check_windback(shard_id, period):
                return False

        # the vote carries our aggregatable BLS signature over
        # (shard, period, chunkRoot) — the artifact the period audit
        # batch-verifies (smc/state_machine.py vote_digest)
        digest = vote_digest(shard_id, period, record.chunk_root)
        try:
            self.client.submit_vote(shard_id, period, registry.pool_index,
                                    record.chunk_root,
                                    bls_sig=self.client.bls_sign(digest))
        except SMCRevert as exc:
            self.record_error(f"vote reverted: {exc}")
            return False
        if self.journal is not None:
            # journal AFTER the chain accepted: the journal answers
            # "already submitted?", the chain stays authoritative
            self.journal.record_vote(shard_id, period)
        self.votes_submitted += 1
        self.m_votes.inc()

        # on quorum, persist the canonical header (notary.go:165)
        if self.client.last_approved_collation(shard_id) == period:
            self._set_canonical(shard_id, period, record)
        return True

    # -- the batched period audit (the re-architected hot loop) ------------

    def audit_period(self, period: int) -> Optional[bool]:
        """Verify a whole period's committee votes in ONE device dispatch.

        For every shard with a collation record in `period`, aggregate the
        accepted votes' BLS signatures and the voters' registered pubkeys,
        then verify all shards' aggregates in a single sig-backend call
        (with sigbackend 'jax': one batched optimal-ate pairing dispatch —
        BASELINE.md config 3, the loop `sharding/notary/notary.go:62`
        re-architected). The quorum outcome recomputed from the verified
        votes must be byte-identical with the SMC's `is_elected` flags;
        a mismatch (forged/invalid stored signature, tally drift) is
        counted and reported. Additionally replays the period's accepted
        vote transactions through the fixed-shape batch kernel
        (`ops/smc_jax.submit_votes_batch`) via the chain's vote log and
        checks state parity with the scalar machine.

        Returns True (all consistent), False (mismatch), or None (nothing
        auditable this period).
        """
        return self.audit_periods([period])[period]

    def _overlap_enabled(self) -> bool:
        """GETHSHARDING_NOTARY_OVERLAP (default on): fire the audit
        dispatch asynchronously and pull the verdict only when it is
        needed, overlapping device execution with host work."""
        return os.environ.get("GETHSHARDING_NOTARY_OVERLAP", "1") != "0"

    def audit_periods(self, periods, overlap: bool = False) -> dict:
        """Audit MANY periods in ONE sig-backend dispatch.

        The catch-up form of `audit_period` (an observer or light server
        re-validating history): rows from every period share a single
        batched aggregation+pairing call, so K periods cost one
        SIGNATURE dispatch of K×shards rows instead of K — on a
        latency-bound kernel nearly the cost of one. (The per-period SMC
        vote-log replay check remains one `verify_period_batch` call per
        period; its kernel shapes are period-local.) Returns
        {period: True/False/None} with `audit_period` semantics.

        ``overlap=True`` switches to the PIPELINED form: one dispatch
        per period, fired through the backend's async face, so period
        N+1's host marshalling/staging (and period N's verdict judging)
        runs while period N executes on device. Verdicts are identical;
        pick batched for a latency-bound kernel (fewer dispatches),
        overlapped when host marshalling is the bottleneck or verdicts
        should stream per period (``bench.py --overlap`` measures the
        ratio).
        """
        periods = list(periods)
        collected = {p: self._collect_audit_rows(p) for p in periods}
        results: dict = {p: None for p in periods}
        if overlap:
            return self._audit_periods_overlapped(periods, collected,
                                                  results)
        msgs, sig_rows, pk_rows, pk_keys = [], [], [], []
        spans = {}
        for period, rows in collected.items():
            if rows is None:
                continue
            start = len(msgs)
            msgs.extend(rows["msgs"])
            sig_rows.extend(rows["sig_rows"])
            pk_rows.extend(rows["pk_rows"])
            pk_keys.extend(rows["pk_keys"])
            spans[period] = (start, len(msgs))

        if not spans:
            return results
        # aggregation + verification are ONE backend call: with sigbackend
        # 'jax' the per-shard point sums AND the batched pairing happen in
        # a single device dispatch (no host point arithmetic per vote)
        with tracing.span("notary/audit", periods=len(spans),
                          rows=len(msgs)):
            with self.m_audit_latency.time():
                # the period audit is bulk traffic: behind a serving
                # tier it must coalesce under the bulk_audit admission
                # class (weighted share, shed before interactive), and
                # the thread-local tag survives the failover/soundness
                # wrapper composition in between
                with admission_class(CLASS_BULK_AUDIT):
                    ok = self.sig_backend.bls_verify_committees(
                        msgs, sig_rows, pk_rows, pk_row_keys=pk_keys)
        self.audits_run += len(spans)
        for period, (start, end) in spans.items():
            results[period] = self._judge_period(
                period, collected[period], ok[start:end])
        return results

    def _audit_periods_overlapped(self, periods, collected,
                                  results) -> dict:
        """The marshal/dispatch pipeline: submit every period's dispatch
        through the async backend face (each submit returns once the
        device is launched, so period N+1 marshals while N executes),
        then judge verdicts in order — each `result()` pull overlaps
        the remaining periods' device work."""
        pending = []  # (period, rows, verdict future)
        n_rows = sum(len(r["msgs"]) for r in collected.values()
                     if r is not None)
        with tracing.span("notary/audit", periods=len(periods),
                          rows=n_rows, overlap=True):
            # the latency timer covers submits + verdict pulls ONLY —
            # judging (incl. the per-period replay check) stays outside,
            # like the sync branch, so notary/period_audit_latency is
            # comparable between the batched and overlapped modes
            verdicts = []
            with self.m_audit_latency.time():
                for period in periods:
                    rows = collected[period]
                    if rows is None:
                        continue
                    # bulk_audit admission class (see audit_periods)
                    with admission_class(CLASS_BULK_AUDIT):
                        future = (self.sig_backend
                                  .bls_verify_committees_async(
                                      rows["msgs"], rows["sig_rows"],
                                      rows["pk_rows"],
                                      pk_row_keys=rows["pk_keys"]))
                    pending.append((period, rows, future))
                for period, rows, future in pending:
                    verdicts.append((period, rows, future.result()))
            for period, rows, ok in verdicts:
                results[period] = self._judge_period(period, rows, ok)
        self.audits_run += len(pending)
        return results

    def _begin_period_audit(self, period: int) -> Callable[[], None]:
        """Fire one period's audit dispatch NOW; returns the finalize
        closure that pulls the verdict and judges it. The head loop
        calls finalize after the vote phases, so the device verifies
        the previous period underneath the current period's votes. The
        audit-latency timer records submit + collect time only — the
        overlapped middle belongs to the vote phases, not the audit."""
        with tracing.span("notary/audit_submit", period=period):
            collected = self._collect_audit_rows(period)
            if collected is None:
                return lambda: None
            # the latency timer mirrors the sync path's scope — the
            # sig-backend call only: row collection stays before it and
            # judging (incl. the replay dispatch) after, so the metric
            # keeps one meaning across GETHSHARDING_NOTARY_OVERLAP
            t0 = time.monotonic()
            # bulk_audit admission class (see audit_periods)
            with admission_class(CLASS_BULK_AUDIT):
                future = self.sig_backend.bls_verify_committees_async(
                    collected["msgs"], collected["sig_rows"],
                    collected["pk_rows"], pk_row_keys=collected["pk_keys"])
            submit_s = time.monotonic() - t0

        def finish() -> None:
            with tracing.span("notary/audit_collect", period=period):
                t1 = time.monotonic()
                ok = future.result()
                self.m_audit_latency.observe(
                    submit_s + (time.monotonic() - t1))
                self.audits_run += 1
                self._judge_period(period, collected, ok)

        return finish

    def _collect_audit_rows(self, period: int) -> Optional[dict]:
        """One bulk pull of a period's auditable rows (or None)."""
        from gethsharding_tpu.rpc import codec
        from gethsharding_tpu.utils.hexbytes import Hash32

        # ONE bulk pull: records + vote sigs + voter pubkeys, resolved by
        # the attribution recorded AT VOTE TIME (pool slots can be freed/
        # reused before the audit runs; registry entries persist until
        # release). Remote backends serve this in a single round trip
        # (shard_auditData) instead of O(shards) record reads + O(votes)
        # registry lookups.
        data = self.client.audit_data(period)
        raw = bool(data.get("raw"))  # in-process pull: no hex wire codec
        shards, msgs, sig_rows, pk_rows, pk_keys = [], [], [], [], []
        signed_counts, total_counts, expected = [], [], []
        for shard_id in sorted(data["shards"]):
            rec = data["shards"][shard_id]
            member_pks, sigs, key_parts = [], [], []
            for vote in rec["votes"]:
                pk = (vote["pubkey"] if raw
                      else codec.dec_g2(vote["pubkey"]))
                if pk is None:
                    member_pks = None  # released voter: not resolvable
                    break
                member_pks.append(pk)
                sigs.append(vote["sig"] if raw
                            else codec.dec_g1(vote["sig"]))
                # transport-independent cache key: the decoded point's
                # int limbs identify the row's pubkeys either way
                x, y = pk
                key_parts.extend((x.a, x.b, y.a, y.b))
            if member_pks is None:
                continue
            shards.append(shard_id)
            root = (Hash32(rec["chunk_root"]) if raw
                    else Hash32(bytes.fromhex(rec["chunk_root"])))
            msgs.append(vote_digest(shard_id, period, root))
            sig_rows.append(sigs)
            pk_rows.append(member_pks)
            # the decoded pubkey limbs uniquely determine the row: the
            # backend caches the marshalled row under this key, so a
            # repeat committee (the steady state) skips the G2 limb
            # conversion entirely
            pk_keys.append(tuple(key_parts))
            signed_counts.append(len(rec["votes"]))
            total_counts.append(rec["vote_count"])
            expected.append(bool(rec["is_elected"]))
        if not shards:
            return None
        return {"shards": shards, "msgs": msgs, "sig_rows": sig_rows,
                "pk_rows": pk_rows, "pk_keys": pk_keys,
                "signed_counts": signed_counts,
                "total_counts": total_counts, "expected": expected}

    def _judge_period(self, period: int, rows: dict, ok) -> bool:
        """Outcome checks for one period's verified rows (`ok` aligns
        with rows["shards"])."""
        shards = rows["shards"]
        signed_counts = rows["signed_counts"]
        total_counts = rows["total_counts"]
        expected = rows["expected"]
        verified = sum(n for n, good in zip(signed_counts, ok) if good)
        self.aggregate_sigs_verified += verified
        self.m_sigs_verified.inc(verified)

        consistent = True
        quorum = self.config.quorum_size
        for shard_id, good, n_signed, n_total, elected in zip(
                shards, ok, signed_counts, total_counts, expected):
            # two independent checks: (1) the signed aggregate must verify
            # (a failure means a stored signature is forged/corrupt);
            # (2) the SMC's election flag must match the quorum rule over
            # the persistent accepted-vote count. n_signed can lag n_total
            # when key-less (legacy-registered) notaries voted — their
            # votes count for quorum but cannot be signature-audited.
            mismatch = None
            if not good:
                mismatch = (f"invalid aggregate signature "
                            f"({n_signed}/{n_total} votes signed)")
            elif (n_total >= quorum) != elected:
                mismatch = (f"tally drift: votes={n_total} quorum={quorum} "
                            f"smc_elected={elected}")
            if mismatch is not None:
                consistent = False
                self.audit_mismatches += 1
                self.m_audit_mismatch.inc()
                self.record_error(
                    f"period {period} audit mismatch on shard {shard_id}: "
                    f"{mismatch}")

        # the replay check runs the jax batch kernel; skip it for pure-host
        # control planes (sigbackend 'python') to keep them accelerator-free.
        # Wrappers (serving tier, failover breaker, chaos injection) keep
        # the wrapped backend's nature: unwrap the whole chain.
        base = self.sig_backend
        while hasattr(base, "inner"):
            base = base.inner
        replay = (self.client.verify_period_batch(period)
                  if base.name == "jax" else None)
        if replay is False:
            consistent = False
            self.audit_mismatches += 1
            self.record_error(
                f"period {period} batch-replay mismatch: "
                f"submit_votes_batch disagrees with the scalar SMC")
        if self.journal is not None:
            # this period's audit is DONE (mismatches are reported, not
            # retried): persist the watermark so a restart skips it —
            # and prune vote entries for closed periods (a vote can
            # only target the CURRENT period, so anything older than
            # the audited one can never be resubmitted)
            self.journal.set_audit_high_water(period)
            self.journal.prune_votes(before_period=period)
        return consistent

    def verify_proposer_signatures(self, records) -> list:
        """Batch-verify proposer signatures over collation-header records.

        `records`: [(shard_id, period, record)]. The signed digest is the
        header hash with an EMPTY signature field (the proposer signs
        before add_sig — proposer.py create_collation). One backend
        dispatch covers the whole batch: with sigbackend 'jax' this is the
        vmapped recovery ladder over every shard's record at once.
        """
        digests, sigs = self._proposer_sig_inputs(records)
        recovered = self.sig_backend.ecrecover_addresses(digests, sigs)
        return self._match_proposers(recovered, records)

    @staticmethod
    def _proposer_sig_inputs(records) -> Tuple[list, list]:
        """(digests, sigs65) for a [(shard_id, period, record)] batch."""
        digests, sigs = [], []
        for shard_id, period, record in records:
            unsigned = CollationHeader(
                shard_id=shard_id,
                chunk_root=record.chunk_root,
                period=period,
                proposer_address=record.proposer,
            )
            digests.append(bytes(unsigned.hash()))
            sigs.append(record.signature)
        return digests, sigs

    @staticmethod
    def _match_proposers(recovered, records) -> list:
        return [
            got is not None and got == rec[2].proposer
            for got, rec in zip(recovered, records)
        ]

    # -- data-availability sampling (--da-mode=sampled) --------------------

    def _sampled(self) -> bool:
        return self.da_mode == "sampled" and self.das is not None

    def _sampled_verdicts(self, candidates) -> dict:
        """Availability verdicts for many (shard, period, record) rows
        from ONE batched `das_verify_samples` dispatch.

        Per candidate: fetch the proposer's commitment + the notary's k
        deterministic sampled (chunk, proof) rows over shardp2p
        (das/service.collect_rows — retry + chaos seams inside), then
        verify EVERY candidate's samples in a single sig-backend call
        (with sigbackend 'jax': one keccak-lane dispatch over samples ×
        shards). A shard is available iff its commitment resolved and
        every one of its samples verified; missing samples were
        synthesized as invalid rows, so they fail loudly rather than
        shrink k."""
        verdicts = {}
        fresh = []
        account = bytes(self.client.account())
        for shard_id, period, record in candidates:
            if self._da_verdicts.get((shard_id, period)):
                verdicts[shard_id] = True  # immutable content: cached
                continue
            fresh.append((shard_id, period, record))
        # fire every candidate's commitment request up front so the
        # serial per-shard collect below mostly finds parked responses
        # instead of paying a broadcast round trip per shard
        if fresh:
            self.das.prefetch_commitments(
                [(shard_id, period) for shard_id, period, _ in fresh])
        if getattr(self.das, "proof_mode", "merkle") == "poly":
            return self._poly_verdicts(fresh, account, verdicts)
        collected = []
        for shard_id, period, record in fresh:
            rows = self.das.collect_rows(shard_id, period, record,
                                         account)
            collected.append((shard_id, period, rows))
        chunks, indices, proofs, roots = [], [], [], []
        spans = {}
        for shard_id, _, rows in collected:
            if rows is None:
                continue
            start = len(chunks)
            chunks.extend(rows["chunks"])
            indices.extend(rows["indices"])
            proofs.extend(rows["proofs"])
            roots.extend(rows["roots"])
            spans[shard_id] = (start, len(chunks))
        ok: list = []
        if chunks:
            with tracing.span("notary/das_verify", rows=len(chunks),
                              shards=len(spans)):
                ok = self.sig_backend.das_verify_samples(
                    chunks, indices, proofs, roots)
        for shard_id, period, rows in collected:
            if rows is None:
                verdicts[shard_id] = False  # no commitment: unavailable
                continue
            start, end = spans[shard_id]
            row_ok = ok[start:end]
            self.das.note_verdicts(row_ok)
            good = bool(row_ok) and all(row_ok)
            verdicts[shard_id] = good
            if good:
                self._da_verdicts[(shard_id, period)] = True
        if len(self._da_verdicts) > self._DA_CACHE_MAX:
            # prune oldest periods first: closed periods stop being
            # re-checked once the head loop moves on anyway
            for key in sorted(self._da_verdicts,
                              key=lambda sp: sp[1])[:len(self._da_verdicts)
                                                    - self._DA_CACHE_MAX]:
                del self._da_verdicts[key]
        return verdicts

    def _poly_verdicts(self, fresh, account: bytes, verdicts: dict) -> dict:
        """The --da-proofs=poly phase-3: ONE `das_verify_multiproofs`
        row per candidate shard (constant-size proof per collation, the
        whole period folded into one batched pairing dispatch). The
        same availability semantics as the merkle path: no commitment
        -> unavailable; a failed or merkle-only fetch was synthesized
        as an invalid row by `collect_poly_row`, so it scores False."""
        collected = []
        for shard_id, period, record in fresh:
            row = self.das.collect_poly_row(shard_id, period, record,
                                            account)
            collected.append((shard_id, period, row))
        batched = [(shard_id, period, row)
                   for shard_id, period, row in collected
                   if row is not None]
        ok: list = []
        if batched:
            with tracing.span("notary/das_poly_verify",
                              rows=len(batched)):
                ok = self.sig_backend.das_verify_multiproofs(
                    [row["poly_commitment"] for _, _, row in batched],
                    [row["indices"] for _, _, row in batched],
                    [row["evals"] for _, _, row in batched],
                    [row["proof"] for _, _, row in batched],
                    [row["n"] for _, _, row in batched])
        it = iter(ok)
        row_verdicts = {shard_id: next(it)
                        for shard_id, _, _ in batched}
        for shard_id, period, row in collected:
            if row is None:
                verdicts[shard_id] = False  # no commitment: unavailable
                continue
            good = bool(row_verdicts.get(shard_id, False))
            self.das.note_verdicts([good])
            verdicts[shard_id] = good
            if good:
                self._da_verdicts[(shard_id, period)] = True
        if len(self._da_verdicts) > self._DA_CACHE_MAX:
            for key in sorted(self._da_verdicts,
                              key=lambda sp: sp[1])[:len(self._da_verdicts)
                                                    - self._DA_CACHE_MAX]:
                del self._da_verdicts[key]
        return verdicts

    # one verdict per (shard, period): 100 shards x a 40-period horizon
    # fits with room; entries are a bool each
    _DA_CACHE_MAX = 4096

    def _check_sampled(self, shard_id: int, period: int, record) -> bool:
        """The single-shard sampled check (direct submit_vote callers;
        the period flow batches across shards instead)."""
        return self._sampled_verdicts(
            [(shard_id, period, record)]).get(shard_id, False)

    def _check_windback(self, shard_id: int, period: int) -> bool:
        """Enforced windback: verify availability of the last
        `config.windback_depth` periods' collations on this shard chain
        (fetching missing bodies over shardp2p), refusing to vote while
        any of them is unavailable.

        Prior-period records come from the mirror snapshot's
        `prior_records` (closed periods are immutable, so the bulk pull
        is exact) — a remote notary pays ZERO extra round trips here;
        only periods outside the snapshot's depth fall back to direct
        `collation_record` reads."""
        depth = self.config.windback_depth
        if depth <= 0:
            return True
        from gethsharding_tpu.mainchain.mirror import decode_record

        snap = self.mirror.snapshot() if self.mirror is not None else None
        prior_records = (snap or {}).get("prior_records") or {}
        if snap is not None and (snap.get("period") or 0) != period:
            prior_records = {}  # stale snapshot: its window may not align
        for prior in range(max(1, period - depth), period):
            if prior in prior_records:
                rec = prior_records[prior].get(shard_id)
                record = None if rec is None else decode_record(rec)
            else:
                record = self.client.collation_record(shard_id, prior)
            if record is None:
                continue  # no collation that period: nothing to hold
            self.m_windback_checks.inc()
            # sampled mode holds the windback by proof too: prior
            # periods are re-sampled, never body-fetched
            held = (self._check_sampled(shard_id, prior, record)
                    if self._sampled()
                    else self._check_availability(shard_id, prior, record))
            if not held:
                self.record_error(
                    f"windback: collation body unavailable for shard "
                    f"{shard_id} period {prior}; refusing to vote")
                return False
        return True

    def _availability_probe(self, shard_id: int, period: int, record):
        """(header, verdict): the shardDB's LOCAL answer. True/False is
        authoritative; None means the body is not local (ShardError), in
        which case the body request has been broadcast over shardp2p —
        fire-and-forget, never blocks."""
        header = self._reconstruct_header(shard_id, period, record)
        try:
            return header, self.shard.check_availability(header)
        except ShardError:
            pass
        if self.p2p is not None:
            self.p2p.broadcast(
                CollationBodyRequest(
                    chunk_root=record.chunk_root,
                    shard_id=shard_id,
                    period=period,
                    proposer=record.proposer,
                )
            )
        return header, None

    def _prefetch_availability(self, shard_id: int, period: int,
                               record) -> None:
        """Fire the body request for a not-yet-local collation NOW so
        the responding syncer's round trip runs concurrently with
        whatever this thread overlaps it with; `_check_availability`
        remains the authoritative (polling) gate. In sampled DA mode
        this is a no-op — the whole point is that NO body request ever
        leaves a sampled notary (the sampled check fetches k
        chunks+proofs in phase 3 instead)."""
        if self._sampled():
            return
        self._availability_probe(shard_id, period, record)

    def _check_availability(self, shard_id: int, period: int, record) -> bool:
        header, verdict = self._availability_probe(shard_id, period, record)
        if verdict is not None:
            return verdict
        if self.p2p is None:
            return False

        # body not local: poll briefly for the responding syncer's
        # asynchronous store, under the body-fetch retry policy — every
        # retry RE-BROADCASTS the request (via the probe), so one lost
        # frame or one slow peer costs a capped backoff, not the vote
        def attempt() -> bool:
            got = poll_probe(
                lambda: self.shard.check_availability(header), self.wait,
                interval_s=0.05, polls=7, not_ready=(ShardError,))
            if got is not POLL_MISS:
                return got
            _, late = self._availability_probe(shard_id, period, record)
            if late is not None:
                return late
            raise _BodyUnavailable(
                f"shard {shard_id} period {period} body not delivered")

        try:
            return self._body_retry.call(attempt)
        except (_BodyUnavailable, FetchAborted):
            return False

    def _reconstruct_header(self, shard_id: int, period: int,
                            record) -> CollationHeader:
        return CollationHeader(
            shard_id=shard_id,
            chunk_root=record.chunk_root,
            period=period,
            proposer_address=record.proposer,
            proposer_signature=record.signature,
        )

    def _set_canonical(self, shard_id: int, period: int, record) -> None:
        if self._sampled():
            # a sampled notary verified availability by proof — it holds
            # no body, and the shardDB canonical index requires one.
            # Body-holding nodes (proposer, observer) index canonical.
            return
        header = self._reconstruct_header(shard_id, period, record)
        try:
            if self.shard.shard_id == shard_id:
                # the header is reconstructed from the on-chain record; make
                # sure it is persisted locally before indexing it canonical
                self.shard.save_header(header)
                self.shard.set_canonical(header)
                self.canonical_set += 1
                self.log.info("Canonical header set: shard %s period %s",
                              shard_id, period)
        except ShardError as exc:
            self.record_error(f"set canonical failed: {exc}")
