"""Notary actor: joins the pool, watches heads, votes on data availability.

Parity: `sharding/notary/service.go` (Start :31, notarizeCollations :44)
and `notary.go` (subscribeBlockHeaders :28, checkSMCForNotary :62,
joinNotaryPool :267, leaveNotaryPool :318, releaseNotary :365, submitVote
:413, verifyNotary :245, isLockUpOver :129). The vote path — which the
reference only exercises from tests — is wired into the head loop here:

  head -> in pool? -> per shard: sampled for committee? -> collation record
  exists for this period? -> chunk-root/availability check (requesting the
  body over shardp2p if missing) -> submitVote at our poolIndex -> on
  quorum, set the header canonical in the shardDB.

The `sig_backend` seam is where batched TPU verification plugs in: votes
for all shards in a period are verified as one batch (see
`gethsharding_tpu.ops` and BASELINE.md configs 2-3).
"""

from __future__ import annotations

from typing import Optional

from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.core.shard import Shard, ShardError
from gethsharding_tpu.core.types import CollationHeader
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.p2p.messages import CollationBodyRequest
from gethsharding_tpu.p2p.service import P2PServer
from gethsharding_tpu.params import Config, DEFAULT_CONFIG
from gethsharding_tpu.sigbackend import SigBackend, get_backend
from gethsharding_tpu.smc.state_machine import SMCRevert


class Notary(Service):
    name = "notary"

    def __init__(self, client: SMCClient, shard: Shard,
                 p2p: Optional[P2PServer] = None,
                 config: Config = DEFAULT_CONFIG,
                 deposit_flag: bool = False,
                 all_shards: bool = True,
                 sig_backend: Optional[SigBackend] = None):
        super().__init__()
        self.client = client
        self.shard = shard
        self.p2p = p2p
        self.config = config
        self.deposit_flag = deposit_flag
        # notaries watch every shard (the reference scans 0..shardCount)
        self.all_shards = all_shards
        self.sig_backend = sig_backend or get_backend("python")
        self.votes_submitted = 0
        self.canonical_set = 0
        self.signatures_rejected = 0
        self._unsubscribe = None

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        if self.deposit_flag:
            try:
                self.join_notary_pool()
            except Exception as exc:
                self.record_error(f"joining notary pool failed: {exc}")
        self._unsubscribe = self.client.subscribe_new_head(self._on_head)

    def on_stop(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()

    # -- pool membership (notary.go:267,318,365) ---------------------------

    def join_notary_pool(self) -> None:
        registry = self.client.notary_registry()
        if registry is not None and registry.deposited:
            self.log.info("Already joined notary pool")
            return
        self.client.register_notary()
        self.log.info("Joined notary pool: %s", self.client.account().hex_str)

    def leave_notary_pool(self) -> None:
        self.client.deregister_notary()

    def release_notary(self) -> None:
        registry = self.client.notary_registry()
        if registry is None or registry.deregistered_period == 0:
            raise RuntimeError("account has not deregistered")
        if not self.is_lockup_over(registry):
            raise RuntimeError("lockup period is not over")
        self.client.release_notary()

    def is_lockup_over(self, registry) -> bool:
        """isLockUpOver (notary.go:129)."""
        return (self.client.current_period()
                > registry.deregistered_period + self.config.notary_lockup_length)

    def is_account_in_notary_pool(self) -> bool:
        registry = self.client.notary_registry()
        return registry is not None and registry.deposited

    # -- the hot loop (notarizeCollations / checkSMCForNotary) -------------

    def _on_head(self, block) -> None:
        try:
            self.notarize_collations()
        except Exception as exc:
            self.record_error(f"notarize failed at head {block.number}: {exc}")

    def notarize_collations(self) -> None:
        if not self.is_account_in_notary_pool():
            return
        period = self.client.current_period()
        # a vote submitted now executes in the PENDING block; if that block
        # already belongs to the next period the SMC will revert with
        # "period is not current" — skip and wait for the new period's head
        pending_period = (self.client.block_number + 1) // self.config.period_length
        if pending_period != period:
            return
        shard_ids = (range(self.client.shard_count())
                     if self.all_shards else [self.shard.shard_id])
        for shard_id in shard_ids:
            self.check_shard(shard_id, period)

    def check_shard(self, shard_id: int, period: int) -> None:
        # committee sampling: eligible iff sample(our poolIndex) == us
        sampled = self.client.get_notary_in_committee(shard_id)
        me = self.client.account()
        if sampled != me:
            return
        record = self.client.collation_record(shard_id, period)
        if record is None or self.client.last_submitted_collation(shard_id) != period:
            return
        self.submit_vote(shard_id, period, record)

    # -- voting (notary.go:413 submitVote) ---------------------------------

    def submit_vote(self, shard_id: int, period: int, record) -> bool:
        registry = self.client.notary_registry()
        if registry is None or not registry.deposited:
            self.record_error("cannot vote: not a deposited notary")
            return False
        if registry.pool_index >= self.config.committee_size:
            self.record_error(
                f"invalid pool index {registry.pool_index}: exceeds committee "
                f"size {self.config.committee_size}"
            )
            return False
        if self.client.has_voted(shard_id, registry.pool_index):
            return False

        # proposer-signature check through the sig backend (the reference's
        # native-crypto seam; batch-verified on TPU with sigbackend 'jax').
        # An unsigned record (empty sig) is accepted for parity with the
        # reference flow, where header signatures are not yet enforced
        # on-chain — but a PRESENT signature must recover to the proposer.
        if record.signature:
            if not self.verify_proposer_signatures(
                    [(shard_id, period, record)])[0]:
                self.signatures_rejected += 1
                self.record_error(
                    f"proposer signature invalid: shard {shard_id} "
                    f"period {period}")
                return False

        # data-availability check against the local shardDB; fetch the body
        # over shardp2p when missing (the reference's syncer round-trip)
        if not self._check_availability(shard_id, period, record):
            self.record_error(
                f"collation body unavailable for shard {shard_id} "
                f"period {period}"
            )
            return False

        try:
            self.client.submit_vote(shard_id, period, registry.pool_index,
                                    record.chunk_root)
        except SMCRevert as exc:
            self.record_error(f"vote reverted: {exc}")
            return False
        self.votes_submitted += 1

        # on quorum, persist the canonical header (notary.go:165)
        if self.client.last_approved_collation(shard_id) == period:
            self._set_canonical(shard_id, period, record)
        return True

    def verify_proposer_signatures(self, records) -> list:
        """Batch-verify proposer signatures over collation-header records.

        `records`: [(shard_id, period, record)]. The signed digest is the
        header hash with an EMPTY signature field (the proposer signs
        before add_sig — proposer.py create_collation). One backend
        dispatch covers the whole batch: with sigbackend 'jax' this is the
        vmapped recovery ladder over every shard's record at once.
        """
        digests, sigs = [], []
        for shard_id, period, record in records:
            unsigned = CollationHeader(
                shard_id=shard_id,
                chunk_root=record.chunk_root,
                period=period,
                proposer_address=record.proposer,
            )
            digests.append(bytes(unsigned.hash()))
            sigs.append(record.signature)
        recovered = self.sig_backend.ecrecover_addresses(digests, sigs)
        return [
            got is not None and got == rec[2].proposer
            for got, rec in zip(recovered, records)
        ]

    def _check_availability(self, shard_id: int, period: int, record) -> bool:
        header = self._reconstruct_header(shard_id, period, record)
        try:
            return self.shard.check_availability(header)
        except ShardError:
            pass
        # body not local: request over shardp2p, then poll briefly — the
        # responding syncer stores the body asynchronously
        if self.p2p is not None:
            self.p2p.broadcast(
                CollationBodyRequest(
                    chunk_root=record.chunk_root,
                    shard_id=shard_id,
                    period=period,
                    proposer=record.proposer,
                )
            )
            for _ in range(20):
                if self.wait(0.05):
                    return False
                try:
                    return self.shard.check_availability(header)
                except ShardError:
                    continue
        return False

    def _reconstruct_header(self, shard_id: int, period: int,
                            record) -> CollationHeader:
        return CollationHeader(
            shard_id=shard_id,
            chunk_root=record.chunk_root,
            period=period,
            proposer_address=record.proposer,
            proposer_signature=record.signature,
        )

    def _set_canonical(self, shard_id: int, period: int, record) -> None:
        header = self._reconstruct_header(shard_id, period, record)
        try:
            if self.shard.shard_id == shard_id:
                # the header is reconstructed from the on-chain record; make
                # sure it is persisted locally before indexing it canonical
                self.shard.save_header(header)
                self.shard.set_canonical(header)
                self.canonical_set += 1
                self.log.info("Canonical header set: shard %s period %s",
                              shard_id, period)
        except ShardError as exc:
            self.record_error(f"set canonical failed: {exc}")
