"""Simulator: fakes remote-notary traffic for in-process multi-node tests.

Parity: `sharding/simulator/service.go` (simulateNotaryRequests :70): on a
ticker, read the SMC collation record for the current period and inject a
CollationBodyRequest into the local feeds, exercising the syncer
round-trip without a real network.
"""

from __future__ import annotations

from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.actors.syncer import request_collation_body
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.p2p.service import P2PServer


class Simulator(Service):
    name = "simulator"
    supervisable = True

    def __init__(self, client: SMCClient, p2p: P2PServer, shard_id: int,
                 tick_interval: float = 15.0):
        super().__init__()
        self.client = client
        self.p2p = p2p
        self.shard_id = shard_id
        self.tick_interval = tick_interval
        self.requests_sent = 0

    def on_start(self) -> None:
        self.spawn(self._simulate_notary_requests)

    def _simulate_notary_requests(self) -> None:
        while not self.wait(self.tick_interval):
            try:
                period = self.client.current_period()
                request = request_collation_body(self.client, self.shard_id,
                                                 period)
                if request is not None:
                    self.p2p.loopback(request)
                    self.requests_sent += 1
                    self.log.info("Sent request for collation body")
            except Exception as exc:
                self.record_error(f"simulator tick failed: {exc}")
