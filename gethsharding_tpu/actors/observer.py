"""Observer actor: the default, non-staking shard watcher.

Parity: `sharding/observer/service.go` (NewObserver :27) — the reference
observer only logs lifecycle. Here it also tails new canonical collations
for its shard (the documented intent of the observer role: "simply observe
the shard network").
"""

from __future__ import annotations

from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.core.shard import Shard, ShardError
from gethsharding_tpu.mainchain.client import SMCClient


class Observer(Service):
    name = "observer"
    supervisable = True

    def __init__(self, client: SMCClient, shard: Shard):
        super().__init__()
        self.client = client
        self.shard = shard
        self.seen_periods = set()
        self._unsubscribe = None

    def on_start(self) -> None:
        self.log.info("Starting observer service in shard %d",
                      self.shard.shard_id)
        self._unsubscribe = self.client.subscribe_new_head(self._on_head)

    def on_stop(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()

    def _on_head(self, block) -> None:
        try:
            self._observe_head()
            self.record_success()
        except Exception as exc:
            self.record_failure(f"observe failed: {exc}")

    def _observe_head(self) -> None:
        period = self.client.current_period()
        shard_id = self.shard.shard_id
        if period in self.seen_periods:
            return
        if self.client.last_approved_collation(shard_id) == period:
            self.seen_periods.add(period)
            try:
                collation = self.shard.canonical_collation(shard_id, period)
                self.log.info(
                    "Observed canonical collation: shard %d period %d txs %d",
                    shard_id, period, len(collation.transactions),
                )
            except ShardError:
                # header approved on-chain but body not yet synced locally
                self.log.info(
                    "Canonical header approved for shard %d period %d "
                    "(body not local)", shard_id, period,
                )
