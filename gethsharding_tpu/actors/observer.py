"""Observer actor: the default, non-staking shard watcher.

Parity: `sharding/observer/service.go` (NewObserver :27) — the reference
observer only logs lifecycle. Here it also tails new canonical collations
for its shard (the documented intent of the observer role: "simply observe
the shard network") and REPLAYS them: every canonical collation's
transactions run through the phase-1 state transition
(`core/state_processor`, the `core/state_processor.go:56` Process analog),
maintaining the shard's running account state and a per-period state
root. With `replay_engine="jax"` the replay is the batched device kernel
(`ops/replay_jax`, BASELINE config 4) — sender recovery + transition in
one dispatch — with results folded back into the host state table.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from gethsharding_tpu import metrics
from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.core import state_processor as sp
from gethsharding_tpu.core.shard import Shard, ShardError
from gethsharding_tpu.mainchain.client import SMCClient
from gethsharding_tpu.utils.hexbytes import Address20, Hash32

_ZERO_COINBASE = Address20(b"\x00" * 20)


class Observer(Service):
    name = "observer"
    supervisable = True

    def __init__(self, client: SMCClient, shard: Shard,
                 replay_engine: str = "python",
                 genesis: Optional[Dict[Address20, sp.AccountState]] = None):
        if replay_engine not in ("python", "jax", "off"):
            raise ValueError(f"unknown replay engine {replay_engine!r}")
        super().__init__()
        self.client = client
        self.shard = shard
        self.replay_engine = replay_engine
        # deep-copy account rows: replay mutates them in place, and the
        # caller's genesis mapping must stay pristine
        self.state = sp.ShardState(
            {addr: dataclasses.replace(acct)
             for addr, acct in genesis.items()} if genesis else None)
        self.state_roots: Dict[int, Hash32] = {}
        # canonical secure-MPT roots (statedb.go:562 parity) per period —
        # the commitment a Go node recomputes; state_roots stays the fast
        # flat integrity check shared bit-for-bit with the device kernel
        self.canonical_roots: Dict[int, Hash32] = {}
        self.txs_replayed = 0
        self.txs_rejected = 0
        self.seen_periods = set()
        self._unsubscribe = None
        self.m_replay_latency = metrics.timer("observer/replay_latency")
        self.m_txs_replayed = metrics.counter("observer/txs_replayed")
        self.m_txs_rejected = metrics.counter("observer/txs_rejected")

    def on_start(self) -> None:
        self.log.info("Starting observer service in shard %d",
                      self.shard.shard_id)
        self._unsubscribe = self.client.subscribe_new_head(self._on_head)

    def on_stop(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()

    def _on_head(self, block) -> None:
        try:
            self._observe_head()
            self.record_success()
        except Exception as exc:
            self.record_failure(f"observe failed: {exc}")

    def _observe_head(self) -> None:
        period = self.client.current_period()
        shard_id = self.shard.shard_id
        if period in self.seen_periods:
            return
        if self.client.last_approved_collation(shard_id) == period:
            try:
                collation = self.shard.canonical_collation(shard_id, period)
            except ShardError:
                # header approved on-chain but body not yet synced locally:
                # do NOT mark the period seen — the next head retries, so
                # a late-arriving body cannot leave a silent gap in the
                # replayed state
                self.log.info(
                    "Canonical header approved for shard %d period %d "
                    "(body not local yet)", shard_id, period,
                )
                return
            self.seen_periods.add(period)
            self.log.info(
                "Observed canonical collation: shard %d period %d txs %d",
                shard_id, period, len(collation.transactions),
            )
            if self.replay_engine != "off":
                self.replay_collation(period, collation)

    # -- the collation replay (state_processor.go Process analog) ----------

    def replay_collation(self, period: int, collation) -> Hash32:
        """Apply the collation's transactions to the shard's running
        state; record and return the post-state root."""
        txs = collation.transactions
        coinbase = collation.header.proposer_address or _ZERO_COINBASE
        with self.m_replay_latency.time():
            if self.replay_engine == "jax" and txs:
                applied = self._replay_on_device(txs, coinbase)
            else:
                # materialize the same account rows the device table holds
                # (zero rows hash into the root; the two engines must
                # agree even when every tx is rejected)
                for addr in sp.replay_account_table(
                        txs, self.state.accounts, coinbase):
                    self.state.get(addr)
                receipts = sp.process(self.state, txs, coinbase)
                applied = sum(r.status for r in receipts)
        self.txs_replayed += applied
        self.txs_rejected += len(txs) - applied
        self.m_txs_replayed.inc(applied)
        self.m_txs_rejected.inc(len(txs) - applied)
        root = self.state.root()
        self.state_roots[period] = root
        canonical = self.state.trie_root()
        self.canonical_roots[period] = canonical
        self.log.info("Replayed collation: shard %d period %d applied %d/%d "
                      "root 0x%s state_root 0x%s", self.shard.shard_id,
                      period, applied, len(txs), bytes(root).hex()[:16],
                      bytes(canonical).hex()[:16])
        return canonical

    def _replay_on_device(self, txs, coinbase: Address20) -> int:
        """One batched device dispatch (recovery ladder + vmapped
        transition), folded back into the host account table. The table
        order must mirror `build_replay_inputs` (current accounts ∪
        touched addresses, ascending by bytes)."""
        import numpy as np

        from gethsharding_tpu.ops import replay_jax

        inp = replay_jax.build_replay_inputs(
            [txs], [self.state.accounts], [coinbase])
        out = replay_jax.replay_batch(inp)

        table = sp.replay_account_table(txs, self.state.accounts, coinbase)
        nonces = np.asarray(out.nonces[0])
        balances = np.asarray(out.balances[0])
        for i, addr in enumerate(table):
            acct = self.state.get(addr)
            acct.nonce = int(nonces[i])
            acct.balance = int.from_bytes(
                bytes(balances[i].astype(np.uint8)), "little")
        return int(np.asarray(out.statuses[0]).sum())
