"""Actor services: notary, proposer, observer, syncer, simulator, txpool.

Parity targets (SURVEY.md §2.1): `sharding/notary`, `sharding/proposer`,
`sharding/observer`, `sharding/syncer`, `sharding/simulator`,
`sharding/txpool` — each a Service with Start/Stop lifecycle running its
loop on a background thread, errors funneled to a channel-equivalent
(`sharding/utils/service.go` HandleServiceErrors).

Unlike the reference (where the vote path is only exercised from tests),
the notary's subscribe -> committee-check -> availability-check -> vote ->
canonical pipeline is fully wired.
"""

from gethsharding_tpu.actors.base import Service  # noqa: F401
from gethsharding_tpu.actors.txpool import TXPool  # noqa: F401
from gethsharding_tpu.actors.proposer import Proposer  # noqa: F401
from gethsharding_tpu.actors.notary import Notary  # noqa: F401
from gethsharding_tpu.actors.observer import Observer  # noqa: F401
from gethsharding_tpu.actors.syncer import Syncer  # noqa: F401
from gethsharding_tpu.actors.simulator import Simulator  # noqa: F401
