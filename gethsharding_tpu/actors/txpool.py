"""Shard transaction pool service.

Parity: `sharding/txpool/service.go` — the reference emits a fake
1024-random-byte tx every 5 s into an event feed (`sendTestTransaction
:47`). This pool keeps that simulation mode (configurable interval) and
additionally supports real intake via `submit()`, the step the reference
stubs out.
"""

from __future__ import annotations

import os
from typing import Optional

from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.p2p.feed import Feed


class TXPool(Service):
    name = "txpool"

    def __init__(self, simulate_interval: Optional[float] = 5.0,
                 payload_size: int = 1024):
        super().__init__()
        self.transactions_feed = Feed()
        self.simulate_interval = simulate_interval
        self.payload_size = payload_size
        self._nonce = 0

    def on_start(self) -> None:
        if self.simulate_interval is not None:
            self.spawn(self._send_test_transactions)

    def submit(self, tx: Transaction) -> int:
        """Real tx intake: push into the feed, return subscriber count."""
        return self.transactions_feed.send(tx)

    def _make_test_tx(self) -> Transaction:
        self._nonce += 1
        return Transaction(
            nonce=self._nonce,
            gas_limit=0,
            payload=os.urandom(self.payload_size),
        )

    def _send_test_transactions(self) -> None:
        while not self.wait(self.simulate_interval):
            self.submit(self._make_test_tx())
