"""Shard transaction pool service.

Parity targets, two tiers:
- `sharding/txpool/service.go` — the reference's shard pool emits a fake
  1024-random-byte tx every 5 s into an event feed (`sendTestTransaction
  :47`). That simulation mode is kept (configurable interval).
- `core/tx_pool.go:184` — the REAL pool underneath geth, which the
  sharding stub never grew into: a validated, deduplicated, price-aware
  pending set with per-sender nonce ordering, gapped-nonce queueing,
  capacity eviction of the cheapest transactions, and a crash-safe
  journal replayed on restart (`core/tx_journal.go:51`).

`submit()` feeds both worlds: accepted transactions enter the pending
structures AND are published on the feed the proposer subscribes to.
Signed transactions are keyed by recovered sender; phase-1 opaque
payloads (no signature) are admitted under a zero sender with feed-order
nonce semantics.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from gethsharding_tpu import metrics, tracing
from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.core.state_processor import recover_sender
from gethsharding_tpu.core.types import Transaction
from gethsharding_tpu.p2p.feed import Feed
from gethsharding_tpu.utils.hexbytes import Address20


class TxPoolError(Exception):
    pass


class TXPool(Service):
    name = "txpool"

    def __init__(self, simulate_interval: Optional[float] = 5.0,
                 payload_size: int = 1024, capacity: int = 4096,
                 max_payload: int = 1 << 20,
                 journal_path: Optional[str] = None,
                 sig_backend=None):
        super().__init__()
        self.transactions_feed = Feed()
        self.simulate_interval = simulate_interval
        self.payload_size = payload_size
        self.capacity = capacity
        self.max_payload = max_payload
        self.journal_path = journal_path
        # opt-in serving-tier wiring (--serving): sender recovery goes
        # through the coalescing SigBackend, so many submitter threads'
        # single-tx recoveries share device dispatches instead of each
        # paying the scalar host path (core/tx_pool.go keeps a sender
        # cache for the same hot spot)
        self.sig_backend = sig_backend
        self._nonce = 0
        # sender -> {nonce: tx}; contiguous-from-lowest prefix is pending,
        # the gapped remainder queued (tx_pool.go pending/queue split)
        self._by_sender: Dict[Address20, Dict[int, Transaction]] = {}
        self._hashes: set = set()
        # tx hash -> sender recovered at admission (core/tx_pool.go's
        # sender cache): removal paths — take_pending() for every
        # collation — must never pay recovery again
        self._senders: Dict[bytes, Address20] = {}
        self.m_known = metrics.gauge("txpool/known")
        self.m_dropped = metrics.counter("txpool/evicted")

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        if self.journal_path:
            self._replay_journal()
        if self.simulate_interval is not None:
            self.spawn(self._send_test_transactions)

    # -- intake (core/tx_pool.go add/validateTx) ---------------------------

    def submit(self, tx: Transaction) -> int:
        """Validate + admit a transaction, journal it, and publish it on
        the proposer feed. Returns the feed subscriber count.
        Raises TxPoolError for invalid or duplicate transactions."""
        self._admit(tx)
        if self.journal_path:
            self._journal(tx)
        return self.transactions_feed.send(tx)

    def _admit(self, tx: Transaction) -> None:
        if len(tx.payload) > self.max_payload:
            raise TxPoolError("payload exceeds size cap")
        tx_hash = bytes(tx.hash())
        if tx_hash in self._hashes:
            raise TxPoolError("already known")
        sender = self._sender_of(tx)
        slot = self._by_sender.setdefault(sender, {})
        existing = slot.get(tx.nonce)
        if existing is not None:
            # replacement requires a strictly higher price (the reference's
            # price-bump rule, simplified to >)
            if tx.gas_price <= existing.gas_price:
                raise TxPoolError("replacement transaction underpriced")
            old_hash = bytes(existing.hash())
            self._hashes.discard(old_hash)
            self._senders.pop(old_hash, None)
        slot[tx.nonce] = tx
        self._hashes.add(tx_hash)
        self._senders[tx_hash] = sender
        self._enforce_capacity()
        self.m_known.set(len(self._hashes))

    def _sender_of(self, tx: Transaction) -> Address20:
        if tx.v or tx.r or tx.s:
            # the admission hot spot: behind --serving this span parents
            # the coalesced serving/ecrecover request spans, attributing
            # recovery latency per submitted transaction
            with tracing.span("txpool/recover_sender"):
                if self.sig_backend is not None:
                    try:
                        sender = self._recover_via_backend(tx)
                    except Exception as exc:  # noqa: BLE001 - the pool's
                        # contract is TxPoolError only: a serving tier
                        # shedding under overload (or shutting down) must
                        # read as a pool rejection the caller can retry,
                        # not crash the submitter/proposer loop
                        raise TxPoolError(
                            f"signature verification unavailable: {exc}"
                        ) from exc
                else:
                    sender = recover_sender(tx)
            if sender is None:
                raise TxPoolError("invalid signature")
            return sender
        return Address20()  # phase-1 opaque txs pool under the zero sender

    def _recover_via_backend(self, tx: Transaction) -> Optional[Address20]:
        """`recover_sender` through the SigBackend seam: same homestead
        rule (v = 27 + parity over sig_hash), but the recovery itself is
        a backend batch row — behind a serving backend, concurrent
        submitters coalesce into one device dispatch."""
        if tx.v not in (27, 28):
            return None
        try:
            sig65 = (tx.r.to_bytes(32, "big") + tx.s.to_bytes(32, "big")
                     + bytes([tx.v - 27]))
        except (OverflowError, ValueError):
            return None  # out-of-range r/s: invalid, like the scalar path
        return self.sig_backend.ecrecover_addresses(
            [bytes(tx.sig_hash())], [sig65])[0]

    def _enforce_capacity(self) -> None:
        """Evict the globally cheapest transactions over capacity
        (highest nonce first within a sender, so prefixes stay intact)."""
        while len(self._hashes) > self.capacity:
            cheapest: Optional[Tuple[Address20, int]] = None
            cheapest_price = None
            for sender, slot in self._by_sender.items():
                nonce = max(slot)
                price = slot[nonce].gas_price
                if cheapest_price is None or price < cheapest_price:
                    cheapest, cheapest_price = (sender, nonce), price
            sender, nonce = cheapest
            victim = self._by_sender[sender].pop(nonce)
            if not self._by_sender[sender]:
                del self._by_sender[sender]
            victim_hash = bytes(victim.hash())
            self._hashes.discard(victim_hash)
            self._senders.pop(victim_hash, None)
            self.m_dropped.inc()

    # -- views (tx_pool.go Pending) ----------------------------------------

    def pending(self, limit: Optional[int] = None) -> List[Transaction]:
        """Executable transactions: per sender the contiguous nonce run
        from its lowest pooled nonce, merged across senders by price
        (descending), nonce order preserved within a sender."""
        runs = []
        for sender, slot in self._by_sender.items():
            nonces = sorted(slot)
            run = [slot[nonces[0]]]
            for prev, cur in zip(nonces, nonces[1:]):
                if cur != prev + 1:
                    break
                run.append(slot[cur])
            runs.append(run)
        # price-greedy merge: repeatedly take the head with the best price
        out: List[Transaction] = []
        heads = [(run, 0) for run in runs]
        while heads and (limit is None or len(out) < limit):
            best = max(range(len(heads)),
                       key=lambda i: heads[i][0][heads[i][1]].gas_price)
            run, idx = heads[best]
            out.append(run[idx])
            if idx + 1 < len(run):
                heads[best] = (run, idx + 1)
            else:
                heads.pop(best)
        return out

    def queued_count(self) -> int:
        """Transactions parked behind nonce gaps."""
        total = 0
        for slot in self._by_sender.values():
            nonces = sorted(slot)
            run = 1
            for prev, cur in zip(nonces, nonces[1:]):
                if cur != prev + 1:
                    break
                run += 1
            total += len(nonces) - run
        return total

    def known_count(self) -> int:
        return len(self._hashes)

    def take_pending(self, limit: Optional[int] = None) -> List[Transaction]:
        """Pop the current pending selection for inclusion in a collation
        (the reference drops mined txs from the pool on block events)."""
        out = self.pending(limit)
        self.remove(out)
        return out

    def remove(self, txs: List[Transaction]) -> None:
        for tx in txs:
            tx_hash = bytes(tx.hash())
            if tx_hash not in self._hashes:
                continue
            self._hashes.discard(tx_hash)
            # admission-time sender cache: the removal hot path
            # (take_pending per collation) must not re-run recovery —
            # per tx that would be a fresh backend dispatch each
            sender = self._senders.pop(tx_hash, None)
            if sender is None:
                sender = self._sender_of(tx)
            slot = self._by_sender.get(sender)
            if slot is not None:
                slot.pop(tx.nonce, None)
                if not slot:
                    del self._by_sender[sender]
        self.m_known.set(len(self._hashes))

    # -- journal (core/tx_journal.go) --------------------------------------

    def _journal(self, tx: Transaction) -> None:
        try:
            with open(self.journal_path, "ab") as fh:
                blob = tx.encode_rlp()
                fh.write(len(blob).to_bytes(4, "big") + blob)
        except OSError as exc:
            self.record_error(f"journal write failed: {exc}")

    def _replay_journal(self) -> None:
        """Reload journaled transactions on restart (rotate semantics:
        invalid/duplicate entries are dropped silently, like the
        reference's journal.load device)."""
        try:
            with open(self.journal_path, "rb") as fh:
                raw = fh.read()
        except FileNotFoundError:
            return
        except OSError as exc:
            self.record_error(f"journal read failed: {exc}")
            return
        offset, replayed = 0, 0
        while offset + 4 <= len(raw):
            size = int.from_bytes(raw[offset:offset + 4], "big")
            blob = raw[offset + 4:offset + 4 + size]
            if len(blob) < size:
                break  # torn tail from a crash mid-write
            offset += 4 + size
            try:
                self._admit(Transaction.decode_rlp(blob))
                replayed += 1
            except (TxPoolError, Exception):
                continue
        if replayed:
            self.log.info("replayed %d journaled transactions", replayed)

    # -- simulation mode (sharding/txpool/service.go parity) ---------------

    def _make_test_tx(self) -> Transaction:
        self._nonce += 1
        return Transaction(
            nonce=self._nonce,
            gas_limit=0,
            payload=os.urandom(self.payload_size),
        )

    def _send_test_transactions(self) -> None:
        while not self.wait(self.simulate_interval):
            try:
                self.submit(self._make_test_tx())
            except TxPoolError as exc:
                self.record_error(f"test tx rejected: {exc}")
