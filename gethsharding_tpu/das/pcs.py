"""KZG-style polynomial commitments over bn256 for DAS multiproofs.

**Why.** The merkle sample proofs of `das/proofs.py` cost
m × depth × 32 bytes per sampled collation and verify with host keccak
— the one high-volume verification path that bypasses the bn256
pairing machinery this repo accelerates. A polynomial commitment turns
the same m sampled chunks into ONE constant-size opening proof (a
single G1 point) verified by one two-pair pairing check — exactly the
shape `ops/bn256_jax.bls_verify_aggregate_batch` batches across
collations ("Polynomial Multiproofs for Scalable Data Availability
Sampling in Blockchain Light Clients"; the constant-size batched-check
structure follows the 2G2T verifier).

**The scheme.** A collation's extended chunks become field elements
``v_i = keccak256(chunk_i) mod N`` — evaluations of a degree-<n
polynomial p over the domain x_i = i. The commitment is C = [p(τ)]₁
under a structured reference string of powers of a secret τ. A
multiproof for an index set S is π = [q(τ)]₁ where
``q(x) = (p(x) − r(x)) / z_S(x)``, r interpolating the claimed evals
over S and z_S(x) = ∏_{i∈S}(x − x_i) the vanishing polynomial. The
verifier checks

    e(C − [r(τ)]₁, H) · e(−π, [z_S(τ)]₂) == 1

with [r(τ)]₁ / [z_S(τ)]₂ computed by honest MSMs over the SRS — one
G1 proof regardless of m. `verify_multi` here is THE scalar
differential reference; `das/poly_proofs.py` marshals batches of rows
onto the jitted pairing kernel, bit-identical by construction.

**Trust model (dev SRS).** τ is derived from a keccak chain over an
env-pinned seed (``GETHSHARDING_DAS_SRS_SEED``), so every node in a
devnet derives the SAME SRS — and τ is public, which is fine for a
development/benchmarking curve model but means a malicious prover
could forge openings. A production deployment substitutes a ceremony
SRS file; the verifier code below never uses τ (honest MSMs only), so
only `dev_srs`/the prover shortcut would change. The prover-side
shortcut (evaluate at the known τ, one scalar mult) produces
bit-identical group elements to the honest MSM — group elements are
canonical — and keeps publish cheap in pure python.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from gethsharding_tpu.crypto import bn256
from gethsharding_tpu.crypto.bn256 import (G1_GEN, G2_GEN, N, G1Point,
                                           G2Point, g1_add, g1_is_on_curve,
                                           g1_mul, g1_neg, g2_add, g2_mul,
                                           pairing_check)
from gethsharding_tpu.crypto.keccak import keccak256

# one uncompressed G1 point: 32-byte x || 32-byte y (all-zero = infinity).
# THE constant the proof-size comparison vs merkle paths is about.
G1_BYTES = 64
PROOF_BYTES = G1_BYTES

# SRS shape defaults: G1 powers cover every polynomial a ≤255-chunk
# erasure extension commits to (degree ≤ 254); G2 powers cover the
# vanishing polynomial of the largest index set one multiproof may
# open (das/service.MAX_SAMPLE_INDICES = 64 → degree ≤ 64).
MAX_SRS_DEGREE = 255
MAX_MULTIPROOF_INDICES = 64

DEFAULT_SRS_SEED = "gethsharding-dev-srs"
_SRS_DOMAIN = b"gethsharding-das-srs:"


def chunk_value(chunk: bytes) -> int:
    """A chunk's field element: keccak of the full chunk bytes reduced
    into the bn256 scalar field. The polynomial's evaluation at the
    chunk's own index — so a multiproof over fetched chunks proves the
    DATA, not just proposer-known scalars."""
    return int.from_bytes(keccak256(bytes(chunk)), "big") % N


# -- the structured reference string ----------------------------------------


@dataclass(frozen=True)
class SRS:
    """Powers of τ: g1_powers[i] = [τ^i]₁, g2_powers[j] = [τ^j]₂.

    `tau` is carried ONLY for the dev-setup prover shortcut; the
    verifier path touches the power tables exclusively."""

    seed: str
    tau: int
    g1_powers: Tuple[G1Point, ...]
    g2_powers: Tuple[G2Point, ...]

    @property
    def max_degree(self) -> int:
        return len(self.g1_powers) - 1

    @property
    def max_set(self) -> int:
        return len(self.g2_powers) - 1


@functools.lru_cache(maxsize=4)
def _dev_srs(seed: str, degree: int, max_set: int) -> SRS:
    tau = int.from_bytes(
        keccak256(_SRS_DOMAIN + seed.encode("utf-8")), "big") % N
    if tau == 0:  # pragma: no cover - a keccak output of exactly kN
        tau = 1
    g1_powers: List[G1Point] = []
    g2_powers: List[G2Point] = []
    acc = 1
    for i in range(degree + 1):
        g1_powers.append(g1_mul(acc, G1_GEN))
        if i <= max_set:
            g2_powers.append(g2_mul(acc, G2_GEN))
        acc = (acc * tau) % N
    return SRS(seed=seed, tau=tau, g1_powers=tuple(g1_powers),
               g2_powers=tuple(g2_powers))


def dev_srs() -> SRS:
    """The process-wide deterministic dev SRS.

    ``GETHSHARDING_DAS_SRS_SEED`` pins the τ derivation seed (every
    node of a devnet must agree or no proof verifies across nodes);
    ``GETHSHARDING_DAS_SRS_SIZE`` overrides the G1 power count for
    experiments with larger domains. Cached per (seed, shape)."""
    seed = os.environ.get("GETHSHARDING_DAS_SRS_SEED", DEFAULT_SRS_SEED)
    degree = int(os.environ.get("GETHSHARDING_DAS_SRS_SIZE",
                                str(MAX_SRS_DEGREE)))
    return _dev_srs(seed, degree, MAX_MULTIPROOF_INDICES)


# -- scalar-field polynomial helpers (mod N) --------------------------------


def _inv(a: int) -> int:
    return pow(a % N, N - 2, N)


def eval_poly(coeffs: Sequence[int], x: int) -> int:
    """Horner evaluation of a coefficient-form polynomial mod N."""
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % N
    return acc


def vanishing_coeffs(xs: Sequence[int]) -> List[int]:
    """Coefficients of z_S(x) = ∏ (x − x_i), low-order first."""
    coeffs = [1]
    for x in xs:
        nxt = [0] * (len(coeffs) + 1)
        for i, c in enumerate(coeffs):
            nxt[i + 1] = (nxt[i + 1] + c) % N
            nxt[i] = (nxt[i] - c * x) % N
        coeffs = nxt
    return coeffs


def lagrange_coeffs(xs: Sequence[int], ys: Sequence[int]) -> List[int]:
    """Coefficient form of the unique degree-<m interpolation of
    (x_i, y_i), low-order first. O(m²) — m ≤ MAX_MULTIPROOF_INDICES."""
    m = len(xs)
    coeffs = [0] * m
    for i in range(m):
        # numerator ∏_{j≠i}(x − x_j) built by synthetic division of the
        # full vanishing polynomial is numerically touchy mod N only if
        # done with floats; exact integer division of polynomials works
        # but the direct product is just as cheap at m ≤ 64
        basis = [1]
        denom = 1
        for j in range(m):
            if j == i:
                continue
            nxt = [0] * (len(basis) + 1)
            for k, c in enumerate(basis):
                nxt[k + 1] = (nxt[k + 1] + c) % N
                nxt[k] = (nxt[k] - c * xs[j]) % N
            basis = nxt
            denom = (denom * (xs[i] - xs[j])) % N
        scale = (ys[i] * _inv(denom)) % N
        for k, c in enumerate(basis):
            coeffs[k] = (coeffs[k] + c * scale) % N
    return coeffs


def eval_from_values(values: Sequence[int], x: int) -> int:
    """p(x) for the polynomial defined BY ITS EVALUATIONS values[i] at
    domain points i = 0..n−1 (the chunk-row representation): full-
    domain Lagrange with factorial denominators, O(n)."""
    n = len(values)
    if n == 0:
        return 0
    # prefix[i] = ∏_{j<i}(x−j), suffix[i] = ∏_{j>i}(x−j)
    prefix = [1] * (n + 1)
    for j in range(n):
        prefix[j + 1] = (prefix[j] * (x - j)) % N
    suffix = [1] * (n + 1)
    for j in range(n - 1, -1, -1):
        suffix[j] = (suffix[j + 1] * (x - j)) % N
    fact = [1] * n
    for i in range(1, n):
        fact[i] = (fact[i - 1] * i) % N
    acc = 0
    for i in range(n):
        num = (prefix[i] * suffix[i + 1]) % N
        denom = (fact[i] * fact[n - 1 - i]) % N
        if (n - 1 - i) & 1:
            denom = (-denom) % N
        acc = (acc + values[i] * num % N * _inv(denom)) % N
    return acc


# -- group helpers ----------------------------------------------------------


def g1_msm(scalars: Sequence[int], points: Sequence[G1Point]) -> G1Point:
    """Σ scalars[i]·points[i] — the honest-verifier MSM over SRS
    powers (no τ). Plain double-and-add per term: m ≤ 65 terms."""
    acc: G1Point = None
    for s, p in zip(scalars, points):
        acc = g1_add(acc, g1_mul(s % N, p))
    return acc


def g2_msm(scalars: Sequence[int], points: Sequence[G2Point]) -> G2Point:
    acc: G2Point = None
    for s, p in zip(scalars, points):
        acc = g2_add(acc, g2_mul(s % N, p))
    return acc


def g1_to_bytes(p: G1Point) -> bytes:
    """Uncompressed wire form: x‖y big-endian, all-zero = infinity."""
    if p is None:
        return b"\x00" * G1_BYTES
    return int(p[0]).to_bytes(32, "big") + int(p[1]).to_bytes(32, "big")


def g1_from_bytes(raw: bytes) -> G1Point:
    """Decode `g1_to_bytes`; raises ValueError on wrong length,
    out-of-range coordinates, or an off-curve point (infinity OK)."""
    raw = bytes(raw)
    if len(raw) != G1_BYTES:
        raise ValueError(f"G1 wire point must be {G1_BYTES} bytes")
    x = int.from_bytes(raw[:32], "big")
    y = int.from_bytes(raw[32:], "big")
    if x == 0 and y == 0:
        return None
    if x >= bn256.P or y >= bn256.P:
        raise ValueError("G1 coordinate out of field range")
    point = (x, y)
    if not g1_is_on_curve(point):
        raise ValueError("G1 wire point not on curve")
    return point


# -- commit / open / verify -------------------------------------------------


def commit(values: Sequence[int], srs: Optional[SRS] = None) -> G1Point:
    """C = [p(τ)]₁ for the polynomial with evaluations `values` over
    0..n−1. Dev-setup shortcut: evaluate at the known τ and do ONE
    scalar mult — bit-identical to the honest coefficient MSM because
    group elements are canonical."""
    srs = srs or dev_srs()
    if len(values) > srs.max_degree + 1:
        raise ValueError(f"{len(values)} evaluations exceed SRS degree "
                         f"{srs.max_degree}")
    return g1_mul(eval_from_values([v % N for v in values], srs.tau), G1_GEN)


def open_multi(values: Sequence[int], indices: Sequence[int],
               srs: Optional[SRS] = None) -> Tuple[G1Point, List[int]]:
    """The multiproof for index set `indices`: (π, evals). π is ONE G1
    point whatever len(indices) is. Dev shortcut: q(τ) computed from
    the known τ (q is a polynomial, so q(τ) = (p(τ)−r(τ))/z_S(τ) —
    the division is exact in the field because z_S | p−r)."""
    srs = srs or dev_srs()
    xs = [int(i) for i in indices]
    if len(set(xs)) != len(xs):
        raise ValueError("duplicate indices in multiproof set")
    if any(not 0 <= x < len(values) for x in xs):
        raise ValueError("multiproof index outside the evaluation domain")
    if len(xs) > srs.max_set:
        raise ValueError(f"{len(xs)} indices exceed SRS multiproof cap "
                         f"{srs.max_set}")
    vals = [v % N for v in values]
    evals = [vals[x] for x in xs]
    if not xs:
        return None, []
    p_tau = eval_from_values(vals, srs.tau)
    r_tau = eval_poly(lagrange_coeffs(xs, evals), srs.tau)
    z_tau = 1
    for x in xs:
        z_tau = (z_tau * (srs.tau - x)) % N
    q_tau = ((p_tau - r_tau) * _inv(z_tau)) % N
    return g1_mul(q_tau, G1_GEN), evals


def check_shape(indices: Sequence[int], evals: Sequence[int],
                n: int, srs: SRS) -> bool:
    """The multiproof row's domain preconditions — shared verbatim by
    the scalar reference and the batch marshal so rejection is
    bit-identical by construction. False for: empty set (proves
    nothing, like an empty committee), ragged evals, duplicate or
    out-of-domain indices, evals outside the field, sets beyond the
    SRS cap, domains beyond the SRS degree."""
    try:
        xs = [int(i) for i in indices]
        es = [int(e) for e in evals]
        n = int(n)
    except (TypeError, ValueError):
        return False
    if not xs or len(xs) != len(es):
        return False
    if len(xs) > srs.max_set or len(set(xs)) != len(xs):
        return False
    if not 1 <= n <= srs.max_degree + 1:
        return False
    if any(not 0 <= x < n for x in xs):
        return False
    if any(not 0 <= e < N for e in es):
        return False
    return True


def verify_multi(commitment: G1Point, indices: Sequence[int],
                 evals: Sequence[int], proof: G1Point, n: int,
                 srs: Optional[SRS] = None) -> bool:
    """THE scalar differential reference: does `proof` open
    `commitment` to `evals` at `indices` over a degree-<n domain?

    Honest verifier — τ never consulted: [r(τ)]₁ and [z_S(τ)]₂ are
    MSMs over the SRS power tables, then one two-pair check
    e(C − R, H)·e(−π, Z) == 1. Malformed inputs (bad shapes, off-curve
    points) are False, never an exception — a hostile proof must cost
    a verdict, not a batch."""
    srs = srs or dev_srs()
    if not check_shape(indices, evals, n, srs):
        return False
    xs = [int(i) for i in indices]
    es = [int(e) for e in evals]
    try:
        r_point = g1_msm(lagrange_coeffs(xs, es), srs.g1_powers)
        z_point = g2_msm(vanishing_coeffs(xs), srs.g2_powers)
        a_point = g1_add(commitment, g1_neg(r_point))
        return pairing_check([(a_point, G2_GEN), (g1_neg(proof), z_point)])
    except (ValueError, TypeError):
        # off-curve / out-of-subgroup inputs raise inside the pairing;
        # the row is hostile, the verdict is False
        return False
