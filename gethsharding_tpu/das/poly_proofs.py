"""Batched DAS multiproof verification: scalar truth + fixed-shape planes.

The `das_verify_multiproofs` SigBackend op. One ROW is one sampled
collation in a period: a 64-byte G1 commitment, the sampled index set,
the claimed chunk-value evaluations, ONE 64-byte G1 multiproof, and
the collation's domain size n. The verdict is `pcs.verify_multi` —
does e(C − [r(τ)]₁, H)·e(−π, [z_S(τ)]₂) == 1.

`verify_multiproofs` is the scalar batch face
(`PythonSigBackend.das_verify_multiproofs`) and THE differential
reference. `marshal_multiproofs` folds each row's interpolation and
vanishing MSMs host-side into three group points per row —
A = C − [r(τ)]₁ (G1), π (G1), Z = [z_S(τ)]₂ (G2) — exactly the
(sig, H, pk) slots of the already-jitted two-pair kernel
`ops/bn256_jax.bls_verify_aggregate_batch`, which computes
e(sig, G2_GEN)·e(−H, pk) == 1. No new kernel, no new compile shapes.

Bit-identity with the scalar path is BY CONSTRUCTION, the same way
`das/proofs.py` does it: every scalar rejection (bad shapes, undecodable
or off-curve wire points) becomes `valid=False` at marshal time, and
the rare degenerate rows the pairing kernel cannot represent (A, π, or
Z at infinity — e.g. a constant polynomial's zero quotient) are
resolved host-side with the scalar verifier itself, substituting a
trivially-true pairing row when the scalar verdict is True.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from gethsharding_tpu.crypto.bn256 import G2_GEN, G1_GEN, g1_add, g1_neg
from gethsharding_tpu.das import pcs

# re-exported caps: the service/sampler size their index sets by these
MAX_MULTIPROOF_INDICES = pcs.MAX_MULTIPROOF_INDICES
PROOF_BYTES = pcs.PROOF_BYTES


def verify_multiproof(commitment: bytes, indices: Sequence[int],
                      evals: Sequence[int], proof: bytes, n: int,
                      srs: Optional[pcs.SRS] = None) -> bool:
    """One row verdict from wire-form (64-byte) G1 points. THE
    reference semantics: undecodable points are False, never raise."""
    srs = srs or pcs.dev_srs()
    try:
        c_point = pcs.g1_from_bytes(commitment)
        p_point = pcs.g1_from_bytes(proof)
    except (TypeError, ValueError):
        return False
    return pcs.verify_multi(c_point, indices, evals, p_point, n, srs)


def verify_multiproofs(commitments: Sequence[bytes],
                       index_rows: Sequence[Sequence[int]],
                       eval_rows: Sequence[Sequence[int]],
                       proofs: Sequence[bytes],
                       ns: Sequence[int]) -> List[bool]:
    """The scalar batch face (`PythonSigBackend.das_verify_multiproofs`)."""
    srs = pcs.dev_srs()
    return [verify_multiproof(c, idx, ev, pf, n, srs)
            for c, idx, ev, pf, n
            in zip(commitments, index_rows, eval_rows, proofs, ns)]


def marshal_multiproofs(commitments: Sequence[bytes],
                        index_rows: Sequence[Sequence[int]],
                        eval_rows: Sequence[Sequence[int]],
                        proofs: Sequence[bytes],
                        ns: Sequence[int], bucket: int) -> dict:
    """Rows -> the pairing kernel's fixed (bucket, ...) limb planes.

    Host side per row: decode the two wire points, run the row's
    interpolation MSM [r(τ)]₁ and vanishing MSM [z_S(τ)]₂ over the SRS
    power tables, and fold A = C − [r(τ)]₁. The device then checks
    e(A, G2_GEN)·e(−π, Z) == 1 for the whole bucket in one dispatch.

    Planes: px/py = π limbs (the kernel's H slot, negated on device),
    ax/ay = A limbs (sig slot), zx/zy = Z limbs (pk slot), valid, rows.
    """
    # lazy: scalar users of this module must never pull in jax
    from gethsharding_tpu.ops.bn256_jax import g1_to_limbs, g2_to_limbs

    srs = pcs.dev_srs()
    rows = len(commitments)
    a_points = [None] * bucket
    p_points = [None] * bucket
    z_points = [None] * bucket
    valid = [False] * bucket
    for b in range(rows):
        indices = index_rows[b]
        evals = eval_rows[b]
        if not pcs.check_shape(indices, evals, ns[b], srs):
            continue
        try:
            c_point = pcs.g1_from_bytes(commitments[b])
            p_point = pcs.g1_from_bytes(proofs[b])
        except (TypeError, ValueError):
            continue
        xs = [int(i) for i in indices]
        es = [int(e) for e in evals]
        r_point = pcs.g1_msm(pcs.lagrange_coeffs(xs, es), srs.g1_powers)
        z_point = pcs.g2_msm(pcs.vanishing_coeffs(xs), srs.g2_powers)
        a_point = g1_add(c_point, g1_neg(r_point))
        if a_point is None or p_point is None or z_point is None:
            # a point at infinity has no affine limb form; the scalar
            # pairing skips such pairs, so resolve the row host-side
            # and ship either a trivially-true pairing or valid=False
            if pcs.verify_multi(c_point, xs, es, p_point, ns[b], srs):
                a_point, p_point, z_point = G1_GEN, G1_GEN, G2_GEN
            else:
                continue
        a_points[b] = a_point
        p_points[b] = p_point
        z_points[b] = z_point
        valid[b] = True
    ax, ay, aok = g1_to_limbs(a_points)
    px, py, pok = g1_to_limbs(p_points)
    zx, zy, zok = g2_to_limbs(z_points)
    return {"px": px, "py": py, "ax": ax, "ay": ay, "zx": zx, "zy": zy,
            "valid": aok & pok & zok & valid, "rows": rows}
