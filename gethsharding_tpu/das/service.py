"""DASService: the actor-facing face of data-availability sampling.

One Service, three roles, mirroring how `storage/netstore.py` fronts
the chunk plane:

- **publisher** (proposer side): `publish()` erasure-extends a freshly
  created collation body, files every extended chunk into the local
  chunk store under its content address (so parity chunks are ordinary
  netstore chunks any peer can pull), builds the commitment tree, and
  signs the commitment with the node key — the binding between the
  on-chain chunk_root and the off-chain DAS root is the proposer's
  signature, the same key that signed the header;
- **server**: answers `DASCommitmentRequest` / `DASampleRequest` from
  peers out of the published state (chunk + sibling path per sampled
  index);
- **fetcher** (notary / light side): `fetch_commitment()` and
  `fetch_samples()` broadcast, poll, and RETRY under the resilience
  policy executors (each attempt re-broadcasts — a lost frame costs a
  capped backoff, not the availability verdict), with the
  ``das.commitment_fetch`` / ``das.sample_fetch`` / ``das.parity_publish``
  chaos seams fired per attempt so `--chaos` specs cover the new paths.
  `collect_rows()` is the notary's one-stop: commitment + deterministic
  sample indices (`sampler.py`) + fetched (chunk, proof) rows shaped
  for ONE batched `das_verify_samples` dispatch across shards.
  `prefetch_commitments()` fires the commitment broadcasts for a whole
  candidate set up front so the per-shard fetches find parked
  responses instead of paying a round trip each.

Trust model (stated, not hidden): sample verdicts prove the sampled
chunks are consistent with the PROPOSER-SIGNED das_root; a proposer
that commits to a das_root inconsistent with its on-chain chunk_root
is detected by any full node that reconstructs (the standard DAS
honest-proposer-or-fraud-proof posture — `sampler.py` documents the
withholding side). Only solicited responses are accepted, and a
sample response is admitted only after its proof VERIFIES against the
requested das_root (the netstore content-verified-delivery rule), so
a hostile peer can waste a request — or a counter — but can neither
grow state it was not asked for nor shadow an honest peer's answer
with garbage. Commitment responses, which can only be validated
against the on-chain record the fetcher holds, are parked in a small
per-key list for the same reason: a forged frame arriving first must
not evict the genuine one.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from gethsharding_tpu import metrics, tracing
from gethsharding_tpu.actors.base import Service
from gethsharding_tpu.crypto import secp256k1 as ecdsa
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.das.erasure import (DAS_CHUNK_SIZE, MAX_TOTAL_CHUNKS,
                                          extend_body)
from gethsharding_tpu.das import pcs
from gethsharding_tpu.das.poly_proofs import verify_multiproof
from gethsharding_tpu.das.proofs import (MAX_PROOF_DEPTH, chunk_leaf,
                                         merkle_levels, merkle_proof,
                                         verify_sample)
from gethsharding_tpu.das.sampler import sample_indices, sample_seed
from gethsharding_tpu.p2p.messages import (DASCommitmentRequest,
                                           DASCommitmentResponse,
                                           DASMultiproofRequest,
                                           DASMultiproofResponse,
                                           DASampleRequest, DASampleResponse)
from gethsharding_tpu.resilience.errors import FetchAborted, TransientError
from gethsharding_tpu.resilience.policy import (DEFAULT_RETRYABLE,
                                                POLL_MISS, RetryExecutor,
                                                RetryPolicy, poll_probe)
from gethsharding_tpu.storage.chunker import ChunkStore

# the chaos seam prefix the node CLI wires for --da-mode=sampled specs
CHAOS_SEAMS = ("das.commitment_fetch", "das.sample_fetch",
               "das.parity_publish", "das.multiproof_fetch")

# the supported --da-proofs modes: merkle sibling paths (PR 6) or one
# constant-size polynomial multiproof per sampled collation (das/pcs.py)
PROOF_MODES = ("merkle", "poly")

# per-request index cap at the serving side: an unauthenticated request
# stream must not turn one frame into unbounded proof work
MAX_SAMPLE_INDICES = 64

# commitment responses parked per (shard, period) while the fetcher
# polls: >1 so a forged frame cannot shadow the genuine one, small so
# a flooding peer cannot grow state
MAX_PARKED_COMMITMENTS = 4

_COMMIT_DOMAIN = b"gethsharding-das-commit:"


class _CommitmentMiss(TransientError):
    """No peer delivered the commitment within one fetch attempt."""


class _SampleMiss(TransientError):
    """Sampled chunks still missing after one fetch attempt."""


class _MultiproofMiss(TransientError):
    """No verified multiproof response within one fetch attempt."""


@dataclass(frozen=True)
class DASCommitment:
    """The proposer's published extension commitment for one
    (shard, period) collation."""

    shard_id: int
    period: int
    chunk_root: bytes
    das_root: bytes
    k: int
    n: int
    body_len: int
    # 64-byte G1 polynomial commitment (das/pcs.py) in --da-proofs=poly
    # mode; empty in merkle-only mode. Signed into the same digest, and
    # the digest of a merkle-only commitment is BIT-IDENTICAL to the
    # pre-poly wire format (appending zero bytes appends nothing).
    poly_commitment: bytes = b""
    signature: bytes = b""

    def digest(self) -> bytes:
        return commitment_digest(self.shard_id, self.period,
                                 self.chunk_root, self.das_root,
                                 self.k, self.n, self.body_len,
                                 self.poly_commitment)


def commitment_digest(shard_id: int, period: int, chunk_root: bytes,
                      das_root: bytes, k: int, n: int, body_len: int,
                      poly_commitment: bytes = b"") -> bytes:
    """What the proposer signs: every field of the commitment, bound to
    the on-chain chunk_root, under a DAS domain tag."""
    return keccak256(_COMMIT_DOMAIN
                     + int(shard_id).to_bytes(8, "big")
                     + int(period).to_bytes(8, "big")
                     + bytes(chunk_root) + bytes(das_root)
                     + int(k).to_bytes(2, "big")
                     + int(n).to_bytes(2, "big")
                     + int(body_len).to_bytes(8, "big")
                     + bytes(poly_commitment))


def _poly_commitment_ok(poly_commitment: bytes) -> bool:
    """Empty (merkle-only publisher) or a decodable on-curve 64-byte G1
    point — a commitment carrying undecodable poly bytes is rejected
    outright, before it can poison a multiproof fetch."""
    if not poly_commitment:
        return True
    try:
        pcs.g1_from_bytes(poly_commitment)
    except (TypeError, ValueError):
        return False
    return True


def verify_commitment(commitment: DASCommitment, proposer) -> bool:
    """The proposer's signature must recover to the record's proposer —
    the same authorship check the header signature carries."""
    try:
        sig = ecdsa.Signature.from_bytes65(bytes(commitment.signature))
        recovered = ecdsa.ecrecover_address(commitment.digest(), sig)
    except (ValueError, AssertionError):
        return False
    return recovered is not None and recovered == proposer


class DASService(Service):
    """Publish / serve / fetch DAS commitments and sampled chunks."""

    name = "das"
    supervisable = True

    def __init__(self, client=None, p2p=None,
                 store: Optional[ChunkStore] = None,
                 parity_ratio: float = 0.5,
                 samples: int = 16,
                 chaos=None,
                 poll_interval: float = 0.02,
                 fetch_timeout: float = 3.0,
                 fetch_attempts: int = 3,
                 proof_mode: str = "merkle"):
        super().__init__()
        if proof_mode not in PROOF_MODES:
            raise ValueError(f"unknown DAS proof mode {proof_mode!r}; "
                             f"choose from {PROOF_MODES}")
        self.client = client
        self.p2p = p2p
        self.proof_mode = proof_mode
        # the parity-publish sink: extended chunks are filed here under
        # their content address, so a node that ALSO runs a NetStore on
        # the same store serves them over the ordinary chunk protocol
        self.store = store if store is not None else ChunkStore()
        self.parity_ratio = parity_ratio
        self.samples = samples
        self.chaos = chaos
        self.poll_interval = poll_interval
        self.fetch_timeout = fetch_timeout
        self._attempt_timeout = fetch_timeout / max(1, fetch_attempts)
        # the default transient set PLUS this layer's own miss signals:
        # a chaos InjectedFault (ConnectionError) at the das.* seams
        # rides the same retry-then-succeed ladder as a real lost frame
        self._fetch_retry = RetryExecutor(
            "das", RetryPolicy(attempts=max(1, fetch_attempts),
                               base_s=poll_interval, cap_s=0.25,
                               deadline_s=fetch_timeout,
                               retryable=DEFAULT_RETRYABLE))
        # published state (server side)
        self._blobs: Dict[bytes, tuple] = {}   # das_root -> (xb, levels)
        self._poly: Dict[bytes, list] = {}     # das_root -> chunk values
        self._commitments: Dict[Tuple[int, int], DASCommitment] = {}
        # fetched state (fetcher side); solicited-only admission
        self._want_commitments: set = set()    # (shard, period)
        self._want_samples: set = set()        # (das_root, index)
        # (das_root, indices) -> (poly_commitment, n) while a
        # multiproof fetch is in flight — the pump verifies responses
        # against exactly what was solicited
        self._want_multi: Dict[tuple, tuple] = {}
        self._recv_commitments: Dict[tuple, list] = {}
        self._recv_samples: Dict[tuple, tuple] = {}
        self._recv_multi: Dict[tuple, tuple] = {}
        self._lock = threading.Lock()
        self._subs = []
        # counters (the /status `das` namespace + Prometheus rows)
        self.m_published = metrics.counter("das/published")
        self.m_samples_served = metrics.counter("das/samples_served")
        self.m_samples_fetched = metrics.counter("das/samples_fetched")
        self.m_sample_wire_bytes = metrics.counter("das/sample_wire_bytes")
        self.m_samples_verified = metrics.counter("das/samples_verified")
        self.m_sample_failures = metrics.counter("das/sample_failures")
        self.m_commitments_rejected = metrics.counter(
            "das/commitments_rejected")
        self.m_samples_rejected = metrics.counter("das/samples_rejected")
        self.m_multiproofs_served = metrics.counter(
            "das/multiproofs_served")
        self.m_multiproofs_fetched = metrics.counter(
            "das/multiproofs_fetched")
        self.m_multiproofs_rejected = metrics.counter(
            "das/multiproofs_rejected")
        self.bytes_fetched = 0

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        if self.p2p is None:
            return  # local-only: publish/serve in-process (tests, RPC)
        self.p2p.start()
        handlers = ((DASCommitmentRequest, self._on_commitment_request),
                    (DASampleRequest, self._on_sample_request),
                    (DASCommitmentResponse, self._on_commitment_response),
                    (DASampleResponse, self._on_sample_response),
                    (DASMultiproofRequest, self._on_multiproof_request),
                    (DASMultiproofResponse, self._on_multiproof_response))
        for kind, handler in handlers:
            sub = self.p2p.subscribe(kind)
            self._subs.append(sub)
            self.spawn(self._pump(sub, handler),
                       name=f"das-{kind.__name__}")

    def on_stop(self) -> None:
        for sub in self._subs:
            sub.unsubscribe()
        self._subs = []

    def _pump(self, sub, handler):
        def loop() -> None:
            while not self.stopped():
                try:
                    msg = sub.get(timeout=self.poll_interval)
                except Exception:
                    continue
                try:
                    handler(msg)
                except Exception as exc:  # noqa: BLE001 - hostile frames
                    # must cost a counter, never the pump thread
                    self.record_error(f"das handler failed: {exc}")
        return loop

    def _fire(self, seam: str) -> None:
        if self.chaos is not None:
            self.chaos.fire(seam)

    # -- publisher side ----------------------------------------------------

    def publish(self, shard_id: int, period: int, chunk_root,
                body: bytes) -> DASCommitment:
        """Extend `body`, file every extended chunk into the chunk
        store (parity chunks become ordinary netstore chunks), build
        and sign the commitment, and start serving both. The proposer
        calls this right after `save_collation`."""
        with tracing.span("das/publish", shard=shard_id, period=period):
            self._fire("das.parity_publish")
            xb = extend_body(bytes(body), parity_ratio=self.parity_ratio)
            levels = merkle_levels([chunk_leaf(c) for c in xb.chunks])
            das_root = levels[-1][0]
            for chunk in xb.chunks:
                self.store.put_chunk(DAS_CHUNK_SIZE, chunk)
            poly_commitment = b""
            values = None
            if self.proof_mode == "poly":
                # the chunk values ARE the polynomial's evaluations;
                # the 64-byte commitment rides the same signed digest
                values = [pcs.chunk_value(c) for c in xb.chunks]
                poly_commitment = pcs.g1_to_bytes(pcs.commit(values))
            digest = commitment_digest(shard_id, period, bytes(chunk_root),
                                       das_root, xb.k, xb.n, xb.body_len,
                                       poly_commitment)
            signature = (self.client.sign(digest)
                         if self.client is not None else b"")
            commitment = DASCommitment(
                shard_id=shard_id, period=period,
                chunk_root=bytes(chunk_root), das_root=das_root,
                k=xb.k, n=xb.n, body_len=xb.body_len,
                poly_commitment=poly_commitment, signature=signature)
            with self._lock:
                self._blobs[das_root] = (xb, levels)
                if values is not None:
                    self._poly[das_root] = values
                self._commitments[(shard_id, period)] = commitment
            self.m_published.inc()
            return commitment

    def commitment(self, shard_id: int,
                   period: int) -> Optional[DASCommitment]:
        with self._lock:
            return self._commitments.get((shard_id, period))

    # -- server side -------------------------------------------------------

    def _on_commitment_request(self, msg) -> None:
        req: DASCommitmentRequest = msg.data
        commitment = self.commitment(int(req.shard_id), int(req.period))
        if commitment is None:
            return  # not ours to serve; another peer may hold it
        self.p2p.send(DASCommitmentResponse(
            shard_id=commitment.shard_id, period=commitment.period,
            chunk_root=commitment.chunk_root,
            das_root=commitment.das_root, k=commitment.k,
            n=commitment.n, body_len=commitment.body_len,
            poly_commitment=commitment.poly_commitment,
            signature=commitment.signature), msg.peer)

    def _on_sample_request(self, msg) -> None:
        req: DASampleRequest = msg.data
        with self._lock:
            blob = self._blobs.get(bytes(req.das_root))
        if blob is None:
            return
        xb, levels = blob
        for index in list(req.indices)[:MAX_SAMPLE_INDICES]:
            index = int(index)
            if not 0 <= index < xb.n:
                continue
            self.p2p.send(DASampleResponse(
                das_root=bytes(req.das_root), index=index,
                chunk=xb.chunks[index],
                proof=merkle_proof(levels, index)), msg.peer)
            self.m_samples_served.inc()

    def _on_multiproof_request(self, msg) -> None:
        req: DASMultiproofRequest = msg.data
        root = bytes(req.das_root)
        with self._lock:
            blob = self._blobs.get(root)
            values = self._poly.get(root)
        if blob is None or values is None:
            return  # not ours to serve, or published merkle-only
        xb, _levels = blob
        indices = tuple(int(i) for i in
                        list(req.indices)[:MAX_SAMPLE_INDICES])
        if (not indices or len(set(indices)) != len(indices)
                or any(not 0 <= i < xb.n for i in indices)):
            return  # malformed request costs the requester its answer
        proof, _evals = pcs.open_multi(values, indices)
        self.p2p.send(DASMultiproofResponse(
            das_root=root, indices=indices,
            chunks=tuple(xb.chunks[i] for i in indices),
            proof=pcs.g1_to_bytes(proof)), msg.peer)
        self.m_multiproofs_served.inc()

    # -- fetcher side ------------------------------------------------------

    def _on_commitment_response(self, msg) -> None:
        # parked raw until the fetcher validates it against the record
        # — only the fetcher knows the expected proposer/chunk_root.
        # A bounded LIST per key, not a slot: a forged frame that wins
        # the race must not evict the honest one behind it.
        resp: DASCommitmentResponse = msg.data
        key = (int(resp.shard_id), int(resp.period))
        with self._lock:
            if key not in self._want_commitments:
                return  # unsolicited
            parked = self._recv_commitments.setdefault(key, [])
            if len(parked) < MAX_PARKED_COMMITMENTS:
                parked.append(resp)

    def _on_sample_response(self, msg) -> None:
        resp: DASampleResponse = msg.data
        key = (bytes(resp.das_root), int(resp.index))
        with self._lock:
            if key not in self._want_samples or key in self._recv_samples:
                return  # unsolicited, or already answered
        chunk = bytes(resp.chunk)
        proof = tuple(bytes(s) for s in resp.proof)
        if (len(chunk) > DAS_CHUNK_SIZE or len(proof) > MAX_PROOF_DEPTH
                or not verify_sample(key[0], key[1], chunk, proof)):
            # content-verified delivery (the netstore admission rule):
            # a garbage frame is dropped HERE — outside the lock, the
            # proof check is ~129 keccaks — so it can never occupy the
            # slot an honest peer's answer needs. The verdict the
            # batched op later computes for admitted rows is therefore
            # True by construction for delivered samples; False rows
            # come from withheld (never-delivered) indices.
            self.m_samples_rejected.inc()
            return
        with self._lock:
            if (key not in self._want_samples
                    or key in self._recv_samples):
                return  # answered while we were verifying (first wins)
            self._recv_samples[key] = (chunk, proof)
        self.m_samples_fetched.inc()
        self.m_sample_wire_bytes.inc(len(chunk) + 32 * len(proof) + 40)
        self.bytes_fetched += len(chunk) + 32 * len(proof) + 40

    def _on_multiproof_response(self, msg) -> None:
        resp: DASMultiproofResponse = msg.data
        root = bytes(resp.das_root)
        indices = tuple(int(i) for i in resp.indices)
        key = (root, indices)
        with self._lock:
            want = self._want_multi.get(key)
            if want is None or key in self._recv_multi:
                return  # unsolicited, or already answered
        poly_commitment, n = want
        chunks = tuple(bytes(c) for c in resp.chunks)
        proof = bytes(resp.proof)
        # content-verified delivery, multiproof edition: the response
        # is admitted only if the single proof OPENS the solicited poly
        # commitment to the delivered chunks' derived values. The check
        # is the scalar PCS verifier — one host pairing per admitted
        # response, the same cost class as a scalar bls_verify — so a
        # garbage frame can never occupy the slot an honest answer
        # needs (first VERIFIED wins).
        if (len(chunks) != len(indices)
                or any(len(c) != DAS_CHUNK_SIZE for c in chunks)
                or not verify_multiproof(
                    poly_commitment, indices,
                    [pcs.chunk_value(c) for c in chunks], proof, n)):
            self.m_multiproofs_rejected.inc()
            return
        with self._lock:
            if key not in self._want_multi or key in self._recv_multi:
                return  # answered while we were verifying (first wins)
            self._recv_multi[key] = (chunks, proof)
        self.m_multiproofs_fetched.inc()
        wire = sum(len(c) for c in chunks) + len(proof) + 40
        self.m_sample_wire_bytes.inc(wire)
        self.bytes_fetched += wire

    def fetch_commitment(self, shard_id: int, period: int, chunk_root,
                         proposer) -> Optional[DASCommitment]:
        """The validated commitment for (shard, period): local first,
        then the network under the retry policy. A response only
        lands if its chunk_root matches the ON-CHAIN record, its shape
        is sane, and its signature recovers to the record's proposer."""
        key = (int(shard_id), int(period))
        local = self.commitment(shard_id, period)
        if local is not None:
            with self._lock:  # clear any prefetch leftovers for the key
                self._want_commitments.discard(key)
                self._recv_commitments.pop(key, None)
            return local
        if self.p2p is None or self.stopped():
            return None
        expected_root = bytes(chunk_root)

        def take() -> DASCommitment:
            with self._lock:
                parked = self._recv_commitments.pop(key, None)
            if not parked:
                raise _CommitmentMiss("no response yet")
            # validate every parked response; the FIRST VALID one wins,
            # so a forged frame that won the race costs nothing
            rejected = 0
            for resp in parked:
                commitment = DASCommitment(
                    shard_id=key[0], period=key[1],
                    chunk_root=bytes(resp.chunk_root),
                    das_root=bytes(resp.das_root), k=int(resp.k),
                    n=int(resp.n), body_len=int(resp.body_len),
                    poly_commitment=bytes(
                        getattr(resp, "poly_commitment", b"")),
                    signature=bytes(resp.signature))
                if (commitment.chunk_root != expected_root
                        or not 1 <= commitment.k <= commitment.n
                        or commitment.n > MAX_TOTAL_CHUNKS
                        or not 0 <= commitment.body_len
                        <= commitment.k * DAS_CHUNK_SIZE
                        or not _poly_commitment_ok(
                            commitment.poly_commitment)
                        or not verify_commitment(commitment, proposer)):
                    rejected += 1
                    continue
                if rejected:
                    self.m_commitments_rejected.inc(rejected)
                with self._lock:
                    self._commitments[key] = commitment
                return commitment
            self.m_commitments_rejected.inc(rejected)
            self.record_error(
                f"rejected DAS commitment for shard {shard_id} "
                f"period {period}: binding/signature check failed")
            raise _CommitmentMiss("rejected response")

        def attempt() -> DASCommitment:
            self._fire("das.commitment_fetch")
            self.p2p.broadcast(DASCommitmentRequest(shard_id=key[0],
                                                    period=key[1]))
            got = poll_probe(
                take, self.wait, interval_s=self.poll_interval,
                polls=max(1, int(self._attempt_timeout
                                 / self.poll_interval)),
                not_ready=(_CommitmentMiss,))
            if got is POLL_MISS:
                raise _CommitmentMiss(
                    f"DAS commitment for shard {shard_id} period "
                    f"{period} not delivered")
            return got

        with self._lock:
            self._want_commitments.add(key)
        try:
            return self._fetch_retry.call(attempt)
        except (TransientError, FetchAborted, ConnectionError,
                TimeoutError, OSError):
            return None
        finally:
            with self._lock:
                self._want_commitments.discard(key)
                self._recv_commitments.pop(key, None)

    def prefetch_commitments(self, pairs) -> None:
        """Fire-and-forget commitment requests for many (shard, period)
        pairs at once: registers the want keys and broadcasts, so the
        responses park while the caller does other work and the later
        per-pair `fetch_commitment` finds them without paying a round
        trip each — the sampled notary's analog of the full-fetch
        path's overlapped body prefetch. Never blocks, never raises."""
        if self.p2p is None or self.stopped():
            return
        wanted = []
        with self._lock:
            for shard_id, period in pairs:
                key = (int(shard_id), int(period))
                if key not in self._commitments:
                    self._want_commitments.add(key)
                    wanted.append(key)
        for key in wanted:
            try:
                self.p2p.broadcast(DASCommitmentRequest(shard_id=key[0],
                                                        period=key[1]))
            except Exception:  # noqa: BLE001 - best-effort warmup only
                return

    def fetch_samples(self, commitment: DASCommitment,
                      indices) -> Dict[int, tuple]:
        """(chunk, proof) per requested index, fetched from peers under
        the retry policy (each attempt re-broadcasts the still-missing
        subset). Missing entries mean no peer answered in time — the
        caller scores them as failed samples."""
        indices = [int(i) for i in indices]
        root = bytes(commitment.das_root)
        # locally published blobs answer without a network round trip
        with self._lock:
            blob = self._blobs.get(root)
        if blob is not None:
            xb, levels = blob
            return {i: (xb.chunks[i], merkle_proof(levels, i))
                    for i in indices if 0 <= i < xb.n}
        if self.p2p is None or self.stopped() or not indices:
            return {}
        keys = {(root, i) for i in indices}

        def missing() -> list:
            with self._lock:
                return [i for i in indices
                        if (root, i) not in self._recv_samples]

        def complete() -> bool:
            if missing():
                raise _SampleMiss("samples still missing")
            return True

        def attempt() -> None:
            self._fire("das.sample_fetch")
            still = missing()
            if not still:
                return
            self.p2p.broadcast(DASampleRequest(das_root=root,
                                               indices=tuple(still)))
            got = poll_probe(
                complete, self.wait, interval_s=self.poll_interval,
                polls=max(1, int(self._attempt_timeout
                                 / self.poll_interval)),
                not_ready=(_SampleMiss,))
            if got is POLL_MISS:
                raise _SampleMiss(
                    f"{len(missing())} of {len(indices)} DAS samples "
                    f"not delivered")

        with self._lock:
            self._want_samples.update(keys)
        try:
            self._fetch_retry.call(attempt)
        except (TransientError, FetchAborted, ConnectionError,
                TimeoutError, OSError):
            pass  # partial results are still results: caller scores them
        finally:
            with self._lock:
                self._want_samples.difference_update(keys)
                out = {i: self._recv_samples.pop((root, i))
                       for i in indices
                       if (root, i) in self._recv_samples}
        return out

    def fetch_multiproof(self, commitment: DASCommitment,
                         indices) -> Optional[tuple]:
        """(chunks, proof) for the sampled `indices` under one
        constant-size multiproof, fetched from peers under the retry
        policy. Responses are verified against the commitment's poly
        commitment BEFORE admission (content-verified delivery), so a
        returned tuple is already proven; None means no peer delivered
        a verifying answer in time."""
        indices = tuple(int(i) for i in indices)
        if not indices:
            return None
        root = bytes(commitment.das_root)
        # locally published blobs answer without a network round trip
        with self._lock:
            blob = self._blobs.get(root)
            values = self._poly.get(root)
        if blob is not None and values is not None:
            xb, _levels = blob
            if any(not 0 <= i < xb.n for i in indices):
                return None
            proof, _evals = pcs.open_multi(values, indices)
            return (tuple(xb.chunks[i] for i in indices),
                    pcs.g1_to_bytes(proof))
        if (self.p2p is None or self.stopped()
                or not commitment.poly_commitment):
            return None
        key = (root, indices)

        def take() -> tuple:
            with self._lock:
                got = self._recv_multi.get(key)
            if got is None:
                raise _MultiproofMiss("no verified response yet")
            return got

        def attempt() -> tuple:
            self._fire("das.multiproof_fetch")
            self.p2p.broadcast(DASMultiproofRequest(das_root=root,
                                                    indices=indices))
            got = poll_probe(
                take, self.wait, interval_s=self.poll_interval,
                polls=max(1, int(self._attempt_timeout
                                 / self.poll_interval)),
                not_ready=(_MultiproofMiss,))
            if got is POLL_MISS:
                raise _MultiproofMiss(
                    f"DAS multiproof for {len(indices)} indices "
                    f"not delivered")
            return got

        with self._lock:
            self._want_multi[key] = (bytes(commitment.poly_commitment),
                                     int(commitment.n))
        try:
            return self._fetch_retry.call(attempt)
        except (TransientError, FetchAborted, ConnectionError,
                TimeoutError, OSError):
            return None
        finally:
            with self._lock:
                self._want_multi.pop(key, None)
                self._recv_multi.pop(key, None)

    # -- the notary-side one-stop ------------------------------------------

    def collect_rows(self, shard_id: int, period: int, record,
                     account) -> Optional[dict]:
        """Everything one (shard, period) availability check needs, as
        rows for the batched `das_verify_samples` op: the validated
        commitment, the notary's deterministic sample indices, and the
        fetched (chunk, proof) per index — a missing sample becomes a
        synthesized invalid row so it SCORES as a failed check instead
        of silently shrinking k. None = no commitment (unavailable)."""
        with tracing.span("das/collect", shard=shard_id, period=period):
            commitment = self.fetch_commitment(
                shard_id, period, record.chunk_root, record.proposer)
            if commitment is None:
                return None
            indices = sample_indices(
                sample_seed(bytes(account), shard_id, period,
                            commitment.das_root),
                self.samples, commitment.n)
            got = self.fetch_samples(commitment, indices)
            chunks, proofs = [], []
            for i in indices:
                chunk, proof = got.get(i, (b"", ()))
                chunks.append(chunk)
                proofs.append(proof)
            return {"chunks": chunks, "indices": indices,
                    "proofs": proofs,
                    "roots": [commitment.das_root] * len(indices),
                    "commitment": commitment}

    def collect_poly_row(self, shard_id: int, period: int, record,
                         account) -> Optional[dict]:
        """The --da-proofs=poly analog of `collect_rows`: ONE row of
        the batched `das_verify_multiproofs` op per (shard, period) —
        the validated commitment, the notary's deterministic sample
        indices, the chunk-derived evaluations, and the single
        constant-size proof. A failed fetch (or a merkle-only
        commitment) becomes a synthesized invalid row (empty proof)
        so it SCORES as a failed check. None = no commitment."""
        with tracing.span("das/collect_poly", shard=shard_id,
                          period=period):
            commitment = self.fetch_commitment(
                shard_id, period, record.chunk_root, record.proposer)
            if commitment is None:
                return None
            indices = sample_indices(
                sample_seed(bytes(account), shard_id, period,
                            commitment.das_root),
                self.samples, commitment.n)
            got = self.fetch_multiproof(commitment, indices)
            if got is None:
                chunks: tuple = ()
                evals = [0] * len(indices)
                proof = b""
            else:
                chunks, proof = got
                evals = [pcs.chunk_value(c) for c in chunks]
            return {"poly_commitment": commitment.poly_commitment,
                    "indices": list(indices), "evals": evals,
                    "proof": proof, "n": commitment.n,
                    "chunks": chunks, "commitment": commitment}

    def note_verdicts(self, verdicts) -> int:
        """Score one batch's verdicts into the das counters; returns
        the number of failures."""
        ok = sum(1 for v in verdicts if v)
        bad = len(list(verdicts)) - ok
        if ok:
            self.m_samples_verified.inc(ok)
        if bad:
            self.m_sample_failures.inc(bad)
        return bad

    # -- RPC / light-client serving ----------------------------------------

    def get_sample(self, shard_id: int, period: int,
                   index: int) -> Optional[dict]:
        """One locally held sample (the `shard_getSample` body), or
        None when this node never published/held the blob."""
        commitment = self.commitment(shard_id, period)
        if commitment is None:
            return None
        with self._lock:
            blob = self._blobs.get(bytes(commitment.das_root))
        if blob is None or not 0 <= int(index) < commitment.n:
            return None
        xb, levels = blob
        index = int(index)
        return {"commitment": commitment, "index": index,
                "chunk": xb.chunks[index],
                "proof": merkle_proof(levels, index)}

    def get_multiproof(self, shard_id: int, period: int,
                       indices) -> Optional[dict]:
        """The locally held multiproof plane (the `shard_getSample`
        poly body): all requested chunks + ONE 64-byte proof. None
        when this node never published the blob in poly mode or any
        index is out of range."""
        commitment = self.commitment(shard_id, period)
        if commitment is None:
            return None
        indices = tuple(int(i) for i in
                        list(indices)[:MAX_SAMPLE_INDICES])
        if (not indices or len(set(indices)) != len(indices)
                or any(not 0 <= i < commitment.n for i in indices)):
            return None
        with self._lock:
            blob = self._blobs.get(bytes(commitment.das_root))
            values = self._poly.get(bytes(commitment.das_root))
        if blob is None or values is None:
            return None
        xb, _levels = blob
        proof, _evals = pcs.open_multi(values, indices)
        self.m_multiproofs_served.inc()
        return {"commitment": commitment, "indices": list(indices),
                "chunks": [xb.chunks[i] for i in indices],
                "proof": pcs.g1_to_bytes(proof)}

    def da_status(self, shard_id: int, period: int) -> dict:
        """The `shard_daStatus` body: is a commitment known for the
        pair, and what shape is the extension?"""
        commitment = self.commitment(shard_id, period)
        if commitment is None:
            return {"known": False, "shard_id": shard_id,
                    "period": period}
        with self._lock:
            holds_blob = bytes(commitment.das_root) in self._blobs
        return {"known": True, "shard_id": shard_id, "period": period,
                "das_root": commitment.das_root.hex(),
                "chunk_root": bytes(commitment.chunk_root).hex(),
                "k": commitment.k, "n": commitment.n,
                "body_len": commitment.body_len,
                "holds_blob": holds_blob,
                "proof_mode": self.proof_mode,
                "poly_commitment": commitment.poly_commitment.hex(),
                "default_samples": self.samples}
