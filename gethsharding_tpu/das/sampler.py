"""Deterministic sample selection + the soundness accounting behind k.

**Selection.** A notary's sample indices for one (shard, period) are a
pure function of (its account, the shard, the period, the DAS root):
keccak-chained draws without replacement. Deterministic on purpose —
a vote can be audited by replaying the exact indices the notary was
obliged to check, a crashed notary resumes the same check, and tests
are seedable. The classic objection (a withholding proposer could
precompute a known notary's indices and serve exactly those) is
accounted for in the soundness model below rather than hidden:
per-checker unpredictability is the LIGHT-client posture
(`actors/light.py` draws a fresh random seed per check); committee
soundness rests on the adversary having to satisfy EVERY sampled
committee member at once, and the committee itself is sampled by the
SMC from the mainchain blockhash AFTER the header lands — the
proposer commits to the blob before it learns who will check it.

**Soundness.** The erasure code (`erasure.py`) forces an adversary who
wants the body unrecoverable to withhold at least n-k_data+1 of the n
extended chunks (fewer and any k_data survivors reconstruct). The
best such adversary withholds exactly that minimum, leaving
a = k_data-1 available chunks. One checker sampling s distinct uniform
indices misses every withheld chunk with probability
C(a, s)/C(n, s) = prod_{i<s} (a-i)/(n-i); q independent checkers all
miss with that to the q-th power. `detection_probability` computes the
complement; `soundness_table` renders the README table that justifies
the default k.
"""

from __future__ import annotations

from typing import List, Sequence

from gethsharding_tpu.crypto.keccak import keccak256

_DOMAIN = b"gethsharding-das-sample:"


def sample_seed(account: bytes, shard_id: int, period: int,
                das_root: bytes) -> bytes:
    """The per-(notary, shard, period, blob) selection seed."""
    return keccak256(_DOMAIN + bytes(account)
                     + int(shard_id).to_bytes(8, "big")
                     + int(period).to_bytes(8, "big") + bytes(das_root))


def sample_indices(seed: bytes, k: int, n: int) -> List[int]:
    """k distinct indices in [0, n), drawn by keccak chain from `seed`.

    Returns them sorted (the fetch order; verification is per-row and
    order-independent). k >= n degenerates to checking every chunk."""
    if n <= 0:
        return []
    if k >= n:
        return list(range(n))
    picked: set = set()
    digest = seed
    counter = 0
    while len(picked) < k:
        digest = keccak256(digest + counter.to_bytes(4, "big"))
        # 8 independent 4-byte draws per squeeze; modulo bias over a
        # u32 range is < 2^-24 for n <= 255 — irrelevant next to the
        # soundness bounds this feeds
        for off in range(0, 32, 4):
            picked.add(int.from_bytes(digest[off:off + 4], "big") % n)
            if len(picked) >= k:
                break
        counter += 1
    return sorted(picked)


def detection_probability(samples: int, n: int, k_data: int,
                          checkers: int = 1) -> float:
    """P(withholding detected): the minimal unrecoverability adversary
    withholds n-k_data+1 chunks; `checkers` independent samplers each
    check `samples` distinct chunks."""
    if n <= 0 or k_data <= 0 or k_data > n:
        raise ValueError(f"bad shape n={n} k_data={k_data}")
    available = k_data - 1
    samples = min(samples, n)
    miss_one = 1.0
    for i in range(samples):
        if available - i <= 0:
            miss_one = 0.0
            break
        miss_one *= (available - i) / (n - i)
    return 1.0 - miss_one ** max(1, checkers)


def proof_bytes(samples: int, mode: str = "merkle") -> int:
    """Proof bytes ONE checker pulls for `samples` sampled chunks
    (chunk payload excluded — both modes carry the same chunk bytes).
    Merkle: a sibling path per sample (<= MAX_PROOF_DEPTH 32-byte
    hashes). Poly (`--da-proofs=poly`, das/pcs.py): ONE 64-byte
    multiproof point covering the whole index set — constant in the
    sample count, which is the entire point of the scheme."""
    from gethsharding_tpu.das.pcs import PROOF_BYTES
    from gethsharding_tpu.das.proofs import MAX_PROOF_DEPTH

    if mode == "merkle":
        return int(samples) * MAX_PROOF_DEPTH * 32
    if mode == "poly":
        return PROOF_BYTES if samples > 0 else 0
    raise ValueError(f"unknown proof mode {mode!r}")


def soundness_table(n: int, k_data: int,
                    ks: Sequence[int] = (4, 8, 16, 32),
                    checkers: int = 1) -> List[dict]:
    """Rows for the README soundness table: k vs detection probability
    (per checker and, when `checkers` > 1, for the committee), plus
    the (samples, proof-bytes, detection) trade-off per proof mode —
    the table that shows poly mode buys more samples per wire byte."""
    rows = []
    for k in ks:
        row = {"k": k,
               "p_detect": detection_probability(k, n, k_data),
               "merkle_proof_bytes": proof_bytes(k, "merkle"),
               "poly_proof_bytes": proof_bytes(k, "poly")}
        if checkers > 1:
            row["p_detect_committee"] = detection_probability(
                k, n, k_data, checkers=checkers)
        rows.append(row)
    return rows
