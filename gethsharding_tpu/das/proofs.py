"""DAS commitments and sample proofs: scalar truth + fixed-shape planes.

**The commitment.** An extended blob's DAS root is the root of a
binary keccak merkle tree whose leaves are the blob's NETSTORE CHUNK
KEYS — `chunk_key(span, chunk) = keccak256(span_le8 || bmt_root(chunk))`
from `storage/chunker.py`. That choice is the "parity chunks commit
through the existing chunker + bmt roots" requirement made literal:
the DAS leaf for a chunk is the same 32-byte address the storage tier
files it under, so a sampled chunk fetched from ANY surface (DAS
sample response, raw netstore delivery, local store) verifies against
the same commitment, and the per-chunk half of a sample proof IS the
storage tier's BMT structure.

**A sample proof** for chunk i is just the merkle sibling path from
leaf i to the DAS root (<= MAX_PROOF_DEPTH siblings; n <= 255 chunks
caps the padded tree at 256 leaves). The verifier recomputes the leaf
from the chunk bytes — 127 keccaks of BMT tree + 1 key derivation —
then folds the path. That recompute is the accelerator-friendly half
of the pipeline (the zkSpeed observation): `verify_samples` is the
scalar differential reference; `marshal_samples` + `batch_verifier`
are the fixed-shape planes and the batched kernel the jax sig backend
dispatches, keccak lanes `vmap`-shaped over samples × shards.

Scalar and batched verdicts are bit-identical BY CONSTRUCTION: every
malformed-row rejection the scalar path takes is computed host-side
into the `valid` plane at marshal time, and the device kernel computes
exactly the well-formed case.
"""

from __future__ import annotations

import struct
from typing import List, Sequence, Tuple

import numpy as np

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.das.erasure import DAS_CHUNK_SIZE
from gethsharding_tpu.storage.bmt import SEGMENT_COUNT, SEGMENT_SIZE
from gethsharding_tpu.storage.chunker import chunk_key

# n <= erasure.MAX_TOTAL_CHUNKS = 255 -> padded tree of <= 256 leaves.
# Proofs longer than this are invalid by protocol, in BOTH backends.
MAX_PROOF_DEPTH = 8

ZERO_LEAF = b"\x00" * 32

_SPAN_PREFIX = struct.pack("<Q", DAS_CHUNK_SIZE)


def chunk_leaf(chunk: bytes) -> bytes:
    """A DAS tree leaf: the netstore address of one full-size chunk."""
    return chunk_key(DAS_CHUNK_SIZE, chunk)


# -- the commitment tree ----------------------------------------------------


def merkle_levels(leaves: Sequence[bytes]) -> List[List[bytes]]:
    """All levels of the commitment tree, leaves padded to a power of
    two with ZERO_LEAF (levels[0] = padded leaves, levels[-1][0] =
    root)."""
    level = [bytes(leaf) for leaf in leaves] or [ZERO_LEAF]
    size = 1
    while size < len(level):
        size *= 2
    level = level + [ZERO_LEAF] * (size - len(level))
    levels = [level]
    while len(level) > 1:
        level = [keccak256(level[i] + level[i + 1])
                 for i in range(0, len(level), 2)]
        levels.append(level)
    return levels


def merkle_root(leaves: Sequence[bytes]) -> bytes:
    return merkle_levels(leaves)[-1][0]


def merkle_proof(levels: List[List[bytes]], index: int) -> Tuple[bytes, ...]:
    """Sibling path leaf->root for leaf `index` of a `merkle_levels`
    tree (empty tuple for the single-leaf tree)."""
    if not 0 <= index < len(levels[0]):
        raise ValueError(f"leaf {index} out of range")
    path = []
    for level in levels[:-1]:
        path.append(level[index ^ 1])
        index >>= 1
    return tuple(path)


# -- scalar verification (the differential reference) -----------------------


def verify_sample(root: bytes, index: int, chunk: bytes,
                  proof: Sequence[bytes]) -> bool:
    """One sample verdict, scalar host keccak. THE reference semantics:
    the batched backends must agree with this bit-for-bit on every
    input, malformed ones included."""
    root = bytes(root)
    chunk = bytes(chunk)
    try:
        index = int(index)
    except (TypeError, ValueError):
        return False
    if len(root) != 32 or len(chunk) != DAS_CHUNK_SIZE:
        return False
    if index < 0 or len(proof) > MAX_PROOF_DEPTH:
        return False
    if index >> len(proof):
        return False  # the claimed index lies outside the proven tree
    siblings = [bytes(s) for s in proof]
    if any(len(s) != 32 for s in siblings):
        return False
    node = chunk_leaf(chunk)
    for level, sibling in enumerate(siblings):
        if (index >> level) & 1:
            node = keccak256(sibling + node)
        else:
            node = keccak256(node + sibling)
    return node == root


def verify_samples(chunks: Sequence[bytes], indices: Sequence[int],
                   proofs: Sequence[Sequence[bytes]],
                   roots: Sequence[bytes]) -> List[bool]:
    """The scalar batch face (`PythonSigBackend.das_verify_samples`)."""
    return [verify_sample(root, index, chunk, proof)
            for chunk, index, proof, root
            in zip(chunks, indices, proofs, roots)]


# -- fixed-shape planes for the batched backend -----------------------------


def marshal_samples(chunks: Sequence[bytes], indices: Sequence[int],
                    proofs: Sequence[Sequence[bytes]],
                    roots: Sequence[bytes], bucket: int) -> dict:
    """Rows -> fixed (bucket, ...) uint8/bool planes.

    Every scalar-path rejection (wrong chunk size, bad index, long or
    malformed proof) becomes `valid[b] = False` HERE, so the device
    kernel only ever computes the well-formed case and the verdicts
    stay bit-identical to `verify_samples`."""
    n = len(chunks)
    chunk_plane = np.zeros((bucket, DAS_CHUNK_SIZE), dtype=np.uint8)
    sib_plane = np.zeros((bucket, MAX_PROOF_DEPTH, 32), dtype=np.uint8)
    bit_plane = np.zeros((bucket, MAX_PROOF_DEPTH), dtype=bool)
    lvl_plane = np.zeros((bucket, MAX_PROOF_DEPTH), dtype=bool)
    root_plane = np.zeros((bucket, 32), dtype=np.uint8)
    valid = np.zeros((bucket,), dtype=bool)
    for b in range(n):
        chunk = bytes(chunks[b])
        root = bytes(roots[b])
        proof = [bytes(s) for s in proofs[b]]
        try:
            index = int(indices[b])
        except (TypeError, ValueError):
            continue
        if (len(chunk) != DAS_CHUNK_SIZE or len(root) != 32
                or index < 0 or len(proof) > MAX_PROOF_DEPTH
                or index >> len(proof)
                or any(len(s) != 32 for s in proof)):
            continue
        chunk_plane[b] = np.frombuffer(chunk, dtype=np.uint8)
        for level, sibling in enumerate(proof):
            sib_plane[b, level] = np.frombuffer(sibling, dtype=np.uint8)
            bit_plane[b, level] = bool((index >> level) & 1)
            lvl_plane[b, level] = True
        root_plane[b] = np.frombuffer(root, dtype=np.uint8)
        valid[b] = True
    return {"chunks": chunk_plane, "sibs": sib_plane, "bits": bit_plane,
            "levels": lvl_plane, "roots": root_plane, "valid": valid,
            "rows": n}


def _build_batch_fn():
    """The jitted (bucket-shaped) kernel. Lazy: scalar users of this
    module must never trigger a JAX backend init."""
    import jax
    import jax.numpy as jnp

    from gethsharding_tpu.ops.keccak_jax import keccak256_fixed

    span = np.frombuffer(_SPAN_PREFIX, dtype=np.uint8)
    bmt_levels = SEGMENT_COUNT.bit_length() - 1  # 128 segments -> 7

    def verify(chunk_plane, sib_plane, bit_plane, lvl_plane, root_plane,
               valid):
        B = chunk_plane.shape[0]
        # BMT of each full chunk: 128 leaf keccaks then 7 perfectly
        # balanced pair levels — the batch-first form of storage/bmt's
        # recursion for exactly-CHUNK_SIZE chunks (the only size DAS
        # chunks come in)
        nodes = keccak256_fixed(
            chunk_plane.reshape(B, SEGMENT_COUNT, SEGMENT_SIZE))
        for _ in range(bmt_levels):
            nodes = keccak256_fixed(jnp.concatenate(
                [nodes[:, 0::2], nodes[:, 1::2]], axis=-1))
        bmt_root = nodes[:, 0]  # (B, 32)
        # the netstore address: keccak(span_le8 || bmt_root)
        node = keccak256_fixed(jnp.concatenate(
            [jnp.broadcast_to(span, (B, 8)), bmt_root], axis=-1))
        # fold the sibling path; masked levels pass the node through
        for level in range(MAX_PROOF_DEPTH):
            sib = sib_plane[:, level]
            right = bit_plane[:, level][:, None]
            msg = jnp.where(
                right,
                jnp.concatenate([sib, node], axis=-1),
                jnp.concatenate([node, sib], axis=-1))
            digest = keccak256_fixed(msg)
            node = jnp.where(lvl_plane[:, level][:, None], digest, node)
        return valid & jnp.all(node == root_plane, axis=-1)

    return jax.jit(verify)


_BATCH_FN = None


def batch_verifier():
    """The process-wide jitted sample verifier (compiled per bucket
    shape by XLA, like every other batched op)."""
    global _BATCH_FN
    if _BATCH_FN is None:
        _BATCH_FN = _build_batch_fn()
    return _BATCH_FN
