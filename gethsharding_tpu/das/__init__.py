"""Data-availability sampling (DAS): erasure-coded collation bodies,
sampled-chunk proofs, and the wiring that turns the notary's
availability vote from a full-body download into k batched on-device
proof checks.

The phase-1 notary (the reference and our seed) votes availability by
fetching the WHOLE collation body over shardp2p — availability is
bandwidth-bound and the device never sees it. Following "Polynomial
Multiproofs for Scalable Data Availability Sampling in Blockchain
Light Clients" (PAPERS.md), this package replaces that workload shape:

- ``erasure``  — systematic Reed–Solomon extension of bodies over
  GF(2^8), chunk-aligned to the 4096-byte storage chunk so parity
  chunks are ordinary netstore chunks (content-addressed through the
  existing ``storage/chunker`` + ``storage/bmt`` key derivation), with
  decode-from-any-k recovery;
- ``sampler``  — seeded deterministic per-(notary, shard, period)
  sample-index selection plus the soundness accounting (withholding-
  detection probability as a function of k);
- ``proofs``   — the DAS commitment (a binary merkle tree over the
  extended blob's chunk keys), scalar sample-proof verification (the
  differential reference), and the fixed-shape plane marshalling the
  batched ``das_verify_samples`` sig-backend op dispatches through
  ``sigbackend``/``serving``;
- ``service``  — the actor-facing ``DASService``: proposers extend and
  publish, notaries in ``--da-mode=sampled`` fetch only k
  chunks+proofs (retry + chaos seams included), light clients sample
  with scalar verification, and the ``shard_getSample`` /
  ``shard_daStatus`` RPC surface serves from it.
"""

from gethsharding_tpu.das.erasure import (  # noqa: F401
    DAS_CHUNK_SIZE,
    ErasureError,
    ExtendedBody,
    MAX_TOTAL_CHUNKS,
    extend_body,
    recover_body,
    rs_decode,
    rs_encode,
)
from gethsharding_tpu.das.proofs import (  # noqa: F401
    MAX_PROOF_DEPTH,
    chunk_leaf,
    merkle_levels,
    merkle_proof,
    merkle_root,
    verify_sample,
)
from gethsharding_tpu.das.sampler import (  # noqa: F401
    detection_probability,
    sample_indices,
    sample_seed,
    soundness_table,
)

__all__ = [
    "DAS_CHUNK_SIZE",
    "ErasureError",
    "ExtendedBody",
    "MAX_PROOF_DEPTH",
    "MAX_TOTAL_CHUNKS",
    "chunk_leaf",
    "detection_probability",
    "extend_body",
    "merkle_levels",
    "merkle_proof",
    "merkle_root",
    "recover_body",
    "rs_decode",
    "rs_encode",
    "sample_indices",
    "sample_seed",
    "soundness_table",
    "verify_sample",
]
