"""Systematic Reed–Solomon erasure extension of collation bodies.

The DAS design needs one property from the code: a body split into k
data chunks, extended with m parity chunks, must be reconstructible
from ANY k of the n = k+m chunks — then a withholding proposer has to
suppress at least m+1 chunks to make the body unrecoverable, and a
sampler that hits any suppressed chunk detects it (`sampler.py` does
the probability accounting).

The code is the classic byte-wise systematic RS over GF(2^8)
(primitive polynomial 0x11d, the QR/RAID-6 field): the generator is a
Vandermonde matrix over n distinct field points re-based so its top
k×k block is the identity — data chunks pass through verbatim (the
systematic property netstore depends on: a data chunk IS a body
slice), and every k×k submatrix of the re-based generator stays
invertible (the any-k recovery property), because it is a product of
Vandermonde submatrices with distinct evaluation points. Encoding and
decoding are table-lookup numpy over whole 4096-byte chunk rows, so
the host cost is O(m·k) vectorized chunk combines, not per-byte python.

Chunk alignment is deliberate: DAS chunks are exactly the storage
tier's `CHUNK_SIZE` (storage/chunker.py), so a parity chunk is an
ordinary content-addressed netstore chunk — published, fetched and
integrity-checked through the machinery that already exists; the DAS
commitment (`proofs.py`) merklizes the same `chunk_key` derivation the
store uses.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence

import numpy as np

from gethsharding_tpu.storage.chunker import CHUNK_SIZE

DAS_CHUNK_SIZE = CHUNK_SIZE  # 4096: DAS chunks ARE storage chunks
# GF(2^8) Vandermonde needs n distinct field points: n <= 256. One short
# of that keeps every point's log defined (we use points 0..n-1 and the
# re-based generator, so 256 would be fine too — 255 is just a clean
# safety margin that also bounds commitment trees to depth 8).
MAX_TOTAL_CHUNKS = 255

_GF_POLY = 0x11D


class ErasureError(Exception):
    pass


# -- GF(2^8) tables ---------------------------------------------------------

_GF_EXP = np.zeros(512, dtype=np.uint8)
_GF_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    _GF_EXP[_i] = _x
    _GF_LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= _GF_POLY
_GF_EXP[255:510] = _GF_EXP[:255]  # doubled: exp[log a + log b] needs no mod
del _x, _i


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_GF_EXP[int(_GF_LOG[a]) + int(_GF_LOG[b])])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("no inverse of 0 in GF(2^8)")
    return int(_GF_EXP[255 - int(_GF_LOG[a])])


def _mul_row(coeff: int, row: np.ndarray) -> np.ndarray:
    """coeff * row over GF(2^8), vectorized over a whole chunk row."""
    if coeff == 0:
        return np.zeros_like(row)
    if coeff == 1:
        return row.copy()
    log_c = int(_GF_LOG[coeff])
    out = _GF_EXP[_GF_LOG[row] + log_c]
    out[row == 0] = 0  # log(0) is undefined; 0 * x = 0
    return out


def _matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF matrix product of small uint8 matrices (host setup cost)."""
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for i in range(a.shape[0]):
        for j in range(a.shape[1]):
            coeff = int(a[i, j])
            if coeff:
                out[i] ^= _mul_row(coeff, b[j])
    return out


def _mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss–Jordan inverse over GF(2^8); raises on singular input."""
    k = m.shape[0]
    aug = np.concatenate([m.astype(np.uint8),
                          np.eye(k, dtype=np.uint8)], axis=1)
    for col in range(k):
        pivot = next((r for r in range(col, k) if aug[r, col]), None)
        if pivot is None:
            raise ErasureError("singular decode matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        aug[col] = _mul_row(gf_inv(int(aug[col, col])), aug[col])
        for r in range(k):
            if r != col and aug[r, col]:
                aug[r] ^= _mul_row(int(aug[r, col]), aug[col])
    return aug[:, k:]


def _generator(k: int, n: int) -> np.ndarray:
    """The systematic n×k generator: Vandermonde over points 0..n-1,
    re-based by inv(top k rows) so rows 0..k-1 are the identity. Any k
    rows of the result are invertible (Vandermonde submatrix product),
    which is exactly the decode-from-any-k guarantee."""
    if not 1 <= k <= n <= MAX_TOTAL_CHUNKS:
        raise ErasureError(f"bad RS shape k={k} n={n} "
                           f"(need 1 <= k <= n <= {MAX_TOTAL_CHUNKS})")
    vand = np.zeros((n, k), dtype=np.uint8)
    for i in range(n):
        acc = 1
        for j in range(k):
            vand[i, j] = acc
            acc = gf_mul(acc, i)
    return _matmul(vand, _mat_inv(vand[:k]))


_GEN_CACHE: Dict[tuple, np.ndarray] = {}


def _gen(k: int, n: int) -> np.ndarray:
    key = (k, n)
    if key not in _GEN_CACHE:
        _GEN_CACHE[key] = _generator(k, n)
    return _GEN_CACHE[key]


# -- encode / decode --------------------------------------------------------


def rs_encode(data_chunks: Sequence[bytes], parity: int) -> List[bytes]:
    """Extend k equal-length data chunks with `parity` parity chunks;
    returns all n = k + parity chunks (data first — systematic)."""
    k = len(data_chunks)
    if k == 0:
        raise ErasureError("need at least one data chunk")
    size = len(data_chunks[0])
    if any(len(c) != size for c in data_chunks):
        raise ErasureError("data chunks must be equal-length")
    n = k + parity
    gen = _gen(k, n)
    data = np.frombuffer(b"".join(data_chunks),
                         dtype=np.uint8).reshape(k, size)
    out = list(data_chunks)
    for p in range(k, n):
        row = np.zeros(size, dtype=np.uint8)
        for j in range(k):
            coeff = int(gen[p, j])
            if coeff:
                row ^= _mul_row(coeff, data[j])
        out.append(row.tobytes())
    return [bytes(c) for c in out]


def rs_decode(shares: Dict[int, bytes], k: int, n: int) -> List[bytes]:
    """Reconstruct the k data chunks from ANY k of the n extended
    chunks. `shares` maps chunk index (0..n-1) -> chunk bytes; extra
    shares beyond k are ignored (the first k by index are used)."""
    if k < 1 or n < k:
        raise ErasureError(f"bad RS shape k={k} n={n}")
    have = sorted(idx for idx in shares if 0 <= idx < n)
    if len(have) < k:
        raise ErasureError(
            f"unrecoverable: {len(have)} of {n} chunks, need {k}")
    rows = have[:k]
    size = len(shares[rows[0]])
    if any(len(shares[idx]) != size for idx in rows):
        raise ErasureError("shares must be equal-length")
    if rows == list(range(k)):
        return [bytes(shares[i]) for i in rows]  # all data present
    gen = _gen(k, n)
    inv = _mat_inv(gen[rows])
    stacked = np.stack([np.frombuffer(shares[idx], dtype=np.uint8)
                        for idx in rows])
    out = []
    for j in range(k):
        row = np.zeros(size, dtype=np.uint8)
        for i in range(k):
            coeff = int(inv[j, i])
            if coeff:
                row ^= _mul_row(coeff, stacked[i])
        out.append(row.tobytes())
    return out


# -- body-level extension ---------------------------------------------------


@dataclass(frozen=True)
class ExtendedBody:
    """One collation body, erasure-extended to n chunk-aligned chunks.

    ``chunks[:k]`` is the zero-padded body (the systematic half);
    ``chunks[k:]`` are parity. ``body_len`` is the exact original
    length — padding is a storage artifact, never protocol data."""

    chunks: tuple  # tuple[bytes, ...], each exactly DAS_CHUNK_SIZE
    k: int
    n: int
    body_len: int


def extend_body(body: bytes, parity_ratio: float = 0.5) -> ExtendedBody:
    """Pad `body` to k full chunks and extend with ceil(k·ratio) >= 1
    parity chunks. The erasure code runs over FULL storage chunks so
    every extended chunk is an ordinary netstore chunk."""
    import math

    if parity_ratio <= 0:
        raise ErasureError("parity_ratio must be positive")
    body_len = len(body)
    k = max(1, -(-body_len // DAS_CHUNK_SIZE))
    parity = max(1, math.ceil(k * parity_ratio))
    n = k + parity
    if n > MAX_TOTAL_CHUNKS:
        raise ErasureError(
            f"body of {body_len} bytes needs {n} extended chunks; the "
            f"GF(2^8) code caps at {MAX_TOTAL_CHUNKS}")
    padded = body + b"\x00" * (k * DAS_CHUNK_SIZE - body_len)
    data_chunks = [padded[i * DAS_CHUNK_SIZE:(i + 1) * DAS_CHUNK_SIZE]
                   for i in range(k)]
    chunks = rs_encode(data_chunks, parity)
    return ExtendedBody(chunks=tuple(chunks), k=k, n=n, body_len=body_len)


def recover_body(shares: Dict[int, bytes], k: int, n: int,
                 body_len: int) -> bytes:
    """The inverse of `extend_body`: any k of the n chunks -> the exact
    original body (padding stripped by `body_len`)."""
    data = rs_decode(shares, k, n)
    joined = b"".join(data)
    if body_len > len(joined):
        raise ErasureError(
            f"body_len {body_len} exceeds recovered {len(joined)} bytes")
    return joined[:body_len]
