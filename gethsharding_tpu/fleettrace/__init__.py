"""fleettrace: cross-process trace assembly, tail-sampled exemplars,
and critical-path latency attribution.

PR 15 made the serving path multi-process (actor -> frontend router ->
chain_server replicas -> device); the trace envelope already crosses
the RPC wire, but spans died in per-process rings — nobody ever
reassembled a request. This package closes the loop, Dapper-style:

- ``exporter.py``  — each process drains its tracer's finished spans
  (bounded, batched, drop-counted) to the collector, in-proc or over
  ``shard_traceExport`` with a per-connection clock-offset handshake;
- ``collector.py`` — groups spans by trace id into cross-process
  trees, retains full traces from the TAIL (SLO breaches, hedges,
  breaker windows, the top latency quantile, plus a deterministic
  sample), and feeds retained exemplars to the perfwatch flight
  recorder;
- ``critical_path.py`` — walks an assembled tree and attributes
  end-to-end wall time to named segments (wire, frontend route/WFQ,
  replica queue_wait / batch_assembly / device_dispatch, future_wake,
  hedge-wasted duplicate work), aggregated into per-class p50/p99
  tables served by ``shard_traceAttribution``, /status, and
  ``scripts/fleettrace_report.py``.

Two boot shapes, both idempotent and torn down by `shutdown()`:

- `boot_collector()` — this process OWNS assembly (the fleet frontend;
  a single-process node with ``--fleettrace``). Starts the sweep, an
  in-proc exporter for the process's own spans, the SLO breach hook,
  and the flight-recorder exemplar payload.
- `boot_exporter("host:port")` — this process only PRODUCES spans
  (chain_server replicas, actors): ship everything to the collector at
  the endpoint.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from gethsharding_tpu import tracing

_LAZY = {
    "RpcExportSink": ("exporter", "RpcExportSink"),
    "SpanExporter": ("exporter", "SpanExporter"),
    "TraceCollector": ("collector", "TraceCollector"),
    "attribute": ("critical_path", "attribute"),
    "SEGMENTS": ("critical_path", "SEGMENTS"),
}


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    module = importlib.import_module(f"{__name__}.{module_name}")
    value = getattr(module, attr)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


_STATE_LOCK = threading.Lock()
COLLECTOR = None
EXPORTER = None
_SINK = None
_BREACH_HOOK = None


def boot_collector(registry=None, *, export_self: bool = True,
                   start_sweep: bool = True):
    """Own trace assembly in this process. Enables tracing if it is
    off (a collector with no spans is a no-op), wires the SLO breach
    hook, the flight-recorder retention/exemplar hooks, and (by
    default) an in-proc exporter for this process's own spans."""
    global COLLECTOR, EXPORTER
    from gethsharding_tpu import metrics
    from gethsharding_tpu.fleettrace.collector import TraceCollector
    from gethsharding_tpu.fleettrace.exporter import SpanExporter

    with _STATE_LOCK:
        if COLLECTOR is not None:
            return COLLECTOR
        if not tracing.TRACER.enabled:
            tracing.enable()
        collector = TraceCollector(registry or metrics.DEFAULT_REGISTRY)
        if start_sweep:
            collector.start()
        _wire_hooks(collector)
        COLLECTOR = collector
        if export_self and EXPORTER is None:
            EXPORTER = SpanExporter(
                sink=collector.ingest_payload,
                registry=collector.registry,
                label=f"pid{os.getpid()}").start()
        return collector


def boot_exporter(endpoint: str, registry=None, label: Optional[str] = None):
    """Produce spans only: ship this process's spans to the collector
    at ``host:port`` (the fleet frontend). Dial failures are absorbed
    and retried batch-to-batch — replicas boot before the frontend."""
    global EXPORTER, _SINK
    from gethsharding_tpu import metrics
    from gethsharding_tpu.fleettrace.exporter import RpcExportSink, \
        SpanExporter

    with _STATE_LOCK:
        if EXPORTER is not None:
            return EXPORTER
        if not tracing.TRACER.enabled:
            tracing.enable()
        _SINK = RpcExportSink(endpoint)
        EXPORTER = SpanExporter(
            sink=_SINK,
            registry=registry or metrics.DEFAULT_REGISTRY,
            label=label or f"pid{os.getpid()}").start()
        return EXPORTER


def _wire_hooks(collector) -> None:
    """Connect the tail-retention triggers: SLO breach onsets and the
    flight recorder's fatal events mark exemplars; retained traces ride
    into every bundle as ``exemplars.json``."""
    global _BREACH_HOOK
    from gethsharding_tpu import slo
    from gethsharding_tpu.perfwatch import RECORDER

    _BREACH_HOOK = collector.on_breach
    slo.tracker().on_breach(_BREACH_HOOK)
    RECORDER.add_event_hook(collector.on_recorder_event)
    RECORDER.add_payload_provider(
        "exemplars.json", lambda: collector.exemplars(limit=8))


def active():
    """The process's collector, or None — the RPC handlers' guard."""
    return COLLECTOR


def mark_trace(trace_id: Optional[int], reason: str) -> None:
    """Flag a trace for tail retention (no-op without a collector).
    The router's hedge path calls this on the request hot path, so it
    must stay one attribute read when fleettrace is off."""
    collector = COLLECTOR
    if collector is not None:
        collector.mark_trace(trace_id, reason)


def fleettrace_status() -> dict:
    """The /status section: collector + exporter state in one dict."""
    collector, exporter = COLLECTOR, EXPORTER
    out = {"active": collector is not None}
    if collector is not None:
        out.update(collector.status())
    if exporter is not None:
        out["export"] = exporter.stats()
    return out


def shutdown() -> None:
    """Tear down exporter, collector, and every registered hook (tests
    boot and unboot repeatedly in one process)."""
    global COLLECTOR, EXPORTER, _SINK, _BREACH_HOOK
    with _STATE_LOCK:
        exporter, EXPORTER = EXPORTER, None
        sink, _SINK = _SINK, None
        collector, COLLECTOR = COLLECTOR, None
        breach_hook, _BREACH_HOOK = _BREACH_HOOK, None
    if exporter is not None:
        exporter.close()
    if sink is not None:
        sink.close()
    if collector is not None:
        collector.close()
        from gethsharding_tpu import slo
        from gethsharding_tpu.perfwatch import RECORDER

        if breach_hook is not None:
            slo.tracker().remove_breach_hook(breach_hook)
        RECORDER.remove_event_hook(collector.on_recorder_event)
        RECORDER.remove_payload_provider("exemplars.json")


__all__ = [
    "active",
    "boot_collector",
    "boot_exporter",
    "fleettrace_status",
    "mark_trace",
    "shutdown",
    *sorted(_LAZY),
]
