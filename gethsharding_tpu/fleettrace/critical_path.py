"""Critical-path attribution over an assembled cross-process trace.

An assembled trace is a TREE: the RPC trace envelope parents every
handler span under the calling process's client span, the serving
pipeline parents its phase spans under the handler, so a fleet request
(actor -> frontend -> replica -> device) is one connected tree rooted
at the outermost client span. Walking it answers the question metrics
cannot: of the request's end-to-end wall time, how much was wire, how
much frontend routing/WFQ wait, how much replica queue wait vs batch
assembly vs device execution — and how much was duplicate work a hedge
threw away.

The attribution rule is SELF-TIME: each span contributes its duration
minus the union of its children's intervals (clipped to the span, so a
skewed child can't drive a negative), and every self-time lands in a
named segment keyed by the span-name vocabulary the instrumented
layers already emit. Self-times over a tree telescope, so the segment
table sums to the root's duration (small cross-clock skews and
post-parent overhangs like `future_wake` aside — the bench gate allows
10%). Hedge-wasted spans are CONCURRENT duplicate work, not wall time,
so they are reported beside the table, excluded from the sum identity.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# segment vocabulary, in rough request order (display order for the
# /status section and the scripts/fleettrace_report.py table)
SEGMENTS = (
    "actor_queue",      # actor-side spans before the wire
    "wire",             # client-span time not covered by the remote handler
    "rpc_handler",      # JSON decode/encode + dispatch glue, both tiers
    "frontend_route",   # fleet/route + fleet/attempt self: WFQ wait, picks
    "queue_wait",       # replica admission queue
    "batch_assembly",   # replica micro-batcher coalescing window
    "device_dispatch",  # device execution (the span the paper is about)
    "future_wake",      # completion future wake latency
    "serving_other",    # serving/*/request self (should be ~0)
    "other",            # anything the vocabulary doesn't know
)

HEDGE_WASTED = "hedge_wasted"


def segment_for(name: str) -> str:
    """Map one span name to its attribution segment."""
    if name.endswith("/queue_wait"):
        return "queue_wait"
    if name.endswith("/batch_assembly"):
        return "batch_assembly"
    if name.endswith("/device_dispatch"):
        return "device_dispatch"
    if name.endswith("/future_wake"):
        return "future_wake"
    if name == "fleet/hedge_wasted":
        return HEDGE_WASTED
    if name.startswith("rpc/client/"):
        return "wire"
    if name.startswith("rpc/"):
        return "rpc_handler"
    if name in ("fleet/route", "fleet/attempt"):
        return "frontend_route"
    if name.startswith("serving/"):
        return "serving_other"
    if name.startswith(("notary/", "proposer/", "actor/")):
        return "actor_queue"
    return "other"


def _covered(intervals: List[Tuple[float, float]], lo: float,
             hi: float) -> float:
    """Total length of the union of `intervals` clipped to [lo, hi]."""
    clipped = sorted((max(lo, s), min(hi, e)) for s, e in intervals
                     if e > lo and s < hi)
    total = 0.0
    cur_s = cur_e = None
    for s, e in clipped:
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        elif e > cur_e:
            cur_e = e
    if cur_e is not None:
        total += cur_e - cur_s
    return total


def attribute(spans: List[dict]) -> Optional[dict]:
    """Walk one trace's span records (collector-rebased, each dict
    carrying name/span/parent/start/end/tags and optionally pid) and
    return the segment table. None when there is nothing to attribute.

    Roots whose parent never arrived (a lossy source, a one-sided
    trace) are left out of the walk and surfaced as `orphan_spans` —
    presenting a truncated tree as a complete request is exactly the
    failure mode the drop accounting exists to prevent."""
    if not spans:
        return None
    by_id: Dict[int, dict] = {s["span"]: s for s in spans}
    children: Dict[Optional[int], List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        parent = s.get("parent")
        if parent is None or parent not in by_id:
            roots.append(s)
        else:
            children.setdefault(parent, []).append(s)
    # the MAIN root is the widest interval: the outermost client span
    # covers the whole request; orphaned subtrees are narrower
    root = max(roots, key=lambda s: s["end"] - s["start"])
    segments = {name: 0.0 for name in SEGMENTS}
    wasted = 0.0
    klass = None
    pids = set()
    reached = 0
    stack = [root]
    while stack:
        span = stack.pop()
        reached += 1
        if span.get("pid") is not None:
            pids.add(span["pid"])
        tags = span.get("tags") or {}
        if klass is None and "klass" in tags:
            klass = tags["klass"]
        kids = children.get(span["span"], ())
        stack.extend(kids)
        dur = span["end"] - span["start"]
        segment = segment_for(span["name"])
        if segment == HEDGE_WASTED:
            # concurrent duplicate work: full duration, outside the
            # wall-time identity
            wasted += max(0.0, dur)
            continue
        covered = _covered([(k["start"], k["end"]) for k in kids],
                           span["start"], span["end"])
        segments[segment] += max(0.0, dur - covered)
    return {
        "total_s": max(0.0, root["end"] - root["start"]),
        "root": root["name"],
        "segments": segments,
        "hedge_wasted_s": wasted,
        "klass": klass,
        "processes": len(pids),
        "spans": reached,
        "orphan_spans": len(spans) - reached,
    }
