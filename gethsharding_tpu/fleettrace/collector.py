"""Trace collector: cross-process assembly + tail-based sampling.

Receives span batches from `SpanExporter`s (remote processes over
``shard_traceExport``, the owning process in-proc), rebases every span
onto the collector's wall clock using the batch's ``clock_offset_us``
anchor plus the handshake-measured per-connection skew, groups spans
by trace id, and — once a trace has gone quiet for a linger window —
assembles it, runs critical-path attribution, and decides retention
Dapper-style from the TAIL:

- keep every trace somebody flagged (hedged requests, breaker-window
  traffic, SLO-breach onsets mark recent traces of the breached
  class);
- keep the top latency quantile (the exemplars a p99 regression needs);
- keep a deterministic probabilistic sample of the rest;
- attribute EVERYTHING before dropping — the per-class segment tables
  are unbiased even though only exemplars keep their spans.

Retained exemplars live in a bounded ring served to
``shard_traceExemplars``, the /status section, and the perfwatch
flight-recorder bundle (``exemplars.json``).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Set, Tuple

from gethsharding_tpu import metrics
from gethsharding_tpu.fleettrace import critical_path

# recorder event kinds that open a retain-everything window: each is a
# fatal trigger whose post-mortem wants full traces, not samples
RETAIN_EVENT_KINDS = frozenset((
    "breaker_trip", "watchdog_timeout", "soundness_violation",
    "hedge_storm",
))

_ATTR_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                    100.0, 250.0, 500.0, 1000.0, 2500.0, 10000.0)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _TraceBuf:
    """One in-flight trace: spans seen so far + assembly state."""

    __slots__ = ("spans", "pids", "last_seen", "incomplete", "klass",
                 "reasons")

    def __init__(self) -> None:
        self.spans: List[dict] = []
        self.pids: Set[int] = set()
        self.last_seen = 0.0
        self.incomplete = False
        self.klass: Optional[str] = None
        self.reasons: Set[str] = set()


class TraceCollector:
    """Span-batch sink + trace assembler + tail sampler.

    Thread-safe: batches arrive on RPC handler threads, marks arrive
    from the router's hot path, the sweep runs on its own thread, and
    /status reads concurrently.
    """

    def __init__(self, registry: metrics.Registry = metrics.DEFAULT_REGISTRY,
                 *, max_traces: Optional[int] = None,
                 linger_s: Optional[float] = None,
                 sample: Optional[float] = None,
                 quantile: Optional[float] = None,
                 exemplars: Optional[int] = None,
                 breach_window_s: Optional[float] = None):
        self.registry = registry
        self.max_traces = max_traces if max_traces is not None else \
            _env_int("GETHSHARDING_FLEETTRACE_TRACES", 512)
        self.linger_s = linger_s if linger_s is not None else \
            _env_float("GETHSHARDING_FLEETTRACE_LINGER_S", 1.0)
        self.sample = sample if sample is not None else \
            _env_float("GETHSHARDING_FLEETTRACE_SAMPLE", 0.01)
        self.quantile = quantile if quantile is not None else \
            _env_float("GETHSHARDING_FLEETTRACE_QUANTILE", 0.99)
        max_exemplars = exemplars if exemplars is not None else \
            _env_int("GETHSHARDING_FLEETTRACE_EXEMPLARS", 32)
        self.breach_window_s = breach_window_s if breach_window_s is not None \
            else _env_float("GETHSHARDING_FLEETTRACE_BREACH_WINDOW_S", 5.0)
        self._lock = threading.Lock()
        self._live: "OrderedDict[int, _TraceBuf]" = OrderedDict()
        self._marks: "OrderedDict[int, str]" = OrderedDict()
        self._sources: Dict[Tuple, int] = {}
        self._durations: deque = deque(maxlen=512)
        self._breach_until: Dict[str, float] = {}
        self._window_until = 0.0
        self._exemplars: deque = deque(maxlen=max(1, max_exemplars))
        self._attr: Dict[Tuple[str, str], metrics.Histogram] = {}
        self._sweeper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # eager instruments: the observability smoke asserts the
        # fleettrace/* prom rows exist as soon as the collector boots
        self._m_spans = registry.counter("fleettrace/ingest/spans")
        self._m_batches = registry.counter("fleettrace/ingest/batches")
        self._m_lossy = registry.counter("fleettrace/ingest/lossy_batches")
        self._m_assembled = registry.counter("fleettrace/traces/assembled")
        self._m_retained = registry.counter("fleettrace/traces/retained")
        self._m_sampled_out = registry.counter("fleettrace/traces/sampled_out")
        self._m_incomplete = registry.counter("fleettrace/traces/incomplete")
        self._m_evicted = registry.counter("fleettrace/traces/evicted")
        self._m_marked = registry.counter("fleettrace/marks")
        self._g_live = registry.gauge("fleettrace/traces/live")
        self._g_exemplars = registry.gauge("fleettrace/exemplars")

    # -- ingest (the shard_traceExport sink) --------------------------------

    def ingest_payload(self, payload: dict) -> dict:
        """Accept one exporter batch: decode, rebase to this process's
        wall clock, fold into per-trace buffers. Returns the ack the
        RPC handler ships back."""
        from gethsharding_tpu.rpc import codec

        spans = codec.dec_spans(payload.get("spans") or [])
        pid = payload.get("pid")
        label = payload.get("label")
        shift_s = (float(payload.get("clock_offset_us") or 0.0)
                   + float(payload.get("skew_us") or 0.0)) / 1e6
        dropped = int(payload.get("dropped") or 0)
        now = time.monotonic()
        source = (pid, label)
        with self._lock:
            lossy = dropped > self._sources.get(source, 0)
            self._sources[source] = dropped
            if lossy:
                self._m_lossy.inc()
            for record in spans:
                record["pid"] = pid
                record["src"] = label
                record["start"] += shift_s
                record["end"] += shift_s
                buf = self._live.get(record["trace"])
                if buf is None:
                    while len(self._live) >= self.max_traces:
                        self._live.popitem(last=False)
                        self._m_evicted.inc()
                    buf = _TraceBuf()
                    self._live[record["trace"]] = buf
                buf.spans.append(record)
                if pid is not None:
                    buf.pids.add(pid)
                buf.last_seen = now
                if lossy:
                    # this source admitted losing spans since its last
                    # batch: any trace it feeds may be missing subtrees
                    buf.incomplete = True
                tags = record["tags"]
                if buf.klass is None and "klass" in tags:
                    buf.klass = tags["klass"]
                reason = self._marks.pop(record["trace"], None)
                if reason is not None:
                    buf.reasons.add(reason)
            self._m_spans.inc(len(spans))
            self._m_batches.inc()
            self._g_live.set(len(self._live))
        return {"accepted": True, "spans": len(spans)}

    # -- exemplar marking (tail-retention triggers) -------------------------

    def mark_trace(self, trace_id: Optional[int], reason: str) -> None:
        """Flag a trace for retention (hedge issued, breaker-adjacent,
        caller interest). Safe before OR after its spans arrive."""
        if trace_id is None:
            return
        with self._lock:
            buf = self._live.get(trace_id)
            if buf is not None:
                buf.reasons.add(reason)
            else:
                self._marks[trace_id] = reason
                self._marks.move_to_end(trace_id)
                while len(self._marks) > 4096:
                    self._marks.popitem(last=False)
            self._m_marked.inc()

    def on_breach(self, objective: str, fast_burn: float,
                  slow_burn: float) -> None:
        """`SLOTracker.on_breach` hook: a breach onset retains every
        live trace of the breached class and opens a per-class window
        so the traces that BREACH the objective (not just precede it)
        are captured too."""
        now = time.monotonic()
        with self._lock:
            self._breach_until[objective] = now + self.breach_window_s
            for buf in self._live.values():
                if buf.klass == objective:
                    buf.reasons.add("slo_breach")

    def on_recorder_event(self, kind: str) -> None:
        """Flight-recorder event hook: fatal triggers open a global
        retain-everything window — their post-mortems want whole
        traces."""
        if kind in RETAIN_EVENT_KINDS:
            with self._lock:
                self._window_until = time.monotonic() + self.breach_window_s

    # -- assembly sweep -----------------------------------------------------

    def start(self) -> None:
        """Run the assembly sweep on a background thread."""
        if self._sweeper is not None:
            return
        self._stop.clear()
        self._sweeper = threading.Thread(
            target=self._sweep_loop, name="fleettrace-sweep", daemon=True)
        self._sweeper.start()

    def close(self) -> None:
        self._stop.set()
        sweeper = self._sweeper
        if sweeper is not None:
            sweeper.join(timeout=5.0)
            self._sweeper = None
        self.sweep(force=True)

    def _sweep_loop(self) -> None:
        interval = max(0.1, self.linger_s / 2.0)
        while not self._stop.wait(interval):
            try:
                self.sweep()
            except Exception:  # noqa: BLE001 - the sweep must survive
                import logging
                logging.getLogger("fleettrace").exception("sweep failed")

    def sweep(self, now: Optional[float] = None, force: bool = False) -> int:
        """Finalize traces quiet for at least the linger window (all of
        them with `force`, for shutdown and tests). Returns the number
        of traces assembled."""
        now = time.monotonic() if now is None else now
        ready: List[Tuple[int, _TraceBuf]] = []
        with self._lock:
            for trace_id, buf in list(self._live.items()):
                if force or now - buf.last_seen >= self.linger_s:
                    del self._live[trace_id]
                    ready.append((trace_id, buf))
            self._g_live.set(len(self._live))
        for trace_id, buf in ready:
            self._finalize(trace_id, buf, now)
        return len(ready)

    def _finalize(self, trace_id: int, buf: _TraceBuf, now: float) -> None:
        attr = critical_path.attribute(buf.spans)
        if attr is None:
            return
        klass = attr.get("klass") or buf.klass or "unclassified"
        attr["klass"] = klass
        attr["incomplete"] = buf.incomplete
        self._observe(klass, attr)
        self._m_assembled.inc()
        if buf.incomplete:
            self._m_incomplete.inc()
        duration = attr["total_s"]
        reasons = set(buf.reasons)
        threshold = self._tail_threshold()
        with self._lock:
            if self._breach_until.get(klass, 0.0) > now:
                reasons.add("slo_breach_window")
            if self._window_until > now:
                reasons.add("event_window")
            self._durations.append(duration)
        if threshold is not None and duration >= threshold:
            reasons.add("tail_quantile")
        if not reasons and self.sample > 0.0 and \
                (trace_id * 2654435761) % (1 << 32) < self.sample * (1 << 32):
            # deterministic hash sample: the same trace id makes the
            # same decision on every collector — no RNG in the hot path
            reasons.add("sampled")
        if not reasons:
            self._m_sampled_out.inc()
            return
        exemplar = {
            "trace_id": trace_id,
            "reasons": sorted(reasons),
            "incomplete": buf.incomplete,
            "klass": klass,
            "attribution": _round_attr(attr),
            "spans": sorted(buf.spans, key=lambda s: s["start"]),
        }
        with self._lock:
            self._exemplars.append(exemplar)
            self._g_exemplars.set(len(self._exemplars))
        self._m_retained.inc()

    def _tail_threshold(self) -> Optional[float]:
        """Duration above which a trace is a top-quantile exemplar;
        None until enough history has accumulated to rank against."""
        with self._lock:
            history = sorted(self._durations)
        if len(history) < 16:
            return None
        index = min(len(history) - 1, int(self.quantile * len(history)))
        return history[index]

    def _observe(self, klass: str, attr: dict) -> None:
        self._hist(klass, "total").observe(attr["total_s"] * 1e3)
        for segment, seconds in attr["segments"].items():
            if seconds > 0.0:
                self._hist(klass, segment).observe(seconds * 1e3)
        if attr["hedge_wasted_s"] > 0.0:
            self._hist(klass, critical_path.HEDGE_WASTED).observe(
                attr["hedge_wasted_s"] * 1e3)

    def _hist(self, klass: str, segment: str) -> metrics.Histogram:
        key = (klass, segment)
        hist = self._attr.get(key)
        if hist is None:
            hist = self.registry.histogram(
                f"fleettrace/attr/{klass}/{segment}_ms",
                buckets=_ATTR_BUCKETS_MS)
            with self._lock:
                self._attr[key] = hist
        return hist

    # -- consumers ----------------------------------------------------------

    def attribution(self) -> dict:
        """Per-class critical-path tables: segment -> count/p50/p99 ms,
        the `shard_traceAttribution` / report-script payload."""
        with self._lock:
            items = list(self._attr.items())
        classes: Dict[str, dict] = {}
        for (klass, segment), hist in items:
            _, count, total = hist.read()
            classes.setdefault(klass, {})[segment] = {
                "count": count,
                "mean_ms": round(total / count, 3) if count else 0.0,
                "p50_ms": round(hist.quantile(0.50), 3),
                "p99_ms": round(hist.quantile(0.99), 3),
            }
        return {
            "classes": classes,
            "segments": list(critical_path.SEGMENTS)
            + [critical_path.HEDGE_WASTED, "total"],
            "traces": {
                "assembled": self._m_assembled.value,
                "retained": self._m_retained.value,
                "sampled_out": self._m_sampled_out.value,
                "incomplete": self._m_incomplete.value,
            },
        }

    def exemplars(self, limit: int = 8) -> List[dict]:
        """Most recent retained traces, newest first."""
        with self._lock:
            out = list(self._exemplars)
        return list(reversed(out[-max(0, int(limit)):]))

    def status(self) -> dict:
        with self._lock:
            live = len(self._live)
            exemplar_count = len(self._exemplars)
            pending_marks = len(self._marks)
            classes = sorted({klass for klass, _ in self._attr})
        return {
            "live_traces": live,
            "exemplars": exemplar_count,
            "pending_marks": pending_marks,
            "classes": classes,
            "spans_ingested": self._m_spans.value,
            "batches": self._m_batches.value,
            "assembled": self._m_assembled.value,
            "retained": self._m_retained.value,
            "sampled_out": self._m_sampled_out.value,
            "incomplete": self._m_incomplete.value,
            "evicted": self._m_evicted.value,
            "sample": self.sample,
            "quantile": self.quantile,
            "linger_s": self.linger_s,
        }


def _round_attr(attr: dict) -> dict:
    out = dict(attr)
    out["total_s"] = round(attr["total_s"], 6)
    out["hedge_wasted_s"] = round(attr["hedge_wasted_s"], 6)
    out["segments"] = {k: round(v, 6) for k, v in attr["segments"].items()}
    return out
