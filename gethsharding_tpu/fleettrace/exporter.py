"""Span export plane: ship finished spans to the fleet collector.

Every process that participates in cross-process tracing runs one
`SpanExporter`: it opens the tracer's bounded export buffer, drains it
on a short cadence, and ships batches to a sink — either a
`TraceCollector.ingest_payload` in the same process (single-process
runs, the frontend's own spans) or an `RpcExportSink` riding the
existing JSON-RPC framing as ``shard_traceExport``.

The batch envelope carries everything the collector needs to place the
spans on ONE timeline and to stay honest about loss:

- ``clock_offset_us`` — the producer's wall-minus-monotonic anchor
  (the same anchor `tracing/export.py` stamps on Chrome dumps);
- ``skew_us`` — the per-connection handshake-measured wall-clock skew
  between producer and collector hosts (``shard_traceHandshake``,
  NTP-style midpoint estimate), so cross-HOST spans land on the
  collector's timeline, not just cross-process ones;
- ``dropped`` — the cumulative count of spans this process finished
  but could not ship (export-buffer evictions + failed sends), so the
  collector marks the traces this source feeds as incomplete instead
  of presenting truncated trees as complete.

Ship failures never block or break the traced process: the batch is
counted lost, the connection is torn down, and the next flush redials
— the collector may simply not be up yet (replicas boot before the
frontend in every topology script).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from gethsharding_tpu import metrics, tracing
from gethsharding_tpu.tracing.export import clock_offset_us


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class RpcExportSink:
    """Dial-on-demand `shard_traceExport` shipper with the clock
    handshake. Raises on ship failure (the exporter does the loss
    accounting); the dead connection is dropped so the next attempt
    redials."""

    def __init__(self, endpoint: str, timeout_s: float = 5.0):
        host, _, port = endpoint.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.timeout_s = timeout_s
        self._client = None
        self._skew_us = 0.0
        self._lock = threading.Lock()

    def _ensure(self):
        from gethsharding_tpu.rpc.client import RPCClient

        with self._lock:
            if self._client is None:
                client = RPCClient(self.host, self.port,
                                   timeout=self.timeout_s)
                try:
                    # NTP-style midpoint estimate: the collector's wall
                    # clock read halfway through the round trip is the
                    # best single-exchange guess of "its now vs ours"
                    t0 = time.time()
                    reply = client.call("shard_traceHandshake")
                    rtt = time.time() - t0
                    remote_wall_us = float(reply["wall_us"])
                    self._skew_us = remote_wall_us - (t0 + rtt / 2.0) * 1e6
                except Exception:
                    client.close()
                    raise
                self._client = client
            return self._client, self._skew_us

    def __call__(self, payload: dict) -> None:
        client, skew_us = self._ensure()
        payload["skew_us"] = skew_us
        try:
            client.call("shard_traceExport", payload)
        except Exception:
            self.close()
            raise

    @property
    def skew_us(self) -> float:
        """Handshake-measured wall-clock skew toward the collector
        host (0.0 until the first successful dial). Feed this to
        ``scripts/trace_merge.py --skew-us`` when hand-merging Chrome
        dumps from different hosts."""
        return self._skew_us

    def close(self) -> None:
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except Exception:  # noqa: BLE001 - teardown must not raise
                pass


class SpanExporter:
    """Background drain of the tracer's export buffer into a sink."""

    def __init__(self, sink: Callable[[dict], None],
                 tracer: Optional[tracing.Tracer] = None,
                 registry: metrics.Registry = metrics.DEFAULT_REGISTRY,
                 label: Optional[str] = None,
                 interval_s: Optional[float] = None,
                 batch_spans: Optional[int] = None,
                 buffer_spans: Optional[int] = None):
        self.sink = sink
        self.tracer = tracer if tracer is not None else tracing.TRACER
        self.label = label or f"pid{os.getpid()}"
        self.interval_s = interval_s if interval_s is not None else \
            _env_float("GETHSHARDING_FLEETTRACE_INTERVAL_MS", 200.0) / 1e3
        self.batch_spans = batch_spans if batch_spans is not None else \
            _env_int("GETHSHARDING_FLEETTRACE_BATCH", 512)
        self.tracer.enable_export(
            buffer_spans if buffer_spans is not None
            else _env_int("GETHSHARDING_FLEETTRACE_BUFFER", 8192))
        self._lost = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_spans = registry.counter("fleettrace/export/spans")
        self._m_batches = registry.counter("fleettrace/export/batches")
        self._m_failures = registry.counter("fleettrace/export/failures")
        self._m_lost = registry.counter("fleettrace/export/lost")

    def start(self) -> "SpanExporter":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="fleettrace-export", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush()

    def flush(self) -> int:
        """Drain and ship everything currently staged. Returns spans
        shipped; a failed send counts the batch lost (the drop count
        rides out on the next successful batch) and returns 0."""
        from gethsharding_tpu.rpc import codec

        shipped = 0
        while True:
            batch, dropped = self.tracer.drain_export(self.batch_spans)
            if not batch:
                return shipped
            payload = {
                "pid": os.getpid(),
                "label": self.label,
                "clock_offset_us": clock_offset_us(),
                "dropped": dropped + self._lost,
                "spans": codec.enc_spans(batch),
            }
            try:
                self.sink(payload)
            except Exception:  # noqa: BLE001 - export must never break
                # the traced process; the collector may not be up yet
                self._m_failures.inc()
                self._lost += len(batch)
                self._m_lost.inc(len(batch))
                return shipped
            shipped += len(batch)
            self._m_spans.inc(len(batch))
            self._m_batches.inc()

    def close(self) -> None:
        """Stop the drain thread and ship a final batch."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None
        try:
            self.flush()
        except Exception:  # noqa: BLE001 - shutdown must not raise
            pass
        self.tracer.disable_export()

    def stats(self) -> dict:
        out = {"label": self.label,
               "spans": self._m_spans.value,
               "batches": self._m_batches.value,
               "failures": self._m_failures.value,
               "lost": self._m_lost.value + self.tracer.export_dropped}
        skew = getattr(self.sink, "skew_us", None)
        if skew is not None:
            out["skew_us"] = skew
        return out
