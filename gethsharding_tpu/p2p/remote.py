"""RemoteHub: the shardp2p feed bus across OS processes.

The in-process `Hub` gives actors typed pub/sub within one process; this
adapter runs the SAME `P2PServer` API across processes, with the role
split of the reference's p2p stack:

- the chain process's relay (`rpc/server.py` shard_p2p*) is the
  INTRODUCTION tier — authenticated attach, peer table, broadcast
  fan-out (the discovery/dial-scheduling role, `p2p/discover`,
  `p2p/dial.go`);
- directed messages flow PEER TO PEER over direct TCP sockets
  (`p2p/direct.py`), authenticated by a secp256k1 challenge handshake —
  the RLPx transport role (`p2p/rlpx.go:86,178`), minus encryption.

Attaching REQUIRES an identity: the handshake carries the node's
account and a signature over a relay-issued challenge, so `account` in
the peer table is proven, not claimed. The relay refuses unsigned or
forged attaches; peers refuse direct connections whose account doesn't
match the relay's table. Wire format: the codec registry in
`rpc/codec.py` (type-tagged JSON).
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from gethsharding_tpu.p2p import direct
from gethsharding_tpu.p2p.service import (
    Message, Peer, PROTOCOL_NAME, PROTOCOL_VERSION)
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.rpc.client import RPCClient

log = logging.getLogger("p2p.remote")


class RemoteHub:
    """Hub duck-type backed by the chain-process relay + direct sockets.

    One RemoteHub carries ONE attached P2PServer (one actor process); its
    peer id is allocated by the relay and is meaningful across every
    process attached to the same relay.
    """

    def __init__(self, rpc: RPCClient, network_id: Optional[int] = None,
                 accounts=None, account=None):
        self.rpc = rpc
        self.network_id = network_id
        self._server = None
        self._self_peer: Optional[Peer] = None
        self._accounts = accounts      # AccountManager (holds the key)
        self._account = account        # Address20
        self._listener: Optional[direct.PeerListener] = None
        self._dialer: Optional[direct.DirectDialer] = None
        self._peer_info_cache: dict = {}  # peer ids never recycle
        rpc.on_notification("shard_p2p", self._on_message)

    @classmethod
    def dial(cls, host: str, port: int,
             network_id: Optional[int] = None,
             accounts=None, account=None) -> "RemoteHub":
        """Dial the relay. The identity (accounts manager + address) can
        also be supplied later via `set_identity` — it must be present by
        the time a P2PServer attaches."""
        return cls(RPCClient(host, port), network_id=network_id,
                   accounts=accounts, account=account)

    def set_identity(self, accounts, account) -> None:
        """Bind the node's key (AccountManager + Address20) used to sign
        the attach and direct-peer handshakes."""
        self._accounts = accounts
        self._account = account

    @property
    def account_hex(self) -> Optional[str]:
        return None if self._account is None else bytes(self._account).hex()

    def close(self) -> None:
        if self._dialer is not None:
            self._dialer.close()
        if self._listener is not None:
            self._listener.stop()
            self._listener = None
        self.rpc.close()

    def _sign(self, digest: bytes) -> bytes:
        return self._accounts.sign_hash(self._account, digest)

    # -- Hub surface (p2p/service.py) --------------------------------------

    def attach(self, server) -> Peer:
        if self._server is not None:
            raise RuntimeError("RemoteHub carries exactly one P2PServer; "
                               "dial another connection per actor")
        if self._accounts is None or self._account is None:
            raise RuntimeError(
                "p2p identity required: the relay refuses unsigned "
                "attaches (set_identity or dial(accounts=, account=))")
        if self.network_id is None:
            self.network_id = self.rpc.call("shard_networkId")
        # register the delivery target BEFORE the relay learns about the
        # peer: it may start pushing the instant the attach call lands
        self._server = server
        self._listener = direct.PeerListener(
            deliver=self._deliver, resolve=self.peer_info,
            network_id=self.network_id, sign=self._sign,
            account_hex=self.account_hex)
        self._listener.start()
        challenge = bytes.fromhex(self.rpc.call("shard_p2pChallenge"))
        handshake = {
            "protocol": PROTOCOL_NAME,
            "version": PROTOCOL_VERSION,
            "network_id": self.network_id,
            "account": self.account_hex,
            "sig": self._sign(
                direct.attach_digest(self.network_id, challenge)).hex(),
            "endpoint": list(self._listener.address),
        }
        try:
            peer_id = self.rpc.call("shard_p2pAttach", handshake)
        except Exception:
            self._server = None
            self._listener.stop()
            self._listener = None
            raise
        self._dialer = direct.DirectDialer(
            network_id=self.network_id, account_hex=self.account_hex,
            sign=self._sign)
        self._self_peer = Peer(peer_id)
        return self._self_peer

    def detach(self, peer: Peer) -> None:
        """Detach = end of this hub's life (it carries exactly one
        P2PServer): deregister from the relay and close the connection,
        so a stopped node leaks neither socket nor reader threads."""
        self._server = None
        try:
            self.rpc.call("shard_p2pDetach", peer.peer_id)
        except Exception:  # connection may already be down
            pass
        self.close()

    def peer_info(self, peer_id: int) -> Optional[dict]:
        """Relay peer-table lookup (cached: relay ids never recycle)."""
        info = self._peer_info_cache.get(peer_id)
        if info is None:
            try:
                info = self.rpc.call("shard_p2pPeerInfo", peer_id)
            except Exception:
                return None
            if info is not None:
                self._peer_info_cache[peer_id] = info
        return info

    def route(self, sender: Peer, target: Peer, data: Any) -> bool:
        """Directed send: peer-to-peer over the direct socket; the relay
        is the fallback only when the peer's listener is unreachable."""
        kind, payload = codec.enc_p2p(data)
        info = self.peer_info(target.peer_id)
        if (info is not None and info.get("endpoint")
                and self._dialer is not None):
            # DirectDialer.send retries a stale cached connection once
            # internally; a dial/handshake failure falls straight back to
            # the relay (retrying here would stack HANDSHAKE_TIMEOUT
            # stalls on the data-plane hot path)
            if self._dialer.send(tuple(info["endpoint"]), sender.peer_id,
                                 kind, payload,
                                 expect_account=info.get("account")):
                return True
            log.warning("direct send to peer %d failed; relay fallback",
                        target.peer_id)
        return self.rpc.call("shard_p2pSend", sender.peer_id,
                             target.peer_id, kind, payload)

    def broadcast(self, sender: Peer, data: Any) -> int:
        kind, payload = codec.enc_p2p(data)
        return self.rpc.call("shard_p2pBroadcast", sender.peer_id, kind,
                             payload)

    # -- inbound -----------------------------------------------------------

    def _deliver(self, message: Message) -> None:
        server = self._server
        if server is not None:
            server._deliver(message)

    def _on_message(self, params: dict) -> None:
        if self._server is None:
            return
        try:
            data = codec.dec_p2p(params["type"], params["payload"])
        except Exception:
            log.exception("undecodable p2p message")
            return
        self._deliver(Message(peer=Peer(params["from"]), data=data))
