"""RemoteHub: the shardp2p feed bus across OS processes.

The in-process `Hub` gives actors typed pub/sub within one process; this
adapter runs the SAME `P2PServer` API over the RPC relay hosted by the
chain process (`rpc/server.py` shard_p2p* methods), so body requests and
responses between a proposer process and a notary process cross a real
socket — the transport the reference's shardp2p stubs out
(`sharding/p2p/service.go:41-50` Send/Broadcast TODOs) and defers to a
future devp2p integration.

Wire format: messages serialize through the codec registry in
`rpc/codec.py` (type-tagged JSON); peers are relay-allocated ids, so a
responder can reply directly to the requesting peer across processes.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from gethsharding_tpu.p2p.service import (
    Message, Peer, PROTOCOL_NAME, PROTOCOL_VERSION)
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.rpc.client import RPCClient

log = logging.getLogger("p2p.remote")


class RemoteHub:
    """Hub duck-type backed by the chain process's p2p relay.

    One RemoteHub carries ONE attached P2PServer (one actor process); its
    peer id is allocated by the relay and is meaningful across every
    process attached to the same relay.
    """

    def __init__(self, rpc: RPCClient, network_id: Optional[int] = None,
                 account: Optional[str] = None):
        self.rpc = rpc
        self.network_id = network_id
        self.account = account
        self._server = None
        rpc.on_notification("shard_p2p", self._on_message)

    @classmethod
    def dial(cls, host: str, port: int,
             network_id: Optional[int] = None,
             account: Optional[str] = None) -> "RemoteHub":
        """Dial the relay. `network_id`/`account` go into the attach
        handshake: a stated network id must match the chain process's
        (protocol/version always must), and the account becomes the
        peer's public identity in the relay's peer table."""
        return cls(RPCClient(host, port), network_id=network_id,
                   account=account)

    def close(self) -> None:
        self.rpc.close()

    # -- Hub surface (p2p/service.py) --------------------------------------

    def attach(self, server) -> Peer:
        if self._server is not None:
            raise RuntimeError("RemoteHub carries exactly one P2PServer; "
                               "dial another connection per actor")
        # register the delivery target BEFORE the relay learns about the
        # peer: it may start pushing the instant the attach call lands
        self._server = server
        handshake = {"protocol": PROTOCOL_NAME,
                     "version": PROTOCOL_VERSION}
        if self.network_id is not None:
            handshake["network_id"] = self.network_id
        if self.account is not None:
            handshake["account"] = self.account
        try:
            peer_id = self.rpc.call("shard_p2pAttach", handshake)
        except Exception:
            self._server = None
            raise
        return Peer(peer_id)

    def detach(self, peer: Peer) -> None:
        """Detach = end of this hub's life (it carries exactly one
        P2PServer): deregister from the relay and close the connection,
        so a stopped node leaks neither socket nor reader threads."""
        self._server = None
        try:
            self.rpc.call("shard_p2pDetach", peer.peer_id)
        except Exception:  # connection may already be down
            pass
        self.close()

    def route(self, sender: Peer, target: Peer, data: Any) -> bool:
        kind, payload = codec.enc_p2p(data)
        return self.rpc.call("shard_p2pSend", sender.peer_id,
                             target.peer_id, kind, payload)

    def broadcast(self, sender: Peer, data: Any) -> int:
        kind, payload = codec.enc_p2p(data)
        return self.rpc.call("shard_p2pBroadcast", sender.peer_id, kind,
                             payload)

    # -- inbound -----------------------------------------------------------

    def _on_message(self, params: dict) -> None:
        server = self._server
        if server is None:
            return
        try:
            data = codec.dec_p2p(params["type"], params["payload"])
        except Exception:
            log.exception("undecodable p2p message")
            return
        server._deliver(Message(peer=Peer(params["from"]), data=data))
