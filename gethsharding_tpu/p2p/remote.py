"""RemoteHub: the shardp2p feed bus across OS processes.

The in-process `Hub` gives actors typed pub/sub within one process; this
adapter runs the SAME `P2PServer` API across processes, with the role
split of the reference's p2p stack:

- the chain process's relay / standalone bootnode (`rpc/server.py`
  shard_p2p*) is the FIRST-CONTACT tier — authenticated attach and the
  initial peer table (`cmd/bootnode` role);
- ongoing introduction is DECENTRALIZED: signed peer announces +
  liveness-checked gossip over the direct sockets (`p2p/discovery.py` —
  the `p2p/discover` + `p2p/dial.go` + ENR role), so the relay's death
  stops neither directed sends nor broadcasts between introduced peers;
- message payloads flow PEER TO PEER over direct TCP sockets
  (`p2p/direct.py`): mutual secp256k1 authentication and (when the host
  offers AEAD) ephemeral-ECDH AES-256-GCM frames — the RLPx transport
  role (`p2p/rlpx.go:86,178`). Broadcast is a direct fan-out over the
  peer directory; the relay is a per-peer FALLBACK path, not a
  chokepoint.

Attaching REQUIRES an identity: the handshake carries the node's
account and a signature over a relay-issued challenge, so `account` in
the peer table is proven, not claimed. The relay refuses unsigned or
forged attaches; peers refuse direct connections whose account doesn't
match the relay's table or a verified announce. Wire format: the codec
registry in `rpc/codec.py` (type-tagged JSON).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Optional

from gethsharding_tpu.p2p import direct
from gethsharding_tpu.p2p.discovery import (
    PeerDirectory, PeerTableRequest, PeerTableResponse)
from gethsharding_tpu.p2p.service import (
    Message, Peer, PROTOCOL_NAME, PROTOCOL_VERSION)
from gethsharding_tpu.rpc import codec
from gethsharding_tpu.rpc.client import RPCClient

log = logging.getLogger("p2p.remote")

GOSSIP_INTERVAL_S = float(os.environ.get("GETHSHARDING_P2P_GOSSIP_S", "25"))


class RemoteHub:
    """Hub duck-type backed by the chain-process relay + direct sockets.

    One RemoteHub carries ONE attached P2PServer (one actor process); its
    peer id is allocated by the relay and is meaningful across every
    process attached to the same relay.
    """

    def __init__(self, rpc: RPCClient, network_id: Optional[int] = None,
                 accounts=None, account=None):
        self.rpc = rpc
        self.network_id = network_id
        self._server = None
        self._self_peer: Optional[Peer] = None
        self._accounts = accounts      # AccountManager (holds the key)
        self._account = account        # Address20
        self._listener: Optional[direct.PeerListener] = None
        self._dialer: Optional[direct.DirectDialer] = None
        self._peer_info_cache: dict = {}  # peer ids never recycle
        self.directory: Optional[PeerDirectory] = None
        self._gossip_stop = threading.Event()
        self._gossip_thread: Optional[threading.Thread] = None
        rpc.on_notification("shard_p2p", self._on_message)

    @classmethod
    def dial(cls, host: str, port: int,
             network_id: Optional[int] = None,
             accounts=None, account=None) -> "RemoteHub":
        """Dial the relay. The identity (accounts manager + address) can
        also be supplied later via `set_identity` — it must be present by
        the time a P2PServer attaches."""
        return cls(RPCClient(host, port), network_id=network_id,
                   accounts=accounts, account=account)

    def set_identity(self, accounts, account) -> None:
        """Bind the node's key (AccountManager + Address20) used to sign
        the attach and direct-peer handshakes."""
        self._accounts = accounts
        self._account = account

    @property
    def account_hex(self) -> Optional[str]:
        return None if self._account is None else bytes(self._account).hex()

    def close(self) -> None:
        self._gossip_stop.set()
        if self._gossip_thread is not None:
            self._gossip_thread.join(timeout=2.0)
            self._gossip_thread = None
        if self._dialer is not None:
            self._dialer.close()
        if self._listener is not None:
            self._listener.stop()
            self._listener = None
        self.rpc.close()

    def _sign(self, digest: bytes) -> bytes:
        return self._accounts.sign_hash(self._account, digest)

    # -- Hub surface (p2p/service.py) --------------------------------------

    def attach(self, server) -> Peer:
        if self._server is not None:
            raise RuntimeError("RemoteHub carries exactly one P2PServer; "
                               "dial another connection per actor")
        if self._accounts is None or self._account is None:
            raise RuntimeError(
                "p2p identity required: the relay refuses unsigned "
                "attaches (set_identity or dial(accounts=, account=))")
        if self.network_id is None:
            self.network_id = self.rpc.call("shard_networkId")
        # register the delivery target BEFORE the relay learns about the
        # peer: it may start pushing the instant the attach call lands
        self._server = server
        self._listener = direct.PeerListener(
            deliver=self._deliver, resolve=self.peer_info,
            network_id=self.network_id, sign=self._sign,
            account_hex=self.account_hex)
        self._listener.start()
        challenge = bytes.fromhex(self.rpc.call("shard_p2pChallenge"))
        handshake = {
            "protocol": PROTOCOL_NAME,
            "version": PROTOCOL_VERSION,
            "network_id": self.network_id,
            "account": self.account_hex,
            "sig": self._sign(
                direct.attach_digest(self.network_id, challenge)).hex(),
            "endpoint": list(self._listener.address),
        }
        try:
            peer_id = self.rpc.call("shard_p2pAttach", handshake)
        except Exception:
            self._server = None
            self._listener.stop()
            self._listener = None
            raise
        self._dialer = direct.DirectDialer(
            network_id=self.network_id, account_hex=self.account_hex,
            sign=self._sign)
        self._self_peer = Peer(peer_id)
        # decentralized introduction: publish our signed announce, seed
        # the directory from the relay's first-contact table, and start
        # the gossip loop (p2p/discovery.py)
        self.directory = PeerDirectory(self.network_id)
        self.directory.make_self(peer_id, self.account_hex,
                                 self._listener.address, self._sign)
        self._seed_from_relay()
        try:
            # one relay fan-out of our announce so already-attached peers
            # learn us even before their next gossip round
            kind, payload = codec.enc_p2p(
                PeerTableResponse(announces=(self.directory.self_announce,)))
            self.rpc.call("shard_p2pBroadcast", peer_id, kind, payload)
        except Exception:
            pass
        self.gossip_once()
        self._gossip_thread = threading.Thread(
            target=self._gossip_loop, daemon=True, name="p2p-gossip")
        self._gossip_thread.start()
        return self._self_peer

    # -- decentralized introduction ----------------------------------------

    def _seed_from_relay(self) -> None:
        """First-contact peer list from the relay/bootnode table
        (unsigned claims — dialable, never re-gossiped)."""
        try:
            peers = self.rpc.call("shard_p2pPeers") or []
        except Exception:
            return
        for entry in peers:
            pid = entry.get("id")
            if pid is None or pid == self._self_peer.peer_id:
                continue
            self.directory.add_claim(pid, entry.get("account"),
                                     entry.get("endpoint"))

    def gossip_once(self, fanout: int = 3) -> None:
        """One gossip round: ask up to `fanout` live peers for their
        verified tables (responses merge in `_deliver`)."""
        if self.directory is None or self._self_peer is None:
            return
        req = PeerTableRequest()
        sent = 0
        for pid, info in self.directory.live_peers(self._self_peer.peer_id):
            if sent >= fanout:
                break
            if self._direct_send(pid, info, req):
                sent += 1

    def _gossip_loop(self) -> None:
        while not self._gossip_stop.wait(GOSSIP_INTERVAL_S):
            try:
                # relay re-seed rides the background cadence, NEVER the
                # broadcast hot path (a hung relay must not add its RPC
                # timeout to every broadcast)
                self._seed_from_relay()
                self.gossip_once()
            except Exception:  # pragma: no cover - keep the loop alive
                log.exception("gossip round failed")

    def _direct_send(self, peer_id: int, info: dict, data: Any) -> bool:
        """One frame straight to a peer's listener (no relay)."""
        if self._dialer is None or not info.get("endpoint"):
            return False
        kind, payload = codec.enc_p2p(data)
        ok = self._dialer.send(tuple(info["endpoint"]),
                               self._self_peer.peer_id, kind, payload,
                               expect_account=info.get("account"))
        if self.directory is not None:
            (self.directory.mark_ok if ok
             else self.directory.mark_failed)(peer_id)
        return ok

    def detach(self, peer: Peer) -> None:
        """Detach = end of this hub's life (it carries exactly one
        P2PServer): deregister from the relay and close the connection,
        so a stopped node leaks neither socket nor reader threads."""
        self._server = None
        try:
            self.rpc.call("shard_p2pDetach", peer.peer_id)
        except Exception:  # connection may already be down
            pass
        self.close()

    def peer_info(self, peer_id: int) -> Optional[dict]:
        """Peer lookup: relay table (cached: relay ids never recycle),
        then the gossip directory — so resolution outlives the relay."""
        info = self._peer_info_cache.get(peer_id)
        if info is None:
            try:
                info = self.rpc.call("shard_p2pPeerInfo", peer_id)
            except Exception:
                info = None
            if info is not None:
                self._peer_info_cache[peer_id] = info
        if info is None and self.directory is not None:
            info = self.directory.lookup(peer_id)
        return info

    def route(self, sender: Peer, target: Peer, data: Any) -> bool:
        """Directed send: peer-to-peer over the direct socket; the relay
        is the fallback only when the peer's listener is unreachable."""
        kind, payload = codec.enc_p2p(data)
        info = self.peer_info(target.peer_id)
        if (info is not None and info.get("endpoint")
                and self._dialer is not None):
            # DirectDialer.send retries a stale cached connection once
            # internally; a dial/handshake failure falls straight back to
            # the relay (retrying here would stack HANDSHAKE_TIMEOUT
            # stalls on the data-plane hot path)
            if self._dialer.send(tuple(info["endpoint"]), sender.peer_id,
                                 kind, payload,
                                 expect_account=info.get("account")):
                if self.directory is not None:
                    self.directory.mark_ok(target.peer_id)
                return True
            if self.directory is not None:
                self.directory.mark_failed(target.peer_id)
            log.warning("direct send to peer %d failed; relay fallback",
                        target.peer_id)
        try:
            return self.rpc.call("shard_p2pSend", sender.peer_id,
                                 target.peer_id, kind, payload)
        except Exception:
            return False  # relay gone AND no direct path

    def broadcast(self, sender: Peer, data: Any) -> int:
        """Fan out over direct peer sockets (the devp2p runPeer pattern,
        `p2p/server.go:882`); the relay carries only the per-peer
        FALLBACK for endpoints we cannot reach — it is no longer the
        broadcast chokepoint, and a dead relay no longer silences the
        network between introduced peers."""
        if self.directory is None:
            kind, payload = codec.enc_p2p(data)
            return self.rpc.call("shard_p2pBroadcast", sender.peer_id,
                                 kind, payload)
        delivered = 0
        kind, payload = codec.enc_p2p(data)
        for pid, info in self.directory.live_peers(sender.peer_id):
            if self._direct_send(pid, info, data):
                delivered += 1
                continue
            try:
                if self.rpc.call("shard_p2pSend", sender.peer_id, pid,
                                 kind, payload):
                    delivered += 1
            except Exception:
                pass  # relay gone too: the peer is unreachable this round
        # peers attached to the relay WITHOUT a direct listener (the
        # attach protocol permits it) are reachable only via the relay
        for pid in self.directory.relay_only_peers(sender.peer_id):
            try:
                if self.rpc.call("shard_p2pSend", sender.peer_id, pid,
                                 kind, payload):
                    delivered += 1
            except Exception:
                break  # relay down: none of them are reachable
        return delivered

    # -- inbound -----------------------------------------------------------

    def _deliver(self, message: Message) -> None:
        # gossip control frames are the hub's own traffic — answer/merge
        # here, never surface them to the actor's feeds
        if isinstance(message.data, PeerTableRequest):
            if self.directory is not None and self._self_peer is not None:
                info = self.peer_info(message.peer.peer_id)
                if info is not None:
                    self._direct_send(
                        message.peer.peer_id, info,
                        PeerTableResponse(
                            announces=tuple(self.directory.gossip_set())))
            return
        if isinstance(message.data, PeerTableResponse):
            if self.directory is not None:
                self.directory.merge(message.data.announces)
            return
        server = self._server
        if server is not None:
            server._deliver(message)

    def _on_message(self, params: dict) -> None:
        if self._server is None:
            return
        try:
            data = codec.dec_p2p(params["type"], params["payload"])
        except Exception:
            log.exception("undecodable p2p message")
            return
        self._deliver(Message(peer=Peer(params["from"]), data=data))
