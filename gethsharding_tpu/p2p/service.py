"""P2P server: typed feeds + directed send + broadcast over a process-local
hub.

The reference's shardp2p holds a `map[reflect.Type]*event.Feed` and stubs
out Send/Broadcast (`sharding/p2p/service.go:41-50`). Here the same feed-map
API is kept (`feed(MessageType)`) and the transport intent is implemented:
a `Hub` connects any number of `P2PServer` instances (one per actor/node in
a simulation, or one per process over the RPC bridge later); `send` routes
to one peer, `broadcast` to all others. Messages arrive wrapped in
`Message(peer, data)` so handlers can reply to the requesting peer —
mirroring `p2p.Message{Peer, Data}` (`sharding/p2p/message.go`).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Type

from gethsharding_tpu.p2p.feed import Feed, Subscription

# Protocol identity carried in the cross-process handshake — the
# `p2p.Protocol{Name, Version}` + NetworkId gate of the reference's RLPx
# layer (p2p/protocol.go:26, eth/handler.go status exchange), minus the
# crypto (the relay rides a trusted local RPC link, not the open internet).
PROTOCOL_NAME = "shardp2p"
PROTOCOL_VERSION = 1


@dataclass(frozen=True)
class Peer:
    """Identity of a remote server attached to the same hub."""

    peer_id: int

    def __repr__(self) -> str:  # pragma: no cover
        return f"Peer({self.peer_id})"


@dataclass(frozen=True)
class Message:
    """Envelope delivered to feeds: the sending peer + payload."""

    peer: Peer
    data: Any


class Hub:
    """Process-local interconnect: the 'network' behind P2PServer instances."""

    def __init__(self):
        self._servers: Dict[int, "P2PServer"] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def attach(self, server: "P2PServer") -> Peer:
        with self._lock:
            peer = Peer(next(self._ids))
            self._servers[peer.peer_id] = server
            return peer

    def detach(self, peer: Peer) -> None:
        with self._lock:
            self._servers.pop(peer.peer_id, None)

    def route(self, sender: Peer, target: Peer, data: Any) -> bool:
        with self._lock:
            server = self._servers.get(target.peer_id)
        if server is None:
            return False
        server._deliver(Message(peer=sender, data=data))
        return True

    def broadcast(self, sender: Peer, data: Any) -> int:
        with self._lock:
            targets = [s for pid, s in self._servers.items()
                       if pid != sender.peer_id]
        for server in targets:
            server._deliver(Message(peer=sender, data=data))
        return len(targets)


class P2PServer:
    """Per-node p2p endpoint with typed feeds.

    Lifecycle parity with `sharding/p2p/service.go` (NewServer :23,
    Start/Stop logging-only :28-38): a server is usable as soon as it is
    constructed; start/stop manage hub attachment.
    """

    def __init__(self, hub: Optional[Hub] = None):
        self.hub = hub or Hub()
        self._feeds: Dict[Type, Feed] = {}
        self._lock = threading.Lock()
        self.self_peer: Optional[Peer] = None

    # -- service lifecycle -------------------------------------------------

    def start(self) -> None:
        if self.self_peer is None:
            self.self_peer = self.hub.attach(self)

    def stop(self) -> None:
        if self.self_peer is not None:
            self.hub.detach(self.self_peer)
            self.self_peer = None

    # -- feed map (parity: Feed(msg) sharding/p2p/feed.go:27) --------------

    def feed(self, msg_type: Type) -> Feed:
        with self._lock:
            if msg_type not in self._feeds:
                self._feeds[msg_type] = Feed()
            return self._feeds[msg_type]

    def subscribe(self, msg_type: Type, maxsize: int = 1024) -> Subscription:
        return self.feed(msg_type).subscribe(maxsize=maxsize)

    def _deliver(self, message: Message) -> None:
        feed = self.feed(type(message.data))
        feed.send(message)

    # -- transport ---------------------------------------------------------

    def send(self, data: Any, peer: Peer) -> bool:
        """Directed send to one peer (implements the reference's TODO)."""
        if self.self_peer is None:
            self.start()
        return self.hub.route(self.self_peer, peer, data)

    def broadcast(self, data: Any) -> int:
        """Send to every other server on the hub."""
        if self.self_peer is None:
            self.start()
        return self.hub.broadcast(self.self_peer, data)

    def loopback(self, data: Any) -> None:
        """Inject a message into our own feeds (simulator pattern)."""
        if self.self_peer is None:
            self.start()
        self._deliver(Message(peer=self.self_peer, data=data))
