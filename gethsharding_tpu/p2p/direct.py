"""Direct authenticated + encrypted peer sockets for shardp2p (the
de-starred data plane).

The chain-process relay (`rpc/server.py` shard_p2p*) remains the
INTRODUCTION service — it allocates peer ids and keeps the table of
(account, listener endpoint) per peer — but directed message payloads
flow over direct TCP sockets between actor processes. This is the
reference's RLPx role split (`p2p/rlpx.go:86,178` authenticated
encrypted transport vs `p2p/dial.go`/discovery introduction), with the
same security class: MUTUAL secp256k1 authentication and, when the host
offers AEAD primitives, ephemeral-ECDH-derived AES-256-GCM frame
encryption (the modern equivalent of RLPx's ECIES handshake +
AES-CTR/keccak-MAC frames).

Wire protocol:

  1. listener -> dialer (plaintext JSON line):
       {"challenge": hex32, "eph_pub": hex64?}          # eph iff AEAD
  2. dialer -> listener (plaintext JSON line):
       {"peer_id": N, "account": hex20, "sig": hex65,
        "challenge2": hex32, "eph_pub": hex64?}
       sig  = sign(keccak(b"shardp2p-direct:" || nid8 || challenge ||
                          dialer_eph || listener_eph))
  3. listener -> dialer (first frame; encrypted iff both sides sent
     eph_pub):
       {"ok": true, "account": hex20, "sig2": hex65} | {"error": ...}
       sig2 = sign(keccak(b"shardp2p-accept:" || nid8 || challenge2 ||
                          dialer_eph || listener_eph))
  4. data frames: {"type": kind, "payload": ...} — plaintext newline
     JSON, or AES-256-GCM with 4-byte big-endian length prefix and a
     per-direction 12-byte counter nonce.

Security properties: the dialer's signature binds BOTH ephemeral keys
to its relay-registered account (verified against the relay's table for
the claimed peer id), the listener's signature binds them to the
account the dialer looked up for the endpoint — so neither end can be
impersonated and a middle man cannot substitute ephemeral keys without
breaking a signature. Fresh challenges on both sides prevent replay.
Per-direction keys derive as keccak256(ecdh_x || direction-label).
"""

from __future__ import annotations

import json
import logging
import secrets
import socket
import socketserver
import struct
import threading
from typing import Callable, Optional, Tuple

from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.p2p.service import Message, Peer
from gethsharding_tpu.rpc import codec

log = logging.getLogger("p2p.direct")

HANDSHAKE_TIMEOUT = 10.0

try:  # AEAD frames need the host's cryptography package; gate, don't require
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except Exception:  # pragma: no cover - AEAD-less host
    AESGCM = None

    class InvalidTag(Exception):  # noqa: N818 - mirror cryptography's name
        pass


SEND_TIMEOUT = 30.0  # post-handshake socket timeout: a peer that stops
# draining must stall only its own connection, never the caller forever


def attach_digest(network_id: int, challenge: bytes) -> bytes:
    """What an attaching node signs to prove its account to the relay."""
    return keccak256(b"shardp2p-attach:" + network_id.to_bytes(8, "big")
                     + challenge)


def direct_digest(network_id: int, challenge: bytes,
                  dialer_eph: bytes = b"", listener_eph: bytes = b"") -> bytes:
    """What a dialing node signs: account + BOTH ephemeral keys."""
    return keccak256(b"shardp2p-direct:" + network_id.to_bytes(8, "big")
                     + challenge + dialer_eph + listener_eph)


def accept_digest(network_id: int, challenge2: bytes,
                  dialer_eph: bytes = b"", listener_eph: bytes = b"") -> bytes:
    """What the accepting listener signs: mutual authentication."""
    return keccak256(b"shardp2p-accept:" + network_id.to_bytes(8, "big")
                     + challenge2 + dialer_eph + listener_eph)


def prove(digest: bytes, sig65: bytes, account_hex: str) -> bool:
    """Does the signature recover to the claimed 20-byte hex account?"""
    try:
        addr = secp256k1.ecrecover_address(
            digest, secp256k1.Signature.from_bytes65(sig65))
    except (ValueError, AssertionError):
        return False
    return bytes(addr).hex() == account_hex.lower().removeprefix("0x")


# -- AEAD channel ----------------------------------------------------------


def _ephemeral_keypair() -> Tuple[int, bytes]:
    priv = (int.from_bytes(secrets.token_bytes(32), "big")
            % (secp256k1.N - 1)) + 1
    pub = secp256k1.pubkey_from_priv(priv)
    # raw 64-byte X || Y (no SEC1 prefix): fixed width for the digests
    return priv, pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")


def _ecdh_secret(priv: int, peer_pub64: bytes) -> bytes:
    pub = (int.from_bytes(peer_pub64[:32], "big"),
           int.from_bytes(peer_pub64[32:], "big"))
    if not secp256k1.is_on_curve(pub):
        raise ValueError("ephemeral key not on curve")
    shared = secp256k1.point_mul(priv, pub)
    return keccak256(shared[0].to_bytes(32, "big"))


class _Channel:
    """One direction of AES-256-GCM framing with a counter nonce."""

    def __init__(self, key: bytes):
        self.aead = AESGCM(key)
        self.counter = 0
        self.lock = threading.Lock()

    def seal(self, plaintext: bytes) -> bytes:
        with self.lock:
            nonce = self.counter.to_bytes(12, "big")
            self.counter += 1
        blob = self.aead.encrypt(nonce, plaintext, None)
        return struct.pack(">I", len(blob)) + blob

    def open_frame(self, rfile) -> Optional[bytes]:
        header = rfile.read(4)
        if len(header) < 4:
            return None
        (length,) = struct.unpack(">I", header)
        if length > 16 * 1024 * 1024:
            raise ValueError("oversized frame")
        blob = rfile.read(length)
        if len(blob) < length:
            return None
        with self.lock:
            nonce = self.counter.to_bytes(12, "big")
            self.counter += 1
        return self.aead.decrypt(nonce, blob, None)


def _derive_channels(secret: bytes, dialer_side: bool):
    """(send, recv) channels; keys separated by direction labels."""
    k_d2l = keccak256(secret + b"dialer->listener")
    k_l2d = keccak256(secret + b"listener->dialer")
    if dialer_side:
        return _Channel(k_d2l), _Channel(k_l2d)
    return _Channel(k_l2d), _Channel(k_d2l)


# -- inbound ---------------------------------------------------------------


class PeerListener:
    """Inbound side: accepts authenticated (and, when possible,
    encrypted) peer connections and delivers their frames into the
    local P2PServer."""

    def __init__(self, deliver: Callable[[Message], None],
                 resolve: Callable[[int], Optional[dict]],
                 network_id: int, sign: Callable[[bytes], bytes],
                 account_hex: str, host: str = "127.0.0.1"):
        self.deliver = deliver
        self.resolve = resolve
        self.network_id = network_id
        self.sign = sign
        self.account_hex = account_hex
        listener = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                listener._handle(self)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = Server((host, 0), Handler)
        self.address: Tuple[str, int] = self._tcp.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, daemon=True, name="p2p-listener")
        self._thread.start()

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- connection handling ----------------------------------------------

    def _handle(self, handler) -> None:
        handler.connection.settimeout(HANDSHAKE_TIMEOUT)
        challenge = secrets.token_bytes(32)
        eph_priv, eph_pub = (None, b"")
        if AESGCM is not None:
            eph_priv, eph_pub = _ephemeral_keypair()
        try:
            greeting = {"challenge": challenge.hex()}
            if eph_pub:
                greeting["eph_pub"] = eph_pub.hex()
            handler.wfile.write((json.dumps(greeting) + "\n").encode())
            handler.wfile.flush()

            hello = json.loads(handler.rfile.readline())
            peer_id = int(hello["peer_id"])
            account = str(hello["account"])
            sig = bytes.fromhex(hello["sig"])
            challenge2 = bytes.fromhex(hello["challenge2"])
            dialer_eph = bytes.fromhex(hello.get("eph_pub", ""))
            encrypt = bool(eph_pub) and bool(dialer_eph)

            # downgrade protection: each side's digests use the keys it
            # SENT for its own slot and the keys it RECEIVED for the
            # peer's — so a middle man stripping either eph_pub breaks
            # one of the two signatures instead of silently forcing
            # plaintext (the dialer's sig commits to the listener key it
            # saw; our sig2 commits to the key we actually offered)
            err = self._verify(peer_id, account, sig, challenge,
                               dialer_eph, eph_pub)
            sig2 = self.sign(accept_digest(
                self.network_id, challenge2, dialer_eph, eph_pub))
            reply = ({"ok": True, "account": self.account_hex,
                      "sig2": sig2.hex()}
                     if err is None else {"error": err})
            if encrypt and err is None:
                secret = _ecdh_secret(eph_priv, dialer_eph)
                send, recv = _derive_channels(secret, dialer_side=False)
                handler.wfile.write(send.seal(json.dumps(reply).encode()))
            else:
                handler.wfile.write((json.dumps(reply) + "\n").encode())
            handler.wfile.flush()
            if err is not None:
                log.warning("refused direct peer %s: %s", account, err)
                return
        except (OSError, ValueError, KeyError, TypeError, InvalidTag,
                json.JSONDecodeError):
            return
        handler.connection.settimeout(None)
        try:
            while True:
                if encrypt:
                    raw = recv.open_frame(handler.rfile)
                    if raw is None:
                        break
                else:
                    raw = handler.rfile.readline()
                    if not raw:
                        break
                    raw = raw.strip()
                    if not raw:
                        continue
                frame = json.loads(raw)
                data = codec.dec_p2p(frame["type"], frame["payload"])
                self.deliver(Message(peer=Peer(peer_id), data=data))
        except (OSError, ValueError, KeyError, InvalidTag,
                json.JSONDecodeError):
            log.debug("direct peer %d connection ended", peer_id)

    def _verify(self, peer_id: int, account: str, sig: bytes,
                challenge: bytes, dialer_eph: bytes,
                listener_eph: bytes) -> Optional[str]:
        digest = direct_digest(self.network_id, challenge, dialer_eph,
                               listener_eph)
        if not prove(digest, sig, account):
            return "signature does not prove the claimed account"
        meta = self.resolve(peer_id)
        if meta is None:
            return f"unknown relay peer id {peer_id}"
        on_file = (meta.get("account") or "").lower().removeprefix("0x")
        if on_file != account.lower().removeprefix("0x"):
            return "account does not match the relay's table for this peer"
        return None


# -- outbound --------------------------------------------------------------


class DirectDialer:
    """Outbound side: a cache of authenticated connections to peer
    listeners; `send` dials + handshakes on first use per endpoint."""

    def __init__(self, network_id: int, account_hex: str,
                 sign: Callable[[bytes], bytes]):
        self.network_id = network_id
        self.account_hex = account_hex
        self.sign = sign
        self._conns: dict = {}  # endpoint -> (sock, rfile, wfile, channel)
        self._lock = threading.Lock()

    def close(self) -> None:
        with self._lock:
            conns, self._conns = dict(self._conns), {}
        for sock, *_ in conns.values():
            try:
                sock.close()
            except OSError:
                pass

    def send(self, endpoint: Tuple[str, int], self_peer_id: int,
             kind: str, payload, expect_account: Optional[str] = None
             ) -> bool:
        """One frame to the peer listening at `endpoint`; False when the
        peer is unreachable or either handshake check fails (caller
        falls back to the relay). `expect_account` pins the listener's
        identity to the relay's table entry (mutual auth)."""
        frame = json.dumps({"type": kind, "payload": payload}).encode()
        for attempt in (0, 1):  # one retry on a stale cached connection
            conn = self._get(tuple(endpoint), self_peer_id, expect_account)
            if conn is None:
                return False
            sock, _, wfile, channel, wlock = conn
            try:
                wire = (channel.seal(frame) if channel is not None
                        else frame + b"\n")
                # per-connection lock: one hung peer must never wedge
                # sends to every other peer
                with wlock:
                    wfile.write(wire)
                    wfile.flush()
                return True
            except OSError:
                self._drop(tuple(endpoint))
        return False

    def _get(self, endpoint: Tuple[str, int], self_peer_id: int,
             expect_account: Optional[str]):
        with self._lock:
            conn = self._conns.get(endpoint)
        if conn is not None:
            return conn
        try:
            sock = socket.create_connection(endpoint,
                                            timeout=HANDSHAKE_TIMEOUT)
            rfile = sock.makefile("rb")
            wfile = sock.makefile("wb")
            greeting = json.loads(rfile.readline())
            challenge = bytes.fromhex(greeting["challenge"])
            listener_eph = bytes.fromhex(greeting.get("eph_pub", ""))
            encrypt = AESGCM is not None and bool(listener_eph)
            eph_priv, eph_pub = (_ephemeral_keypair() if encrypt
                                 else (None, b""))
            challenge2 = secrets.token_bytes(32)
            # downgrade protection: sign over OUR sent key (possibly
            # empty) and the listener key AS RECEIVED — a stripped
            # greeting makes the listener's verification fail, a
            # stripped hello makes our sig2 check below fail
            sig = self.sign(direct_digest(
                self.network_id, challenge, eph_pub, listener_eph))
            hello = {"peer_id": self_peer_id, "account": self.account_hex,
                     "sig": sig.hex(), "challenge2": challenge2.hex()}
            if eph_pub:
                hello["eph_pub"] = eph_pub.hex()
            wfile.write((json.dumps(hello) + "\n").encode())
            wfile.flush()

            send = recv = None
            if encrypt:
                secret = _ecdh_secret(eph_priv, listener_eph)
                send, recv = _derive_channels(secret, dialer_side=True)
                raw = recv.open_frame(rfile)
                reply = json.loads(raw) if raw is not None else {}
            else:
                reply = json.loads(rfile.readline())
            if not reply.get("ok"):
                log.warning("direct handshake refused by %s: %s", endpoint,
                            reply.get("error"))
                sock.close()
                return None
            # mutual authentication: the listener must prove the account
            # the relay's table advertises for this endpoint, committing
            # to the same (sent, received) ephemeral-key view
            sig2 = bytes.fromhex(reply.get("sig2", ""))
            listed = reply.get("account", "")
            digest2 = accept_digest(self.network_id, challenge2,
                                    eph_pub, listener_eph)
            if not prove(digest2, sig2, listed) or (
                    expect_account is not None
                    and listed.lower().removeprefix("0x")
                    != expect_account.lower().removeprefix("0x")):
                log.warning("direct listener at %s failed mutual auth",
                            endpoint)
                sock.close()
                return None
            sock.settimeout(SEND_TIMEOUT)
        except (OSError, ValueError, KeyError, InvalidTag,
                json.JSONDecodeError) as exc:
            log.debug("direct dial to %s failed: %s", endpoint, exc)
            return None
        conn = (sock, rfile, wfile, send, threading.Lock())
        with self._lock:
            existing = self._conns.get(endpoint)
            if existing is not None:
                # a racing first-send finished its handshake before us:
                # keep theirs, close ours (no leaked socket/handler)
                try:
                    sock.close()
                except OSError:
                    pass
                return existing
            self._conns[endpoint] = conn
        return conn

    def _drop(self, endpoint: Tuple[str, int]) -> None:
        with self._lock:
            conn = self._conns.pop(endpoint, None)
        if conn is not None:
            try:
                conn[0].close()
            except OSError:
                pass
