"""Direct authenticated peer sockets for shardp2p (the de-starred data
plane).

The chain-process relay (`rpc/server.py` shard_p2p*) remains the
INTRODUCTION service — it allocates peer ids and keeps the table of
(account, listener endpoint) per peer — but directed message payloads
flow over direct TCP sockets between actor processes. This is the
reference's RLPx role split (`p2p/rlpx.go:86,178` authenticated
transport vs `p2p/dial.go`/discovery introduction), with the secp256k1
challenge handshake providing authentication; the ECIES/AES encryption
layer is out of scope here (authentication is mandatory, encryption a
stretch goal).

Wire protocol — newline-delimited JSON frames:

    listener -> dialer : {"challenge": hex32}
    dialer  -> listener: {"peer_id": N, "account": hex20, "sig": hex65}
        sig over keccak256(b"shardp2p-direct:" || network_id_be8 ||
        challenge) with the node's key
    listener -> dialer : {"ok": true} | {"error": reason}
    dialer  -> listener: {"type": kind, "payload": ...}   (repeated)

The listener binds the claimed relay `peer_id` to the PROVEN account by
resolving the relay's peer table: a dialer that cannot sign for the
account the relay has on file for that id is refused, so a relay peer id
cannot be impersonated even by another authenticated peer.
"""

from __future__ import annotations

import json
import logging
import secrets
import socket
import socketserver
import threading
from typing import Callable, Optional, Tuple

from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.p2p.service import Message, Peer
from gethsharding_tpu.rpc import codec

log = logging.getLogger("p2p.direct")

HANDSHAKE_TIMEOUT = 10.0


def attach_digest(network_id: int, challenge: bytes) -> bytes:
    """What an attaching node signs to prove its account to the relay."""
    return keccak256(b"shardp2p-attach:" + network_id.to_bytes(8, "big")
                     + challenge)


def direct_digest(network_id: int, challenge: bytes) -> bytes:
    """What a dialing node signs to prove its account to a peer."""
    return keccak256(b"shardp2p-direct:" + network_id.to_bytes(8, "big")
                     + challenge)


def prove(digest: bytes, sig65: bytes, account_hex: str) -> bool:
    """Does the signature recover to the claimed 20-byte hex account?"""
    try:
        addr = secp256k1.ecrecover_address(
            digest, secp256k1.Signature.from_bytes65(sig65))
    except (ValueError, AssertionError):
        return False
    return bytes(addr).hex() == account_hex.lower().removeprefix("0x")


class PeerListener:
    """Inbound side: accepts authenticated peer connections and delivers
    their frames into the local P2PServer."""

    def __init__(self, deliver: Callable[[Message], None],
                 resolve: Callable[[int], Optional[dict]],
                 network_id: int, host: str = "127.0.0.1"):
        self.deliver = deliver
        self.resolve = resolve
        self.network_id = network_id
        listener = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                listener._handle(self)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = Server((host, 0), Handler)
        self.address: Tuple[str, int] = self._tcp.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, daemon=True, name="p2p-listener")
        self._thread.start()

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- connection handling ----------------------------------------------

    def _handle(self, handler) -> None:
        handler.connection.settimeout(HANDSHAKE_TIMEOUT)
        challenge = secrets.token_bytes(32)
        try:
            handler.wfile.write(
                (json.dumps({"challenge": challenge.hex()}) + "\n").encode())
            handler.wfile.flush()
            hello = json.loads(handler.rfile.readline())
            peer_id = int(hello["peer_id"])
            account = str(hello["account"])
            sig = bytes.fromhex(hello["sig"])
            err = self._verify(peer_id, account, sig, challenge)
            reply = {"ok": True} if err is None else {"error": err}
            handler.wfile.write((json.dumps(reply) + "\n").encode())
            handler.wfile.flush()
            if err is not None:
                log.warning("refused direct peer %s: %s", account, err)
                return
        except (OSError, ValueError, KeyError, TypeError,
                json.JSONDecodeError):
            return
        handler.connection.settimeout(None)
        try:
            for raw in handler.rfile:
                raw = raw.strip()
                if not raw:
                    continue
                frame = json.loads(raw)
                data = codec.dec_p2p(frame["type"], frame["payload"])
                self.deliver(Message(peer=Peer(peer_id), data=data))
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            log.debug("direct peer %d connection ended", peer_id)

    def _verify(self, peer_id: int, account: str, sig: bytes,
                challenge: bytes) -> Optional[str]:
        if not prove(direct_digest(self.network_id, challenge), sig, account):
            return "signature does not prove the claimed account"
        meta = self.resolve(peer_id)
        if meta is None:
            return f"unknown relay peer id {peer_id}"
        on_file = (meta.get("account") or "").lower().removeprefix("0x")
        if on_file != account.lower().removeprefix("0x"):
            return "account does not match the relay's table for this peer"
        return None


class DirectDialer:
    """Outbound side: a cache of authenticated connections to peer
    listeners; `send` dials + handshakes on first use per endpoint."""

    def __init__(self, network_id: int, account_hex: str,
                 sign: Callable[[bytes], bytes]):
        self.network_id = network_id
        self.account_hex = account_hex
        self.sign = sign
        self._conns: dict = {}  # (host, port) -> (sock, rfile, wfile, lock)
        self._lock = threading.Lock()

    def close(self) -> None:
        with self._lock:
            conns, self._conns = dict(self._conns), {}
        for sock, *_ in conns.values():
            try:
                sock.close()
            except OSError:
                pass

    def send(self, endpoint: Tuple[str, int], self_peer_id: int,
             kind: str, payload) -> bool:
        """One frame to the peer listening at `endpoint`; False when the
        peer is unreachable or refuses the handshake (caller falls back
        to the relay)."""
        frame = (json.dumps({"type": kind, "payload": payload}) + "\n"
                 ).encode()
        for attempt in (0, 1):  # one retry on a stale cached connection
            conn = self._get(tuple(endpoint), self_peer_id)
            if conn is None:
                return False
            _, _, wfile, lock = conn
            try:
                with lock:
                    wfile.write(frame)
                    wfile.flush()
                return True
            except OSError:
                self._drop(tuple(endpoint))
        return False

    def _get(self, endpoint: Tuple[str, int], self_peer_id: int):
        with self._lock:
            conn = self._conns.get(endpoint)
        if conn is not None:
            return conn
        try:
            sock = socket.create_connection(endpoint,
                                            timeout=HANDSHAKE_TIMEOUT)
            rfile = sock.makefile("rb")
            wfile = sock.makefile("wb")
            challenge = bytes.fromhex(
                json.loads(rfile.readline())["challenge"])
            sig = self.sign(direct_digest(self.network_id, challenge))
            hello = {"peer_id": self_peer_id, "account": self.account_hex,
                     "sig": sig.hex()}
            wfile.write((json.dumps(hello) + "\n").encode())
            wfile.flush()
            reply = json.loads(rfile.readline())
            if not reply.get("ok"):
                log.warning("direct handshake refused by %s: %s", endpoint,
                            reply.get("error"))
                sock.close()
                return None
            sock.settimeout(None)
        except (OSError, ValueError, KeyError, json.JSONDecodeError) as exc:
            log.debug("direct dial to %s failed: %s", endpoint, exc)
            return None
        conn = (sock, rfile, wfile, threading.Lock())
        with self._lock:
            self._conns[endpoint] = conn
        return conn

    def _drop(self, endpoint: Tuple[str, int]) -> None:
        with self._lock:
            conn = self._conns.pop(endpoint, None)
        if conn is not None:
            try:
                conn[0].close()
            except OSError:
                pass
