"""Decentralized peer introduction: signed announces + liveness-checked
gossip over the authenticated direct data plane.

Role parity: the reference's discovery tier — the Kademlia peer table
(`p2p/discover/table.go:68`), dial scheduling (`p2p/dial.go:1`) and
discv5's SIGNED node records (ENR: account-bound, seq-versioned). The
chain-process relay / bootnode remains only the FIRST contact:

- every node publishes a `PeerAnnounce` — its (peer_id, account,
  endpoint) self-signed over the network id and a monotonic `seq`, so
  any third party can verify the binding without trusting the gossiper
  (a forwarded announce is evidence, not a claim);
- each node keeps a `PeerDirectory` of announces (verified) plus
  relay-table entries (claims, used for dialing exactly as the relay
  flow always did — the direct handshake's mutual auth still pins the
  dialed listener to the expected account);
- `PeerTableRequest`/`PeerTableResponse` frames ride the SAME
  authenticated direct sockets as data messages; `RemoteHub` answers
  them internally and merges what peers return, with per-peer failure
  counts aging dead entries out of the broadcast set.

With that, introduction survives the relay: once two nodes have
exchanged announces, directed sends, broadcasts and body exchange all
run peer-to-peer with the relay process gone (the r3 SPOF, VERDICT
Missing #1).

Scale bound (documented, by design): the directory is FLAT — verified
announces gossip to every peer and the table caps at MAX_VERIFIED
entries with liveness aging, so lookups are O(1) and table state/churn
traffic are O(n) per node. That is the right trade at this framework's
deployment scale (a devnet or a pod-local fleet of dozens of actor
processes: the reference's own devnet topology), where the XOR-bucket
Kademlia structure (`p2p/discover/table.go:68`) would add lookup
round-trips without shrinking any real table. What changes at
thousand-node WAN scale: the flat table stops fitting (MAX_VERIFIED
evicts live peers) and O(n) gossip dominates — the upgrade path is
XOR-distance buckets over the EXISTING verified announces (they already
carry the node identity the distance metric needs) with the same
authenticated frames serving FINDNODE-style bucket queries; nothing in
the data plane or the announce format would change.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.p2p import direct

# a peer whose direct endpoint failed this many consecutive times is
# dropped from the live set until a fresh announce or successful send
DEAD_AFTER_FAILURES = 3

# table bound: one verified announce per ACCOUNT (an attacker with one
# key cannot mint unbounded peer_ids into everyone's tables) and a hard
# entry cap with lowest-seq eviction (memory + broadcast-fanout bound)
MAX_VERIFIED = 512


@dataclass(frozen=True)
class PeerAnnounce:
    """Self-signed node record (the ENR analog)."""

    peer_id: int
    account: str      # 20-byte hex, no 0x
    host: str
    port: int
    seq: int          # monotonic per node; higher wins on merge
    sig: bytes        # secp256k1 over announce_digest, 65 bytes


@dataclass(frozen=True)
class PeerTableRequest:
    """Ask a peer for its verified announce table (+ its own record)."""


@dataclass(frozen=True)
class PeerTableResponse:
    announces: tuple  # tuple[PeerAnnounce, ...]


def announce_digest(network_id: int, peer_id: int, account_hex: str,
                    host: str, port: int, seq: int) -> bytes:
    return keccak256(
        b"shardp2p-announce:" + network_id.to_bytes(8, "big")
        + peer_id.to_bytes(8, "big")
        + bytes.fromhex(account_hex.lower().removeprefix("0x"))
        + host.encode() + b":" + port.to_bytes(4, "big")
        + seq.to_bytes(8, "big"))


def verify_announce(network_id: int, ann: PeerAnnounce) -> bool:
    try:
        digest = announce_digest(network_id, ann.peer_id, ann.account,
                                 ann.host, int(ann.port), int(ann.seq))
    except (ValueError, AttributeError, OverflowError):
        return False
    return direct.prove(digest, ann.sig, ann.account)


class PeerDirectory:
    """Thread-safe table of peers: verified announces + relay claims.

    Only VERIFIED announces are re-served to other peers (a node never
    launders unsigned relay claims into gossip); claims still feed the
    local dial/broadcast set, with the direct handshake's mutual auth as
    the enforcement point."""

    def __init__(self, network_id: int):
        self.network_id = network_id
        self._lock = threading.Lock()
        self._verified: Dict[int, PeerAnnounce] = {}
        self._claims: Dict[int, dict] = {}     # peer_id -> {account, endpoint}
        self._relay_only: set = set()          # attached without a listener
        self._failures: Dict[int, int] = {}
        self.self_announce: Optional[PeerAnnounce] = None

    # -- self record -------------------------------------------------------

    def make_self(self, peer_id: int, account_hex: str,
                  endpoint: Tuple[str, int],
                  sign: Callable[[bytes], bytes]) -> PeerAnnounce:
        host, port = endpoint
        seq = int(time.time() * 1000)
        sig = sign(announce_digest(self.network_id, peer_id, account_hex,
                                   host, int(port), seq))
        ann = PeerAnnounce(peer_id=peer_id, account=account_hex,
                           host=host, port=int(port), seq=seq, sig=sig)
        with self._lock:
            self.self_announce = ann
            self._verified[peer_id] = ann
        return ann

    # -- merge paths -------------------------------------------------------

    def merge(self, announces) -> int:
        """Verify + absorb gossiped announces; returns how many entries
        were new or fresher (higher seq). One entry per account; the
        table is hard-capped with lowest-seq eviction."""
        changed = 0
        for ann in announces:
            if not isinstance(ann, PeerAnnounce):
                continue
            if not verify_announce(self.network_id, ann):
                continue
            acct = ann.account.lower().removeprefix("0x")
            with self._lock:
                held = self._verified.get(ann.peer_id)
                if held is not None and held.seq >= ann.seq:
                    continue
                # one announce per account: the freshest wins, older
                # peer_ids signed by the same key are dropped
                stale = [pid for pid, a in self._verified.items()
                         if a.account.lower().removeprefix("0x") == acct
                         and pid != ann.peer_id]
                if any(self._verified[pid].seq >= ann.seq for pid in stale):
                    continue
                for pid in stale:
                    del self._verified[pid]
                self_pid = (self.self_announce.peer_id
                            if self.self_announce is not None else None)
                while len(self._verified) >= MAX_VERIFIED:
                    victim = min(
                        (pid for pid in self._verified if pid != self_pid),
                        key=lambda pid: self._verified[pid].seq,
                        default=None)
                    if victim is None:
                        break
                    del self._verified[victim]
                self._verified[ann.peer_id] = ann
                self._claims.pop(ann.peer_id, None)
                self._relay_only.discard(ann.peer_id)
                self._failures.pop(ann.peer_id, None)  # fresh evidence
                changed += 1
        return changed

    def add_claim(self, peer_id: int, account: Optional[str],
                  endpoint) -> None:
        """Relay-table entry (unsigned): usable for dialing, never
        re-gossiped. Endpoint-less peers (the relay protocol allows an
        attach without a listener) are tracked as RELAY-ONLY so
        broadcasts still reach them through the relay."""
        with self._lock:
            if peer_id in self._verified:
                return
            if not endpoint:
                self._relay_only.add(peer_id)
                return
            self._claims[peer_id] = {
                "account": (account or "").lower().removeprefix("0x"),
                "endpoint": (endpoint[0], int(endpoint[1])),
            }
            self._relay_only.discard(peer_id)

    def relay_only_peers(self, exclude: int) -> List[int]:
        """Peers reachable only through the relay (no direct endpoint)."""
        with self._lock:
            return [pid for pid in self._relay_only
                    if pid != exclude and pid not in self._verified
                    and pid not in self._claims]

    # -- reads -------------------------------------------------------------

    def gossip_set(self) -> List[PeerAnnounce]:
        with self._lock:
            return list(self._verified.values())

    def lookup(self, peer_id: int) -> Optional[dict]:
        """peer_info-shaped view: {"account", "endpoint"} or None."""
        with self._lock:
            ann = self._verified.get(peer_id)
            if ann is not None:
                return {"account": ann.account,
                        "endpoint": (ann.host, ann.port)}
            claim = self._claims.get(peer_id)
            return dict(claim) if claim is not None else None

    def live_peers(self, exclude: int) -> List[Tuple[int, dict]]:
        """Dialable peers (verified + claims) that are not failure-aged."""
        with self._lock:
            out = []
            for pid, ann in self._verified.items():
                if pid == exclude:
                    continue
                if self._failures.get(pid, 0) >= DEAD_AFTER_FAILURES:
                    continue
                out.append((pid, {"account": ann.account,
                                  "endpoint": (ann.host, ann.port)}))
            for pid, claim in self._claims.items():
                if pid == exclude or pid in self._verified:
                    continue
                if self._failures.get(pid, 0) >= DEAD_AFTER_FAILURES:
                    continue
                out.append((pid, dict(claim)))
            return out

    # -- liveness ----------------------------------------------------------

    def mark_ok(self, peer_id: int) -> None:
        with self._lock:
            self._failures.pop(peer_id, None)

    def mark_failed(self, peer_id: int) -> None:
        with self._lock:
            self._failures[peer_id] = self._failures.get(peer_id, 0) + 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._verified) + len(self._claims)
