"""Wire message types for the shard data-availability protocol.

Parity: `sharding/p2p/messages/messages.go` (CollationBodyRequest :11,
CollationBodyResponse :20).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from gethsharding_tpu.utils.hexbytes import Address20, Hash32


@dataclass(frozen=True)
class CollationBodyRequest:
    chunk_root: Optional[Hash32]
    shard_id: int
    period: int
    proposer: Optional[Address20]
    # signature of the reconstructed header by the requester
    signature: bytes = b""


@dataclass(frozen=True)
class CollationBodyResponse:
    header_hash: Hash32
    body: bytes
