"""Wire message types for the shard data-availability protocol.

Parity: `sharding/p2p/messages/messages.go` (CollationBodyRequest :11,
CollationBodyResponse :20).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from gethsharding_tpu.utils.hexbytes import Address20, Hash32


@dataclass(frozen=True)
class CollationBodyRequest:
    chunk_root: Optional[Hash32]
    shard_id: int
    period: int
    proposer: Optional[Address20]
    # signature of the reconstructed header by the requester
    signature: bytes = b""


@dataclass(frozen=True)
class CollationBodyResponse:
    header_hash: Hash32
    body: bytes


@dataclass(frozen=True)
class ChunkProofRequest:
    """On-demand-retrieval request (the les/light ODR analog): prove
    body byte `index` against a collation's chunk root."""

    chunk_root: Hash32
    shard_id: int
    period: int
    index: int


@dataclass(frozen=True)
class ChunkProofResponse:
    """Merkle proof for one body byte in the per-byte DeriveSha trie;
    `proof` is the root-to-leaf node-blob list (`trie/proof.go` shape).
    Out-of-range indices get a proof of ABSENCE. `body_len` is the
    serving peer's length claim — a light client PROVES it by checking
    a presence proof at body_len-1 and an absence proof at body_len."""

    chunk_root: Hash32
    index: int
    proof: tuple  # tuple[bytes, ...]
    body_len: int = 0


# -- data-availability sampling (gethsharding_tpu/das) ----------------------


@dataclass(frozen=True)
class DASCommitmentRequest:
    """Who holds the DAS commitment for this (shard, period)?"""

    shard_id: int
    period: int


@dataclass(frozen=True)
class DASCommitmentResponse:
    """The proposer's erasure-extension commitment: the DAS merkle
    root over the extended blob's netstore chunk keys, the code shape
    (k data of n total chunks), the exact body length, and the
    proposer's signature binding all of it to the on-chain chunk_root
    (das/service.commitment_digest)."""

    shard_id: int
    period: int
    chunk_root: Hash32
    das_root: bytes
    k: int
    n: int
    body_len: int
    # 64-byte G1 polynomial commitment to the extended blob's chunk
    # values (das/pcs.py) — empty in merkle-only mode; when present it
    # is signed into the same commitment digest as the merkle root
    poly_commitment: bytes = b""
    signature: bytes = b""


@dataclass(frozen=True)
class DASampleRequest:
    """Sampled-chunk pull: the requester wants chunks `indices` of the
    blob committed at `das_root`, each with its inclusion proof."""

    das_root: bytes
    indices: tuple  # tuple[int, ...]


@dataclass(frozen=True)
class DASampleResponse:
    """One sampled chunk + its sibling path to `das_root` — the unit a
    notary feeds the batched `das_verify_samples` dispatch."""

    das_root: bytes
    index: int
    chunk: bytes
    proof: tuple  # tuple[bytes, ...]


@dataclass(frozen=True)
class DASMultiproofRequest:
    """Multiproof-mode sampled-chunk pull: the requester wants chunks
    `indices` of the blob committed at `das_root` plus ONE constant-
    size polynomial multiproof opening the poly commitment at exactly
    those indices (das/pcs.open_multi)."""

    das_root: bytes
    indices: tuple  # tuple[int, ...]


@dataclass(frozen=True)
class DASMultiproofResponse:
    """All requested chunks + the single 64-byte G1 multiproof — the
    unit a notary or light client turns into one row of the batched
    `das_verify_multiproofs` dispatch (evaluations are derived from
    the chunk bytes host-side, never trusted from the wire)."""

    das_root: bytes
    indices: tuple  # tuple[int, ...]
    chunks: tuple  # tuple[bytes, ...], aligned with indices
    proof: bytes = b""
