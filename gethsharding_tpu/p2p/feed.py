"""Typed event feeds: the in-process pub/sub backbone.

Parity with `event/feed.go` (Feed.Subscribe/Send) and the per-type feed map
in `sharding/p2p/feed.go:27`: a Feed fans a posted value out to every
subscriber's queue; Subscription supports unsubscribe and iteration with
timeouts (services poll with their shutdown event).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, List, Optional


class Subscription:
    def __init__(self, feed: "Feed", maxsize: int = 1024):
        self._feed = feed
        self._queue: "queue.Queue[Any]" = queue.Queue(maxsize=maxsize)
        self.active = True

    def deliver(self, item: Any) -> None:
        try:
            self._queue.put_nowait(item)
        except queue.Full:
            # drop-oldest policy keeps slow consumers from blocking the bus
            try:
                self._queue.get_nowait()
            except queue.Empty:
                pass
            try:
                self._queue.put_nowait(item)
            except queue.Full:
                pass

    def get(self, timeout: Optional[float] = None) -> Any:
        """Blocking receive; raises queue.Empty on timeout."""
        return self._queue.get(timeout=timeout)

    def try_get(self) -> Optional[Any]:
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            return None

    def unsubscribe(self) -> None:
        self.active = False
        self._feed._remove(self)


class Feed:
    """Fan-out channel: every send reaches all active subscribers."""

    def __init__(self):
        self._subs: List[Subscription] = []
        self._lock = threading.Lock()

    def subscribe(self, maxsize: int = 1024) -> Subscription:
        sub = Subscription(self, maxsize=maxsize)
        with self._lock:
            self._subs.append(sub)
        return sub

    def _remove(self, sub: Subscription) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def send(self, item: Any) -> int:
        """Deliver to all subscribers; returns the number reached."""
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            sub.deliver(item)
        return len(subs)

    @property
    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)
