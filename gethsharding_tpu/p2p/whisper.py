"""Whisper analog: PoW-gated, encrypted, topic-addressed messaging on
the shardp2p bus (`whisper/whisperv6` role).

The reference ships whisper as an orthogonal capability stack: darkness-
preserving messaging where envelopes carry a 4-byte topic, a TTL, a
proof-of-work nonce (spam deterrent: required work scales with size x
ttl, `whisperv6/envelope.go` PoW()) and an AES/ECIES-encrypted payload,
flooded to every peer and opened only by nodes holding a matching key
(`whisperv6/whisper.go`, `filter.go`). This module re-expresses that
capability over this framework's transports instead of devp2p: envelopes
are typed bus messages (`p2p/service.py` feeds in-process, the
authenticated relay/direct tier across processes via `rpc/codec.py`).

Kept semantics:
  - envelope = {expiry, ttl, topic, nonce, ciphertext}; its identity is
    keccak256 of the RLP (envelope.go Hash());
  - PoW value = 2^(leading zero bits of hash) / (size * ttl)
    (envelope.go:120 PoW) — minting searches the nonce, relays drop
    envelopes below their threshold (wh.MinPow);
  - symmetric mode: a shared 32-byte topic key (AES-GCM here, matching
    the framework's AEAD baseline rather than v6's AES-GCM too);
  - asymmetric mode: ephemeral secp256k1 ECDH against the recipient's
    public key (the ECIES role, reusing `p2p/direct.py` primitives);
  - filters: subscribe by topic + key; only matching, decryptable,
    unexpired envelopes are delivered (filter.go MatchEnvelope).

Scalar host code by design: messaging is a control-plane capability; the
TPU path stays reserved for the consensus kernels.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from gethsharding_tpu.crypto import secp256k1
from gethsharding_tpu.crypto.keccak import keccak256
from gethsharding_tpu.p2p.direct import (
    AESGCM, InvalidTag, _ecdh_secret, _ephemeral_keypair)
from gethsharding_tpu.utils.rlp import int_to_big_endian, rlp_encode

TOPIC_LEN = 4
DEFAULT_TTL = 60
DEFAULT_MIN_POW = 4.0  # ~2^8 hash attempts for a tiny envelope
_MAX_MINT_ATTEMPTS = 1 << 22


class WhisperError(Exception):
    pass


@dataclass(frozen=True)
class Envelope:
    """The flooded unit. Only ciphertext travels; topic is the routing
    hint (4 bytes of darkness, not a cleartext subject)."""

    expiry: int
    ttl: int
    topic: bytes
    ciphertext: bytes
    nonce: int

    def _rlp(self) -> bytes:
        return rlp_encode([
            int_to_big_endian(self.expiry),
            int_to_big_endian(self.ttl),
            self.topic,
            self.ciphertext,
            int_to_big_endian(self.nonce),
        ])

    def hash(self) -> bytes:
        return keccak256(self._rlp())

    def pow(self) -> float:
        """2^(leading zero bits) / (size * ttl) (envelope.go PoW)."""
        return _pow_of(self._rlp(), self.ttl)


def _pow_of(blob: bytes, ttl: int) -> float:
    digest = keccak256(blob)
    bits = 0
    for byte in digest:
        if byte == 0:
            bits += 8
            continue
        bits += 8 - byte.bit_length()
        break
    return (2.0 ** bits) / (len(blob) * max(ttl, 1))


@dataclass(frozen=True)
class ReceivedMessage:
    payload: bytes
    topic: bytes
    envelope_hash: bytes


def _seal_sym(payload: bytes, key: bytes, topic: bytes) -> bytes:
    if AESGCM is None:  # pragma: no cover - cryptography is baked in
        raise WhisperError("AESGCM unavailable")
    if len(key) != 32:
        raise WhisperError("symmetric key must be 32 bytes")
    iv = os.urandom(12)
    return iv + AESGCM(key).encrypt(iv, payload, topic)


def _open_sym(ciphertext: bytes, key: bytes, topic: bytes) -> bytes:
    if AESGCM is None:  # pragma: no cover - cryptography is baked in
        raise WhisperError("AESGCM unavailable")
    if len(ciphertext) < 13:
        raise WhisperError("ciphertext too short")
    try:
        return AESGCM(key).decrypt(ciphertext[:12], ciphertext[12:], topic)
    except InvalidTag as exc:
        raise WhisperError("wrong key") from exc


def _seal_asym(payload: bytes, recipient_pub64: bytes,
               topic: bytes) -> bytes:
    eph_priv, eph_pub = _ephemeral_keypair()
    secret = _ecdh_secret(eph_priv, recipient_pub64)
    return eph_pub + _seal_sym(payload, secret, topic)


def _open_asym(ciphertext: bytes, priv: int, topic: bytes) -> bytes:
    if len(ciphertext) < 64:
        raise WhisperError("ciphertext too short")
    secret = _ecdh_secret(priv, ciphertext[:64])
    return _open_sym(ciphertext[64:], secret, topic)


def seal(payload: bytes, topic: bytes, *, sym_key: Optional[bytes] = None,
         to_pub: Optional[bytes] = None, ttl: int = DEFAULT_TTL,
         min_pow: float = DEFAULT_MIN_POW,
         now: Optional[float] = None) -> Envelope:
    """Encrypt + PoW-mint an envelope (exactly one key mode)."""
    if len(topic) != TOPIC_LEN:
        raise WhisperError(f"topic must be {TOPIC_LEN} bytes")
    if (sym_key is None) == (to_pub is None):
        raise WhisperError("exactly one of sym_key/to_pub required")
    if sym_key is not None:
        ciphertext = _seal_sym(payload, sym_key, topic)
    else:
        ciphertext = _seal_asym(payload, to_pub, topic)
    expiry = int(now if now is not None else time.time()) + ttl
    # mint without re-encoding the (large, nonce-independent) body every
    # attempt: pre-encode the stable items, vary only the nonce suffix
    # and the list header
    from gethsharding_tpu.utils.rlp import _encode_length

    stable = b"".join(rlp_encode(item) for item in (
        int_to_big_endian(expiry), int_to_big_endian(ttl), topic,
        ciphertext))
    for nonce in range(_MAX_MINT_ATTEMPTS):
        payload = stable + rlp_encode(int_to_big_endian(nonce))
        blob = _encode_length(len(payload), 0xC0) + payload
        if _pow_of(blob, ttl) >= min_pow:
            env = Envelope(expiry=expiry, ttl=ttl, topic=topic,
                           ciphertext=ciphertext, nonce=nonce)
            assert env._rlp() == blob  # one-time self-check per mint
            return env
    raise WhisperError("PoW target unreachable")  # pragma: no cover


class Filter:
    """One subscription: topic + key, with a bounded delivery queue."""

    def __init__(self, topic: bytes, sym_key: Optional[bytes],
                 priv: Optional[int], maxsize: int):
        self.topic = topic
        self.sym_key = sym_key
        self.priv = priv
        self.queue: "queue.Queue[ReceivedMessage]" = queue.Queue(maxsize)

    def try_open(self, env: Envelope) -> Optional[ReceivedMessage]:
        if env.topic != self.topic:
            return None
        try:
            if self.sym_key is not None:
                payload = _open_sym(env.ciphertext, self.sym_key, env.topic)
            elif self.priv is not None:
                payload = _open_asym(env.ciphertext, self.priv, env.topic)
            else:
                return None
        except WhisperError:
            return None
        return ReceivedMessage(payload=payload, topic=env.topic,
                               envelope_hash=env.hash())

    def get(self, timeout: Optional[float] = None) -> ReceivedMessage:
        return self.queue.get(timeout=timeout)


class Whisper:
    """The node-side service: posts envelopes to the bus, matches
    incoming ones against local filters, drops spam (low PoW) and
    expired traffic (whisper.go Send/processQueue)."""

    def __init__(self, p2p, min_pow: float = DEFAULT_MIN_POW):
        self.p2p = p2p
        self.min_pow = min_pow
        self._filters: List[Filter] = []
        self._seen: Dict[bytes, int] = {}  # envelope hash -> expiry
        self._lock = threading.Lock()
        self._sub = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.stats = {"posted": 0, "delivered": 0, "dropped_pow": 0,
                      "dropped_expired": 0, "dropped_future": 0,
                      "dropped_dup": 0}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.p2p.start()  # attach to the hub before envelopes can flow
        self._sub = self.p2p.subscribe(Envelope)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="whisper")
        self._running = True
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._sub is not None:
            self._sub.unsubscribe()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while self._running:
            try:
                message = self._sub.get(timeout=0.2)
            except queue.Empty:
                continue
            env = getattr(message, "data", message)
            if isinstance(env, Envelope):
                try:
                    self._ingest(env)
                except Exception:  # noqa: BLE001 - daemon must survive
                    # a malformed envelope (hostile peer) must not kill
                    # the delivery loop: that would be a permanent DoS
                    # from one message
                    import logging

                    logging.getLogger("sharding.whisper").exception(
                        "dropping malformed envelope")

    # -- posting -----------------------------------------------------------

    def post(self, payload: bytes, topic: bytes, *,
             sym_key: Optional[bytes] = None,
             to_pub: Optional[bytes] = None,
             ttl: int = DEFAULT_TTL,
             pow_target: Optional[float] = None) -> Envelope:
        """Seal, mint and flood an envelope; also delivered locally so a
        node can message itself (whisper.go Send -> postEvent)."""
        env = seal(payload, topic, sym_key=sym_key, to_pub=to_pub,
                   ttl=ttl,
                   min_pow=self.min_pow if pow_target is None
                   else pow_target)
        self.stats["posted"] += 1
        self.p2p.broadcast(env)
        # local delivery is unconditional: a node's own post reaches its
        # own filters even when minted below the node's relay threshold
        self._ingest(env, local=True)
        return env

    # -- receiving ---------------------------------------------------------

    def subscribe(self, topic: bytes, *, sym_key: Optional[bytes] = None,
                  priv: Optional[int] = None,
                  maxsize: int = 256) -> Filter:
        if (sym_key is None) == (priv is None):
            raise WhisperError("exactly one of sym_key/priv required")
        flt = Filter(topic, sym_key, priv, maxsize)
        with self._lock:
            self._filters.append(flt)
        return flt

    def unsubscribe(self, flt: Filter) -> None:
        with self._lock:
            if flt in self._filters:
                self._filters.remove(flt)

    def _ingest(self, env: Envelope, local: bool = False) -> None:
        now = int(time.time())
        if env.expiry < now:
            self.stats["dropped_expired"] += 1
            return
        # an expiry inconsistent with the TTL would pin the dedup cache
        # entry (and duck the PoW-per-ttl economics) — reject it the way
        # the reference bounds expiry to now+ttl (whisper.go add())
        if env.expiry > now + env.ttl + 60:
            self.stats["dropped_future"] += 1
            return
        if not local and env.pow() < self.min_pow:
            self.stats["dropped_pow"] += 1
            return
        digest = env.hash()
        with self._lock:
            if digest in self._seen:
                self.stats["dropped_dup"] += 1
                return
            self._seen[digest] = env.expiry
            if len(self._seen) > 4096:  # expiry sweep, amortized
                self._seen = {h: e for h, e in self._seen.items()
                              if e >= now}
                while len(self._seen) > 8192:  # hard bound: oldest out
                    self._seen.pop(next(iter(self._seen)))
            filters = list(self._filters)
        for flt in filters:
            message = flt.try_open(env)
            if message is not None:
                try:
                    flt.queue.put_nowait(message)
                    self.stats["delivered"] += 1
                except queue.Full:
                    pass


def public_key_bytes(priv: int) -> bytes:
    """64-byte uncompressed public key for asymmetric addressing."""
    pub = secp256k1.pubkey_from_priv(priv)
    return pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")
