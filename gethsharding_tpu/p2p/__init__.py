"""Shard p2p: typed feed bus + request/response messaging.

Parity target: `sharding/p2p/` (feed map Server, messages) — but where the
reference's Send/Broadcast are empty TODO stubs (`sharding/p2p/service.go:
41-50`), this implements the documented intent: typed per-message feeds,
directed send, and broadcast over an in-process hub that multiple nodes
(actors) can attach to, mirroring the sharding README's request/response
data-availability protocol (SURVEY.md §3.4).
"""

from gethsharding_tpu.p2p.feed import Feed, Subscription  # noqa: F401
from gethsharding_tpu.p2p.messages import (  # noqa: F401
    CollationBodyRequest,
    CollationBodyResponse,
)
from gethsharding_tpu.p2p.service import P2PServer, Hub, Peer, Message  # noqa: F401
