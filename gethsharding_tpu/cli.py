"""CLI entry point: `tpu-sharding sharding --actor {notary,proposer,observer}`.

Parity target: `cmd/geth/shardingcmd.go` + the sharding flags in
`cmd/utils/flags.go:536-549`. The full node wiring lands with the actor
services; this module keeps the console-script entry importable.
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from gethsharding_tpu.node.cli import run_cli

    return run_cli(argv)


if __name__ == "__main__":
    raise SystemExit(main())
