"""host-sync: device→host pulls belong in the marshal layer.

A `.item()`, `np.asarray(device_array)`, `jax.device_get` or
`.block_until_ready()` is a synchronous device round trip: the calling
thread stalls until the device drains. The architecture confines those
pulls to the designated marshal/finalize stages (the `sigbackend/`
package, the kernel modules under `ops/`, the mesh code under
`parallel/`, and the
DAS proof marshaller) — everywhere else a pull on the hot path silently
serializes dispatch against device execution (the exact failure mode
PR 3's staging split was built to remove).

This rule flags pull-shaped calls OUTSIDE the allowed zones, in files
that import jax (a pure-NumPy module's `np.asarray` is host→host and
exempt). `jnp.asarray(...)` is host→device marshalling, not a sync, and
is never flagged. Deliberate pulls (the observer's replay mirror, the
SMC state machine's host-resident step boundary) are recorded in the
baseline with justifications rather than exempted here — new ones
should have to argue their case in review.
"""

from __future__ import annotations

import ast
from typing import List

from gethsharding_tpu.analysis.core import (
    Corpus, Finding, SourceFile, dotted_name, rule)

RULE = "host-sync"

# rel-path prefixes (or exact files) where pulls are the job
ALLOWED_ZONES = (
    "gethsharding_tpu/sigbackend/",
    "gethsharding_tpu/ops/",
    "gethsharding_tpu/parallel/",
    "gethsharding_tpu/das/proofs.py",
    "gethsharding_tpu/analysis/",  # the linter itself names the patterns
    # the perfwatch DeviceTimer IS the designated pull site: every
    # timing closes over a checked block+pull by design
    "gethsharding_tpu/perfwatch/timer.py",
)

_PULL_METHODS = {"item", "block_until_ready"}


def _imports_jax(sf: SourceFile) -> bool:
    """Files that can plausibly hold device arrays: direct jax imports,
    or imports of the kernel/mesh modules whose return values are
    device-resident (the observer pulls `replay_jax` outputs without
    ever importing jax itself)."""
    for target in sf.imports.values():
        if target == "jax" or target.startswith("jax."):
            return True
        if target.startswith("gethsharding_tpu.ops") or \
                target.startswith("gethsharding_tpu.parallel"):
            return True
    return False


def _pull_tag(node: ast.Call, sf: SourceFile) -> str:
    """Non-empty tag when this call is a device→host pull shape."""
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr in _PULL_METHODS and not node.args:
            return f".{func.attr}()"
        name = dotted_name(func)
        if name:
            root, _, tail = name.partition(".")
            resolved = sf.imports.get(root, root)
            base = resolved.split(".", 1)[0]
            if tail == "device_get" and base == "jax":
                return "jax.device_get"
            # np.asarray / numpy.asarray — but NOT jnp.asarray
            if tail in ("asarray", "array") and base == "numpy":
                return f"{root}.{tail}"
    return ""


@rule(RULE, "device→host pulls (.item()/np.asarray/device_get/"
            "block_until_ready) outside the marshal stages")
def check(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf in corpus.files:
        if sf.tree is None or any(
                sf.rel == z or sf.rel.startswith(z) for z in ALLOWED_ZONES):
            continue
        if not _imports_jax(sf):
            continue
        per_fn_seen = set()
        # attribute enclosing function names for stable idents
        parents = {}
        for parent in ast.walk(sf.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent

        def qual(node: ast.AST) -> str:
            cur = parents.get(node)
            names = []
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                    names.append(cur.name)
                cur = parents.get(cur)
            return ".".join(reversed(names)) or "<module>"

        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            tag = _pull_tag(node, sf)
            if not tag:
                continue
            where = qual(node)
            ident = f"{where}:{tag}"
            if ident in per_fn_seen:  # one finding per (function, shape)
                continue
            per_fn_seen.add(ident)
            findings.append(Finding(
                RULE, sf.rel, node.lineno,
                f"`{where}` pulls device state to host via `{tag}` outside "
                f"the marshal layer — hot-path host sync",
                ident))
    return findings
