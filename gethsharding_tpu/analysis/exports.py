"""export-completeness: package `__all__`s are complete and truthful.

Generalizes the PR 7 one-off (`FetchAborted` shipped missing from
`resilience.__all__`) into a corpus rule over EVERY package:

- every name in a package's `__init__.__all__` must actually be bound
  in that `__init__.py` (import or assignment) — a dangling export is
  an ImportError waiting for the first `from pkg import *` or
  re-export consumer;
- every public exception class defined in a package's `errors.py`
  must be listed in the package `__all__` — the error surface is API,
  and a new error type that can't be caught by name from the package
  is how PR 4's regression happened.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from gethsharding_tpu.analysis.core import (
    Corpus, Finding, SourceFile, dotted_name, rule)

RULE = "export-completeness"

_EXC_BASES = {"Exception", "BaseException", "RuntimeError", "ValueError",
              "TypeError", "KeyError", "OSError", "IOError",
              "ConnectionError", "TimeoutError", "ArithmeticError",
              "LookupError", "AssertionError", "StopIteration"}


def _all_names(tree: ast.Module) -> Optional[List[ast.Constant]]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "__all__" and \
                isinstance(node.value, (ast.List, ast.Tuple)):
            return [el for el in node.value.elts
                    if isinstance(el, ast.Constant) and
                    isinstance(el.value, str)]
    return None


def _bound_names(tree: ast.Module) -> Set[str]:
    bound: Set[str] = set()
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    bound.add(t.id)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            bound.add(node.target.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            bound.add(node.name)
        elif isinstance(node, (ast.If, ast.Try)):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Import, ast.ImportFrom)):
                    for alias in sub.names:
                        if alias.name != "*":
                            bound.add(alias.asname or
                                      alias.name.split(".")[0])
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            bound.add(t.id)
    return bound


def _public_exceptions(sf: SourceFile) -> List[ast.ClassDef]:
    """Classes in errors.py that are (transitively) exception types."""
    if sf.tree is None:
        return []
    local = {n.name: n for n in sf.tree.body if isinstance(n, ast.ClassDef)}
    memo = {}

    def is_exc(cls: ast.ClassDef) -> bool:
        if cls.name in memo:
            return memo[cls.name]
        memo[cls.name] = False  # cycle guard
        for b in cls.bases:
            name = dotted_name(b)
            if not name:
                continue
            last = name.rsplit(".", 1)[-1]
            if last in _EXC_BASES or last.endswith("Error") and \
                    last not in local:
                memo[cls.name] = True
                break
            if last in local and is_exc(local[last]):
                memo[cls.name] = True
                break
        return memo[cls.name]

    return [cls for cls in local.values()
            if not cls.name.startswith("_") and is_exc(cls)]


@rule(RULE, "package __all__ entries are bound, and every public "
            "errors.py exception is exported")
def check(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf in corpus.files:
        if sf.tree is None or not sf.rel.endswith("/__init__.py"):
            continue
        exported = _all_names(sf.tree)
        if exported is None:
            continue
        package = sf.rel.rsplit("/", 1)[0]
        bound = _bound_names(sf.tree)
        seen: Set[str] = set()
        for el in exported:
            name = el.value
            if name in seen:
                findings.append(Finding(
                    RULE, sf.rel, el.lineno,
                    f"`{name}` listed twice in `__all__`",
                    f"duplicate-export:{package}:{name}"))
            seen.add(name)
            if name not in bound:
                findings.append(Finding(
                    RULE, sf.rel, el.lineno,
                    f"`__all__` exports `{name}` but `__init__.py` never "
                    f"binds it — dangling export",
                    f"dangling-export:{package}:{name}"))
        errors_sf = corpus.get(f"{package}/errors.py")
        if errors_sf is not None:
            for cls in _public_exceptions(errors_sf):
                if cls.name not in seen:
                    findings.append(Finding(
                        RULE, errors_sf.rel, cls.lineno,
                        f"public exception `{cls.name}` in "
                        f"{errors_sf.rel} is missing from "
                        f"`{package}.__all__` — uncatchable by name from "
                        f"the package",
                        f"unexported-error:{package}:{cls.name}"))
    return findings
