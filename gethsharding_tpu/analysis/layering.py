"""layering: the package DAG is declared in layers.json and enforced.

ROADMAP item 1 split the ~1.2k-line ``sigbackend.py`` into the
``sigbackend/`` package (marshal / layout / dispatch / cache); without
a declared dependency structure that refactor (and every PR after it)
can quietly re-tangle the tree — a serving module importing ``node``,
the analysis package growing a runtime dependency, ``sigbackend``
importing the serving tier at module scope and recreating the import
cycle the lazy registry factory exists to avoid. Units that split into
packages additionally declare their INTRA-package DAG (the
``internal`` block): the same two-list contract, one level down, so
``marshal`` staying the bottom of ``sigbackend`` is enforced, not
hoped.

``analysis/layers.json`` is the committed contract: for every
top-level unit of ``gethsharding_tpu`` (a subpackage, or a single
module like ``metrics``/``sigbackend``), the cross-unit imports it may
make — split into ``imports`` (module scope: these define the import
DAG and must stay acyclic where declared) and ``lazy`` (function
scope: the repo's sanctioned cycle-breaking idiom, still declared so
a new back-edge is a decision, not an accident).

Checks, both directions (the flag-doc shape):

- a module-scope cross-unit import absent from the unit's ``imports``
  list -> ``undeclared-import``;
- a function-scope import absent from BOTH lists -> ``undeclared-lazy``
  (anything allowed eagerly is allowed lazily);
- a unit with cross-unit imports but no layers.json entry ->
  ``undeclared-unit`` (new packages must declare their place);
- a declared edge no code exercises -> ``stale-layer`` (the DAG file
  must not accumulate dead permissions);
- hard bans are structural, not just declarative: ``analysis`` may
  import NO runtime unit in either list, and no unit but the
  composition roots (``node``, ``cli``) may import ``node``;
- units with an ``internal`` block get the same checks one level down
  (``internal-undeclared-import``/``-lazy``, ``internal-stale``/
  ``-stale-lazy``), plus: the declared module-scope internal DAG must
  be acyclic (``internal-cycle``) and every declared submodule must
  exist (``internal-unknown-module``).

Import facts come from the corpus's parsed ASTs (the same import-alias
machinery every other rule uses), so string-built importlib calls are
invisible — which is exactly right: the racecheck class registry uses
importlib BECAUSE analysis must not import the runtime packages.
"""

from __future__ import annotations

import ast
import json
from typing import Dict, List, Set, Tuple

from gethsharding_tpu.analysis.core import Corpus, Finding, rule

RULE = "layering"
LAYERS_REL = "gethsharding_tpu/analysis/layers.json"
PACKAGE = "gethsharding_tpu"

# units that may import the composition root; everything else importing
# `node` is an inverted dependency by construction
NODE_IMPORTERS = {"node", "cli"}


def _unit_of(rel: str) -> str:
    parts = rel.split("/")
    if len(parts) < 2 or parts[0] != PACKAGE:
        return ""
    if len(parts) == 2:
        return parts[1][:-3] if parts[1].endswith(".py") else parts[1]
    return parts[1]


def collect_import_edges(corpus: Corpus):
    """((unit, target) -> first (rel, line)) for module-scope and
    function-scope cross-unit imports, from the parsed ASTs."""
    top: Dict[Tuple[str, str], Tuple[str, int]] = {}
    lazy: Dict[Tuple[str, str], Tuple[str, int]] = {}

    for sf in corpus.files:
        if sf.tree is None:
            continue
        unit = _unit_of(sf.rel)
        if not unit:
            continue
        toplevel = {id(n) for n in sf.tree.body}
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            targets: List[str] = []
            if isinstance(node, ast.Import):
                targets = [alias.name.split(".")[1]
                           for alias in node.names
                           if alias.name.startswith(PACKAGE + ".")]
            elif node.level:
                # relative import: resolve against this file's package
                # (same walk as SourceFile.imports) — `from ..fleet
                # import router` inside serving/ IS a cross-unit edge
                # and must not slip the DAG
                base = sf.rel.rsplit("/", 1)[0].replace("/", ".")
                for _ in range(max(node.level - 1, 0)):
                    base = base.rsplit(".", 1)[0]
                module = f"{base}.{node.module}" if node.module else base
                if module == PACKAGE:
                    targets = [alias.name for alias in node.names]
                elif module.startswith(PACKAGE + "."):
                    targets = [module.split(".")[1]]
            elif node.module:
                if node.module == PACKAGE:
                    targets = [alias.name for alias in node.names]
                elif node.module.startswith(PACKAGE + "."):
                    targets = [node.module.split(".")[1]]
            for target in targets:
                if target == unit:
                    continue
                dest = top if id(node) in toplevel else lazy
                dest.setdefault((unit, target), (sf.rel, node.lineno))
    return top, lazy


def collect_internal_edges(corpus: Corpus, unit: str):
    """((sub, target) -> first (rel, line)) for module-scope and
    function-scope imports BETWEEN submodules of one packaged unit.
    Submodule names are file stems (``__init__`` for the package
    root); `from gethsharding_tpu.<unit> import X` resolves to the
    submodule when X is one, else to ``__init__``."""
    prefix = f"{PACKAGE}/{unit}/"
    subs = {sf.rel[len(prefix):-3]
            for sf in corpus.files
            if sf.rel.startswith(prefix) and sf.rel.endswith(".py")
            and "/" not in sf.rel[len(prefix):]}
    top: Dict[Tuple[str, str], Tuple[str, int]] = {}
    lazy: Dict[Tuple[str, str], Tuple[str, int]] = {}
    unit_mod = f"{PACKAGE}.{unit}"

    for sf in corpus.files:
        if sf.tree is None or not sf.rel.startswith(prefix):
            continue
        sub = sf.rel[len(prefix):-3]
        if "/" in sub:
            continue
        toplevel = {id(n) for n in sf.tree.body}
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            targets: List[str] = []
            if isinstance(node, ast.Import):
                targets = [alias.name.split(".")[2]
                           for alias in node.names
                           if alias.name.startswith(unit_mod + ".")]
            else:
                if node.level:
                    base = sf.rel.rsplit("/", 1)[0].replace("/", ".")
                    for _ in range(max(node.level - 1, 0)):
                        base = base.rsplit(".", 1)[0]
                    module = (f"{base}.{node.module}" if node.module
                              else base)
                else:
                    module = node.module or ""
                if module == unit_mod:
                    # names may be submodules (edge to them) or
                    # attributes of the package root (edge to __init__)
                    targets = [alias.name if alias.name in subs
                               else "__init__"
                               for alias in node.names]
                elif module.startswith(unit_mod + "."):
                    targets = [module.split(".")[2]]
            for target in targets:
                if target == sub:
                    continue
                dest = top if id(node) in toplevel else lazy
                dest.setdefault((sub, target), (sf.rel, node.lineno))
    return top, lazy, subs


def _internal_findings(corpus: Corpus, unit: str,
                       internal: dict) -> List[Finding]:
    """The two-list contract one level down, for a unit that split into
    a package: undeclared/stale in both directions, declared-DAG
    acyclicity, and no phantom submodules."""
    findings: List[Finding] = []
    top, lazy, subs = collect_internal_edges(corpus, unit)

    def allowed(sub: str, kind: str) -> Set[str]:
        entry = internal.get(sub)
        if entry is None:
            return set()
        if kind == "imports":
            return set(entry.get("imports", ()))
        return set(entry.get("imports", ())) | set(entry.get("lazy", ()))

    for (sub, target), (rel, line) in sorted(top.items()):
        if target not in allowed(sub, "imports"):
            hint = " (declared lazy-only: move the import into the " \
                   "function that needs it)" \
                if target in allowed(sub, "lazy") else ""
            findings.append(Finding(
                RULE, rel, line,
                f"module-scope intra-package import `{unit}/{sub} -> "
                f"{target}` is not in layers.json's "
                f"`{unit}.internal.{sub}.imports`{hint}",
                f"internal-undeclared-import:{unit}/{sub}->{target}"))
    for (sub, target), (rel, line) in sorted(lazy.items()):
        if target not in allowed(sub, "lazy"):
            findings.append(Finding(
                RULE, rel, line,
                f"function-scope intra-package import `{unit}/{sub} -> "
                f"{target}` is declared nowhere in "
                f"`{unit}.internal.{sub}`",
                f"internal-undeclared-lazy:{unit}/{sub}->{target}"))

    for sub, entry in sorted(internal.items()):
        if sub not in subs:
            findings.append(Finding(
                RULE, LAYERS_REL, 0,
                f"layers.json declares submodule `{unit}.{sub}` but "
                f"`{PACKAGE}/{unit}/{sub}.py` does not exist",
                f"internal-unknown-module:{unit}/{sub}"))
            continue
        for target in sorted(entry.get("imports", ())):
            if (sub, target) not in top:
                findings.append(Finding(
                    RULE, LAYERS_REL, 0,
                    f"layers.json allows `{unit}/{sub} -> {target}` at "
                    f"module scope but no such import exists — stale "
                    f"edge",
                    f"internal-stale:{unit}/{sub}->{target}"))
        for target in sorted(entry.get("lazy", ())):
            if (sub, target) not in lazy:
                findings.append(Finding(
                    RULE, LAYERS_REL, 0,
                    f"layers.json allows lazy `{unit}/{sub} -> "
                    f"{target}` but no function-scope import exists — "
                    f"stale edge",
                    f"internal-stale-lazy:{unit}/{sub}->{target}"))

    # the declared MODULE-SCOPE internal DAG must be acyclic: the lazy
    # list is the sanctioned cycle-breaking idiom, the eager list is
    # the real import graph and a cycle there deadlocks at import time
    graph = {sub: set(entry.get("imports", ()))
             for sub, entry in internal.items()}
    state: Dict[str, int] = {}  # 1 = on stack, 2 = done

    def visit(sub: str, path: List[str]) -> None:
        state[sub] = 1
        for target in sorted(graph.get(sub, ())):
            if state.get(target) == 1:
                cycle = path[path.index(target):] + [target] \
                    if target in path else [sub, target]
                findings.append(Finding(
                    RULE, LAYERS_REL, 0,
                    f"declared internal DAG of `{unit}` has a "
                    f"module-scope cycle: {' -> '.join(cycle)}",
                    f"internal-cycle:{unit}:{'->'.join(cycle)}"))
            elif state.get(target) != 2:
                visit(target, path + [target])
        state[sub] = 2

    for sub in sorted(graph):
        if state.get(sub) != 2:
            visit(sub, [sub])
    return findings


@rule(RULE, "cross-package imports match the DAG declared in "
            "analysis/layers.json (module-scope vs lazy, both "
            "directions)")
def check(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    raw = corpus.read_doc(LAYERS_REL)
    if raw is None:
        return [Finding(RULE, LAYERS_REL, 0,
                        "layers.json is missing — the package DAG must "
                        "be declared and committed",
                        "missing-layers-json")]
    try:
        declared = json.loads(raw).get("units", {})
    except json.JSONDecodeError as exc:
        return [Finding(RULE, LAYERS_REL, 0,
                        f"layers.json is not valid JSON: {exc}",
                        "bad-layers-json")]

    top, lazy = collect_import_edges(corpus)
    units_with_edges: Set[str] = {u for (u, _) in top} | \
        {u for (u, _) in lazy}

    def allowed(unit: str, kind: str) -> Set[str]:
        entry = declared.get(unit)
        if entry is None:
            return set()
        if kind == "imports":
            return set(entry.get("imports", ()))
        # anything allowed eagerly is allowed lazily too
        return set(entry.get("imports", ())) | set(entry.get("lazy", ()))

    for (unit, target), (rel, line) in sorted(top.items()):
        if unit not in declared:
            continue  # reported once as undeclared-unit below
        if target not in allowed(unit, "imports"):
            hint = " (declared lazy-only: move the import into the " \
                   "function that needs it)" \
                if target in allowed(unit, "lazy") else ""
            findings.append(Finding(
                RULE, rel, line,
                f"module-scope import `{unit} -> {target}` is not in "
                f"layers.json's `{unit}.imports`{hint}",
                f"undeclared-import:{unit}->{target}"))
    for (unit, target), (rel, line) in sorted(lazy.items()):
        if unit not in declared:
            continue
        if target not in allowed(unit, "lazy"):
            findings.append(Finding(
                RULE, rel, line,
                f"function-scope import `{unit} -> {target}` is in "
                f"neither `{unit}.imports` nor `{unit}.lazy` in "
                f"layers.json",
                f"undeclared-lazy:{unit}->{target}"))

    for unit in sorted(units_with_edges):
        if unit not in declared:
            rel, line = min(
                [loc for (u, _), loc in list(top.items())
                 + list(lazy.items()) if u == unit])
            findings.append(Finding(
                RULE, rel, line,
                f"unit `{unit}` makes cross-unit imports but has no "
                f"layers.json entry — new packages must declare their "
                f"place in the DAG",
                f"undeclared-unit:{unit}"))

    # stale direction: declared permissions nothing exercises
    for unit, entry in sorted(declared.items()):
        for target in sorted(entry.get("imports", ())):
            if (unit, target) not in top:
                findings.append(Finding(
                    RULE, LAYERS_REL, 0,
                    f"layers.json allows `{unit} -> {target}` at module "
                    f"scope but no such import exists — stale edge",
                    f"stale-layer:{unit}->{target}"))
        for target in sorted(entry.get("lazy", ())):
            if (unit, target) not in lazy:
                findings.append(Finding(
                    RULE, LAYERS_REL, 0,
                    f"layers.json allows lazy `{unit} -> {target}` but "
                    f"no function-scope import exists — stale edge",
                    f"stale-lazy:{unit}->{target}"))

    # packaged units opt into the intra-package DAG with an `internal`
    # block — same contract, one level down
    for unit, entry in sorted(declared.items()):
        if "internal" in entry:
            findings.extend(
                _internal_findings(corpus, unit, entry["internal"]))

    # structural bans, enforced over the DECLARATION so weakening the
    # file is itself a finding
    analysis_entry = declared.get("analysis", {})
    for kind in ("imports", "lazy"):
        for target in analysis_entry.get(kind, ()):
            findings.append(Finding(
                RULE, LAYERS_REL, 0,
                f"analysis must stay runtime-free but layers.json "
                f"grants it `{target}` ({kind}) — the lint must be "
                f"importable without the node",
                f"analysis-not-leaf:{target}"))
    for unit, entry in sorted(declared.items()):
        if unit in NODE_IMPORTERS:
            continue
        for kind in ("imports", "lazy"):
            if "node" in entry.get(kind, ()):
                findings.append(Finding(
                    RULE, LAYERS_REL, 0,
                    f"`{unit}` is granted an import of the composition "
                    f"root `node` ({kind}) — dependencies point INTO "
                    f"the planes, never back out",
                    f"node-inversion:{unit}"))
    return findings
