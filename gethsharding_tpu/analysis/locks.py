"""lock-order: extract the cross-module lock graph, fail on cycles.

Nine subsystems hold `threading.Lock`s (serving dispatcher, watchdog,
router health sweep, breaker, journal, DAS service, SLO tracker, tracer,
metrics); a deadlock needs only two of them to nest in opposite orders
on two threads. This rule builds the static analogue of a lock-order
witness:

- **nodes**: every lock creation site, named `rel::Class.attr` (or
  `rel::NAME` for module-level locks). `threading.Condition(self._lock)`
  aliases to the underlying lock's node; a bare `Condition()` is its own
  node (its hidden RLock is created at that line).
- **edges** `A -> B`: somewhere, B is acquired while A is held — either
  a literally nested `with`, or a call made under A into a method whose
  transitive acquire-set (a fixpoint over the resolved call graph)
  contains B. Calls are resolved through `self.m()`, typed components
  (`self.attr = ClassName(...)`), locally constructed objects, imported
  corpus modules, and annotated factory returns
  (`def counter(...) -> Counter` makes `metrics.counter(...).inc()`
  land on `Counter.inc`).
- **findings**: any strongly-connected component with more than one
  node (a potential AB/BA deadlock), and any self-loop on a
  NON-reentrant lock (a guaranteed self-deadlock if the path executes).

Unresolvable calls (callbacks, getattr indirection) are ignored — the
graph under-approximates, so a clean result is "no deadlock the static
model can see". The runtime validator (`analysis/lockcheck.py`,
`GETHSHARDING_LOCKCHECK=1`) records ACTUAL acquisition orders during
the concurrency tests and cross-checks them against this graph, which
keeps the static model honest from the other side.

The edge extraction is scoped to the threaded subsystems named in the
module list below; the site map covers the whole tree so the runtime
checker can name any lock it sees.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from gethsharding_tpu.analysis.core import (
    Corpus, Finding, SourceFile, dotted_name, rule)

RULE = "lock-order"

# subtrees whose lock nestings form the graph (the threaded subsystems);
# metrics.py is the shared leaf nearly everything calls into under a lock
DEFAULT_SCOPES = (
    "gethsharding_tpu/serving/",
    "gethsharding_tpu/fleet/",
    "gethsharding_tpu/resilience/",
    "gethsharding_tpu/slo/",
    "gethsharding_tpu/tracing/",
    "gethsharding_tpu/metrics.py",
    "gethsharding_tpu/devscope/",
)

_LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": True}


def _lock_ctor(node: ast.AST, sf: SourceFile) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when node is threading.<ctor>(...)."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if not name:
        return None
    root, _, tail = name.rpartition(".")
    if tail not in _LOCK_CTORS:
        return None
    if root:
        base = sf.imports.get(root.split(".", 1)[0], root)
        return tail if base.split(".", 1)[0] == "threading" else None
    return tail if sf.imports.get(tail, "").startswith("threading.") else None


@dataclass
class _ClassInfo:
    rel: str
    name: str  # "<module>" for top-level scope
    node: object
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr -> node id
    reentrant: Set[str] = field(default_factory=set)  # node ids
    attr_types: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)


@dataclass
class LockModel:
    nodes: Set[str] = field(default_factory=set)
    reentrant: Set[str] = field(default_factory=set)
    # (a, b) -> human-readable example site
    edges: Dict[Tuple[str, str], str] = field(default_factory=dict)
    # (rel, lineno of creation call) -> node id, whole tree
    site_map: Dict[Tuple[str, int], str] = field(default_factory=dict)

    def successors(self, node: str) -> List[str]:
        return [b for (a, b) in self.edges if a == node]

    def reachable(self, src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self.successors(cur))
        return False

    def cycles(self) -> List[List[str]]:
        """SCCs with >1 node, plus single-node self-loops."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        order: List[str] = []
        out: List[List[str]] = []
        counter = [0]
        succ = {n: [] for n in self.nodes}
        for (a, b) in self.edges:
            succ.setdefault(a, []).append(b)
            succ.setdefault(b, [])

        def strongconnect(v: str):
            work = [(v, iter(succ[v]))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            order.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        order.append(w)
                        on.add(w)
                        work.append((w, iter(succ[w])))
                        advanced = True
                        break
                    elif w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = order.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        out.append(sorted(comp))
        for v in sorted(succ):
            if v not in index:
                strongconnect(v)
        for (a, b) in self.edges:
            if a == b:
                out.append([a])
        return out


def _class_name_of(call: ast.Call, sf: SourceFile,
                   local_classes: Set[str]) -> Optional[Tuple[str, str]]:
    """(module_rel_dotted, ClassName) when `call` constructs a corpus class."""
    name = dotted_name(call.func)
    if not name:
        return None
    if "." not in name:
        if name in local_classes and name[:1].isupper():
            return ("", name)  # same file
        target = sf.imports.get(name)
        if target and "." in target:
            mod, cls = target.rsplit(".", 1)
            if cls[:1].isupper():
                return (mod, cls)
        return None
    mod_alias, cls = name.rsplit(".", 1)
    if not cls[:1].isupper():
        return None
    module = sf.imports.get(mod_alias.split(".", 1)[0])
    return (module, cls) if module else None


def collect_classes(corpus: Corpus):
    """Pass 1 of the lock model, shared with the race-guard rule
    (analysis/races.py): every class's lock attributes (Condition
    aliasing applied), component attribute types, methods and the
    module-level factory-return annotations, plus a `LockModel` whose
    nodes / reentrancy / site map are filled in (edges still empty).

    Returns ``(classes, factory_returns, model)`` where `classes` maps
    ``(rel, ClassName)`` (and ``(rel, "<module>")``) to `_ClassInfo`.
    """
    model = LockModel()
    classes: Dict[Tuple[str, str], _ClassInfo] = {}
    # (module_rel, fn_name) -> ClassName, from `def f(...) -> Cls:` in file
    factory_returns: Dict[Tuple[str, str], str] = {}

    def note_factory(rel: str, fn: ast.FunctionDef):
        """`def counter(...) -> Counter:` makes call-chain resolution
        (`metrics.counter("x").inc()`) land on Counter.inc."""
        ret = fn.returns
        ret_name = dotted_name(ret) if ret is not None else None
        if isinstance(ret, ast.Constant) and isinstance(ret.value, str):
            ret_name = ret.value.strip('"')
        if ret_name and "." not in ret_name and ret_name[:1].isupper():
            factory_returns[(rel, fn.name)] = ret_name

    # ---- pass 1: locks, component types, factories, site map (whole tree)
    for sf in corpus.files:
        if sf.tree is None:
            continue
        local_classes = {n.name for n in sf.tree.body
                         if isinstance(n, ast.ClassDef)}
        mod_info = _ClassInfo(sf.rel, "<module>", sf.tree)
        classes[(sf.rel, "<module>")] = mod_info

        def record_lock(owner: _ClassInfo, attr: str, call: ast.Call,
                        ctor: str):
            node_id = f"{owner.rel}::{attr}" if owner.name == "<module>" \
                else f"{owner.rel}::{owner.name}.{attr}"
            if ctor == "Condition" and call.args:
                # Condition over an existing lock: alias to its node
                target = dotted_name(call.args[0])
                if target and target.startswith("self."):
                    alias = owner.lock_attrs.get(target[5:])
                    if alias:
                        owner.lock_attrs[attr] = alias
                        return
                elif target and target in mod_info.lock_attrs:
                    owner.lock_attrs[attr] = mod_info.lock_attrs[target]
                    return
            owner.lock_attrs[attr] = node_id
            model.nodes.add(node_id)
            if _LOCK_CTORS[ctor]:
                model.reentrant.add(node_id)
                owner.reentrant.add(node_id)
            model.site_map[(sf.rel, call.lineno)] = node_id

        for top in sf.tree.body:
            if isinstance(top, ast.Assign) and len(top.targets) == 1 and \
                    isinstance(top.targets[0], ast.Name):
                ctor = _lock_ctor(top.value, sf)
                if ctor:
                    record_lock(mod_info, top.targets[0].id, top.value, ctor)
            elif isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod_info.methods[top.name] = top
                note_factory(sf.rel, top)
            elif isinstance(top, ast.ClassDef):
                info = _ClassInfo(sf.rel, top.name, top)
                classes[(sf.rel, top.name)] = info
                for node in ast.walk(top):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) and \
                            node in top.body:
                        info.methods.setdefault(node.name, node)
                        note_factory(sf.rel, node)
                    if isinstance(node, ast.Assign) and \
                            len(node.targets) == 1:
                        tgt = node.targets[0]
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            ctor = _lock_ctor(node.value, sf)
                            if ctor:
                                record_lock(info, tgt.attr, node.value, ctor)
                            elif isinstance(node.value, ast.Call):
                                hit = _class_name_of(node.value, sf,
                                                     local_classes)
                                if hit is not None:
                                    mod, cls = hit
                                    rel = sf.rel if not mod else (
                                        corpus.find_module(mod).rel
                                        if corpus.find_module(mod) else None)
                                    if rel:
                                        info.attr_types[tgt.attr] = (rel, cls)
                    elif isinstance(node, ast.AnnAssign) and \
                            isinstance(node.target, ast.Attribute) and \
                            isinstance(node.target.value, ast.Name) and \
                            node.target.value.id == "self":
                        # `self.peer: "Other" = other` — the annotation
                        # types the component when the value can't
                        ann = node.annotation
                        ann_name = dotted_name(ann)
                        if isinstance(ann, ast.Constant) and \
                                isinstance(ann.value, str):
                            ann_name = ann.value
                        if ann_name:
                            cls = ann_name.rsplit(".", 1)[-1]
                            if cls in local_classes:
                                info.attr_types[node.target.attr] = \
                                    (sf.rel, cls)
                            else:
                                target = sf.imports.get(cls)
                                if target and "." in target:
                                    mod = target.rsplit(".", 1)[0]
                                    other = corpus.find_module(mod)
                                    if other is not None:
                                        info.attr_types[node.target.attr] \
                                            = (other.rel, cls)
    return classes, factory_returns, model


def build_lock_model(corpus: Corpus,
                     scopes: Sequence[str] = DEFAULT_SCOPES) -> LockModel:
    classes, factory_returns, model = collect_classes(corpus)

    def in_scope(rel: str) -> bool:
        return any(rel == s or rel.startswith(s) for s in scopes)

    # ---- pass 2: per-method acquire/call traces (scoped files only)
    # summaries: key -> (direct_acquires, callee_keys, trace records)
    direct: Dict[str, Set[str]] = {}
    callees: Dict[str, Set[str]] = {}
    # (held_node, callee_key, site) across all methods
    calls_under: List[Tuple[str, str, str]] = []

    def method_key(rel: str, cls: str, m: str) -> str:
        return f"{rel}::{cls}.{m}"

    # duck-typed metric sinks: `<anything>.inc()` / `.observe()` on an
    # unresolvable receiver (counters live in dicts and tuples all over
    # the serving tier) conservatively lands on every lock-owning
    # metrics class defining that method — metrics is a strict leaf, so
    # the over-approximation can add edges INTO it but never a cycle
    # through it
    duck_sinks: Dict[str, List[str]] = {}
    metrics_sf = corpus.get("gethsharding_tpu/metrics.py")
    if metrics_sf is not None and metrics_sf.tree is not None:
        for top in metrics_sf.tree.body:
            if not isinstance(top, ast.ClassDef):
                continue
            cinfo = classes.get((metrics_sf.rel, top.name))
            if cinfo is None or not cinfo.lock_attrs:
                continue
            for m in ("inc", "observe", "set"):
                if m in cinfo.methods:
                    duck_sinks.setdefault(m, []).append(
                        method_key(metrics_sf.rel, top.name, m))

    for (rel, cls_name), info in sorted(classes.items()):
        if not in_scope(rel):
            continue
        sf = corpus.get(rel)
        local_classes = {n.name for n in sf.tree.body
                         if isinstance(n, ast.ClassDef)}
        mod_info = classes[(rel, "<module>")]

        for m_name, fn in sorted(info.methods.items()):
            key = method_key(rel, cls_name, m_name)
            direct.setdefault(key, set())
            callees.setdefault(key, set())
            # local var -> (rel, ClassName)
            local_types: Dict[str, Tuple[str, str]] = {}

            def lock_of(expr: ast.AST) -> Optional[str]:
                name = dotted_name(expr)
                if not name:
                    return None
                if name.startswith("self."):
                    return info.lock_attrs.get(name[5:])
                return mod_info.lock_attrs.get(name)

            def resolve_callees(call: ast.Call) -> List[str]:
                func = call.func
                if isinstance(func, ast.Attribute):
                    m = func.attr
                    base = func.value
                    # self.m()
                    if isinstance(base, ast.Name) and base.id == "self":
                        return [method_key(rel, cls_name, m)] \
                            if m in info.methods else []
                    # self.attr.m()
                    bname = dotted_name(base)
                    if bname and bname.startswith("self."):
                        attr = bname[5:]
                        typ = info.attr_types.get(attr)
                        if typ:
                            return [method_key(typ[0], typ[1], m)]
                        return duck_sinks.get(m, [])
                    # local_var.m() / alias.m()
                    if isinstance(base, ast.Name):
                        typ = local_types.get(base.id)
                        if typ:
                            return [method_key(typ[0], typ[1], m)]
                        module = sf.imports.get(base.id)
                        if module:
                            other = corpus.find_module(module)
                            if other is not None:
                                return [method_key(other.rel,
                                                   "<module>", m)]
                        return duck_sinks.get(m, [])
                    # factory(...).m()  e.g. metrics.counter("x").inc()
                    if isinstance(base, ast.Call):
                        for inner in resolve_callees(base):
                            irel, iname = inner.split("::", 1)
                            fn_name = iname.rsplit(".", 1)[-1]
                            cls = factory_returns.get((irel, fn_name))
                            if cls:
                                return [method_key(irel, cls, m)]
                    return duck_sinks.get(m, [])
                if isinstance(func, ast.Name):
                    if func.id in mod_info.methods:
                        return [method_key(rel, "<module>", func.id)]
                    target = sf.imports.get(func.id)
                    if target and "." in target:
                        mod, f_name = target.rsplit(".", 1)
                        other = corpus.find_module(mod)
                        if other is not None:
                            return [method_key(other.rel, "<module>",
                                               f_name)]
                return []

            def visit(node: ast.AST, held: Tuple[str, ...]):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node is not fn:
                    # nested def: body runs later, not under these locks
                    for child in ast.iter_child_nodes(node):
                        visit(child, ())
                    return
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    hit = _class_name_of(node.value, sf, local_classes)
                    if hit is not None:
                        mod, cls = hit
                        trel = rel if not mod else (
                            corpus.find_module(mod).rel
                            if corpus.find_module(mod) else None)
                        if trel:
                            local_types[node.targets[0].id] = (trel, cls)
                if isinstance(node, ast.With):
                    acquired = []
                    for item in node.items:
                        ln = lock_of(item.context_expr)
                        if ln is not None:
                            site = f"{rel}:{item.context_expr.lineno}"
                            direct[key].add(ln)
                            # earlier items of this same `with a, b:` are
                            # already held when this one acquires — they
                            # order-constrain it exactly like an outer with
                            held_here = held + tuple(
                                a for a in acquired if a not in held)
                            for h in held_here:
                                if h != ln:
                                    model.edges.setdefault((h, ln), site)
                                elif ln not in model.reentrant:
                                    model.edges.setdefault((h, ln), site)
                            acquired.append(ln)
                    inner = held + tuple(a for a in acquired
                                         if a not in held)
                    for child in node.body:
                        visit(child, inner)
                    return
                if isinstance(node, ast.Call):
                    for callee in resolve_callees(node):
                        callees[key].add(callee)
                        if held:
                            site = f"{rel}:{node.lineno}"
                            for h in held:
                                calls_under.append((h, callee, site))
                for child in ast.iter_child_nodes(node):
                    visit(child, held)

            for stmt in fn.body:
                visit(stmt, ())

    # ---- pass 3: fixpoint transitive acquire-sets over the call graph
    may: Dict[str, Set[str]] = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for key, cs in callees.items():
            cur = may.setdefault(key, set())
            for c in cs:
                extra = may.get(c)
                if extra and not extra.issubset(cur):
                    cur |= extra
                    changed = True

    # ---- pass 4: lift calls-under-lock into lock→lock edges
    for held, callee, site in calls_under:
        for acquired in may.get(callee, ()):
            if acquired != held:
                model.edges.setdefault((held, acquired), site)
            elif acquired not in model.reentrant:
                model.edges.setdefault((held, acquired), site + " (re-entry)")
    return model


@rule(RULE, "cross-module lock acquisition graph must be cycle-free")
def check(corpus: Corpus) -> List[Finding]:
    model = build_lock_model(corpus)
    findings: List[Finding] = []
    for comp in model.cycles():
        if len(comp) == 1:
            node = comp[0]
            site = model.edges.get((node, node), "?")
            rel = node.split("::", 1)[0]
            m = re.search(r":(\d+)", site)
            line = int(m.group(1)) if m else 0
            findings.append(Finding(
                RULE, rel, line,
                f"non-reentrant lock `{node}` re-acquired while held "
                f"(at {site}) — guaranteed self-deadlock if this path runs",
                f"self-deadlock:{node}"))
            continue
        # name the cycle by its sorted members (stable under edge churn)
        sig = "<->".join(comp)
        sites = []
        for a in comp:
            for b in comp:
                if (a, b) in model.edges:
                    sites.append(f"{a}->{b}@{model.edges[(a, b)]}")
        rel = comp[0].split("::", 1)[0]
        findings.append(Finding(
            RULE, rel, 0,
            f"lock-order cycle between {', '.join(comp)} "
            f"(edges: {'; '.join(sites)}) — opposite nesting orders can "
            f"deadlock",
            f"cycle:{sig}"))
    return findings
