"""jit-purity: no host impurity inside jitted / Pallas kernel functions.

A `jax.jit`/`pjit`/`pallas_call` function body runs at TRACE time; a
`time.time()` read, `random` draw, threading call, or mutation of
enclosing-scope state inside one is at best a silent constant burned
into the compiled program and at worst a correctness bug that only
shows up on the second call. The repo's kernels are pure by
convention; this rule makes the convention mechanical.

Detection is two-phase:

1. collect every jit-wrapped function: ``@jax.jit`` / ``@pjit`` /
   ``@partial(jax.jit, ...)`` decorators, ``jax.jit(fn)`` /
   ``pjit(fn)`` call sites (first positional arg a Name or dotted
   attribute, resolved through the file's imports to defs in other
   corpus modules), and kernels passed to ``pl.pallas_call(kernel,…)``.
2. walk each collected body for impure constructs:
   - calls rooted at the ``time`` / ``random`` / ``threading`` /
     ``secrets`` modules, or ``numpy.random`` chains;
   - ``global`` declarations;
   - stores through an attribute/subscript whose ROOT name is not
     local to the function (params, local assigns, comprehension/for
     targets all count as local) — mutation of captured state.

The walk is shallow on purpose (no interprocedural closure): helpers
called FROM a kernel are usually themselves jitted or trivially pure,
and a deep points-to pass would drown the signal. Nested defs inside a
jitted function are included — they trace with it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from gethsharding_tpu.analysis.core import (
    Corpus, Finding, SourceFile, dotted_name, rule)

RULE = "jit-purity"

_JIT_TAILS = ("jit", "pjit")
_IMPURE_MODULES = {"time", "random", "threading", "secrets"}


def _is_jit_callable(func: ast.AST, sf: SourceFile) -> bool:
    """Is this Call.func a jit/pjit transform?"""
    name = dotted_name(func)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1]
    if last not in _JIT_TAILS:
        return False
    if "." not in name:
        # bare `jit(...)`: require it to be imported from jax-land
        target = sf.imports.get(name, "")
        return target.startswith("jax") or target.endswith(".jit") or \
            target.endswith(".pjit") or name == "pjit"
    return True  # jax.jit / self._jax.jit / pjit-ish attribute chains


def _is_pallas_call(func: ast.AST) -> bool:
    name = dotted_name(func)
    return name is not None and name.rsplit(".", 1)[-1] == "pallas_call"


def _decorator_marks_jit(dec: ast.AST, sf: SourceFile) -> bool:
    if isinstance(dec, ast.Call):
        if _is_jit_callable(dec.func, sf) or _is_pallas_call(dec.func):
            return True
        # @partial(jax.jit, ...) / @functools.partial(jax.jit, ...)
        name = dotted_name(dec.func)
        if name and name.rsplit(".", 1)[-1] == "partial" and dec.args:
            return _is_jit_callable(dec.args[0], sf) or \
                _is_pallas_call(dec.args[0])
        return False
    return _is_jit_callable(dec, sf)


class _DefIndex:
    """name -> FunctionDef nodes, per file (all nesting levels), plus
    `x = functools.partial(fn, ...)` aliases (the pallas kernel idiom:
    ``kernel = partial(_kernel, …); pl.pallas_call(kernel, …)``)."""

    def __init__(self, sf: SourceFile):
        self.by_name: Dict[str, List[ast.FunctionDef]] = {}
        self.partial_of: Dict[str, str] = {}
        if sf.tree is not None:
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.by_name.setdefault(node.name, []).append(node)
                elif isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        isinstance(node.value, ast.Call):
                    fname = dotted_name(node.value.func)
                    if fname and fname.rsplit(".", 1)[-1] == "partial" and \
                            node.value.args:
                        target = dotted_name(node.value.args[0])
                        if target:
                            self.partial_of[node.targets[0].id] = target


def _collect_jitted(corpus: Corpus):
    """-> list of (SourceFile, FunctionDef, how) to purity-check."""
    indexes: Dict[str, _DefIndex] = {}

    def index(sf: SourceFile) -> _DefIndex:
        if sf.rel not in indexes:
            indexes[sf.rel] = _DefIndex(sf)
        return indexes[sf.rel]

    seen: Set[Tuple[str, int]] = set()
    out = []

    def add(sf: SourceFile, fn: ast.FunctionDef, how: str):
        key = (sf.rel, fn.lineno)
        if key not in seen:
            seen.add(key)
            out.append((sf, fn, how))

    def resolve(sf: SourceFile, target: ast.AST) -> Optional[
            Tuple[SourceFile, ast.FunctionDef]]:
        name = dotted_name(target)
        if name is None:
            return None
        idx = index(sf)
        name = idx.partial_of.get(name, name)
        if "." not in name:
            defs = idx.by_name.get(name)
            return (sf, defs[0]) if defs else None
        mod_alias, func = name.rsplit(".", 1)
        if "." in mod_alias:  # self._sec.fn etc.: not statically resolvable
            mod_alias = mod_alias.rsplit(".", 1)[-1]
        module = sf.imports.get(mod_alias)
        if not module:
            return None
        other = corpus.find_module(module)
        if other is None or other.tree is None:
            return None
        defs = index(other).by_name.get(func)
        return (other, defs[0]) if defs else None

    for sf in corpus.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _decorator_marks_jit(dec, sf):
                        add(sf, node, "decorator")
            elif isinstance(node, ast.Call):
                is_jit = _is_jit_callable(node.func, sf)
                is_pallas = _is_pallas_call(node.func)
                if (is_jit or is_pallas) and node.args:
                    hit = resolve(sf, node.args[0])
                    if hit is not None:
                        add(hit[0], hit[1],
                            "pallas_call" if is_pallas else "jit()")
    return out


def _local_names(fn: ast.FunctionDef) -> Set[str]:
    locals_: Set[str] = set()
    args = fn.args
    for a in (list(args.posonlyargs) + list(args.args) +
              list(args.kwonlyargs) +
              ([args.vararg] if args.vararg else []) +
              ([args.kwarg] if args.kwarg else [])):
        locals_.add(a.arg)

    def bind(target: ast.AST):
        if isinstance(target, ast.Name):
            locals_.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                bind(el)
        elif isinstance(target, ast.Starred):
            bind(target.value)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                bind(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            bind(node.target)
        elif isinstance(node, ast.For):
            bind(node.target)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            bind(node.optional_vars)
        elif isinstance(node, ast.comprehension):
            bind(node.target)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            locals_.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                locals_.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.NamedExpr):
            bind(node.target)
    return locals_


def _store_root(target: ast.AST) -> Optional[Tuple[str, ast.AST]]:
    """For a store through Attribute/Subscript, the root Name."""
    node = target
    dotted = isinstance(node, (ast.Attribute, ast.Subscript))
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if dotted and isinstance(node, ast.Name):
        return node.id, node
    return None


def _impure_call(name: str, sf: SourceFile) -> Optional[str]:
    """Non-None = human tag, when dotted call `name` is host-impure."""
    root = name.split(".", 1)[0]
    resolved = sf.imports.get(root, root)
    base = resolved.split(".", 1)[0]
    if base in _IMPURE_MODULES:
        if "." in name:
            return name
        # bare call through a from-import: `from time import time`
        # resolves "time" -> "time.time" (a module member, not the
        # module object itself — calling the module would TypeError
        # anyway)
        if "." in resolved:
            return f"{name} ({resolved})"
    # numpy.random / np.random chains (jax.random is fine: functional)
    if base == "numpy" and ".random." in ("." + name.split(".", 1)[-1] + "."):
        return name
    if "." not in name and resolved.startswith("numpy.random."):
        return f"{name} ({resolved})"
    if name == "print":
        return "print (use jax.debug.print inside kernels)"
    return None


def check_function(sf: SourceFile, fn: ast.FunctionDef,
                   how: str) -> List[Finding]:
    findings: List[Finding] = []
    locals_ = _local_names(fn)
    qual = fn.name

    def emit(node: ast.AST, kind: str, what: str):
        findings.append(Finding(
            RULE, sf.rel, getattr(node, "lineno", fn.lineno),
            f"`{qual}` is jitted ({how}) but {what}",
            f"{qual}:{kind}"))

    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            emit(node, "global:" + ",".join(node.names),
                 f"declares `global {', '.join(node.names)}`")
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name:
                tag = _impure_call(name, sf)
                if tag:
                    emit(node, f"call:{name}", f"calls `{tag}` at trace time")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                hit = _store_root(t)
                if hit and hit[0] not in locals_:
                    emit(t, f"mutate:{hit[0]}",
                         f"mutates enclosing-scope state through "
                         f"`{hit[0]}[...]`/`.attr` — captured objects are "
                         f"trace-time constants")
    return findings


@rule(RULE, "no time/random/threading/global mutation inside "
            "jax.jit / pjit / pallas_call functions")
def check(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf, fn, how in _collect_jitted(corpus):
        findings.extend(check_function(sf, fn, how))
    return findings
