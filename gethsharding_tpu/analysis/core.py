"""shardlint core: corpus loading, rule registry, findings, baseline.

The geth lineage wires `go vet` + the race detector into its build; this
package is the TPU rewrite's analogue — an AST-level pass with
repo-specific rules (jit-purity, host-sync, lock-order, race-guard,
layering, backend-contract, thread-lifecycle, flag-doc,
export-completeness) run by ``python -m gethsharding_tpu.analysis`` and
gated in CI.

Design rules of the framework:

- Every rule is a function ``(corpus) -> list[Finding]`` registered under
  a stable name. Rules read ONLY the corpus (parsed ASTs + repo docs), so
  tests can point them at fixture trees.
- A finding's ``key`` is line-number-free (rule + path + a symbolic
  ident) so routine edits don't churn the committed baseline.
- The baseline file records ACCEPTED findings, each with a one-line
  justification; the gate fails only on findings not in the baseline.
"""

from __future__ import annotations

import ast
import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence

# repo-relative path of the committed baseline
BASELINE_REL = "gethsharding_tpu/analysis/baseline.json"


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location.

    ``ident`` is the stable fingerprint component: a symbol-level
    description (class.method, env var name, lock-cycle signature) that
    survives unrelated line churn. ``line`` is for humans only.
    """

    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    ident: str

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.path}::{self.ident}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed python file plus derived lookup tables."""

    def __init__(self, root: Path, path: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text(encoding="utf-8")
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.text, filename=str(path))
        except SyntaxError as exc:  # surfaced as a finding by the runner
            self.parse_error = exc
        self._imports: Optional[Dict[str, str]] = None

    @property
    def imports(self) -> Dict[str, str]:
        """Local name -> dotted module (or module.symbol) it refers to.

        ``import numpy as np`` -> {"np": "numpy"};
        ``from gethsharding_tpu.ops import bn256_jax`` ->
        {"bn256_jax": "gethsharding_tpu.ops.bn256_jax"};
        ``from x import a as b`` -> {"b": "x.a"}.
        """
        if self._imports is None:
            table: Dict[str, str] = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    if isinstance(node, ast.Import):
                        for alias in node.names:
                            name = alias.asname or alias.name.split(".")[0]
                            table[name] = (alias.name if alias.asname
                                           else alias.name.split(".")[0])
                    elif isinstance(node, ast.ImportFrom):
                        if node.level or not node.module:
                            # relative import: resolve against our package
                            base = self.rel.rsplit("/", 1)[0].replace("/", ".")
                            for _ in range(max(node.level - 1, 0)):
                                base = base.rsplit(".", 1)[0]
                            module = (f"{base}.{node.module}" if node.module
                                      else base)
                        else:
                            module = node.module
                        for alias in node.names:
                            if alias.name == "*":
                                continue
                            name = alias.asname or alias.name
                            table[name] = f"{module}.{alias.name}"
            self._imports = table
        return self._imports

def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Corpus:
    """The parsed source tree the rules run over.

    ``root`` is the repo root; ``files`` covers every ``*.py`` under the
    scanned subtrees. Non-AST inputs the rules need (README.md, bench.py,
    scripts/) are reachable through ``root``.
    """

    # subtrees scanned for AST rules, relative to root
    DEFAULT_SUBTREES = ("gethsharding_tpu",)
    # extra single files / trees the flag rules also read for env knobs
    DEFAULT_EXTRA = ("bench.py", "scripts")

    def __init__(self, root: Path, files: Sequence[SourceFile],
                 extra_files: Sequence[SourceFile] = ()):
        self.root = Path(root)
        self.files = list(files)
        self.extra_files = list(extra_files)
        self._by_rel = {f.rel: f for f in self.files}
        for f in self.extra_files:
            self._by_rel.setdefault(f.rel, f)

    @classmethod
    def load(cls, root, subtrees: Sequence[str] = DEFAULT_SUBTREES,
             extra: Sequence[str] = DEFAULT_EXTRA) -> "Corpus":
        root = Path(root)
        files: List[SourceFile] = []
        for sub in subtrees:
            base = root / sub
            if base.is_file():
                files.append(SourceFile(root, base))
                continue
            for path in sorted(base.rglob("*.py")):
                files.append(SourceFile(root, path))
        extras: List[SourceFile] = []
        for sub in extra:
            base = root / sub
            if base.is_file() and base.suffix == ".py":
                extras.append(SourceFile(root, base))
            elif base.is_dir():
                for path in sorted(base.rglob("*.py")):
                    extras.append(SourceFile(root, path))
        return cls(root, files, extras)

    def get(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)

    def find_module(self, dotted: str) -> Optional[SourceFile]:
        """SourceFile for dotted module 'gethsharding_tpu.serving.queue'."""
        rel = dotted.replace(".", "/")
        return self._by_rel.get(rel + ".py") or \
            self._by_rel.get(rel + "/__init__.py")

    def read_doc(self, rel: str) -> Optional[str]:
        path = self.root / rel
        if path.is_file():
            return path.read_text(encoding="utf-8")
        return None


# -- rule registry -----------------------------------------------------------

RuleFn = Callable[[Corpus], List[Finding]]
RULES: Dict[str, RuleFn] = {}
RULE_DOCS: Dict[str, str] = {}


def rule(name: str, doc: str) -> Callable[[RuleFn], RuleFn]:
    def register(fn: RuleFn) -> RuleFn:
        RULES[name] = fn
        RULE_DOCS[name] = doc
        return fn
    return register


def _parse_findings(corpus: Corpus) -> List[Finding]:
    out = []
    for f in corpus.files:
        if f.parse_error is not None:
            out.append(Finding("parse", f.rel, f.parse_error.lineno or 0,
                               f"syntax error: {f.parse_error.msg}",
                               "syntax-error"))
    return out


def run_rules(corpus: Corpus,
              names: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the selected rules (default: all) and return sorted findings."""
    # rule modules self-register on import; pull them in here so callers
    # (tests, __main__) need only the package
    from gethsharding_tpu.analysis import (  # noqa: F401
        contract, exports, flags, hostsync, layering, lifecycle, locks,
        purity, races)

    selected = list(names) if names is not None else sorted(RULES)
    unknown = [n for n in selected if n not in RULES]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)} "
                       f"(have: {', '.join(sorted(RULES))})")
    findings = _parse_findings(corpus)
    for name in selected:
        findings.extend(RULES[name](corpus))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.ident))
    return findings


# -- baseline ----------------------------------------------------------------

@dataclass
class Baseline:
    """Accepted findings: key -> one-line justification."""

    entries: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def load(cls, path) -> "Baseline":
        path = Path(path)
        if not path.is_file():
            return cls()
        data = json.loads(path.read_text(encoding="utf-8"))
        return cls(dict(data.get("findings", {})))

    def save(self, path) -> None:
        payload = {
            "_comment": ("shardlint baseline: accepted findings with a "
                         "one-line justification each; the gate fails "
                         "only on keys NOT listed here. Regenerate with "
                         "`python -m gethsharding_tpu.analysis "
                         "--write-baseline` and fill in justifications."),
            "findings": {k: self.entries[k] for k in sorted(self.entries)},
        }
        Path(path).write_text(json.dumps(payload, indent=2) + "\n",
                              encoding="utf-8")

    def split(self, findings: Sequence[Finding]):
        """(new, accepted, stale_keys) against this baseline."""
        keys = {f.key for f in findings}
        new = [f for f in findings if f.key not in self.entries]
        accepted = [f for f in findings if f.key in self.entries]
        stale = sorted(k for k in self.entries if k not in keys)
        return new, accepted, stale


@dataclass
class RunReport:
    findings: List[Finding]
    new: List[Finding]
    accepted: List[Finding]
    stale: List[str]
    elapsed_s: float


def run(root, names: Optional[Iterable[str]] = None,
        baseline_path=None) -> RunReport:
    """Load the corpus at `root`, run rules, diff against the baseline."""
    t0 = time.monotonic()
    corpus = Corpus.load(root)
    findings = run_rules(corpus, names)
    if baseline_path is None:
        baseline_path = Path(root) / BASELINE_REL
    baseline = Baseline.load(baseline_path)
    new, accepted, stale = baseline.split(findings)
    return RunReport(findings, new, accepted, stale,
                     time.monotonic() - t0)
