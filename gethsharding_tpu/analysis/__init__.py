"""shardlint: repo-wide static analysis for the TPU sharding node.

The build-time half of the integrity story (the soundness spot-checker
is the runtime half): AST rules enforcing the invariants the threaded
subsystems and the jitted-kernel surface depend on. Run with
``python -m gethsharding_tpu.analysis``; gate is zero findings outside
the committed baseline (`analysis/baseline.json`).

Rules: jit-purity, host-sync, lock-order, race-guard, layering,
backend-contract, thread-lifecycle, flag-doc, export-completeness.
Two rules are cross-validated at runtime: the static lock graph by
`analysis/lockcheck.py` (``GETHSHARDING_LOCKCHECK=1``) and the
race-guard lockset model by the access sanitizer
`analysis/racecheck.py` (``GETHSHARDING_RACECHECK=1``).
"""

from gethsharding_tpu.analysis.core import (
    BASELINE_REL, Baseline, Corpus, Finding, RULE_DOCS, RULES, RunReport,
    run, run_rules)

__all__ = [
    "BASELINE_REL",
    "Baseline",
    "Corpus",
    "Finding",
    "RULES",
    "RULE_DOCS",
    "RunReport",
    "run",
    "run_rules",
]
