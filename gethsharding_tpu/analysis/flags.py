"""flag-doc: every knob the code reads is documented, and vice versa.

The README's flag tables are the operational contract: a
`GETHSHARDING_*` env var or `--flag` that exists only in code is a knob
nobody can discover, and a documented one that no code reads is a doc
that lies. Both directions rot silently; this rule diffs them
mechanically.

Code side:
- env vars: every string literal (and f-string skeleton) shaped
  `GETHSHARDING_[A-Z0-9_]*` anywhere in the package, bench.py and
  scripts/ — call args, dict keys, comparisons — EXCEPT docstrings.
  Dynamic names (`f"GETHSHARDING_CLASS_{op}"`) become skeletons with
  `*` at the formatted holes.
- CLI flags: `add_argument("--…")` literals. Flags of the package CLIs
  (gethsharding_tpu/**) must be documented; bench.py/scripts flags only
  feed the stale-doc direction (internal tools may keep private knobs).

Doc side (README.md): `GETHSHARDING_…` tokens anywhere (placeholders
like `<NAME>` become skeleton holes), `--flag`-shaped tokens anywhere.

Checks:
- code env var with no README mention        -> undocumented-env
- README env var no code reads               -> stale-env-doc
- package CLI flag with no README mention    -> undocumented-flag
- README `--flag` no parser defines          -> stale-flag-doc
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from gethsharding_tpu.analysis.core import Corpus, Finding, rule

RULE = "flag-doc"
DOC_FILES = ("README.md",)

_ENV_RE = re.compile(r"^GETHSHARDING_[A-Z0-9_]*$")
_DOC_ENV_RE = re.compile(r"GETHSHARDING(?:_(?:[A-Z0-9]+|<[A-Za-z_]+>))+_?")
_DOC_FLAG_RE = re.compile(r"--[a-z0-9][a-z0-9-]*")


def _skeleton_to_regex(skel: str) -> "re.Pattern[str]":
    parts = [re.escape(p) for p in skel.split("*")]
    return re.compile("^" + "[A-Z0-9_]+".join(parts) + "$")


def _code_env_tokens(corpus: Corpus) -> Dict[str, Tuple[str, int]]:
    """token/skeleton -> first (rel, line). Skeletons contain '*'."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in list(corpus.files) + list(corpus.extra_files):
        if sf.tree is None:
            continue
        skip = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                body = getattr(node, "body", [])
                if body and isinstance(body[0], ast.Expr) and \
                        isinstance(body[0].value, ast.Constant):
                    skip.add(id(body[0].value))  # docstring
            elif isinstance(node, ast.JoinedStr):
                for v in node.values:  # pieces count via the skeleton
                    skip.add(id(v))
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and id(node) not in skip:
                token = node.value
                if _ENV_RE.match(token):
                    if token.endswith("_"):
                        token += "*"  # concatenation prefix
                    out.setdefault(token, (sf.rel, node.lineno))
            elif isinstance(node, ast.JoinedStr):
                parts = []
                for v in node.values:
                    if isinstance(v, ast.Constant) and \
                            isinstance(v.value, str):
                        parts.append(v.value)
                    else:
                        parts.append("*")
                skel = "".join(parts)
                if skel.startswith("GETHSHARDING_") and \
                        _ENV_RE.match(skel.replace("*", "X")):
                    out.setdefault(skel, (sf.rel, node.lineno))
    return out


_FLAG_LIT_RE = re.compile(r"^--[a-z0-9][a-z0-9-]*$")


def _code_flag_tokens(corpus: Corpus, package_only: bool) -> \
        Dict[str, Tuple[str, int]]:
    out: Dict[str, Tuple[str, int]] = {}
    files = list(corpus.files) if package_only else \
        list(corpus.files) + list(corpus.extra_files)
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "add_argument":
                for arg in node.args:
                    if isinstance(arg, ast.Constant) and \
                            isinstance(arg.value, str) and \
                            arg.value.startswith("--"):
                        out.setdefault(arg.value, (sf.rel, node.lineno))
            elif not package_only and isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _FLAG_LIT_RE.match(node.value):
                # hand-rolled `"--das" in sys.argv` parsing (bench.py):
                # counts as a defined flag for the stale-doc direction
                out.setdefault(node.value, (sf.rel, node.lineno))
    return out


def _doc_tokens(corpus: Corpus):
    env: Set[str] = set()
    flags: Set[str] = set()
    for rel in DOC_FILES:
        text = corpus.read_doc(rel)
        if text is None:
            continue
        for tok in _DOC_ENV_RE.findall(text):
            tok = tok.rstrip("_") if tok.endswith("_") and \
                not tok.endswith("__") else tok
            env.add(re.sub(r"<[A-Za-z_]+>", "*", tok))
        # EVERY `--flag`-shaped token anywhere in the doc counts — the
        # shape doesn't occur in prose, and tying this to backtick
        # pairing breaks on fenced code blocks (3-backtick fences flip
        # span parity) and on multi-flag spans
        flags.update(_DOC_FLAG_RE.findall(text))
    return env, flags


def _env_documented(token: str, doc_env: Set[str]) -> bool:
    if token in doc_env:
        return True
    literals = [d for d in doc_env if "*" not in d]
    skeletons = [d for d in doc_env if "*" in d]
    if "*" in token:
        # a skeleton is documented if the doc has the same skeleton or
        # a literal instance of it (the autotune prefix case)
        pat = _skeleton_to_regex(token)
        return any(pat.match(lit) for lit in literals)
    return any(_skeleton_to_regex(skel).match(token) for skel in skeletons)


def _env_exists(token: str, code_env: Dict[str, Tuple[str, int]]) -> bool:
    if token in code_env:
        return True
    code_literals = [c for c in code_env if "*" not in c]
    code_skels = [c for c in code_env if "*" in c]
    if "*" in token:
        pat = _skeleton_to_regex(token)
        return any(pat.match(lit) for lit in code_literals) or \
            any(_skeleton_to_regex(c).pattern == pat.pattern
                for c in code_skels)
    return any(_skeleton_to_regex(c).match(token) for c in code_skels)


@rule(RULE, "GETHSHARDING_* env vars and CLI --flags are documented in "
            "the README flag tables, and the tables don't go stale")
def check(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    doc_env, doc_flags = _doc_tokens(corpus)
    code_env = _code_env_tokens(corpus)
    pkg_flags = _code_flag_tokens(corpus, package_only=True)
    all_flags = _code_flag_tokens(corpus, package_only=False)

    for token, (rel, line) in sorted(code_env.items()):
        if not _env_documented(token, doc_env):
            findings.append(Finding(
                RULE, rel, line,
                f"env var `{token.replace('*', '<...>')}` is read here but "
                f"appears nowhere in {' / '.join(DOC_FILES)}",
                f"undocumented-env:{token}"))
    for token in sorted(doc_env):
        if not _env_exists(token, code_env):
            findings.append(Finding(
                RULE, DOC_FILES[0], 0,
                f"documented env var `{token.replace('*', '<...>')}` is "
                f"read by no code — stale doc",
                f"stale-env-doc:{token}"))
    for flag, (rel, line) in sorted(pkg_flags.items()):
        if flag not in doc_flags:
            findings.append(Finding(
                RULE, rel, line,
                f"CLI flag `{flag}` is defined here but appears in no "
                f"mention in {' / '.join(DOC_FILES)}",
                f"undocumented-flag:{flag}"))
    for flag in sorted(doc_flags):
        if flag not in all_flags:
            findings.append(Finding(
                RULE, DOC_FILES[0], 0,
                f"documented CLI flag `{flag}` is defined by no parser — "
                f"stale doc",
                f"stale-flag-doc:{flag}"))
    return findings
