"""Runtime access sanitizer — the dynamic half of the race-guard rule.

``GETHSHARDING_RACECHECK=1`` (tests/conftest.py installs it, or call
:func:`install` directly) patches ``__setattr__`` on the REGISTERED
component classes of the threaded planes with recording wrappers, and
piggybacks on the lock-order recorder (analysis/lockcheck.py) so every
instrumented write knows which locks its thread holds:

- per ``(instance, attribute)`` the recorder runs the Eraser state
  machine: writes by the creating thread only are EXCLUSIVE (the
  init-only idiom, free); the moment a second thread writes, the
  attribute is SHARED and a running lockset intersection starts —
  every subsequent write intersects in the labels of the locks held at
  that write. An empty intersection on a shared attribute is a
  runtime race witness, caught even on schedules that happen not to
  corrupt anything this run;
- records aggregate per ``rel::Class.attr`` — exactly the static race
  model's keys — with the first shared-write site kept as evidence;
- :func:`verify_against_static` cross-validates: a runtime-unguarded
  shared write to an attribute the static model calls ``guarded`` (or
  ``init-only``) is a VIOLATION — one of the two is wrong, either the
  code races or the model's call-graph resolution over-promised; a
  statically-``racy`` attribute never observed shared at runtime is an
  honest COVERAGE GAP (the tests never drove that interleaving), and
  one observed shared-and-unguarded is a runtime CONFIRMATION.

The wrappers cost one dict hop and a held-lockset read per write on
instrumented classes only; like lockcheck this is test-harness
overhead, never production overhead (install is explicit). Instance
state lives in a side table keyed by ``id(obj)``; ``__init__`` is
wrapped too so a fresh allocation at a dead instance's address resets
its record instead of inheriting a stale writer-thread history.

Honest limitation: ``__setattr__`` sees attribute REBINDS and
augmented assignments only — in-place container mutation
(``self._x[k] = v``, ``self._x.append(...)``) never reaches the
wrapper, so those sites are covered by the static rule alone. The
coverage-gap report exists precisely to keep that asymmetry visible.
"""

from __future__ import annotations

import importlib
import os
import threading
import traceback
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from gethsharding_tpu.analysis import lockcheck

# the instrumented component classes of the threaded planes, as
# "module:Class" specs resolved lazily at install (importlib, so the
# layering rule's static no-runtime-imports contract for analysis/
# holds). Underscore helpers included: they hold the per-thread state.
DEFAULT_CLASSES = (
    "gethsharding_tpu.serving.queue:AdmissionQueue",
    "gethsharding_tpu.serving.batcher:MicroBatcher",
    "gethsharding_tpu.serving.pipeline:PipelinedDispatcher",
    "gethsharding_tpu.fleet.router:Replica",
    "gethsharding_tpu.fleet.router:FleetRouter",
    "gethsharding_tpu.fleet.router:RpcReplicaBackend",
    "gethsharding_tpu.fleet.frontend:FrontendServer",
    "gethsharding_tpu.fleet.membership:FleetMembership",
    "gethsharding_tpu.fleet.autoscaler:Autoscaler",
    "gethsharding_tpu.resilience.breaker:CircuitBreaker",
    "gethsharding_tpu.resilience.watchdog:DispatchWatchdog",
    "gethsharding_tpu.slo.tracker:SLOTracker",
    "gethsharding_tpu.slo.tracker:_Series",
    "gethsharding_tpu.tracing.tracer:Tracer",
    "gethsharding_tpu.metrics:Counter",
    "gethsharding_tpu.metrics:Gauge",
    "gethsharding_tpu.metrics:Histogram",
    "gethsharding_tpu.metrics:Timer",
    "gethsharding_tpu.metrics:Registry",
    "gethsharding_tpu.metrics:InfluxLineExporter",
    "gethsharding_tpu.rpc.server:RPCServer",
    "gethsharding_tpu.rpc.client:RPCClient",
)

@dataclass
class AttrRecord:
    """Aggregated evidence for one ``rel::Class.attr``."""

    key: str
    writes: int = 0
    writer_threads: Set[int] = field(default_factory=set)
    shared: bool = False  # some INSTANCE saw a second writer thread
    # running intersection of creation-site lock labels over all
    # shared-phase writes (None until the first shared write)
    lockset: Optional[FrozenSet[str]] = None
    first_shared_site: str = ""

    @property
    def unguarded(self) -> bool:
        return self.shared and not self.lockset


class _InstState:
    __slots__ = ("first_thread", "attr_threads")

    def __init__(self, tid: int):
        self.first_thread = tid
        self.attr_threads: Dict[str, Set[int]] = {}


class _Recorder:
    def __init__(self):
        self._mutex = lockcheck.real_lock()
        self.records: Dict[str, AttrRecord] = {}
        self._instances: Dict[int, _InstState] = {}
        self.writes_seen = 0

    def _site(self) -> str:
        for frame in reversed(traceback.extract_stack()[:-2]):
            fn = frame.filename.replace(os.sep, "/")
            if "racecheck.py" in fn or "lockcheck.py" in fn:
                continue
            idx = fn.find("gethsharding_tpu")
            if idx >= 0:
                return f"{fn[idx:]}:{frame.lineno}"
            return f"{fn}:{frame.lineno}"
        return "?"

    def on_init(self, obj) -> None:
        """A registered class is constructing: (re)create the instance
        record. Keyed by ``id(obj)``, so a fresh allocation at a dead
        instance's address must RESET here — otherwise the stale
        record's writer threads would make ordinary ``__init__`` writes
        look cross-thread-shared (observed in long pytest sessions)."""
        with self._mutex:
            self._instances[id(obj)] = _InstState(threading.get_ident())

    def on_write(self, obj, cls_key: str, attr: str) -> None:
        tid = threading.get_ident()
        held = lockcheck.current_held_labels()
        key = f"{cls_key}.{attr}"
        with self._mutex:
            self.writes_seen += 1
            inst = self._instances.get(id(obj))
            if inst is None:
                inst = self._instances[id(obj)] = _InstState(tid)
            threads = inst.attr_threads.setdefault(attr, set())
            threads.add(tid)
            record = self.records.get(key)
            if record is None:
                record = self.records[key] = AttrRecord(key)
            record.writes += 1
            record.writer_threads.add(tid)
            if len(threads) > 1:
                # Eraser shared phase for THIS instance: intersect in
                # the held locks (creation-site labels, the static site
                # map's currency)
                if not record.shared:
                    record.shared = True
                    record.first_shared_site = self._site()
                if record.lockset is None:
                    record.lockset = frozenset(held)
                else:
                    record.lockset &= frozenset(held)


_recorder: Optional[_Recorder] = None
# class -> (original __setattr__, original __init__); None entries mean
# the class inherited the slot
_patched: Dict[type, Tuple[Optional[object], Optional[object]]] = {}
_installed = False
_owns_lockcheck = False  # did OUR install patch threading?


def _resolve(spec: str) -> Optional[type]:
    module, _, cls = spec.partition(":")
    try:
        mod = importlib.import_module(module)
    except Exception:  # pragma: no cover - optional plane not importable
        return None
    return getattr(mod, cls, None)


def class_key(cls: type) -> str:
    """``rel::Class`` matching the static model's keys."""
    rel = cls.__module__.replace(".", "/") + ".py"
    return f"{rel}::{cls.__qualname__}"


_class_key = class_key


def _make_setattr(cls_key: str, orig):
    def recording_setattr(self, name, value):
        recorder = _recorder
        if recorder is not None:
            recorder.on_write(self, cls_key, name)
        orig(self, name, value)
    recording_setattr._racecheck_wrapped = orig  # uninstall marker
    return recording_setattr


def install(classes: Sequence[str] = DEFAULT_CLASSES,
            record_paths: Optional[Sequence[str]] = None) -> None:
    """Patch the registered classes' ``__setattr__`` (idempotent) and
    make sure the lock recorder is on — without it every write would
    look unguarded. Extra classes can be registered later with
    :func:`register`. `record_paths` forwards to the lock recorder
    (tests add their own tree so fixture locks get labels); it has no
    effect when a recorder is already installed."""
    global _recorder, _installed, _owns_lockcheck
    if _installed:
        return
    _owns_lockcheck = not lockcheck.active()
    if record_paths is not None:
        lockcheck.install(record_paths=record_paths)
    else:
        lockcheck.install()
    _recorder = _Recorder()
    _installed = True
    for spec in classes:
        cls = _resolve(spec)
        if cls is not None:
            register(cls)


def _make_init(orig):
    def recording_init(self, *args, **kwargs):
        recorder = _recorder
        if recorder is not None:
            recorder.on_init(self)
        orig(self, *args, **kwargs)
    recording_init._racecheck_wrapped = orig  # uninstall marker
    return recording_init


def register(cls: type) -> None:
    """Instrument one more class (tests register their fixtures)."""
    if not _installed or cls in _patched:
        return
    orig_set = cls.__dict__.get("__setattr__")
    base_set = orig_set if orig_set is not None else cls.__setattr__
    cls.__setattr__ = _make_setattr(_class_key(cls), base_set)
    orig_init = cls.__dict__.get("__init__")
    base_init = orig_init if orig_init is not None else cls.__init__
    cls.__init__ = _make_init(base_init)
    _patched[cls] = (orig_set, orig_init)


def uninstall() -> None:
    """Restore every patched class. The lock recorder is uninstalled
    only if OUR install patched it — a session lockcheck
    (GETHSHARDING_LOCKCHECK=1) someone else installed stays; and a
    fixture-scoped racecheck must not leak wrapped locks into the rest
    of a plain test session."""
    global _recorder, _installed, _owns_lockcheck
    for cls, (orig_set, orig_init) in _patched.items():
        for name, orig in (("__setattr__", orig_set),
                           ("__init__", orig_init)):
            if orig is not None:
                setattr(cls, name, orig)
            else:
                try:
                    delattr(cls, name)
                except AttributeError:  # pragma: no cover - already gone
                    pass
    _patched.clear()
    _recorder = None
    _installed = False
    if _owns_lockcheck:
        lockcheck.uninstall()
        _owns_lockcheck = False


def active() -> bool:
    return _installed


def reset() -> None:
    global _recorder
    if _installed:
        _recorder = _Recorder()


def report() -> Dict[str, AttrRecord]:
    """Aggregated per-attribute records so far."""
    if _recorder is None:
        return {}
    with _recorder._mutex:
        return dict(_recorder.records)


def stats() -> dict:
    rep = report()
    return {
        "classes_instrumented": len(_patched),
        "attrs_written": len(rep),
        "writes_seen": 0 if _recorder is None else _recorder.writes_seen,
        "shared_attrs": sum(1 for r in rep.values() if r.shared),
        "unguarded_shared": sum(1 for r in rep.values() if r.unguarded),
    }


@dataclass
class Verdict:
    """The cross-validation outcome (mirrors lockcheck.Verdict)."""

    violations: List[str]  # runtime contradicts the static claim
    confirmations: List[str]  # both sides agree the attr races
    coverage_gaps: List[str]  # statically racy, never driven shared

    @property
    def ok(self) -> bool:
        return not self.violations


def verify_against_static(model=None, root=None,
                          baseline_keys: Optional[Set[str]] = None
                          ) -> Verdict:
    """Cross-check observed write locksets against the static race
    model (built from `root` when not given). Observed lock labels are
    ``rel:line`` creation sites, mapped onto static lock nodes through
    the SAME site map the lock-order rule exports — the two checkers
    literally share their vocabulary."""
    if model is None:
        from pathlib import Path

        from gethsharding_tpu.analysis.core import Corpus
        from gethsharding_tpu.analysis.races import build_race_model

        if root is None:
            root = Path(__file__).resolve().parents[2]
        model = build_race_model(Corpus.load(root))

    def nodes_of(labels: Optional[FrozenSet[str]]) -> FrozenSet[str]:
        if not labels:
            return frozenset()
        out = set()
        for label in labels:
            rel, _, line = label.rpartition(":")
            try:
                node = model.site_map.get((rel, int(line)))
            except ValueError:
                node = None
            out.add(node if node is not None else label)
        return frozenset(out)

    baseline_keys = baseline_keys or set()
    violations: List[str] = []
    confirmations: List[str] = []
    gaps: List[str] = []
    observed = report()

    for key, record in sorted(observed.items()):
        verdict = model.verdict(key)
        if verdict is None:
            continue  # attr the static model does not track (dunder &c)
        if not record.shared:
            continue
        runtime_nodes = nodes_of(record.lockset)
        if verdict.classification == "guarded":
            if not runtime_nodes:
                violations.append(
                    f"{key}: static model says guarded by "
                    f"{{{', '.join(sorted(verdict.guards))}}} but a "
                    f"shared write ran with NO lock held (first at "
                    f"{record.first_shared_site}) — the model's "
                    f"call-graph resolution over-promised or the code "
                    f"races")
            elif not runtime_nodes & verdict.guards:
                violations.append(
                    f"{key}: static guard "
                    f"{{{', '.join(sorted(verdict.guards))}}} never in "
                    f"the runtime lockset "
                    f"{{{', '.join(sorted(runtime_nodes))}}} (first "
                    f"shared write at {record.first_shared_site}) — "
                    f"guarded by a DIFFERENT lock than modeled")
        elif verdict.classification == "init-only":
            violations.append(
                f"{key}: static model says init-only but "
                f"{len(record.writer_threads)} threads wrote it (first "
                f"shared write at {record.first_shared_site}) — a "
                f"post-publication write the model missed")
        elif verdict.classification == "racy" and record.unguarded:
            confirmations.append(
                f"{key}: statically flagged AND observed unguarded-"
                f"shared at runtime (first at {record.first_shared_site})"
                + (" (baselined: justified)" if key in baseline_keys
                   else " — fix or baseline it"))
        # publication / atomic-type: shared unguarded writes are the
        # modeled idiom; nothing to say

    for key, verdict in sorted(model.attrs.items()):
        if verdict.classification != "racy":
            continue
        record = observed.get(key)
        if record is None or not record.shared:
            gaps.append(
                f"{key}: statically racy but never observed written "
                f"from two threads this run — coverage gap, not "
                f"exoneration"
                + (" (baselined)" if key in baseline_keys else ""))
    return Verdict(violations, confirmations, gaps)
