"""race-guard: Eraser-style lockset inference over the threaded planes.

The reference geth client runs its notary/proposer goroutines under
Go's race detector; this rule is the static half of our analogue. The
lock-order rule (analysis/locks.py) catches locks nested in conflicting
orders — but it is blind to shared mutable state guarded by NO lock at
all, which is the dominant failure mode in the thread-heavy serving /
fleet / resilience / slo / tracing / rpc planes (dispatcher threads,
router health sweeps, watchdogs, SLO rings, RPC handler threads).

The model, per the classic Eraser algorithm adapted to the repo's real
idioms:

- **Threaded classes.** A class is thread-shared when it owns a started
  `threading.Thread`, allocates a lock (a class that buys a lock
  declares itself shared), or is reachable from one — constructed or
  held (typed attributes, container annotations, `__init__` parameter
  annotations) by a threaded class, or constructed inside a function a
  threaded class's methods call (the lifecycle.py escape-to-call
  spirit: `slo.record()` runs on the flusher thread, so the tracker it
  lazily builds is thread-shared).
- **Locksets.** For every write to a `self._x`-style attribute of a
  threaded class the rule computes the set of locks statically held at
  the site: literal `with` nesting (reusing the lock-node identities of
  analysis/locks.py, so the runtime sanitizer can cross-check against
  the same site map) PLUS the method's guaranteed ENTRY lockset — the
  intersection, over every resolved call site, of the locks held there
  (a private helper only ever called under `self._lock` inherits the
  guard; a fixpoint handles helper chains and recursion).
- **Verdicts.** An attribute whose write-site lockset intersection is
  empty is a race candidate — UNLESS it is init-only (written in
  `__init__` / init-only helpers before the object is published),
  an atomic-by-convention type (`threading.Event`, locks, queues,
  `deque`, `threading.local`), or a pure snapshot publication (every
  write is a plain rebind of a fresh value — the GIL makes a single
  reference store atomic, and the repo's snapshot-swap idiom depends
  on exactly that). Read-modify-writes (`+=`, rebinds reading the old
  value), container mutation (`self._x[k] = v`, `.append()`, aliased
  element pops) and check-then-act lazy initialization
  (`if self._x is None: self._x = ...` with no lock) stay findings,
  with the conflicting sites listed.

Like every shardlint rule the graph under-approximates: unresolvable
receivers are ignored, so "guarded" claims are only as strong as the
call-graph resolution — which is why the runtime access sanitizer
(analysis/racecheck.py, ``GETHSHARDING_RACECHECK=1``) records REAL
per-thread write locksets and `verify_against_static` makes each side
vouch for the other: a runtime-unguarded write the static map calls
guarded is a violation; a statically-flagged attribute never observed
written off-thread is an honest coverage gap.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from gethsharding_tpu.analysis.core import (
    Corpus, Finding, SourceFile, dotted_name, rule)
from gethsharding_tpu.analysis.locks import (
    _class_name_of, collect_classes)

RULE = "race-guard"

# the thread-heavy subtrees findings are reported for (the whole corpus
# still feeds threadedness and call resolution)
DEFAULT_SCOPES = (
    "gethsharding_tpu/serving/",
    "gethsharding_tpu/fleet/",
    "gethsharding_tpu/resilience/",
    "gethsharding_tpu/slo/",
    "gethsharding_tpu/tracing/",
    "gethsharding_tpu/metrics.py",
    "gethsharding_tpu/rpc/",
    "gethsharding_tpu/devscope/",
)

# atomic-by-convention constructor names: attributes holding these are
# synchronization primitives or internally-synchronized containers, not
# racy state (threading.*, queue.*, collections.deque)
ATOMIC_CTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "local", "Thread",
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "deque",
}

# receiver methods that mutate the receiver in place — a call
# `self._x.append(...)` is a WRITE to _x's value, not a read
MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "sort", "reverse", "__setitem__",
}

# write kinds, in increasing "needs a lock" order
KIND_REBIND = "rebind"      # self._x = fresh_value (atomic publication)
KIND_LAZY = "lazy-init"     # rebind guarded by a test on the same attr
KIND_RMW = "rmw"            # self._x += 1 / self._x = f(self._x)
KIND_MUTATE = "mutate"      # self._x[k] = v / self._x.append(...)

RACY_KINDS = (KIND_LAZY, KIND_RMW, KIND_MUTATE)


@dataclass
class Access:
    """One attribute access site with its static lockset."""

    rel: str
    cls: str
    attr: str
    line: int
    kind: str  # KIND_* for writes, "read" for reads
    method: str  # method key "rel::Cls.m" the access occurs in
    held: FrozenSet[str]  # literal lock nodes held at the site
    init_phase: bool = False  # inside __init__ / init-only helpers

    def site(self) -> str:
        return f"{self.rel}:{self.line}"


@dataclass
class AttrVerdict:
    """The per-attribute classification the cross-validator reads."""

    key: str  # "rel::Cls.attr" — matches the runtime recorder's keys
    classification: str  # guarded | init-only | atomic-type |
    #                      publication | racy | unwritten
    guards: FrozenSet[str] = frozenset()  # lock nodes, when guarded
    writes: List[Access] = field(default_factory=list)
    init_writes: List[Access] = field(default_factory=list)
    reads: List[Access] = field(default_factory=list)
    atomic_type: Optional[str] = None


@dataclass
class RaceModel:
    """Everything the rule derived: per-attribute verdicts plus the
    threadedness set (for non-vacuity checks) and the lock site map
    (shared with the runtime sanitizer)."""

    attrs: Dict[str, AttrVerdict] = field(default_factory=dict)
    threaded: Set[Tuple[str, str]] = field(default_factory=set)
    scoped_threaded: Set[Tuple[str, str]] = field(default_factory=set)
    site_map: Dict[Tuple[str, int], str] = field(default_factory=dict)

    def verdict(self, key: str) -> Optional[AttrVerdict]:
        return self.attrs.get(key)


# ---------------------------------------------------------------------------
# type lattice helpers: (rel, ClassName) scalar types and container
# element types, resolved through annotations
# ---------------------------------------------------------------------------

_CONTAINER_ANNOTATIONS = {"List", "list", "Sequence", "Tuple", "tuple",
                          "Set", "set", "FrozenSet", "frozenset",
                          "Iterable", "Deque", "deque"}
_DICT_ANNOTATIONS = {"Dict", "dict", "Mapping", "MutableMapping",
                     "OrderedDict", "DefaultDict", "defaultdict"}
_PASSTHROUGH_ANNOTATIONS = {"Optional"}


def _ann_strings(node: ast.AST) -> Optional[ast.AST]:
    """Unquote string annotations: `x: "Replica"` -> a Name-ish str."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            return ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    return node


def _resolve_class_name(name: str, sf: SourceFile, corpus: Corpus,
                        local_classes: Set[str]) -> Optional[Tuple[str, str]]:
    """Class name (possibly dotted) -> (rel, ClassName) in the corpus.

    Unlike the lock model's `_class_name_of`, underscore-prefixed
    helper classes (`_Series`, `_OpMetrics`) resolve too — they hold
    exactly the per-thread state this rule exists to check."""
    cls = name.rsplit(".", 1)[-1]
    if cls in local_classes and "." not in name:
        return (sf.rel, cls)
    if not cls.lstrip("_")[:1].isupper():
        return None
    target = sf.imports.get(name.split(".", 1)[0])
    if "." in name and target:
        other = corpus.find_module(target)
        if other is not None:
            return (other.rel, cls)
        return None
    target = sf.imports.get(cls)
    if target and "." in target:
        mod, cname = target.rsplit(".", 1)
        other = corpus.find_module(mod)
        if other is not None and cname == cls:
            return (other.rel, cls)
    return None


def _annotation_type(ann: Optional[ast.AST], sf: SourceFile, corpus: Corpus,
                     local_classes: Set[str]):
    """Annotation AST -> ('scalar'|'elem', (rel, cls)) or None.

    `Replica` -> scalar; `List[Replica]` / `Dict[str, Replica]` /
    `Optional[Replica]` (scalar) -> the element class; strings unquoted.
    """
    ann = _ann_strings(ann) if ann is not None else None
    if ann is None:
        return None
    if isinstance(ann, ast.Subscript):
        base = dotted_name(ann.value)
        if not base:
            return None
        head = base.rsplit(".", 1)[-1]
        inner = ann.slice
        if head in _PASSTHROUGH_ANNOTATIONS:
            return _annotation_type(inner, sf, corpus, local_classes)
        if head in _DICT_ANNOTATIONS and isinstance(inner, ast.Tuple) \
                and len(inner.elts) == 2:
            hit = _annotation_type(inner.elts[1], sf, corpus, local_classes)
            if hit is not None:
                return ("elem", hit[1])
            return None
        if head in _CONTAINER_ANNOTATIONS:
            if isinstance(inner, ast.Tuple):
                inner = inner.elts[0] if inner.elts else None
            hit = _annotation_type(inner, sf, corpus, local_classes) \
                if inner is not None else None
            if hit is not None:
                return ("elem", hit[1])
            return None
        return None
    name = dotted_name(ann)
    if not name:
        return None
    hit = _resolve_class_name(name, sf, corpus, local_classes)
    return ("scalar", hit) if hit is not None else None


def _ctor_class(call: ast.Call, sf: SourceFile, corpus: Corpus, rel: str,
                local_classes: Set[str]) -> Optional[Tuple[str, str]]:
    """(rel, ClassName) when `call` constructs a corpus class —
    `_class_name_of` plus underscore-prefixed local helper classes."""
    name = dotted_name(call.func)
    if name and "." not in name and name in local_classes:
        return (rel, name)
    hit = _class_name_of(call, sf, local_classes)
    if hit is None:
        return None
    mod, cls = hit
    if not mod:
        return (rel, cls)
    other = corpus.find_module(mod)
    return (other.rel, cls) if other is not None else None


def _atomic_ctor(node: ast.AST, sf: SourceFile) -> Optional[str]:
    """'Event'/'Queue'/... when node constructs an atomic-by-convention
    type from threading / queue / collections."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if not name:
        return None
    root, _, tail = name.rpartition(".")
    if tail not in ATOMIC_CTORS:
        return None
    if root:
        base = sf.imports.get(root.split(".", 1)[0], root).split(".", 1)[0]
        return tail if base in ("threading", "queue", "collections") else None
    target = sf.imports.get(tail, "")
    return tail if target.split(".", 1)[0] in ("threading", "queue",
                                               "collections") else None


# ---------------------------------------------------------------------------
# the model builder
# ---------------------------------------------------------------------------

def build_race_model(corpus: Corpus,
                     scopes: Sequence[str] = DEFAULT_SCOPES) -> RaceModel:
    classes, factory_returns, lock_model = collect_classes(corpus)
    model = RaceModel(site_map=dict(lock_model.site_map))

    def in_scope(rel: str) -> bool:
        return any(rel == s or rel.startswith(s) for s in scopes)

    # ---- enriched per-class typing tables ---------------------------------
    # (rel, cls) -> attr -> ('scalar'|'elem', (rel, cls))
    attr_typing: Dict[Tuple[str, str], Dict[str, Tuple[str, Tuple[str, str]]]]
    attr_typing = {}
    # (rel, cls) -> attr -> atomic ctor name
    attr_atomic: Dict[Tuple[str, str], Dict[str, str]] = {}
    # (rel, cls) -> method name -> ('scalar'|'elem', (rel, cls)) return
    # type from the annotation ('elem' = container of that class, so
    # `for x in self.members():` types the loop variable)
    method_returns: Dict[
        Tuple[str, str], Dict[str, Tuple[str, Tuple[str, str]]]] = {}
    # (rel, cls) -> classes constructed anywhere in its methods
    constructs: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}

    local_classes_of: Dict[str, Set[str]] = {}
    for sf in corpus.files:
        if sf.tree is not None:
            local_classes_of[sf.rel] = {
                n.name for n in sf.tree.body if isinstance(n, ast.ClassDef)}

    # pass A: method return annotations for EVERY class first, so the
    # attribute-typing pass can resolve annotated factory calls across
    # classes (`self.g = registry.gauge(...)` with `Registry.gauge()
    # -> Gauge` types the attribute no matter the collection order)
    for (rel, cls_name), info in classes.items():
        sf = corpus.get(rel)
        if sf is None or sf.tree is None:
            method_returns[(rel, cls_name)] = {}
            continue
        local_classes = local_classes_of.get(rel, set())
        returns: Dict[str, Tuple[str, Tuple[str, str]]] = {}
        for m_name, fn in info.methods.items():
            ret = fn.returns
            hit = _annotation_type(ret, sf, corpus, local_classes) \
                if ret is not None else None
            if hit is not None:
                returns[m_name] = hit
        method_returns[(rel, cls_name)] = returns

    for (rel, cls_name), info in classes.items():
        sf = corpus.get(rel)
        if sf is None or sf.tree is None:
            continue
        local_classes = local_classes_of.get(rel, set())
        typing: Dict[str, Tuple[str, Tuple[str, str]]] = {}
        atomics: Dict[str, str] = {}
        built: Set[Tuple[str, str]] = set()
        # direct component types from the shared collector
        for attr, (trel, tcls) in info.attr_types.items():
            typing.setdefault(attr, ("scalar", (trel, tcls)))
        for m_name, fn in info.methods.items():
            # __init__ param annotations type the matching self.<x> = x
            # (and list(x)/dict(x)/tuple(x)) stores
            param_types: Dict[str, Tuple[str, Tuple[str, str]]] = {}
            for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
                hit = _annotation_type(arg.annotation, sf, corpus,
                                       local_classes) \
                    if arg.annotation is not None else None
                if hit is not None:
                    param_types[arg.arg] = hit
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    hit2 = _ctor_class(node, sf, corpus, rel, local_classes)
                    if hit2 is not None:
                        built.add(hit2)
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                tgt = targets[0] if len(targets) == 1 else None
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                value = node.value
                if value is None:
                    continue
                kind = _atomic_ctor(value, sf)
                if kind is not None:
                    atomics[tgt.attr] = kind
                    continue
                # self.x = <param> / list(<param>) / dict(<param>)
                src = None
                if isinstance(value, ast.Name):
                    src = value.id
                elif isinstance(value, ast.Call) and \
                        isinstance(value.func, ast.Name) and \
                        value.func.id in ("list", "dict", "tuple", "set") \
                        and len(value.args) == 1 and \
                        isinstance(value.args[0], ast.Name):
                    src = value.args[0].id
                if src is not None and src in param_types:
                    typing.setdefault(tgt.attr, param_types[src])
                    continue
                # self.g = registry.gauge(...) — an annotated factory
                # method on a typed parameter/component types the attr
                if isinstance(value, ast.Call) and \
                        isinstance(value.func, ast.Attribute):
                    rname = dotted_name(value.func.value)
                    rtype = None
                    if rname and rname in param_types and \
                            param_types[rname][0] == "scalar":
                        rtype = param_types[rname][1]
                    elif rname and rname.startswith("self."):
                        own = typing.get(rname[5:])
                        if own is not None and own[0] == "scalar":
                            rtype = own[1]
                    if rtype is not None:
                        hit = method_returns.get(rtype, {}).get(
                            value.func.attr)
                        if hit is not None and hit[0] == "scalar":
                            typing.setdefault(tgt.attr, hit)
                            continue
                # self.x = {k: Cls(...) for ...} / [Cls(...) for ...]
                elt = None
                if isinstance(value, ast.DictComp):
                    elt = value.value
                elif isinstance(value, (ast.ListComp, ast.SetComp)):
                    elt = value.elt
                if isinstance(elt, ast.Call):
                    hit2 = _ctor_class(elt, sf, corpus, rel, local_classes)
                    if hit2 is not None:
                        typing.setdefault(tgt.attr, ("elem", hit2))
                # AnnAssign annotations (scalar or container)
                if isinstance(node, ast.AnnAssign):
                    hit = _annotation_type(node.annotation, sf, corpus,
                                           local_classes)
                    if hit is not None:
                        typing.setdefault(tgt.attr, hit)
        attr_typing[(rel, cls_name)] = typing
        attr_atomic[(rel, cls_name)] = atomics
        constructs[(rel, cls_name)] = built

    # ---- threadedness -----------------------------------------------------
    def _owns_thread(info) -> bool:
        for fn in info.methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func)
                    if name and name.rsplit(".", 1)[-1] == "Thread":
                        sf = corpus.get(info.rel)
                        root = name.rpartition(".")[0]
                        if root:
                            base = sf.imports.get(root.split(".", 1)[0],
                                                  root)
                            if base.split(".", 1)[0] == "threading":
                                return True
                        elif sf.imports.get("Thread",
                                            "") == "threading.Thread":
                            return True
        return False

    threaded: Set[Tuple[str, str]] = set()
    for key, info in classes.items():
        if info.name == "<module>":
            continue
        if _owns_thread(info):
            threaded.add(key)
        elif in_scope(info.rel) and info.lock_attrs:
            # a scoped class that allocates a lock declares itself
            # thread-shared — the lock IS the evidence
            threaded.add(key)

    # closure over held/constructed components and reachable calls:
    # a threaded class's components are thread-shared; functions its
    # methods call run on its threads, so classes built there are too
    changed = True
    reachable_scopes: Set[Tuple[str, str]] = set(threaded)
    while changed:
        changed = False
        for key in list(reachable_scopes):
            for attr, (_, tkey) in attr_typing.get(key, {}).items():
                for target in (tkey,):
                    if target in classes and target not in threaded:
                        threaded.add(target)
                        reachable_scopes.add(target)
                        changed = True
            for built in constructs.get(key, ()):
                if built in classes and built not in threaded:
                    threaded.add(built)
                    reachable_scopes.add(built)
                    changed = True

    # module scopes whose functions threaded code calls (one hop through
    # the import-alias tables — `slo.record(...)`, `tracing.span(...)`)
    # contribute the classes they construct
    module_hops: Set[str] = set()
    for key in threaded:
        info = classes.get(key)
        sf = corpus.get(info.rel) if info else None
        if info is None or sf is None:
            continue
        for fn in info.methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name):
                    module = sf.imports.get(node.func.value.id)
                    if module:
                        other = corpus.find_module(module)
                        if other is not None:
                            module_hops.add(other.rel)
    for rel in module_hops:
        for built in constructs.get((rel, "<module>"), ()):
            if built in classes:
                threaded.add(built)
        # one re-export hop: gethsharding_tpu/slo/__init__.py pulls
        # record()/tracker() from slo/tracker.py
        sf = corpus.get(rel)
        if sf is None:
            continue
        for target in set(sf.imports.values()):
            mod = target.rsplit(".", 1)[0] if "." in target else target
            other = corpus.find_module(mod)
            if other is not None:
                for built in constructs.get((other.rel, "<module>"), ()):
                    if built in classes:
                        threaded.add(built)
    # classes constructed by threaded <module> functions' constructions
    changed = True
    while changed:
        changed = False
        for key in list(threaded):
            for built in constructs.get(key, ()):
                if built in classes and built not in threaded:
                    threaded.add(built)
                    changed = True
            for attr, (_, tkey) in attr_typing.get(key, {}).items():
                if tkey in classes and tkey not in threaded:
                    threaded.add(tkey)
                    changed = True

    model.threaded = threaded
    model.scoped_threaded = {k for k in threaded if in_scope(k[0])
                             and classes[k].name != "<module>"}

    # ---- access + call extraction over the scoped classes -----------------
    accesses: List[Access] = []
    # method key -> [(caller key, frozen held at site)]
    call_sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
    method_keys: Set[str] = set()

    def mkey(rel: str, cls: str, m: str) -> str:
        return f"{rel}::{cls}.{m}"

    for (rel, cls_name), info in sorted(classes.items()):
        if not in_scope(rel) or info.name == "<module>":
            continue
        sf = corpus.get(rel)
        if sf is None or sf.tree is None:
            continue
        local_classes = local_classes_of.get(rel, set())
        mod_info = classes.get((rel, "<module>"))
        typing = attr_typing.get((rel, cls_name), {})
        returns = method_returns.get((rel, cls_name), {})

        for m_name, fn in sorted(info.methods.items()):
            key = mkey(rel, cls_name, m_name)
            method_keys.add(key)
            # local name -> ('scalar'|'elem', (rel, cls)); parameter
            # annotations seed it (`def _burns(self, series: _Series)`)
            local_types: Dict[str, Tuple[str, Tuple[str, str]]] = {}
            for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
                if arg.annotation is not None:
                    hit = _annotation_type(arg.annotation, sf, corpus,
                                           local_classes)
                    if hit is not None:
                        local_types[arg.arg] = hit
            # local name -> (cls_key, attr) alias of a self container
            local_alias: Dict[str, Tuple[Tuple[str, str], str]] = {}

            def typed(expr) -> Optional[Tuple[str, str]]:
                """Scalar class of an expression, best effort."""
                name = dotted_name(expr)
                if name:
                    if name == "self":
                        return (rel, cls_name)
                    if name.startswith("self."):
                        hit = typing.get(name[5:])
                        if hit is not None and hit[0] == "scalar":
                            return hit[1]
                        return None
                    root = name.split(".", 1)[0]
                    hit = local_types.get(root)
                    if hit is not None and "." not in name:
                        return hit[1] if hit[0] == "scalar" else None
                    return None
                if isinstance(expr, ast.Subscript):
                    base = dotted_name(expr.value)
                    if base and base.startswith("self."):
                        hit = typing.get(base[5:])
                        if hit is not None and hit[0] == "elem":
                            return hit[1]
                    elif base and base in local_types:
                        hit = local_types[base]
                        if hit[0] == "elem":
                            return hit[1]
                    return None
                if isinstance(expr, ast.Call):
                    func = expr.func
                    if isinstance(func, ast.Attribute):
                        recv_base = dotted_name(func.value)
                        # self._series.get(name) -> element type
                        if func.attr == "get" and recv_base:
                            if recv_base.startswith("self."):
                                hit = typing.get(recv_base[5:])
                                if hit is not None and hit[0] == "elem":
                                    return hit[1]
                            elif recv_base in local_types:
                                hit = local_types[recv_base]
                                if hit[0] == "elem":
                                    return hit[1]
                        # self._replica(name) -> Replica (annotation)
                        if isinstance(func.value, ast.Name) and \
                                func.value.id == "self":
                            hit = returns.get(func.attr)
                            if hit is not None and hit[0] == "scalar":
                                return hit[1]
                        # typed_receiver.m() -> m's return annotation
                        owner = typed(func.value)
                        if owner is not None:
                            hit = method_returns.get(owner, {}) \
                                .get(func.attr)
                            if hit is not None and hit[0] == "scalar":
                                return hit[1]
                    elif isinstance(func, ast.Name):
                        hit2 = _ctor_class(expr, sf, corpus, rel,
                                           local_classes)
                        if hit2 is not None:
                            return hit2
                        target = sf.imports.get(func.id)
                        if target and "." in target:
                            mod, f_name = target.rsplit(".", 1)
                            other = corpus.find_module(mod)
                            if other is not None:
                                # module-level factory annotation
                                fr = factory_returns.get(
                                    (other.rel, f_name))
                                if fr:
                                    return (other.rel, fr)
                return None

            def lock_of(expr) -> Optional[str]:
                name = dotted_name(expr)
                if not name:
                    return None
                if name.startswith("self."):
                    return info.lock_attrs.get(name[5:])
                if "." in name:
                    root, attr = name.split(".", 1)
                    if "." in attr:
                        return None
                    hit = local_types.get(root)
                    if hit is not None and hit[0] == "scalar" and \
                            hit[1] in classes:
                        return classes[hit[1]].lock_attrs.get(attr)
                    return None
                if mod_info is not None:
                    return mod_info.lock_attrs.get(name)
                return None

            def attr_target(expr) -> Optional[Tuple[Tuple[str, str], str]]:
                """((rel, cls), attr) written when `expr` is the
                assignment target root: self.x, typed_local.x,
                self._replica(n).x, alias[k]-style roots."""
                if not isinstance(expr, ast.Attribute):
                    return None
                base = expr.value
                bname = dotted_name(base)
                if bname == "self":
                    return ((rel, cls_name), expr.attr)
                owner = typed(base)
                if owner is not None and owner in classes:
                    return (owner, expr.attr)
                return None

            def root_attr(expr) -> Optional[Tuple[Tuple[str, str], str]]:
                """The (class, attr) whose VALUE a subscript/mutating
                call touches: `self._x[k]`, `alias.pop()` where alias
                came from `self._x[...]` or `self._x`."""
                if isinstance(expr, ast.Subscript):
                    return root_attr(expr.value)
                if isinstance(expr, ast.Attribute):
                    hit = attr_target(expr)
                    return hit
                if isinstance(expr, ast.Name):
                    return local_alias.get(expr.id)
                return None

            def rhs_reads(value: ast.AST, target: Tuple) -> bool:
                for node in ast.walk(value):
                    if isinstance(node, ast.Attribute):
                        if attr_target(node) == target:
                            return True
                return False

            init_phase = m_name == "__init__"

            def record_write(target, line, kind, held):
                (trel, tcls), attr = target
                accesses.append(Access(trel, tcls, attr, line, kind,
                                       key, frozenset(held),
                                       init_phase=init_phase))

            def resolve_call(call: ast.Call) -> List[str]:
                func = call.func
                if isinstance(func, ast.Attribute):
                    base = func.value
                    if isinstance(base, ast.Name) and base.id == "self":
                        if func.attr in info.methods:
                            return [mkey(rel, cls_name, func.attr)]
                        return []
                    owner = typed(base)
                    if owner is not None and owner in classes and \
                            func.attr in classes[owner].methods:
                        return [mkey(owner[0], owner[1], func.attr)]
                    return []
                if isinstance(func, ast.Name):
                    if mod_info is not None and \
                            func.id in mod_info.methods:
                        return [mkey(rel, "<module>", func.id)]
                return []

            def visit(node: ast.AST, held: Tuple[str, ...],
                      guards: FrozenSet[Tuple] = frozenset()):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node is not fn:
                    # nested def runs later, on an unknown thread with
                    # no locks held
                    for child in ast.iter_child_nodes(node):
                        visit(child, (), frozenset())
                    return
                if isinstance(node, ast.With):
                    acquired = []
                    for item in node.items:
                        ln = lock_of(item.context_expr)
                        if ln is not None:
                            acquired.append(ln)
                    inner = held + tuple(a for a in acquired
                                         if a not in held)
                    for child in node.body:
                        visit(child, inner, guards)
                    return
                if isinstance(node, ast.For):
                    # `for replica in self.replicas:` /
                    # `for s in self._series.values():` /
                    # `for k, s in self._series.items():` type the loop
                    # variable from the container's element type
                    src = node.iter
                    values = items = False
                    if isinstance(src, ast.Call) and \
                            isinstance(src.func, ast.Attribute) and \
                            src.func.attr in ("values", "items"):
                        values = src.func.attr == "values"
                        items = src.func.attr == "items"
                        src = src.func.value
                    elem = None
                    sname = dotted_name(src)
                    if sname and sname.startswith("self."):
                        hit = typing.get(sname[5:])
                        if hit is not None and hit[0] == "elem":
                            elem = hit[1]
                    elif sname and sname in local_types:
                        hit = local_types[sname]
                        if hit is not None and hit[0] == "elem":
                            elem = hit[1]
                    elif isinstance(src, ast.Call) and \
                            isinstance(src.func, ast.Attribute):
                        # `for replica in self.members():` — a snapshot
                        # accessor with a container return annotation
                        # types the loop variable like the container
                        # attribute would
                        hit = None
                        if isinstance(src.func.value, ast.Name) and \
                                src.func.value.id == "self":
                            hit = returns.get(src.func.attr)
                        else:
                            owner = typed(src.func.value)
                            if owner is not None:
                                hit = method_returns.get(owner, {}) \
                                    .get(src.func.attr)
                        if hit is not None and hit[0] == "elem":
                            elem = hit[1]
                    if elem is not None:
                        tgt = node.target
                        if items and isinstance(tgt, ast.Tuple) and \
                                len(tgt.elts) == 2 and \
                                isinstance(tgt.elts[1], ast.Name):
                            local_types[tgt.elts[1].id] = ("scalar", elem)
                        elif (values or not items) and \
                                isinstance(tgt, ast.Name):
                            local_types[tgt.id] = ("scalar", elem)
                if isinstance(node, ast.If):
                    # track which attrs the test reads so a rebind in
                    # the body can be classified check-then-act
                    read_targets = set()
                    for sub in ast.walk(node.test):
                        if isinstance(sub, ast.Attribute):
                            hit = attr_target(sub)
                            if hit is not None:
                                read_targets.add(hit)
                    visit(node.test, held, guards)
                    for child in node.body:
                        visit(child, held, guards | read_targets)
                    for child in node.orelse:
                        visit(child, held, guards)
                    return
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    # local typing/aliasing
                    if isinstance(tgt, ast.Name):
                        src = dotted_name(node.value)
                        hit = None
                        if isinstance(node.value, (ast.Call,
                                                   ast.Subscript)):
                            t = typed(node.value)
                            if t is not None:
                                hit = ("scalar", t)
                        if hit is None and src and src.startswith("self."):
                            t = typing.get(src[5:])
                            if t is not None:
                                hit = t
                            alias = ((rel, cls_name), src[5:])
                            local_alias[tgt.id] = alias
                        if hit is None and isinstance(node.value,
                                                      ast.Subscript):
                            base = dotted_name(node.value.value)
                            if base and base.startswith("self."):
                                local_alias[tgt.id] = ((rel, cls_name),
                                                       base[5:])
                        if hit is not None:
                            local_types[tgt.id] = hit
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for tgt in targets:
                        hit = attr_target(tgt) \
                            if isinstance(tgt, ast.Attribute) else None
                        if hit is not None and node.value is not None:
                            if hit in guards or (
                                    rhs_reads(node.value, hit)):
                                kind = KIND_LAZY if hit in guards \
                                    else KIND_RMW
                            else:
                                kind = KIND_REBIND
                            record_write(hit, tgt.lineno, kind, held)
                        elif isinstance(tgt, ast.Subscript):
                            hit = root_attr(tgt)
                            if hit is not None:
                                record_write(hit, tgt.lineno,
                                             KIND_MUTATE, held)
                elif isinstance(node, ast.AugAssign):
                    tgt = node.target
                    hit = attr_target(tgt) \
                        if isinstance(tgt, ast.Attribute) else None
                    if hit is not None:
                        record_write(hit, tgt.lineno, KIND_RMW, held)
                    elif isinstance(tgt, ast.Subscript):
                        hit = root_attr(tgt)
                        if hit is not None:
                            record_write(hit, tgt.lineno, KIND_MUTATE,
                                         held)
                elif isinstance(node, ast.Delete):
                    for tgt in node.targets:
                        hit = root_attr(tgt)
                        if hit is not None:
                            record_write(hit, node.lineno, KIND_MUTATE,
                                         held)
                elif isinstance(node, ast.Call):
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr in MUTATING_METHODS:
                        hit = root_attr(node.func.value)
                        if hit is not None:
                            record_write(hit, node.lineno, KIND_MUTATE,
                                         held)
                    for callee in resolve_call(node):
                        call_sites.setdefault(callee, []).append(
                            (key, frozenset(held)))
                elif isinstance(node, ast.Attribute) and \
                        isinstance(node.ctx, ast.Load):
                    hit = attr_target(node)
                    if hit is not None:
                        (trel, tcls), attr = hit
                        accesses.append(Access(
                            trel, tcls, attr, node.lineno, "read", key,
                            frozenset(held), init_phase=init_phase))
                for child in ast.iter_child_nodes(node):
                    visit(child, held, guards)

            for stmt in fn.body:
                visit(stmt, ())

    # ---- entry-lockset fixpoint ------------------------------------------
    # entry[m] = ⋂ over resolved call sites of (held ∪ entry[caller]);
    # no known callers (public surface, thread targets) -> ∅. Optimistic
    # init (TOP = None), refined downward; a cycle that never gets
    # outside information collapses to ∅ at the end (conservative: no
    # guaranteed locks -> more findings, never a false "guarded").
    entry: Dict[str, Optional[FrozenSet[str]]] = {}
    for m in method_keys:
        entry[m] = None if m in call_sites else frozenset()
    changed = True
    while changed:
        changed = False
        for callee, sites in call_sites.items():
            if callee not in entry:
                continue
            new: Optional[FrozenSet[str]] = None
            for caller, held in sites:
                ce = entry.get(caller)
                if ce is None:
                    if caller in entry:
                        continue  # TOP caller: no constraint yet
                    ce = frozenset()
                site_set = held | ce
                new = site_set if new is None else (new & site_set)
            if new is not None and new != entry[callee]:
                if entry[callee] is None or not new >= entry[callee]:
                    entry[callee] = new if entry[callee] is None \
                        else (entry[callee] & new)
                    changed = True
    for m, e in entry.items():
        if e is None:
            entry[m] = frozenset()

    # init-only helpers: methods whose every resolved call site is the
    # class's own __init__ — their writes are init-phase
    init_only_methods: Set[str] = set()
    for m, sites in call_sites.items():
        if m in method_keys and sites and all(
                caller.endswith(".__init__") and
                caller.rsplit("::", 1)[0] == m.rsplit("::", 1)[0] and
                caller.rsplit(".", 1)[0] == m.rsplit(".", 1)[0]
                for caller, _ in sites):
            init_only_methods.add(m)

    # ---- classify ---------------------------------------------------------
    by_attr: Dict[str, List[Access]] = {}
    for acc in accesses:
        cls_key = (acc.rel, acc.cls)
        if cls_key not in model.scoped_threaded:
            continue
        by_attr.setdefault(f"{acc.rel}::{acc.cls}.{acc.attr}",
                           []).append(acc)

    for key, accs in sorted(by_attr.items()):
        rel, tail = key.split("::", 1)
        cls_name, attr = tail.rsplit(".", 1)
        atomic = attr_atomic.get((rel, cls_name), {}).get(attr)
        writes = [a for a in accs if a.kind != "read"]
        reads = [a for a in accs if a.kind == "read"]
        init_writes = [a for a in writes
                       if a.init_phase or a.method in init_only_methods]
        live_writes = [a for a in writes if a not in init_writes]
        verdict = AttrVerdict(key, "unwritten", writes=live_writes,
                              init_writes=init_writes, reads=reads,
                              atomic_type=atomic)
        if atomic is not None:
            verdict.classification = "atomic-type"
        elif not live_writes:
            verdict.classification = "init-only" if writes else "unwritten"
        else:
            locksets = [a.held | entry.get(a.method, frozenset())
                        for a in live_writes]
            inter = frozenset.intersection(*[frozenset(s)
                                             for s in locksets])
            if inter:
                verdict.classification = "guarded"
                verdict.guards = inter
            elif all(a.kind == KIND_REBIND for a in live_writes):
                verdict.classification = "publication"
            else:
                verdict.classification = "racy"
        model.attrs[key] = verdict
    return model


@rule(RULE, "shared attributes of threaded classes have a consistent "
            "non-empty write lockset (Eraser-style), modulo init-only / "
            "snapshot-publication / atomic-type idioms")
def check(corpus: Corpus) -> List[Finding]:
    model = build_race_model(corpus)
    findings: List[Finding] = []
    for key, verdict in sorted(model.attrs.items()):
        if verdict.classification != "racy":
            continue
        rel, tail = key.split("::", 1)
        racy = [a for a in verdict.writes if a.kind in RACY_KINDS]
        shown = racy or verdict.writes
        sites = ", ".join(
            f"{a.site()} ({a.kind}, locks={{{', '.join(sorted(a.held)) or ''}}})"
            for a in shown[:4])
        read_hint = ""
        cross_reads = [a for a in verdict.reads
                       if a.method not in {w.method
                                           for w in verdict.writes}]
        if cross_reads:
            read_hint = (f"; also read at "
                         f"{cross_reads[0].site()} in another method")
        findings.append(Finding(
            RULE, rel, shown[0].line,
            f"`{tail}` is written with an EMPTY lockset intersection "
            f"from a thread-shared class: {sites}{read_hint} — "
            f"unsynchronized read-modify-write/mutation races under "
            f"concurrent threads",
            tail))
    return findings
