"""thread-lifecycle: every started thread has a reachable join path.

A `threading.Thread` with no `.join()` anywhere is either a daemon the
process abandons at exit (fine for a REPL, lethal for a server that
must drain in-flight verification futures before its datadir unmounts)
or an accidental leak that keeps state alive across test cases. The
repo's convention is: store the thread, stop the loop, join in
`stop()`/`close()` with a bounded timeout. This rule makes the
convention checkable:

- a Thread assigned (directly, or through a local temp — the
  `thread = threading.Thread(...); …; self._t = thread` idiom) to
  `self.<attr>` must have a `<attr>.join(...)` call somewhere in the
  SAME MODULE (reads through locals are followed one step:
  `t = self._t; t.join()` counts);
- a Thread kept only in a local must be joined in the same function;
- a Thread never stored (`threading.Thread(...).start()`) is always a
  finding — nothing can ever join it.

Deliberately unjoined daemons (e.g. a best-effort stats flusher whose
loop sleeps long) belong in the baseline with their one-line reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from gethsharding_tpu.analysis.core import (
    Corpus, Finding, SourceFile, dotted_name, rule)

RULE = "thread-lifecycle"


def _is_thread_ctor(node: ast.AST, sf: SourceFile) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if not name:
        return False
    root, _, tail = name.rpartition(".")
    if tail != "Thread":
        return False
    if not root:
        return sf.imports.get("Thread", "") == "threading.Thread"
    return sf.imports.get(root.split(".", 1)[0],
                          root).split(".", 1)[0] == "threading"


def _join_roots(sf: SourceFile) -> Set[str]:
    """Names X with a `<something X>.join()` call in the module:
    `self.X.join()` and `local.join()` where `local = self.X` both
    yield X; a bare `local.join()` yields the local's name too (for
    function-local threads)."""
    roots: Set[str] = set()
    if sf.tree is None:
        return roots
    # map locals assigned from self.<attr> (one step, module-wide),
    # including iteration over a tuple/list of self attrs
    # (`for t in (self._a, self._b): t.join()`)
    alias_of: Dict[str, Set[str]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            src = dotted_name(node.value)
            if src and src.startswith("self."):
                alias_of.setdefault(node.targets[0].id, set()).add(src[5:])
        elif isinstance(node, ast.For) and \
                isinstance(node.target, ast.Name) and \
                isinstance(node.iter, (ast.Tuple, ast.List)):
            for el in node.iter.elts:
                src = dotted_name(el)
                if src and src.startswith("self."):
                    alias_of.setdefault(node.target.id, set()).add(src[5:])
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            base = dotted_name(node.func.value)
            if not base:
                continue
            if base.startswith("self."):
                roots.add(base[5:])
            else:
                root = base.split(".", 1)[0]
                roots.add(root)
                roots.update(alias_of.get(root, ()))
    return roots


def _scope_nodes(root: ast.AST):
    """Walk `root` WITHOUT descending into nested function scopes —
    each function (and the module itself) is analyzed exactly once, so
    a thread created in a nested def is reported by its own scope only
    and module-level spawns are covered too."""
    yield root
    for child in ast.iter_child_nodes(root):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested scope: analyzed on its own
        yield from _scope_nodes(child)


@rule(RULE, "every started threading.Thread has a reachable join path")
def check(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf in corpus.files:
        if sf.tree is None:
            continue
        joined = _join_roots(sf)
        scopes = [sf.tree] + [n for n in ast.walk(sf.tree)
                              if isinstance(n, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))]
        for scope in scopes:
            scope_name = getattr(scope, "name", "<module>")
            # locals holding a thread in this scope -> ctor line
            local_threads: Dict[str, int] = {}
            stored: Set[str] = set()
            for node in _scope_nodes(scope):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if _is_thread_ctor(node.value, sf):
                        if isinstance(tgt, ast.Name):
                            local_threads[tgt.id] = node.value.lineno
                        elif isinstance(tgt, ast.Attribute):
                            attr = dotted_name(tgt)
                            if attr and attr.startswith("self."):
                                stored.add(attr[5:])
                                if attr[5:] not in joined:
                                    findings.append(Finding(
                                        RULE, sf.rel, node.lineno,
                                        f"thread stored in `self.{attr[5:]}` "
                                        f"(in `{scope_name}`) is never "
                                        f"joined in this module — no "
                                        f"shutdown path drains it",
                                        f"{scope_name}:self.{attr[5:]}"))
                    elif isinstance(tgt, ast.Attribute) and \
                            isinstance(node.value, ast.Name) and \
                            node.value.id in local_threads:
                        attr = dotted_name(tgt)
                        if attr and attr.startswith("self."):
                            stored.add(node.value.id)
                            if attr[5:] not in joined:
                                findings.append(Finding(
                                    RULE, sf.rel, node.lineno,
                                    f"thread stored in `self.{attr[5:]}` "
                                    f"(in `{scope_name}`) is never joined "
                                    f"in this module — no shutdown path "
                                    f"drains it",
                                    f"{scope_name}:self.{attr[5:]}"))
                elif isinstance(node, ast.Expr) and \
                        isinstance(node.value, ast.Call) and \
                        isinstance(node.value.func, ast.Attribute) and \
                        node.value.func.attr == "start" and \
                        _is_thread_ctor(node.value.func.value, sf):
                    findings.append(Finding(
                        RULE, sf.rel, node.lineno,
                        f"`threading.Thread(...).start()` in "
                        f"`{scope_name}` keeps no reference — this thread "
                        f"can never be joined",
                        f"{scope_name}:anonymous"))
            for name, line in sorted(local_threads.items()):
                if name in stored or name in joined:
                    continue
                # a thread that escapes — returned, or passed to a call
                # (`self._threads.append(t)` hands it to the actor base's
                # joining stop()) — becomes the receiver's responsibility
                escapes = any(
                    (isinstance(n, ast.Return) and n.value is not None and
                     name in {x.id for x in ast.walk(n.value)
                              if isinstance(x, ast.Name)}) or
                    (isinstance(n, ast.Call) and
                     any(isinstance(a, ast.Name) and a.id == name
                         for a in n.args))
                    for n in _scope_nodes(scope))
                if escapes:
                    continue
                findings.append(Finding(
                    RULE, sf.rel, line,
                    f"local thread `{name}` in `{scope_name}` is neither "
                    f"stored nor joined — leaked on return",
                    f"{scope_name}:{name}"))
    return findings
