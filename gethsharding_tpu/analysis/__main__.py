"""CLI: ``python -m gethsharding_tpu.analysis [--root DIR] [...]``.

Exit codes: 0 clean (modulo baseline), 1 new findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from gethsharding_tpu.analysis.core import (
    BASELINE_REL, Baseline, RULE_DOCS, RULES, run)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m gethsharding_tpu.analysis",
        description="shardlint: repo-wide static analysis "
                    "(jit-purity, host-sync, lock-order, race-guard, "
                    "layering, backend-contract, thread-lifecycle, "
                    "flag-doc, export-completeness)")
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: the checkout "
                             "this package was imported from)")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="NAME",
                        help="run only this rule (repeatable)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: <root>/"
                             f"{BASELINE_REL})")
    parser.add_argument("--list", action="store_true",
                        help="list registered rules and exit")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as JSON")
    parser.add_argument("--all", action="store_true",
                        help="print baselined findings too, not just new")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept the current findings: write them to "
                             "the baseline (existing justifications are "
                             "kept; new entries get a TODO placeholder)")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="report and DROP baseline entries whose "
                             "fingerprint no longer matches any finding, "
                             "so dead justifications can't accumulate")
    args = parser.parse_args(argv)

    if args.list:
        # rule modules self-register on import
        from gethsharding_tpu.analysis import (  # noqa: F401
            contract, exports, flags, hostsync, layering, lifecycle,
            locks, purity, races)
        for name in sorted(RULES):
            print(f"{name:22s} {RULE_DOCS[name]}")
        return 0

    if args.root is None:
        # the repo root is two levels above this package
        root = Path(__file__).resolve().parents[2]
    else:
        root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"error: root {root} is not a directory", file=sys.stderr)
        return 2

    baseline_path = Path(args.baseline) if args.baseline else \
        root / BASELINE_REL
    try:
        report = run(root, names=args.rule, baseline_path=baseline_path)
    except KeyError as exc:
        print(f"error: {exc.args[0]}", file=sys.stderr)
        return 2

    if args.prune_baseline:
        # `--rule` partial runs must not prune: a rule that did not run
        # reports no findings, so every one of its entries would look
        # stale and be wrongly dropped
        if args.rule:
            print("error: --prune-baseline requires a full run "
                  "(no --rule)", file=sys.stderr)
            return 2
        baseline = Baseline.load(baseline_path)
        if not report.stale:
            print("prune-baseline: nothing stale; "
                  f"{len(baseline.entries)} entr"
                  f"{'y' if len(baseline.entries) == 1 else 'ies'} kept")
        else:
            for key in report.stale:
                print(f"pruning stale baseline entry: {key}\n"
                      f"  (was: {baseline.entries.get(key, '?')})")
                baseline.entries.pop(key, None)
            baseline.save(baseline_path)
            print(f"prune-baseline: dropped {len(report.stale)}, kept "
                  f"{len(baseline.entries)} in {baseline_path}")
        # pruning must not green-wash a dirty tree: new findings still
        # gate exactly like a plain run
        for f in report.new:
            print(f.render())
        if report.new:
            print(f"prune-baseline: {len(report.new)} NEW finding(s) "
                  f"remain — fix or baseline them")
        return 1 if report.new else 0

    if args.write_baseline:
        baseline = Baseline.load(baseline_path)
        entries = {}
        if args.rule:
            # partial run: keep every entry belonging to a rule that did
            # NOT run — only the selected rules' findings are rewritten
            # (a `--rule X --write-baseline` must never wipe the other
            # rules' justified entries)
            ran = set(args.rule)
            entries = {k: v for k, v in baseline.entries.items()
                       if k.split("::", 1)[0] not in ran}
        for f in report.findings:
            entries[f.key] = baseline.entries.get(
                f.key, f"TODO: justify — {f.message[:80]}")
        Baseline(entries).save(baseline_path)
        print(f"wrote {len(entries)} finding(s) to {baseline_path}")
        return 0

    if args.as_json:
        payload = {
            "elapsed_s": round(report.elapsed_s, 3),
            "new": [vars(f) | {"key": f.key} for f in report.new],
            "accepted": [vars(f) | {"key": f.key} for f in report.accepted],
            "stale_baseline_keys": report.stale,
        }
        print(json.dumps(payload, indent=2))
        return 1 if report.new else 0

    shown = report.findings if args.all else report.new
    for f in shown:
        mark = "" if f in report.new else " [baselined]"
        print(f.render() + mark)
    per_rule = {}
    for f in report.findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    summary = ", ".join(f"{k}={v}" for k, v in sorted(per_rule.items())) \
        or "none"
    print(f"shardlint: {len(report.new)} new, {len(report.accepted)} "
          f"baselined, {len(report.stale)} stale baseline entr"
          f"{'y' if len(report.stale) == 1 else 'ies'} "
          f"({summary}) in {report.elapsed_s:.2f}s")
    if report.stale:
        for key in report.stale:
            print(f"  stale baseline entry (finding no longer fires): {key}")
    return 1 if report.new else 0


if __name__ == "__main__":
    sys.exit(main())
