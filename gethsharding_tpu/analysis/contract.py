"""backend-contract: every SigBackend wrapper proxies the full surface.

The composition story (device → chaos → serving → soundness → failover →
router, any prefix of it) only works because every wrapper is a drop-in
`SigBackend`: a wrapper missing one public method works until the first
caller of that method lands on it through a composed stack, then dies
with AttributeError at 2am. PR 7 shipped a one-off lint for the errors
surface; this rule generalizes the idea to the backend contract itself.

Mechanics:

- The REQUIRED surface is computed from `sigbackend.py`: the public
  methods `PythonSigBackend` exposes — its own defs plus the concrete
  defaults it inherits from `SigBackend` (whose NotImplementedError
  stubs mark the abstract set every backend must fill).
- A WRAPPER is any class outside `sigbackend.py` that subclasses
  `SigBackend` (resolved through imports) or duck-types it (defines at
  least half of the required surface — catches `RouterSigBackend` /
  `RpcReplicaBackend`, which wrap without inheriting).
- Each wrapper must define every required method ITSELF (or via a
  corpus base that is itself a wrapper) with a real body. Inheriting
  `SigBackend`'s sync-fallback default silently bypasses the wrap (a
  chaos/soundness/serving wrapper that fell back to the base
  `bls_verify_committees_async` would skip its own seam), so it does
  not count. A method whose whole body is `raise NotImplementedError`
  is flagged as a stub — deliberately unsupported planes belong in the
  baseline with a justification, not silently absent.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from gethsharding_tpu.analysis.core import Corpus, Finding, dotted_name, rule

RULE = "backend-contract"
BASE_MODULE = "gethsharding_tpu.sigbackend"
BASE_CLASS = "SigBackend"
REFERENCE_CLASS = "PythonSigBackend"


def _method_defs(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _is_stub(fn: ast.FunctionDef) -> bool:
    """Body is (docstring +) a single `raise NotImplementedError...`."""
    body = list(fn.body)
    if body and isinstance(body[0], ast.Expr) and \
            isinstance(body[0].value, ast.Constant) and \
            isinstance(body[0].value.value, str):
        body = body[1:]
    if len(body) != 1 or not isinstance(body[0], ast.Raise):
        return False
    exc = body[0].exc
    name = dotted_name(exc.func if isinstance(exc, ast.Call) else exc)
    return name == "NotImplementedError"


def _find_base_file(corpus: Corpus):
    sf = corpus.find_module(BASE_MODULE)
    if sf is not None:
        return sf
    # fixture trees: any file defining both the base and the reference
    for cand in corpus.files:
        if cand.tree is None:
            continue
        names = {n.name for n in cand.tree.body
                 if isinstance(n, ast.ClassDef)}
        if BASE_CLASS in names and REFERENCE_CLASS in names:
            return cand
    return None


def required_surface(corpus: Corpus) -> Tuple[Optional[str], Set[str]]:
    """(base file rel, public method names every backend must serve)."""
    sf = _find_base_file(corpus)
    if sf is None or sf.tree is None:
        return None, set()
    base_cls = ref_cls = None
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef):
            if node.name == BASE_CLASS:
                base_cls = node
            elif node.name == REFERENCE_CLASS:
                ref_cls = node
    required: Set[str] = set()
    if base_cls is not None:
        for name, fn in _method_defs(base_cls).items():
            if not name.startswith("_"):
                required.add(name)
    if ref_cls is not None:
        for name in _method_defs(ref_cls):
            if not name.startswith("_"):
                required.add(name)
    return sf.rel, required


def wrapper_report(corpus: Corpus) -> Dict[str, Dict[str, str]]:
    """class qualname -> {method: 'missing'|'stub'} (empty = complete)."""
    base_rel, required = required_surface(corpus)
    if not required:
        return {}

    # collect every class + resolved base names
    infos: Dict[Tuple[str, str], ast.ClassDef] = {}
    bases: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    subclasses_sig: Set[Tuple[str, str]] = set()
    for sf in corpus.files:
        if sf.tree is None:
            continue
        for node in sf.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            key = (sf.rel, node.name)
            infos[key] = node
            resolved: List[Tuple[str, str]] = []
            for b in node.bases:
                name = dotted_name(b)
                if not name:
                    continue
                if "." in name:
                    mod_alias, cls = name.rsplit(".", 1)
                    module = sf.imports.get(mod_alias.split(".", 1)[0])
                    other = corpus.find_module(module) if module else None
                    if other is not None:
                        resolved.append((other.rel, cls))
                else:
                    target = sf.imports.get(name)
                    if target and "." in target:
                        mod, cls = target.rsplit(".", 1)
                        other = corpus.find_module(mod)
                        if other is not None:
                            resolved.append((other.rel, cls))
                        elif cls == BASE_CLASS and base_rel:
                            resolved.append((base_rel, cls))
                    else:
                        resolved.append((sf.rel, name))
            bases[key] = resolved

    # transitive "subclasses SigBackend"
    def is_sig_subclass(key, seen=None) -> bool:
        if seen is None:
            seen = set()
        if key in seen:
            return False
        seen.add(key)
        for b in bases.get(key, ()):
            if b == (base_rel, BASE_CLASS):
                return True
            if b in infos and is_sig_subclass(b, seen):
                return True
        return False

    report: Dict[str, Dict[str, str]] = {}
    threshold = max(1, len(required) // 2)
    for key, node in sorted(infos.items()):
        rel, cls_name = key
        if rel == base_rel:
            continue  # the backends themselves, not wrappers
        own = _method_defs(node)
        defined_required = [m for m in required if m in own]
        subclasses = is_sig_subclass(key)
        if not subclasses and len(defined_required) < threshold:
            continue  # not a backend wrapper
        # methods available through corpus bases that are NOT SigBackend
        avail: Dict[str, ast.FunctionDef] = {}

        def collect(k, seen=None):
            if seen is None:
                seen = set()
            if k in seen or k == (base_rel, BASE_CLASS):
                return
            seen.add(k)
            n = infos.get(k)
            if n is None:
                return
            for name, fn in _method_defs(n).items():
                avail.setdefault(name, fn)
            for b in bases.get(k, ()):
                collect(b, seen)

        collect(key)
        problems: Dict[str, str] = {}
        for m in sorted(required):
            fn = avail.get(m)
            if fn is None:
                problems[m] = "missing"
            elif _is_stub(fn):
                problems[m] = "stub"
        report[f"{rel}::{cls_name}"] = problems
    return report


@rule(RULE, "every SigBackend wrapper proxies the full "
            "PythonSigBackend public surface")
def check(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for qual, problems in sorted(wrapper_report(corpus).items()):
        rel, cls_name = qual.split("::", 1)
        sf = corpus.get(rel)
        line = 0
        if sf is not None and sf.tree is not None:
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef) and node.name == cls_name:
                    line = node.lineno
                    break
        for method, kind in sorted(problems.items()):
            if kind == "missing":
                msg = (f"backend wrapper `{cls_name}` does not define "
                       f"`{method}` — a composed stack calling it dies "
                       f"with AttributeError (the SigBackend default, if "
                       f"any, bypasses the wrapper's seam)")
            else:
                msg = (f"backend wrapper `{cls_name}.{method}` is a "
                       f"NotImplementedError stub — if the plane is "
                       f"deliberately unsupported, baseline this with the "
                       f"justification")
            findings.append(Finding(RULE, rel, line, msg,
                                    f"{cls_name}.{method}:{kind}"))
    return findings
